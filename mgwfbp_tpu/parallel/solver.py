"""MG-WFBP merge-group solver.

Decides which per-layer gradients to fuse into a single all-reduce so that
communication maximally overlaps the backward pass while amortizing startup
latency (alpha). This is the framework's core contribution, re-derived from the
reference algorithm's semantics (reference distributed_optimizer.py:164-261 for
the adaptive policy, :140-162 for the static threshold policy; papers
arXiv:1811.11141 / arXiv:1912.09268).

Pure functions on plain data — hardware-agnostic math, exhaustively
unit-testable (SURVEY.md §4). The JAX lowering lives in
`mgwfbp_tpu.parallel.buckets` / `allreduce`.

Conventions (differ from the reference's, chosen for clarity):
  * All sequences are in **gradient-arrival order**: index 0 is the first
    gradient produced by the backward pass, i.e. the LAST forward layer.
    (The reference stores layers in forward order and scans from the end;
    arrival order makes the recurrences read left-to-right.)
  * ``tb[i]`` is the backward-compute duration attributable to layer i, so
    gradient i is ready at ``ready[i] = tb[0] + ... + tb[i]``.
  * Group lists are emitted in arrival order as index tuples into the input.

The merge rule, per the paper: scanning arrivals in order with a current open
group whose collective would start at ``start`` and occupy the link for
``comm`` seconds, the next gradient (ready at ``r``) is merged into the group
when either
  (a) the group's collective could not have started yet anyway
      (``start > r`` — merging costs no extra waiting), or
  (b) the wait it introduces is cheaper than the startup latency another
      collective would pay (``r - start < alpha``).
Otherwise the group is closed and a new one opened.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence

import numpy as np

from mgwfbp_tpu.parallel.costmodel import AlphaBeta, TwoLevelAlphaBeta

CostFn = Callable[[float], float]  # bytes -> seconds


def effective_cost_fn(cost_model, comm_op: str = "all_reduce") -> CostFn:
    """Per-bucket link-occupancy predictor for a lowering.

    For the plain collectives this is `cost_model.predict`. The rs_opt_ag
    lowering inserts the fused shard optimizer update BETWEEN the
    reduce-scatter and the param all-gather — the gather cannot start
    before the update finishes, so the update's duration
    (`update_beta * bucket_bytes`, see costmodel.AlphaBeta.update_beta)
    rides the same serial timeline the merge rule and the simulator reason
    about. Keeping the term inside the cost function means every consumer
    (the mgwfbp scan, auto's argmin, predicted_group_times) prices the
    update-in-the-middle consistently without growing their signatures.
    The cross-step rs_fwd_ag lowering pays the same update between its RS
    and the (next-step) AG, so its per-group TOTAL is priced identically;
    the per-phase split lives in `cross_step_phase_costs`.
    """
    ub = float(getattr(cost_model, "update_beta", 0.0))
    if comm_op not in ("rs_opt_ag", "rs_fwd_ag") or ub == 0.0:
        return cost_model.predict
    base = cost_model.predict
    return lambda nbytes: base(nbytes) + ub * nbytes


# A ring all-reduce is reduce-scatter + all-gather, each moving (P-1)/P of
# the payload: absent a measurement, the calibrated full-collective
# predictor splits evenly between the two phases for the cross-step
# timeline. This is only the DEFAULT prior — a `calibrate --allgather`
# sweep measures the link's real split and persists it as the profile's
# `ag_fraction` (costmodel, schema v3), which `cross_step_phase_costs`
# prefers; the split is clamped to [MIN_AG_FRACTION, 1-MIN_AG_FRACTION]
# so a degenerate calibration can never zero out a whole phase.
CROSS_STEP_RS_FRACTION = 0.5
MIN_AG_FRACTION = 0.05


def cross_step_phase_costs(cost_model) -> tuple[CostFn, CostFn]:
    """(rs_cost, ag_cost) per bucket for the rs_fwd_ag lowering.

    The reduce-scatter leg rides the BACKWARD-side link timeline and also
    carries the shard optimizer update (update_beta — the carried shard is
    not ready to gather until the update lands); the deferred all-gather
    leg rides the NEXT step's forward-side timeline. The two sum to
    `effective_cost_fn(cost_model, 'rs_fwd_ag')` by construction, so
    per-group totals (predict_group_times, overlap accounting) and the
    two-phase simulate can never disagree on a bucket's wire time.

    The RS/AG split comes from the cost model's measured ``ag_fraction``
    when a `calibrate --allgather` sweep fit one; models without it (v1/v2
    profiles, built-in tables) keep the historical halved split
    (`CROSS_STEP_RS_FRACTION`)."""
    base = cost_model.predict
    ub = float(getattr(cost_model, "update_beta", 0.0))
    ag_frac = float(getattr(
        cost_model, "ag_fraction", 1.0 - CROSS_STEP_RS_FRACTION
    ))
    ag_frac = min(max(ag_frac, MIN_AG_FRACTION), 1.0 - MIN_AG_FRACTION)
    rs_frac = 1.0 - ag_frac

    def rs_cost(nbytes: float) -> float:
        return rs_frac * base(nbytes) + ub * nbytes

    def ag_cost(nbytes: float) -> float:
        return ag_frac * base(nbytes)

    return rs_cost, ag_cost


def forward_prior_tf(tb: Sequence[float]) -> list[float]:
    """Fallback per-layer FORWARD durations when no measured forward
    profile exists: backward is ~2x forward FLOPs for conv/dense layers
    (grad-of-input + grad-of-weights vs one matmul), so tf = tb/2 keeps
    the measured backward profile's shape at a defensible scale. A
    measured profile (`profiling.benchmark_trainer_forward`) always takes
    precedence."""
    return [0.5 * float(t) for t in tb]


def simulate_cross_step(
    groups: Sequence[Sequence[int]],
    sizes_bytes: Sequence[int],
    tb: Sequence[float],
    tf: Sequence[float],
    rs_cost: CostFn,
    ag_cost: CostFn,
    gamma: float = 0.0,
    overlap: float = 1.0,
    pack_beta: float = 0.0,
) -> tuple[float, float, float]:
    """Steady-state step timeline of the cross-step (rs_fwd_ag) pipeline.

    Returns (total, nonoverlap, comm_time) where `total` is COMPARABLE to
    `simulate_groups`' total for the in-step lowerings: both measure the
    step's critical path from the moment the backward could begin on an
    idle link — i.e. the cross-step total EXCLUDES the forward-compute
    floor sum(tf) that every lowering pays identically, and counts only
    the forward STALL the deferred gathers add on top of it. Concretely::

        total = (fwd_end - sum(tf))          # forward stall from late AGs
              + overlap-blended backward/RS timeline
              + per-group overheads (gamma, pack_beta)

    Two phases share one serial link:

      * forward: groups gather in REVERSE arrival order (group G-1 holds
        the first forward layers). Group g's AG must land before the
        forward reaches its first consuming layer — arrival index max(g),
        whose forward block starts after all later-arrival groups' blocks
        — or the forward stalls for the difference. This is the
        AG-before-first-use deadline.
      * backward: the solver's taoc recurrence (`simulate_groups`) over
        the RS legs, with grad-ready times offset by the forward stall and
        the link initially busy until the last AG finished.

    `nonoverlap` = total - sum(tb): comm time (and stall) not hidden
    behind compute, the same convention as `simulate_groups`.
    """
    groups = list(groups)
    n_layers = len(sizes_bytes)
    if len(tb) != n_layers or len(tf) != n_layers:
        raise ValueError(
            f"tb ({len(tb)}) / tf ({len(tf)}) / sizes ({n_layers}) "
            "length mismatch"
        )
    tf_total = float(np.sum(np.asarray(tf, np.float64))) if n_layers else 0.0
    tb_total = float(np.sum(np.asarray(tb, np.float64))) if n_layers else 0.0

    # ---- forward phase: AG deadlines vs forward compute ----
    link = 0.0  # serial comm link, busy-until
    fwd = 0.0  # forward compute, busy-until
    comm_sum = 0.0
    pack_bytes = 0.0
    for g in reversed(groups):  # forward-consumption order
        gbytes = float(sum(sizes_bytes[i] for i in g))
        t_ag = ag_cost(gbytes)
        link += t_ag  # shards are ready at step start; AGs queue serially
        comm_sum += t_ag
        if len(g) > 1:
            pack_bytes += gbytes
        # the group's layers cannot start their forward before its gather
        fwd = max(fwd, link) + float(sum(tf[i] for i in g))
    fwd_end = fwd
    fwd_stall = max(fwd_end - tf_total, 0.0)

    # ---- backward phase: the taoc recurrence over the RS legs ----
    # Anchor at the backward start (like simulate_groups): grads become
    # ready along the backward, delayed by any forward stall already on
    # the critical path; the link is free once the last AG drained (the
    # forward ran at least as long, so only a comm-bound tail carries over)
    ready = fwd_stall + np.cumsum(np.asarray(tb, dtype=np.float64))
    bwd_end = fwd_stall + tb_total
    link_free = max(link - tf_total, 0.0)
    n_groups = 0
    for g in groups:
        gbytes = float(sum(sizes_bytes[i] for i in g))
        t_rs = rs_cost(gbytes)
        start = max(link_free, float(ready[max(g)]))
        link_free = start + t_rs
        comm_sum += t_rs
        n_groups += 1
    overhead = gamma * n_groups + pack_beta * pack_bytes
    total_hidden = max(bwd_end, link_free)
    total_serial = tb_total + comm_sum  # fully serialized regime
    ov = min(max(overlap, 0.0), 1.0)
    total = ov * total_hidden + (1.0 - ov) * total_serial + overhead
    return total, total - tb_total, comm_sum


# ---------------------------------------------------------------------------
# Two-link (ICI + DCN) scheduling: the hierarchical lowering's timeline.
#
# A multi-slice pod has TWO interconnects at once — fast ICI inside a slice,
# slow DCN across slices — and the paper's own result (the 10GbE and IB
# clusters of arXiv:1912.09268 solve to different groupings) says the merge
# schedule is a function of the link. So a hier schedule is a PAIR of nested
# partitions: the inner (ICI) grouping of layers, plus an outer (DCN)
# grouping of those inner groups — small buckets may merge on the
# high-latency DCN link while staying split on ICI (amortizing the DCN
# alpha without giving up ICI-side overlap granularity).
# ---------------------------------------------------------------------------


def is_two_level(cost_model) -> bool:
    """Duck-typed: does this model price two link classes separately?"""
    return (
        cost_model is not None
        and hasattr(cost_model, "ici")
        and hasattr(cost_model, "dcn")
        and int(getattr(cost_model, "dcn_size", 1)) > 1
    )


def two_level_leg_costs(cost_model) -> tuple[CostFn, CostFn, CostFn]:
    """(rs_cost, dcn_cost, ag_cost) per bucket for the hier lowering.

    All three take the FULL bucket payload in bytes. The ICI side splits
    into its RS and AG legs by the INNER link's measured ag_fraction
    (calibrate --allgather; 0.5 prior); the DCN leg is the outer-link
    all-reduce of the 1/ici_size shard (`TwoLevelAlphaBeta.
    dcn_shard_predict` owns the shard division). The three sum to
    `cost_model.predict` by construction, so per-group totals and the
    two-link simulate can never disagree on a bucket's wire time."""
    ici = cost_model.ici
    af = float(getattr(ici, "ag_fraction", 0.5))
    af = min(max(af, MIN_AG_FRACTION), 1.0 - MIN_AG_FRACTION)

    def rs_cost(nbytes: float) -> float:
        return (1.0 - af) * float(ici.predict(nbytes))

    def ag_cost(nbytes: float) -> float:
        return af * float(ici.predict(nbytes))

    return rs_cost, cost_model.dcn_shard_predict, ag_cost


def singleton_dcn_groups(num_groups: int) -> list[list[int]]:
    """One DCN collective per inner group — the pre-nesting hier shape
    (and the default for explicit/non-auto schedules)."""
    return [[gi] for gi in range(num_groups)]


def check_dcn_partition(
    dcn_groups: Sequence[Sequence[int]], num_groups: int
) -> None:
    """A DCN partition must cover every inner-group index exactly once
    (a gap means a bucket whose cross-slice reduction never happens —
    silently wrong gradients)."""
    flat = sorted(i for d in dcn_groups for i in d)
    if flat != list(range(num_groups)):
        raise ValueError(
            f"dcn_groups must cover every inner-group index exactly once "
            f"(got {num_groups} groups, partition {list(dcn_groups)})"
        )


def simulate_groups_two_level(
    groups: Sequence[Sequence[int]],
    dcn_groups: Sequence[Sequence[int]],
    sizes_bytes: Sequence[int],
    tb: Sequence[float],
    rs_cost: CostFn,
    dcn_cost: CostFn,
    ag_cost: CostFn,
    gamma: float = 0.0,
    dcn_gamma: float = 0.0,
    overlap: float = 1.0,
    pack_beta: float = 0.0,
) -> tuple[float, float, float]:
    """Two-link timeline of the hierarchical lowering for a nested
    schedule. Returns (total, nonoverlap, comm_time), comparable with
    `simulate_groups` (both are backward-anchored).

    Two serial links race the backward pass:

      * ICI link: each inner group's reduce-scatter starts when its last
        gradient is ready and the link is free (the taoc recurrence);
        after the RS phase the same link carries the all-gathers, each
        gated on its DCN group's cross-slice reduction landing — the
        phase order the lowering's token chain realizes.
      * DCN link: one all-reduce per DCN group over the concatenated
        member shards (payload = the members' 1/ici_size shards), issued
        when the group's LAST member's reduce-scatter completes.

    `gamma` is the per-inner-group fixed overhead (pack/dispatch on the
    ICI side), `dcn_gamma` the per-DCN-collective one — nesting exists
    exactly to trade the latter against DCN-link wait. `pack_beta`
    charges the bucketization copy per byte of multi-member inner groups
    plus the shard concat of multi-member DCN groups."""
    groups = list(groups)
    dcn_groups = [list(d) for d in dcn_groups]
    check_dcn_partition(dcn_groups, len(groups))
    ready = np.cumsum(np.asarray(tb, dtype=np.float64))
    bwd_end = float(ready[-1]) if len(ready) else 0.0
    gbytes = [float(sum(sizes_bytes[i] for i in g)) for g in groups]

    # ---- ICI link, RS phase ----
    ici_free = 0.0
    comm_sum = 0.0
    pack_bytes = 0.0
    rs_done = [0.0] * len(groups)
    for gi, g in enumerate(groups):
        t = rs_cost(gbytes[gi])
        start = max(ici_free, float(ready[max(g)]) if len(g) else 0.0)
        ici_free = start + t
        rs_done[gi] = ici_free
        comm_sum += t
        if len(g) > 1:
            pack_bytes += gbytes[gi]

    # ---- DCN link: one cross-slice all-reduce per DCN group ----
    dcn_free = 0.0
    dcn_done = [0.0] * len(groups)
    for d in dcn_groups:
        dbytes = float(sum(gbytes[gi] for gi in d))
        t = dcn_cost(dbytes)
        start = max(dcn_free, max(rs_done[gi] for gi in d))
        dcn_free = start + t
        for gi in d:
            dcn_done[gi] = dcn_free
        comm_sum += t
        # multi-member DCN groups concat/split their members' SHARD
        # buffers (1/ici_size of the bucket each) — a copy so small next
        # to the inner-side bucket pack that charging it would only add
        # an ici_size knob to every caller; left unpriced by design

    # ---- ICI link, AG phase (after the RS queue; gated per DCN group) ----
    for gi in range(len(groups)):
        t = ag_cost(gbytes[gi])
        start = max(ici_free, dcn_done[gi])
        ici_free = start + t
        comm_sum += t

    overhead = (
        gamma * len(groups) + dcn_gamma * len(dcn_groups)
        + pack_beta * pack_bytes
    )
    total_hidden = max(bwd_end, ici_free, dcn_free)
    total_serial = bwd_end + comm_sum
    ov = min(max(overlap, 0.0), 1.0)
    total = ov * total_hidden + (1.0 - ov) * total_serial + overhead
    return total, total - bwd_end, comm_sum


def dcn_partition_candidates(
    groups: Sequence[Sequence[int]],
    sizes_bytes: Sequence[int],
    tb: Sequence[float],
    rs_cost: CostFn,
    dcn_cost: CostFn,
    dcn_alpha: float,
    dcn_gamma: float = 0.0,
) -> list[tuple[str, list[list[int]]]]:
    """Candidate DCN partitions for a FIXED inner grouping, deduped.

    The outer link sees each inner group as one "layer": its payload is
    the group's (full-bucket) bytes and its arrival time the completion
    of its reduce-scatter on the ICI link. Candidates: one collective per
    group (the pre-nesting shape), everything in one, and the mgwfbp scan
    re-run ON THE DCN LINK — the per-link merge decision this module
    exists for (small groups merge on DCN but stay split on ICI when the
    DCN alpha dominates their shard payloads)."""
    ready = np.cumsum(np.asarray(tb, dtype=np.float64))
    gbytes = [int(sum(sizes_bytes[i] for i in g)) for g in groups]
    ici_free = 0.0
    rs_done = []
    for gi, g in enumerate(groups):
        start = max(ici_free, float(ready[max(g)]) if len(g) else 0.0)
        ici_free = start + rs_cost(float(gbytes[gi]))
        rs_done.append(ici_free)
    # per-"layer" time deltas whose cumsum reproduces the arrival times
    tb_dcn = [rs_done[0]] + [
        rs_done[i] - rs_done[i - 1] for i in range(1, len(rs_done))
    ]
    n = len(groups)
    out: list[tuple[str, list[list[int]]]] = [
        ("per-group", singleton_dcn_groups(n)),
        ("single", [list(range(n))] if n else []),
    ]
    if n:
        out.append((
            "scan",
            mgwfbp_groups(
                gbytes, tb_dcn, alpha=dcn_alpha, cost=dcn_cost,
                itemsize=1, gamma=dcn_gamma,
            ),
        ))
    seen: set = set()
    deduped = []
    for detail, part in out:
        key = tuple(map(tuple, part))
        if key in seen:
            continue
        seen.add(key)
        deduped.append((detail, part))
    return deduped


def two_level_frontier(
    sizes: Sequence[int],
    tb: Sequence[float],
    cost_model,
    itemsize: int | Sequence[int] = 4,
    max_candidates: int = 6,
) -> list[tuple[str, list[list[int]], list[list[int]], float]]:
    """Ranked nested schedules for the hier lowering: (detail, groups,
    dcn_groups, predicted_total_s), cheapest first.

    Inner candidates come from `candidate_groupings` priced on the ICI
    link (its RS+AG legs are what occupy that link; the DCN hop rides a
    different wire and must not distort the inner merge rule); each inner
    candidate is then nested under every `dcn_partition_candidates` pick
    and the pair scored by the two-link simulate. This IS the per-link
    merge decision: the argmin is free to keep buckets split on ICI while
    merging their cross-slice reductions on DCN."""
    L = len(sizes)
    if L == 0:
        return []
    if not is_two_level(cost_model):
        raise ValueError(
            "two_level_frontier needs a TwoLevelAlphaBeta-shaped cost "
            f"model (got {type(cost_model).__name__})"
        )
    itemsizes = [itemsize] * L if isinstance(itemsize, int) else list(itemsize)
    nbytes = [int(s) * it for s, it in zip(sizes, itemsizes)]
    rs_cost, dcn_cost, ag_cost = two_level_leg_costs(cost_model)
    ici = cost_model.ici
    dcn = cost_model.dcn
    gamma = float(getattr(ici, "gamma", 0.0))
    dcn_gamma = float(getattr(dcn, "gamma", 0.0))
    overlap = float(getattr(cost_model, "overlap", 1.0))
    pack_beta = float(getattr(cost_model, "pack_beta", 0.0))
    ici_cost = ici.predict
    scored: list[tuple[str, list[list[int]], list[list[int]], float]] = []
    seen: set = set()
    for inner_detail, groups in candidate_groupings(
        sizes, tb, float(getattr(ici, "alpha", 0.0)), ici_cost, itemsizes,
        gamma=gamma, pack_beta=pack_beta,
    ):
        for dcn_detail, part in dcn_partition_candidates(
            groups, nbytes, tb, rs_cost, dcn_cost,
            dcn_alpha=float(getattr(dcn, "alpha", 0.0)),
            dcn_gamma=dcn_gamma,
        ):
            key = (tuple(map(tuple, groups)), tuple(map(tuple, part)))
            if key in seen:
                continue
            seen.add(key)
            total, _, _ = simulate_groups_two_level(
                groups, part, nbytes, tb, rs_cost, dcn_cost, ag_cost,
                gamma=gamma, dcn_gamma=dcn_gamma, overlap=overlap,
                pack_beta=pack_beta,
            )
            scored.append((
                f"{inner_detail}/dcn-{dcn_detail}", groups, part,
                float(total),
            ))
    scored.sort(key=lambda c: c[3])
    return scored[: max(max_candidates, 1)]


def remap_dcn_groups(
    old_groups: Sequence[Sequence[int]],
    new_groups: Sequence[Sequence[int]],
    dcn_groups: Sequence[Sequence[int]],
) -> list[list[int]]:
    """Carry a DCN partition across a refinement of the inner grouping
    (`buckets.build_layout` splits dtype-mixed groups): every new group
    descends from exactly one old group, and inherits its DCN membership.
    Order within each DCN group follows the new (arrival) indices."""
    member_to_old: dict[int, int] = {}
    for oi, g in enumerate(old_groups):
        for i in g:
            member_to_old[i] = oi
    new_owner = [member_to_old[g[0]] for g in new_groups]
    out: list[list[int]] = []
    for d in dcn_groups:
        want = set(int(i) for i in d)
        members = [ni for ni, oi in enumerate(new_owner) if oi in want]
        if members:
            out.append(members)
    return out


def align_dcn_groups(
    dcn_groups: Sequence[Sequence[int]], dtypes: Sequence
) -> list[list[int]]:
    """Split DCN groups at bucket-dtype boundaries: one DCN collective
    concatenates its members' shards into ONE buffer, which only exists
    for a homogeneous dtype. Each split adds a real cross-slice
    collective (and its DCN alpha), so callers re-simulate predictions
    on the partition actually issued."""
    out: list[list[int]] = []
    for d in dcn_groups:
        run: list[int] = []
        for gi in d:
            if run and dtypes[gi] != dtypes[run[-1]]:
                out.append(run)
                run = []
            run.append(int(gi))
        if run:
            out.append(run)
    return out


def auto_groups_two_level(
    sizes: Sequence[int],
    tb: Sequence[float],
    cost_model,
    itemsize: int | Sequence[int] = 4,
) -> tuple[list[list[int]], list[list[int]], str]:
    """`auto_groups` for the hierarchical lowering: argmin over the
    two-level frontier. Returns (groups, dcn_groups, detail) — a PAIR of
    nested partitions, the schedule shape a two-interconnect topology
    actually calls for."""
    if len(sizes) == 0:
        return [], [], "empty"
    best = two_level_frontier(
        sizes, tb, cost_model, itemsize, max_candidates=1
    )[0]
    return best[1], best[2], best[0]


@dataclasses.dataclass(frozen=True)
class LayerSpec:
    """One gradient tensor, in arrival order."""

    name: str
    size: int  # number of elements
    itemsize: int = 4  # bytes per element (4 fp32, 2 bf16)

    @property
    def nbytes(self) -> int:
        return self.size * self.itemsize


@dataclasses.dataclass(frozen=True)
class MergeSchedule:
    """Solver output: groups of arrival-order indices plus predictions."""

    groups: tuple[tuple[int, ...], ...]
    layer_names: tuple[str, ...]
    predicted_total_time: float  # ready-to-step wall clock, seconds
    predicted_nonoverlap_time: float  # comm time not hidden by backward
    predicted_comm_time: float  # sum of per-group collective durations
    # per-group (payload_bytes, predicted_seconds), arrival order — the
    # reference logs this prediction and measures each merged tensor's
    # allreduce in-loop (distributed_optimizer.py:256-259, 374-391);
    # tools/overlap_report.py compares these against trace timings
    predicted_group_times: tuple[tuple[int, float], ...] = ()
    # which candidate won when policy='auto' ('mgwfbp', 'wfbp', 'single',
    # or 'threshold:<elems>'); empty for direct policies
    policy_detail: str = ""
    # hier (two-level) only: the OUTER (DCN) partition — groups of
    # inner-group indices, arrival order; each DCN group issues ONE
    # cross-slice collective over its members' concatenated shards. Empty
    # for flat lowerings (and treated as one-DCN-collective-per-group by
    # the hier lowering when a two-level solve never ran).
    dcn_groups: tuple[tuple[int, ...], ...] = ()

    @property
    def num_groups(self) -> int:
        return len(self.groups)

    @property
    def num_dcn_groups(self) -> int:
        return len(self.dcn_groups) if self.dcn_groups else len(self.groups)

    def named_groups(self) -> list[list[str]]:
        return [[self.layer_names[i] for i in g] for g in self.groups]


def predict_group_times(
    groups: Sequence[Sequence[int]],
    sizes_bytes: Sequence[int],
    cost: CostFn,
) -> tuple[tuple[int, float], ...]:
    """Per-group (payload_bytes, predicted_seconds), arrival order."""
    out = []
    for g in groups:
        b = int(sum(sizes_bytes[i] for i in g))
        out.append((b, float(cost(b))))
    return tuple(out)


def simulate_groups(
    groups: Sequence[Sequence[int]],
    sizes_bytes: Sequence[int],
    tb: Sequence[float],
    cost: CostFn,
    gamma: float = 0.0,
    overlap: float = 1.0,
    pack_beta: float = 0.0,
) -> tuple[float, float, float]:
    """Simulate the backward/comm overlap timeline for a fixed grouping.

    Returns (total_time, nonoverlap_time, comm_time). A group's collective can
    start when its last member's gradient is ready and the link is free
    (reference's taoc recurrence, distributed_optimizer.py:187-192, expressed
    over groups instead of layers). `gamma` is the per-collective fixed
    overhead that lives OUTSIDE the link timeline (pack/unpack/dispatch,
    costmodel.AlphaBeta.gamma): it lands on the step's critical path once per
    group, un-hideable by overlap, so it is added to both the total and the
    nonoverlap prediction.

    `overlap` is the platform's calibrated capability to hide collectives
    behind concurrent compute (costmodel.AlphaBeta.overlap): 1.0 gives the
    reference's fully-async timeline, 0.0 a fully serialized one
    (bwd + all comm back-to-back — the virtual CPU mesh regime, where
    compute and collective thunks share the cores); intermediate values
    blend the two linearly.

    `pack_beta` charges the bucketization copy (flatten-concat + unpack)
    per byte of every MULTI-member group — singleton groups reduce their
    tensor in place, so isolating a huge layer in its own group avoids its
    pack copy entirely (costmodel.AlphaBeta.pack_beta; grouping-dependent,
    hence part of the argmin objective).
    """
    ready = np.cumsum(np.asarray(tb, dtype=np.float64))
    bwd_end = float(ready[-1]) if len(ready) else 0.0
    link_free = 0.0
    comm_sum = 0.0
    pack_bytes = 0.0
    n_groups = 0
    for g in groups:
        gbytes = float(sum(sizes_bytes[i] for i in g))
        t = cost(gbytes)
        start = max(link_free, float(ready[max(g)]))
        link_free = start + t
        comm_sum += t
        n_groups += 1
        if len(g) > 1:
            pack_bytes += gbytes
    overhead = gamma * n_groups + pack_beta * pack_bytes
    total_hidden = max(bwd_end, link_free)
    total_serial = bwd_end + comm_sum
    ov = min(max(overlap, 0.0), 1.0)
    total = ov * total_hidden + (1.0 - ov) * total_serial + overhead
    return total, total - bwd_end, comm_sum


def mgwfbp_groups(
    sizes: Sequence[int],
    tb: Sequence[float],
    alpha: float,
    cost: CostFn,
    itemsize: int | Sequence[int] = 4,
    gamma: float = 0.0,
) -> list[list[int]]:
    """The MG-WFBP adaptive merge scan (reference semantics, arrival order).

    sizes: element counts per gradient, arrival order.
    tb: backward-compute seconds per gradient, arrival order.
    alpha: startup latency a merge saves (rule (b)).
    cost: bytes -> seconds predictor for one all-reduce.
    itemsize: bytes per element, scalar or per-layer.
    gamma: per-collective fixed overhead a merge ALSO saves — closing a
        group costs alpha (link startup) + gamma (pack/dispatch) for the
        next one, so rule (b) tolerates waits up to alpha + gamma.
    """
    L = len(sizes)
    if L == 0:
        return []
    if L != len(tb):
        raise ValueError(f"sizes ({L}) and tb ({len(tb)}) length mismatch")
    itemsizes = [itemsize] * L if isinstance(itemsize, int) else list(itemsize)
    if len(itemsizes) != L:
        raise ValueError(f"itemsize ({len(itemsizes)}) and sizes ({L}) length mismatch")
    nbytes = [int(s) * it for s, it in zip(sizes, itemsizes)]
    ready = np.cumsum(np.asarray(tb, dtype=np.float64)).tolist()

    # Mutable per-position state: mass[i] holds the byte payload accumulated at
    # scan position i (the open group's total rides along the scan, mirroring
    # the reference's p[l-1] += p[l] at :194-201).
    mass = list(nbytes)
    tc = [cost(b) for b in mass]

    def comm_start(i: int) -> float:
        # Link-busy recurrence over positions 0..i: start[j] =
        # max(start[j-1] + tc[j-1], ready[j]). Positions whose mass was merged
        # away have tc == 0 and do not occupy the link.
        start = ready[0]
        for j in range(1, i + 1):
            start = max(start + tc[j - 1], ready[j])
        return start

    groups: list[list[int]] = []
    group: list[int] = [0]
    for i in range(L - 1):
        # The open group's payload currently sits at position i.
        r_next = ready[i + 1]
        start_i = comm_start(i)
        merged = False
        if r_next < start_i + tc[i]:
            # Comm for the open group is still in flight (or hasn't begun)
            # when the next gradient arrives.
            if start_i > r_next:
                merged = True  # rule (a): no extra wait introduced
            elif r_next - start_i < alpha + gamma:
                merged = True  # rule (b): wait cheaper than another startup
        elif gamma > 0.0 and tc[i] - alpha < gamma:
            # rule (c), gamma-only: the link went idle before the next
            # arrival — the reference never merges here (an extra collective
            # costs it nothing but alpha on an idle link) — but each group
            # also costs gamma of pack/dispatch on the critical path.
            # Merging defers the open group's transmit into the next
            # collective: the combined comm runs tc[i] - alpha longer than
            # the next group's alone would, while one gamma is saved — so
            # merge exactly when that deferred transmit is cheaper than the
            # dispatch overhead. (Comparing gamma against the IDLE GAP
            # instead would cascade well-pipelined large groups into one
            # giant late collective to save slivers of gamma.)
            merged = True
        if merged:
            mass[i + 1] += mass[i]
            mass[i] = 0
            tc[i] = 0.0
            tc[i + 1] = cost(mass[i + 1])
            group.append(i + 1)
        else:
            groups.append(group)
            group = [i + 1]
    groups.append(group)
    return groups


def threshold_groups(sizes: Sequence[int], threshold: int) -> list[list[int]]:
    """Static merge policy: pack arrivals until cumulative elements reach
    ``threshold`` (reference distributed_optimizer.py:140-162).

    threshold <= 0 means no merging (pure WFBP: one group per layer);
    a huge threshold yields a single group (SyncEASGD-style).
    """
    L = len(sizes)
    if threshold <= 0:
        return [[i] for i in range(L)]
    groups: list[list[int]] = []
    group: list[int] = []
    acc = 0
    for i in range(L):
        group.append(i)
        acc += int(sizes[i])
        if acc >= threshold:
            groups.append(group)
            group = []
            acc = 0
    if group:
        groups.append(group)
    return groups


def single_group(sizes: Sequence[int]) -> list[list[int]]:
    """All gradients in one collective (threshold=inf limit)."""
    return [list(range(len(sizes)))] if len(sizes) else []


def isolate_bigs_groups(
    nbytes: Sequence[int], big_bytes: int
) -> list[list[int]]:
    """Singleton groups for layers over `big_bytes`; each contiguous run of
    smaller layers fuses into one group. Rationale: a huge tensor pays
    pack_beta * bytes to ride a fused bucket but ~nothing alone, while the
    small layers between two bigs amortize alpha+gamma best as one bucket.
    Neither the scan nor a cumulative threshold can produce this shape
    (threshold packs a big layer together with its predecessors)."""
    groups: list[list[int]] = []
    run: list[int] = []
    for i, b in enumerate(nbytes):
        if b > big_bytes:
            if run:
                groups.append(run)
                run = []
            groups.append([i])
        else:
            run.append(i)
    if run:
        groups.append(run)
    return groups


def auto_groups(
    sizes: Sequence[int],
    tb: Sequence[float],
    alpha: float,
    cost: CostFn,
    itemsize: int | Sequence[int] = 4,
    gamma: float = 0.0,
    overlap: float = 1.0,
    pack_beta: float = 0.0,
) -> tuple[list[list[int]], str]:
    """Simulate-and-argmin policy: evaluate every candidate schedule under
    the calibrated cost model (including gamma) and return the cheapest.

    The mgwfbp scan is locally greedy — it cannot reach, e.g., the
    single-group schedule when gradient gaps exceed alpha + gamma even
    though fusing everything wins globally on links where comm is cheap
    relative to compute (VERDICT r3 Weak #1: single beat mgwfbp on 2 of 3
    measured grids). `auto` closes that gap by construction: its candidate
    set contains wfbp, single, the mgwfbp scan itself, and a geometric
    threshold sweep, so its predicted time is <= every one of them.

    Returns (groups, detail) with detail naming the winning candidate.
    """
    L = len(sizes)
    if L == 0:
        return [], "empty"
    itemsizes = [itemsize] * L if isinstance(itemsize, int) else list(itemsize)
    nbytes = [int(s) * it for s, it in zip(sizes, itemsizes)]
    candidates = candidate_groupings(
        sizes, tb, alpha, cost, itemsizes, gamma=gamma, pack_beta=pack_beta
    )
    best = None
    for detail, groups in candidates:
        total, _, _ = simulate_groups(
            groups, nbytes, tb, cost, gamma, overlap, pack_beta
        )
        if best is None or total < best[0]:
            best = (total, groups, detail)
    return best[1], best[2]


def auto_groups_cross_step(
    sizes: Sequence[int],
    tb: Sequence[float],
    tf: Sequence[float],
    cost_model,
    itemsize: int | Sequence[int] = 4,
) -> tuple[list[list[int]], str]:
    """`auto_groups` for the cross-step (rs_fwd_ag) lowering: the same
    candidate set, scored by the TWO-phase simulate — the deferred
    all-gather against the forward timeline, the reduce-scatter against
    the backward — instead of the in-step backward-only recurrence. The
    candidate scan itself runs on the RS leg's cost (the link the merge
    rule reasons about at backward time)."""
    L = len(sizes)
    if L == 0:
        return [], "empty"
    itemsizes = [itemsize] * L if isinstance(itemsize, int) else list(itemsize)
    nbytes = [int(s) * it for s, it in zip(sizes, itemsizes)]
    gamma = float(getattr(cost_model, "gamma", 0.0))
    overlap = float(getattr(cost_model, "overlap", 1.0))
    pack_beta = float(getattr(cost_model, "pack_beta", 0.0))
    rs_cost, ag_cost = cross_step_phase_costs(cost_model)
    candidates = candidate_groupings(
        sizes, tb, cost_model.alpha, rs_cost, itemsizes, gamma=gamma,
        pack_beta=pack_beta,
    )
    best = None
    for detail, groups in candidates:
        total, _, _ = simulate_cross_step(
            groups, nbytes, tb, tf, rs_cost, ag_cost, gamma, overlap,
            pack_beta,
        )
        if best is None or total < best[0]:
            best = (total, groups, detail)
    return best[1], best[2]


def candidate_groupings(
    sizes: Sequence[int],
    tb: Sequence[float],
    alpha: float,
    cost: CostFn,
    itemsize: int | Sequence[int] = 4,
    gamma: float = 0.0,
    pack_beta: float = 0.0,
) -> list[tuple[str, list[list[int]]]]:
    """Enumerate the solver's candidate schedules, deduped by group shape.

    The shared candidate set behind `auto_groups` (simulate-and-argmin) and
    `schedule_frontier` (the autotuner's race roster): the per-policy picks
    (wfbp / single / the mgwfbp scan), a geometric merge-threshold sweep,
    and — when bucketization has a per-byte price — the isolate-the-bigs
    shapes. Dedup is by group SHAPE, not count: two thresholds can produce
    the same number of groups with different boundaries (e.g. sizes
    [5,5,5,5] at th=6 vs th=11), and those are distinct schedules a
    consumer must see.
    """
    L = len(sizes)
    if L == 0:
        return []
    itemsizes = [itemsize] * L if isinstance(itemsize, int) else list(itemsize)
    nbytes = [int(s) * it for s, it in zip(sizes, itemsizes)]
    candidates: list[tuple[str, list[list[int]]]] = [
        ("wfbp", threshold_groups(sizes, 0)),
        ("single", single_group(sizes)),
        ("mgwfbp", mgwfbp_groups(sizes, tb, alpha, cost, itemsizes, gamma)),
    ]
    total_elems = int(sum(sizes))
    th = 1 << 14
    seen_shapes = {tuple(map(tuple, g)) for _, g in candidates}
    while th < total_elems:
        groups = threshold_groups(sizes, th)
        key = tuple(map(tuple, groups))
        if key not in seen_shapes:
            seen_shapes.add(key)
            candidates.append((f"threshold:{th}", groups))
        th <<= 1
    if pack_beta > 0.0:
        # isolate-the-bigs shapes only pay off when bucketization has a
        # per-byte price; sweep the "big" boundary geometrically
        bb = 1 << 10
        max_b = max(nbytes)
        while bb < max_b:
            groups = isolate_bigs_groups(nbytes, bb)
            key = tuple(map(tuple, groups))
            if key not in seen_shapes:
                seen_shapes.add(key)
                candidates.append((f"isolate-bigs:{bb}", groups))
            bb <<= 1
    return candidates


def schedule_frontier(
    sizes: Sequence[int],
    tb: Sequence[float],
    alpha: float,
    cost: CostFn,
    itemsize: int | Sequence[int] = 4,
    *,
    gamma: float = 0.0,
    overlap: float = 1.0,
    pack_beta: float = 0.0,
    max_candidates: int = 6,
    cross_step: Optional[tuple[Sequence[float], CostFn, CostFn]] = None,
) -> list[tuple[str, list[list[int]], float]]:
    """The argmin's neighbourhood: candidate schedules ranked by predicted
    total step time, for the in-situ autotuner to RACE on the live job
    (`parallel.autotune`).

    Returns up to `max_candidates` (detail, groups, predicted_total_s)
    tuples, cheapest predicted first. The single-group schedule is always
    kept in the roster even when its prediction ranks it out: under a
    mis-calibrated cost model the prediction order is exactly what cannot
    be trusted, and `single` is the structural extreme the prediction most
    often mis-ranks (VERDICT r3 Weak #1: single beat mgwfbp on 2 of 3
    measured grids while the model said otherwise).

    cross_step: (tf, rs_cost, ag_cost) prices the frontier for the
    rs_fwd_ag lowering instead — candidates score under
    `simulate_cross_step`, whose totals are backward-anchored and thus
    DIRECTLY comparable with the in-step lowerings' (both exclude the
    sum(tf) compute floor every lowering pays); `cost` should then be the
    RS leg (the scan's link cost at backward time).
    """
    L = len(sizes)
    if L == 0:
        return []
    itemsizes = [itemsize] * L if isinstance(itemsize, int) else list(itemsize)
    nbytes = [int(s) * it for s, it in zip(sizes, itemsizes)]
    scored: list[tuple[str, list[list[int]], float]] = []
    for detail, groups in candidate_groupings(
        sizes, tb, alpha, cost, itemsizes, gamma=gamma, pack_beta=pack_beta
    ):
        if cross_step is not None:
            tf, rs_cost, ag_cost = cross_step
            total, _, _ = simulate_cross_step(
                groups, nbytes, tb, tf, rs_cost, ag_cost, gamma, overlap,
                pack_beta,
            )
        else:
            total, _, _ = simulate_groups(
                groups, nbytes, tb, cost, gamma, overlap, pack_beta
            )
        scored.append((detail, groups, float(total)))
    scored.sort(key=lambda c: c[2])
    out = scored[: max(max_candidates, 1)]
    if not any(len(g) == 1 and len(g[0]) == L for _, g, _ in out):
        fallback = next(
            (c for c in scored if len(c[1]) == 1 and len(c[1][0]) == L), None
        )
        if fallback is not None:
            out = out[:-1] + [fallback] if len(out) >= max_candidates else (
                out + [fallback]
            )
    return out


def size_prior_tb(
    layers: Sequence["LayerSpec"], cost_model=None
) -> list[float]:
    """Fallback tb when no measured backward profile exists: SHAPE from
    parameter volume, SCALE from the cost model — total backward time taken
    as the predicted time to all-reduce the whole model once (the regime
    where merging decisions matter; if compute is far cheaper than comm the
    solver converges to one group, if far more expensive to per-layer
    groups — both safe). Shared by `make_merged_allreduce` and the
    autotuner so the two can never disagree on the prior."""
    total_size = float(sum(l.size for l in layers)) or 1.0
    total_bytes = float(sum(l.nbytes for l in layers))
    if cost_model is not None:
        tb_total = float(cost_model.predict(total_bytes))
    else:
        tb_total = 1e-3  # last-resort scale, no information available
    return [tb_total * l.size / total_size for l in layers]


def build_schedule(
    layers: Sequence[LayerSpec],
    tb: Optional[Sequence[float]] = None,
    *,
    tf: Optional[Sequence[float]] = None,
    policy: str = "mgwfbp",
    cost_model: AlphaBeta | TwoLevelAlphaBeta | None = None,
    threshold: int = 0,
    comm_op: str = "all_reduce",
    groups: Optional[Sequence[Sequence[int]]] = None,
    dcn_groups: Optional[Sequence[Sequence[int]]] = None,
    policy_detail: Optional[str] = None,
) -> MergeSchedule:
    """Build a MergeSchedule for gradient tensors in arrival order.

    policy: 'mgwfbp' (adaptive; needs tb and cost_model), 'auto'
    (simulate-and-argmin over all candidate schedules; needs tb and
    cost_model), 'threshold', 'single', or 'wfbp' (no merging). Mirrors the
    reference's policy dispatch (distributed_optimizer.py:263-270: adaptive
    iff ADAPTIVE_MERGE and layerwise_times available, else threshold).

    comm_op: the lowering the schedule will be issued as; 'rs_opt_ag' adds
    the update-in-the-middle term to every per-bucket cost prediction
    (`effective_cost_fn`) so the schedule still describes the wire.
    'rs_fwd_ag' (cross-step) additionally needs `tf`, the arrival-ordered
    per-layer FORWARD profile (defaults to `forward_prior_tf(tb)`): its
    predictions come from `simulate_cross_step`, which prices each group's
    deferred all-gather against its first-consuming-layer deadline in the
    next step's forward. The mgwfbp scan then runs on the reduce-scatter
    leg's cost only (the backward-side link the merge rule reasons about).

    groups: an EXPLICIT grouping (arrival-order index groups) that bypasses
    the policy solve — the autotuner's raced candidates and cache hits
    enter here. Must cover every layer index exactly once; predictions are
    still simulated under the cost model so the schedule stays comparable
    to solved ones. `policy_detail` labels its provenance.

    comm_op='hier' with a two-level cost model schedules BOTH links: the
    'auto' policy argmins over the nested frontier
    (`auto_groups_two_level`), an explicit `dcn_groups` partition rides
    through (cache hits / raced candidates), and every other policy keeps
    one DCN collective per inner group; predictions come from the
    two-link simulator either way.
    """
    sizes = [l.size for l in layers]
    names = tuple(l.name for l in layers)
    nbytes = [l.nbytes for l in layers]
    cost_fn = effective_cost_fn(cost_model, comm_op) if cost_model else None
    gamma = float(getattr(cost_model, "gamma", 0.0)) if cost_model else 0.0
    overlap = (
        float(getattr(cost_model, "overlap", 1.0)) if cost_model else 1.0
    )
    pack_beta = (
        float(getattr(cost_model, "pack_beta", 0.0)) if cost_model else 0.0
    )
    cross_step = comm_op == "rs_fwd_ag"
    if cross_step and tb is not None and tf is None:
        tf = forward_prior_tf(tb)
    two_level = comm_op == "hier" and is_two_level(cost_model)
    scan_cost = cost_fn
    if cross_step and cost_model is not None:
        # the merge rule scans BACKWARD arrivals against the link — on the
        # cross-step lowering only the reduce-scatter leg occupies it there
        scan_cost, _ = cross_step_phase_costs(cost_model)

    detail = ""
    dcn_part: Optional[list[list[int]]] = (
        [list(int(i) for i in d) for d in dcn_groups]
        if dcn_groups is not None
        else None
    )
    if groups is not None:
        fixed = [list(int(i) for i in g) for g in groups]
        if sorted(i for g in fixed for i in g) != list(range(len(layers))):
            raise ValueError(
                "explicit groups must cover every layer index exactly once "
                f"(got {len(layers)} layers, groups {fixed})"
            )
        groups = fixed
        detail = policy_detail or "fixed"
    elif policy == "mgwfbp":
        if tb is None or cost_model is None:
            raise ValueError("policy 'mgwfbp' requires tb and cost_model")
        groups = mgwfbp_groups(
            sizes,
            tb,
            alpha=cost_model.alpha,
            cost=scan_cost,
            itemsize=[l.itemsize for l in layers],
            gamma=gamma,
        )
    elif policy == "auto":
        if tb is None or cost_model is None:
            raise ValueError("policy 'auto' requires tb and cost_model")
        if two_level:
            groups, dcn_part, detail = auto_groups_two_level(
                sizes, tb, cost_model,
                itemsize=[l.itemsize for l in layers],
            )
        elif cross_step:
            groups, detail = auto_groups_cross_step(
                sizes,
                tb,
                tf,
                cost_model,
                itemsize=[l.itemsize for l in layers],
            )
        else:
            groups, detail = auto_groups(
                sizes,
                tb,
                alpha=cost_model.alpha,
                cost=cost_fn,
                itemsize=[l.itemsize for l in layers],
                gamma=gamma,
                overlap=overlap,
                pack_beta=pack_beta,
            )
    elif policy == "threshold":
        groups = threshold_groups(sizes, threshold)
    elif policy == "single":
        groups = single_group(sizes)
    elif policy == "wfbp":
        groups = threshold_groups(sizes, 0)
    else:
        raise ValueError(f"unknown policy {policy!r}")

    if comm_op == "hier":
        if dcn_part is None:
            dcn_part = singleton_dcn_groups(len(groups))
        check_dcn_partition(dcn_part, len(groups))
    else:
        dcn_part = None

    if tb is not None and cost_model is not None and len(layers):
        if two_level:
            rs_c, dcn_c, ag_c = two_level_leg_costs(cost_model)
            total, nonoverlap, comm = simulate_groups_two_level(
                groups, dcn_part, nbytes, tb, rs_c, dcn_c, ag_c,
                gamma=float(getattr(cost_model.ici, "gamma", 0.0)),
                dcn_gamma=float(getattr(cost_model.dcn, "gamma", 0.0)),
                overlap=overlap, pack_beta=pack_beta,
            )
        elif cross_step:
            rs_c, ag_c = cross_step_phase_costs(cost_model)
            total, nonoverlap, comm = simulate_cross_step(
                groups, nbytes, tb, tf, rs_c, ag_c, gamma, overlap,
                pack_beta,
            )
        else:
            total, nonoverlap, comm = simulate_groups(
                groups, nbytes, tb, cost_fn, gamma, overlap, pack_beta
            )
        group_times = predict_group_times(groups, nbytes, cost_fn)
    else:
        total = nonoverlap = comm = float("nan")
        group_times = ()
    return MergeSchedule(
        groups=tuple(tuple(g) for g in groups),
        layer_names=names,
        predicted_total_time=total,
        predicted_nonoverlap_time=nonoverlap,
        predicted_comm_time=comm,
        predicted_group_times=group_times,
        policy_detail=detail,
        dcn_groups=(
            tuple(tuple(int(i) for i in d) for d in dcn_part)
            if dcn_part is not None
            else ()
        ),
    )


def check_unique(names: Sequence[str]) -> None:
    """Raise on duplicate layer names (reference utils.py:160-167, called from
    distributed_optimizer.py:204)."""
    seen: set[str] = set()
    for n in names:
        if n in seen:
            raise ValueError(f"duplicate layer name: {n!r}")
        seen.add(n)
