"""Ring attention: sequence/context parallelism over the `seq` mesh axis.

The reference framework is pure data-parallel — its only sequence machinery
is single-device BPTT and padded audio batches (SURVEY.md §5
"Long-context") — so this module is the TPU-native long-context extension
the seq axis exists for. The design is the standard ring schedule
(Liu et al., Ring Attention; blockwise online softmax):

  * the sequence dimension is sharded over SEQ_AXIS: each device holds one
    contiguous block of Q, K, V;
  * Q stays resident; K/V blocks rotate around the ring via `lax.ppermute`
    (one ICI hop per step, P-1 steps), each step accumulating its partial
    attention with numerically-stable online-softmax merging (m, l, acc);
  * compute of step i overlaps the permute bringing step i+1's K/V — the
    same latency-hiding XLA applies to the MG-WFBP buckets.

Memory per device is O(T_local^2 / P) score blocks instead of O(T^2): with
P devices the attainable context length scales linearly in P at fixed HBM.

Causal masking is by global position: device d's queries occupy positions
[d*T_local, (d+1)*T_local); after i rotations its resident K/V block
originated at ring neighbour (d - i) mod P.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from mgwfbp_tpu.parallel.mesh import SEQ_AXIS

_NEG_INF = -1e30  # finite mask value: keeps exp()-arithmetic NaN-free


def _block_attention(q, k, v, mask, scale):
    """One (Q-block x K-block) attention partial.

    q: (B, Tq, H, D), k/v: (B, Tk, H, D), mask: (Tq, Tk) bool (True = keep).
    Returns (partial_acc (B, Tq, H, D), row_max (B, H, Tq), row_sum)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)  # (B, H, Tq)
    # rows with no visible keys: keep exp at 0, not exp(-inf - -inf)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return acc, m, l


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = SEQ_AXIS,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Ring self-attention over a sequence-sharded (B, T_local, H, D) shard.

    Must run inside shard_map with `axis_name` bound; T_global = T_local * P.
    Returns the attention output shard (B, T_local, H, D).
    """
    p_size = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    t_local = q.shape[1]
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    q_pos = my * t_local + jnp.arange(t_local)  # global query positions

    perm = [(j, (j + 1) % p_size) for j in range(p_size)]

    def partial_step(i, k_cur, v_cur):
        src = (my - i) % p_size  # ring origin of the resident K/V block
        k_pos = src * t_local + jnp.arange(t_local)
        if causal:
            mask = k_pos[None, :] <= q_pos[:, None]
        else:
            mask = jnp.ones((t_local, t_local), bool)
        return _block_attention(q, k_cur, v_cur, mask, scale)

    def merge(acc, m, l, part, m_i, l_i):
        # online-softmax merge of (acc, m, l) with the new partial
        m_new = jnp.maximum(m, m_i)
        a_old = jnp.exp(m - m_new)
        a_new = jnp.exp(m_i - m_new)
        l = l * a_old + l_i * a_new
        acc = (
            acc * jnp.moveaxis(a_old, 1, -1)[..., None]
            + part * jnp.moveaxis(a_new, 1, -1)[..., None]
        )
        return acc, m_new, l

    def step(i, carry):
        acc, m, l, k_cur, v_cur = carry
        # rotate FIRST (steps 1..p-1), so exactly p-1 rotations happen and
        # the last block's K/V is never pointlessly sent around the ring
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        acc, m, l = merge(acc, m, l, *partial_step(i, k_cur, v_cur))
        return acc, m, l, k_cur, v_cur

    b, _, h, d = q.shape
    acc0 = jnp.zeros((b, t_local, h, d), jnp.float32)
    m0 = jnp.full((b, h, t_local), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t_local), jnp.float32)
    # step 0: resident K/V, no rotation
    k32, v32 = k.astype(jnp.float32), v.astype(jnp.float32)
    acc, m, l = merge(acc0, m0, l0, *partial_step(0, k32, v32))
    acc, m, l, _, _ = lax.fori_loop(
        1, p_size, step, (acc, m, l, k32, v32)
    )
    l_q = jnp.moveaxis(l, 1, -1)[..., None]  # (B, Tq, H, 1)
    out = acc / jnp.maximum(l_q, 1e-30)
    return out.astype(q.dtype)


def local_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool = True, scale: Optional[float] = None,
) -> jax.Array:
    """Single-device reference semantics of `ring_attention` (full sequence
    resident). Used by tests and as the seq=1 fast path."""
    t = q.shape[1]
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    pos = jnp.arange(t)
    mask = (
        pos[None, :] <= pos[:, None]
        if causal
        else jnp.ones((t, t), bool)
    )
    acc, m, l = _block_attention(
        q, k.astype(jnp.float32), v.astype(jnp.float32), mask, scale
    )
    l_q = jnp.moveaxis(l, 1, -1)[..., None]
    return (acc / jnp.maximum(l_q, 1e-30)).astype(q.dtype)
