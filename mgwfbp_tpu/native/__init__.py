"""Native (C++) host-side data-path kernels with lazy build + ctypes binding.

The compute path of this framework is JAX/XLA on TPU; the runtime AROUND it
— here, the loader's augmentation/normalization hot loop — is native C++
(SURVEY.md §2.9: the reference's data path rides torch DataLoader's C
workers). The extension is built on first use with the container's g++
(no pip; pybind11 unavailable by design — plain C ABI + ctypes), cached
next to the source, and every caller has a bit-identical NumPy fallback:
`available()` returning False never blocks training.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "augment.cpp")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _so_path() -> str:
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:12]
    return os.path.join(_DIR, f"libmgwfbp_native_{tag}.so")


def _build(so: str) -> bool:
    import tempfile

    # per-process temp output: concurrent first-use builds (e.g. two ranks
    # of a multi-process run on one box) must not interleave writes into a
    # shared .tmp before the atomic publish
    fd, tmp = tempfile.mkstemp(dir=_DIR, suffix=".so.tmp")
    os.close(fd)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first call; None when no
    toolchain is available (callers fall back to NumPy)."""
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        so = _so_path()
        if not os.path.exists(so) and not _build(so):
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            return None
        i64 = ctypes.c_int64
        lib.fused_crop_flip_normalize.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p,
            i64, i64, i64, i64, i64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.fused_crop_flip_normalize.restype = None
        lib.normalize_u8.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, i64, i64,
            ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.normalize_u8.restype = None
        _LIB = lib
        return _LIB


def available() -> bool:
    return get_lib() is not None


def fused_crop_flip_normalize(
    x: np.ndarray,
    oy: np.ndarray,
    ox: np.ndarray,
    flip: np.ndarray,
    mean: np.ndarray,
    std: np.ndarray,
    pad: int,
) -> Optional[np.ndarray]:
    """One-pass crop+flip+normalize of a uint8 (B,H,W,C) batch; None when
    the native library is unavailable or inputs don't qualify."""
    lib = get_lib()
    if lib is None or x.dtype != np.uint8 or x.ndim != 4 or x.shape[3] > 16:
        return None
    x = np.ascontiguousarray(x)
    b, h, w, c = x.shape
    out = np.empty((b, h, w, c), np.float32)
    oy = np.ascontiguousarray(oy, np.int64)
    ox = np.ascontiguousarray(ox, np.int64)
    fl = np.ascontiguousarray(flip, np.uint8)
    m = np.ascontiguousarray(mean, np.float32)
    s = np.ascontiguousarray(std, np.float32)
    lib.fused_crop_flip_normalize(
        x.ctypes.data, out.ctypes.data, b, h, w, c, pad,
        oy.ctypes.data, ox.ctypes.data, fl.ctypes.data,
        m.ctypes.data, s.ctypes.data,
    )
    return out


def normalize_u8(
    x: np.ndarray, mean: np.ndarray, std: np.ndarray
) -> Optional[np.ndarray]:
    """Fused uint8 -> normalized float32; None when unavailable."""
    lib = get_lib()
    if lib is None or x.dtype != np.uint8 or x.shape[-1] > 16:
        return None
    x = np.ascontiguousarray(x)
    out = np.empty(x.shape, np.float32)
    m = np.ascontiguousarray(mean, np.float32)
    s = np.ascontiguousarray(std, np.float32)
    lib.normalize_u8(
        x.ctypes.data, out.ctypes.data, x.size, x.shape[-1],
        m.ctypes.data, s.ctypes.data,
    )
    return out
