// Native data-path kernels: fused crop + flip + normalize for the host-side
// loader (mgwfbp_tpu/data). The reference leans on torchvision's C/libjpeg
// transforms inside torch DataLoader workers (SURVEY.md §2.8); this is the
// framework's own native equivalent: one pass over the uint8 batch producing
// normalized float32, instead of numpy's pad -> crop -> flip -> cast ->
// normalize chain (each a full-batch memory round trip).
//
// Randomness stays in Python (offsets/flips are drawn with the same seeded
// generator as the NumPy fallback), so both paths are bit-identical and the
// fallback is always available — no build step required to train.
//
// Build (done lazily by native/build.py):
//   g++ -O3 -shared -fPIC -o libmgwfbp_native.so augment.cpp

#include <cstdint>

extern "C" {

// x: (B, H, W, C) uint8. out: (B, H, W, C) float32.
// oy/ox: (B,) crop offsets into the zero-padded image (0..2*pad).
// flip: (B,) 0/1 horizontal flip AFTER the crop.
// mean/std: (C,) normalization in 0..1 scale: out = (x/255 - mean) / std.
void fused_crop_flip_normalize(
    const uint8_t* x, float* out,
    int64_t b, int64_t h, int64_t w, int64_t c,
    int64_t pad,
    const int64_t* oy, const int64_t* ox, const uint8_t* flip,
    const float* mean, const float* stddev) {
  // precompute per-channel affine: out = px * (1/(255*std)) - mean/std
  float scale[16];
  float shift[16];
  for (int64_t k = 0; k < c && k < 16; ++k) {
    scale[k] = 1.0f / (255.0f * stddev[k]);
    shift[k] = mean[k] / stddev[k];
  }
  for (int64_t i = 0; i < b; ++i) {
    const uint8_t* img = x + i * h * w * c;
    float* dst = out + i * h * w * c;
    const int64_t top = oy[i] - pad;   // source row of output row 0
    const int64_t left = ox[i] - pad;  // source col of output col 0
    const bool fl = flip[i] != 0;
    for (int64_t y = 0; y < h; ++y) {
      const int64_t sy = y + top;
      float* row = dst + y * w * c;
      if (sy < 0 || sy >= h) {  // fully padded row -> normalized zeros
        for (int64_t xcol = 0; xcol < w; ++xcol)
          for (int64_t k = 0; k < c; ++k) row[xcol * c + k] = -shift[k];
        continue;
      }
      const uint8_t* srow = img + sy * w * c;
      for (int64_t xcol = 0; xcol < w; ++xcol) {
        // output col xcol reads crop col (flipped or not)
        const int64_t cc = fl ? (w - 1 - xcol) : xcol;
        const int64_t sx = cc + left;
        float* px = row + xcol * c;
        if (sx < 0 || sx >= w) {
          for (int64_t k = 0; k < c; ++k) px[k] = -shift[k];
        } else {
          const uint8_t* sp = srow + sx * c;
          for (int64_t k = 0; k < c; ++k)
            px[k] = (float)sp[k] * scale[k] - shift[k];
        }
      }
    }
  }
}

// Plain fused uint8 -> normalized float32 (eval path / no augmentation).
void normalize_u8(
    const uint8_t* x, float* out, int64_t n, int64_t c,
    const float* mean, const float* stddev) {
  float scale[16];
  float shift[16];
  for (int64_t k = 0; k < c && k < 16; ++k) {
    scale[k] = 1.0f / (255.0f * stddev[k]);
    shift[k] = mean[k] / stddev[k];
  }
  for (int64_t i = 0; i < n; ++i) {
    const int64_t k = i % c;
    out[i] = (float)x[i] * scale[k] - shift[k];
  }
}

}  // extern "C"
