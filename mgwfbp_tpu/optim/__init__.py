"""Optimizers: SGD with momentum and decay/no-decay parameter groups.

Parity target: reference dl_trainer.py:216-248 — per-dataset momentum /
weight-decay constants and the bn/bias exclusion (:231-241: params with
ndim == 1, i.e. batch-norm scales/offsets and biases, get weight_decay=0).
Expressed as an optax chain so it composes with the MG-WFBP merged
all-reduce (which runs on raw grads BEFORE this transform — reductions are
about communication, the optimizer is local math).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp
import optax

from mgwfbp_tpu.optim import schedules
from mgwfbp_tpu.optim.schedules import EpochSchedule, as_step_fn, resolve

ScalarOrSchedule = Union[float, Callable[[jnp.ndarray], jnp.ndarray]]


@dataclasses.dataclass(frozen=True)
class OptimSpec:
    """Declarative description of an ELEMENTWISE optimizer chain.

    The optax transforms this repo composes (`sgd` below, optax.adam/adamw)
    are opaque closures: nothing can re-run their math on a flattened,
    1/world shard of a merge-group bucket, which is exactly what the
    rs_opt_ag lowering needs (`parallel.allreduce.ShardedOptimStep`). The
    spec is the transparent twin — `make_tx()` builds the optax chain for
    the replicated path, and the sharded path interprets the SAME fields on
    flat buffers, so the two paths cannot drift apart on hyperparameters.

    Field semantics mirror the optax transforms bit for bit:
      * kind 'sgd': optional coupled weight decay (added to the grad BEFORE
        momentum, torch semantics), optax.trace momentum, lr scaling.
      * kind 'adam': optax.scale_by_adam (b1/b2/eps, bias correction by
        count), optional DECOUPLED decay (added to the update AFTER the
        preconditioner — optax.adamw), lr scaling.
      * mask_ndim_gt1: the bn/bias decay exclusion (`decay_mask`).
      * norm_clip: optax.clip_by_global_norm threshold, ALREADY scaled by
        sqrt(1/P) when distributed (`clip_by_global_norm` below does the
        scaling; store the scaled value here).
      * lr: float or optax-style `step -> lr` schedule (`as_step_fn`).
    """

    lr: ScalarOrSchedule
    kind: str = "sgd"  # sgd | adam
    momentum: float = 0.0
    nesterov: bool = False
    weight_decay: float = 0.0
    decoupled_wd: bool = False  # adamw-style (after the preconditioner)
    mask_ndim_gt1: bool = True
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    norm_clip: Optional[float] = None

    def __post_init__(self):
        if self.kind not in ("sgd", "adam"):
            raise ValueError(f"unknown OptimSpec.kind {self.kind!r}")
        if self.kind == "sgd" and self.decoupled_wd:
            raise ValueError("decoupled weight decay requires kind='adam'")

    def learning_rate(self, count):
        """lr at optimizer step `count` (traced or concrete)."""
        return self.lr(count) if callable(self.lr) else self.lr

    def make_tx(self) -> optax.GradientTransformation:
        """The equivalent replicated optax chain (the all_reduce path's
        optimizer; also the checkpoint interchange structure both paths
        save/restore through)."""
        mask = decay_mask if self.mask_ndim_gt1 else None
        if self.kind == "sgd":
            tx = sgd(
                self.lr,
                momentum=self.momentum,
                weight_decay=self.weight_decay,
                nesterov=self.nesterov,
                mask_ndim_gt1=self.mask_ndim_gt1,
            )
        elif self.decoupled_wd or self.weight_decay:
            tx = optax.adamw(
                self.lr, b1=self.b1, b2=self.b2, eps=self.eps,
                weight_decay=self.weight_decay, mask=mask,
            )
        else:
            tx = optax.adam(self.lr, b1=self.b1, b2=self.b2, eps=self.eps)
        if self.norm_clip is not None:
            tx = optax.chain(optax.clip_by_global_norm(self.norm_clip), tx)
        return tx

    @property
    def num_slots(self) -> int:
        """Params-shaped state buffers this chain carries (momentum trace;
        Adam first/second moments) — the leaves the sharded path packs."""
        if self.kind == "adam":
            return 2
        return 1 if self.momentum else 0


def decay_mask(params: Any) -> Any:
    """True for params that SHOULD get weight decay: ndim > 1 (conv/dense
    kernels, embeddings). 1-d params (bn scale/offset, biases) are excluded
    (reference dl_trainer.py:231-241)."""
    return jax.tree_util.tree_map(lambda p: jnp.ndim(p) > 1, params)


def sgd(
    learning_rate: ScalarOrSchedule,
    momentum: float = 0.9,
    weight_decay: float = 0.0,
    nesterov: bool = False,
    mask_ndim_gt1: bool = True,
) -> optax.GradientTransformation:
    """SGD + momentum + masked (coupled) weight decay, matching
    torch.optim.SGD semantics: decay is added to the gradient before the
    momentum buffer update."""
    parts = []
    if weight_decay:
        wd = optax.add_decayed_weights(weight_decay)
        parts.append(optax.masked(wd, decay_mask) if mask_ndim_gt1 else wd)
    if momentum:
        parts.append(optax.trace(decay=momentum, nesterov=nesterov))
    parts.append(
        optax.scale_by_learning_rate(learning_rate)  # handles schedules too
    )
    return optax.chain(*parts)


def scaled_clip_threshold(max_norm: float, world_size: int = 1) -> float:
    """The distributed clip threshold: max_norm scaled by sqrt(1/P)
    (reference distributed_optimizer.py:380-387 — worker-averaged gradients
    have ~sqrt(1/P) the noise norm, so the threshold tightens to match).
    The single source of the scaling rule for both `clip_by_global_norm`
    and `make_optimizer`/OptimSpec."""
    if world_size > 1:
        return float(jnp.sqrt(1.0 / world_size)) * max_norm
    return float(max_norm)


def clip_by_global_norm(max_norm: float, world_size: int = 1):
    """Gradient clipping transform (reference clip_grad_norm_ for the RNN
    workloads, dist_trainer.py:56-60,89-94: lstm 0.25, lstman4 400).

    When distributed, the threshold is scaled by sqrt(1/P) — the reference's
    distributed clip rule (`scaled_clip_threshold`). Known delta
    (PARITY.md): the reference applies that threshold to each MERGED
    GROUP's norm separately (a per-bucket approximation of the global clip
    its single-process path uses); here the principled global-norm clip
    keeps single/multi-worker semantics identical.
    """
    return optax.clip_by_global_norm(
        scaled_clip_threshold(max_norm, world_size)
    )


def make_optimizer(
    base_lr: float,
    *,
    momentum: float = 0.9,
    weight_decay: float = 1e-4,  # reference default (dl_trainer.py:216)
    lr_schedule: str = "auto",
    dataset: str = "cifar10",
    max_epochs: int = 141,
    warmup_epochs: int = 5,
    num_batches_per_epoch: int = 1,
    norm_clip: Optional[float] = None,
    step_offset: int = 0,
    epoch_offset: float = 0.0,
    world_size: int = 1,
    return_spec: bool = False,
):
    """Build the full optimizer chain + its epoch schedule (for logging).

    step_offset/epoch_offset anchor the step->epoch conversion so an elastic
    resize continues the schedule from its current position (as_step_fn).
    world_size scales the norm-clip threshold by sqrt(1/P) (reference
    distributed clip rule, distributed_optimizer.py:380-387).

    return_spec=True appends the `OptimSpec` describing the same chain —
    the transparent form `ShardedOptimStep` re-runs on flat bucket shards
    (rs_opt_ag). Built from the same locals as the optax chain so the two
    representations cannot drift."""
    epoch_schedule = resolve(
        lr_schedule, base_lr, dataset=dataset, max_epochs=max_epochs,
        warmup_epochs=warmup_epochs,
    )
    step_fn = as_step_fn(
        epoch_schedule, num_batches_per_epoch,
        step_offset=step_offset, epoch_offset=epoch_offset,
    )
    tx = sgd(step_fn, momentum=momentum, weight_decay=weight_decay)
    scaled_clip = None
    if norm_clip is not None:
        scaled_clip = scaled_clip_threshold(norm_clip, world_size)
        tx = optax.chain(optax.clip_by_global_norm(scaled_clip), tx)
    if not return_spec:
        return tx, epoch_schedule
    spec = OptimSpec(
        lr=step_fn,
        kind="sgd",
        momentum=momentum,
        weight_decay=weight_decay,
        norm_clip=scaled_clip,
    )
    return tx, epoch_schedule, spec


__all__ = [
    "OptimSpec",
    "decay_mask",
    "sgd",
    "make_optimizer",
    "clip_by_global_norm",
    "scaled_clip_threshold",
    "schedules",
    "resolve",
    "as_step_fn",
]
