"""Optimizers: SGD with momentum and decay/no-decay parameter groups.

Parity target: reference dl_trainer.py:216-248 — per-dataset momentum /
weight-decay constants and the bn/bias exclusion (:231-241: params with
ndim == 1, i.e. batch-norm scales/offsets and biases, get weight_decay=0).
Expressed as an optax chain so it composes with the MG-WFBP merged
all-reduce (which runs on raw grads BEFORE this transform — reductions are
about communication, the optimizer is local math).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp
import optax

from mgwfbp_tpu.optim import schedules
from mgwfbp_tpu.optim.schedules import EpochSchedule, as_step_fn, resolve

ScalarOrSchedule = Union[float, Callable[[jnp.ndarray], jnp.ndarray]]


def decay_mask(params: Any) -> Any:
    """True for params that SHOULD get weight decay: ndim > 1 (conv/dense
    kernels, embeddings). 1-d params (bn scale/offset, biases) are excluded
    (reference dl_trainer.py:231-241)."""
    return jax.tree_util.tree_map(lambda p: jnp.ndim(p) > 1, params)


def sgd(
    learning_rate: ScalarOrSchedule,
    momentum: float = 0.9,
    weight_decay: float = 0.0,
    nesterov: bool = False,
) -> optax.GradientTransformation:
    """SGD + momentum + masked (coupled) weight decay, matching
    torch.optim.SGD semantics: decay is added to the gradient before the
    momentum buffer update."""
    parts = []
    if weight_decay:
        parts.append(
            optax.masked(optax.add_decayed_weights(weight_decay), decay_mask)
        )
    if momentum:
        parts.append(optax.trace(decay=momentum, nesterov=nesterov))
    parts.append(
        optax.scale_by_learning_rate(learning_rate)  # handles schedules too
    )
    return optax.chain(*parts)


def clip_by_global_norm(max_norm: float, world_size: int = 1):
    """Gradient clipping transform (reference clip_grad_norm_ for the RNN
    workloads, dist_trainer.py:56-60,89-94: lstm 0.25, lstman4 400).

    When distributed, the threshold is scaled by sqrt(1/P) — the reference's
    distributed clip rule (distributed_optimizer.py:380-387): worker-averaged
    gradients have ~sqrt(1/P) the noise norm, so the threshold tightens to
    match. Known delta (PARITY.md): the reference applies that threshold to
    each MERGED GROUP's norm separately (a per-bucket approximation of the
    global clip its single-process path uses); here the principled global-norm
    clip keeps single/multi-worker semantics identical.
    """
    if world_size > 1:
        max_norm = float(jnp.sqrt(1.0 / world_size)) * max_norm
    return optax.clip_by_global_norm(max_norm)


def make_optimizer(
    base_lr: float,
    *,
    momentum: float = 0.9,
    weight_decay: float = 1e-4,  # reference default (dl_trainer.py:216)
    lr_schedule: str = "auto",
    dataset: str = "cifar10",
    max_epochs: int = 141,
    warmup_epochs: int = 5,
    num_batches_per_epoch: int = 1,
    norm_clip: Optional[float] = None,
    step_offset: int = 0,
    epoch_offset: float = 0.0,
    world_size: int = 1,
) -> tuple[optax.GradientTransformation, EpochSchedule]:
    """Build the full optimizer chain + its epoch schedule (for logging).

    step_offset/epoch_offset anchor the step->epoch conversion so an elastic
    resize continues the schedule from its current position (as_step_fn).
    world_size scales the norm-clip threshold by sqrt(1/P) (reference
    distributed clip rule, distributed_optimizer.py:380-387)."""
    epoch_schedule = resolve(
        lr_schedule, base_lr, dataset=dataset, max_epochs=max_epochs,
        warmup_epochs=warmup_epochs,
    )
    step_fn = as_step_fn(
        epoch_schedule, num_batches_per_epoch,
        step_offset=step_offset, epoch_offset=epoch_offset,
    )
    tx = sgd(step_fn, momentum=momentum, weight_decay=weight_decay)
    if norm_clip is not None:
        tx = optax.chain(
            clip_by_global_norm(norm_clip, world_size=world_size), tx
        )
    return tx, epoch_schedule


__all__ = [
    "decay_mask",
    "sgd",
    "make_optimizer",
    "clip_by_global_norm",
    "schedules",
    "resolve",
    "as_step_fn",
]
