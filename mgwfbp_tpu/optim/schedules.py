"""Learning-rate schedule library.

Parity target: reference dl_trainer.py:578-709 — per-model LR policies keyed
by epoch: lstman4 anneal (/1.01 per epoch, :578-593), PTB staircase
(:595-610), general 5-epoch linear warmup + step decays at {81,122,155} for
CIFAR / {30,60,80} for ImageNet x0.1 (:612-644), vgg halving every 25 epochs
(:646-651), customized milestone lists (:653-681), cosine with warmup
(:683-702), and the dispatcher (:704-709).

All schedules are pure `epoch -> lr` callables (float epoch allows
intra-epoch warmup). `as_step_fn` converts to an optax-style `step -> lr`
given batches per epoch, so the whole schedule lives inside the jitted train
step as XLA arithmetic — no host round-trip per iteration.
"""

from __future__ import annotations

import math
from typing import Callable, Sequence

import jax.numpy as jnp

EpochSchedule = Callable[[jnp.ndarray], jnp.ndarray]  # float epoch -> lr

CIFAR_MILESTONES = (81, 122, 155)
IMAGENET_MILESTONES = (30, 60, 80)


def constant(lr: float) -> EpochSchedule:
    return lambda epoch: jnp.asarray(lr, jnp.float32) + 0.0 * epoch


def warmup_step(
    base_lr: float,
    milestones: Sequence[int] = CIFAR_MILESTONES,
    gamma: float = 0.1,
    warmup_epochs: int = 5,
    warmup_init_scale: float = 0.1,
) -> EpochSchedule:
    """Linear warmup then multiplicative decay at milestones (reference
    dl_trainer.py:612-644)."""

    def fn(epoch):
        epoch = jnp.asarray(epoch, jnp.float32)
        warm_frac = jnp.clip(epoch / max(warmup_epochs, 1e-8), 0.0, 1.0)
        warm = warmup_init_scale + (1.0 - warmup_init_scale) * warm_frac
        factor = jnp.ones((), jnp.float32)
        for m in milestones:
            factor = factor * jnp.where(epoch >= m, gamma, 1.0)
        if warmup_epochs <= 0:
            warm = jnp.ones((), jnp.float32)
        return base_lr * warm * factor

    return fn


def step_decay(
    base_lr: float, milestones: Sequence[int], gamma: float = 0.1
) -> EpochSchedule:
    """Customized milestone decay, no warmup (reference :653-681)."""
    return warmup_step(base_lr, milestones, gamma, warmup_epochs=0)


def vgg_halving(base_lr: float, every: int = 25) -> EpochSchedule:
    """Halve every `every` epochs (reference :646-651)."""

    def fn(epoch):
        epoch = jnp.asarray(epoch, jnp.float32)
        return base_lr * jnp.power(0.5, jnp.floor(epoch / every))

    return fn


def ptb_staircase(base_lr: float) -> EpochSchedule:
    """The reference's PTB LSTM staircase (dl_trainer.py:595-610): base LR
    until epoch 63 (`first = 23+40`), then x0.01 until 80, then x0.001.
    Note the reference's `second = 60 < first` branch is dead — there is no
    x0.1 step — and its lstm config runs 40 epochs, so within a standard run
    the LR stays at base (22) throughout; reproduced exactly."""

    def fn(epoch):
        epoch = jnp.asarray(epoch, jnp.float32)
        return base_lr * jnp.where(
            epoch < 63, 1.0, jnp.where(epoch < 80, 0.01, 0.001)
        )

    return fn


def anneal(base_lr: float, factor: float = 1.01) -> EpochSchedule:
    """Divide by `factor` each epoch (reference lstman4 anneal, :578-593)."""

    def fn(epoch):
        epoch = jnp.asarray(epoch, jnp.float32)
        return base_lr * jnp.power(1.0 / factor, jnp.floor(epoch))

    return fn


def cosine_warmup(
    base_lr: float, total_epochs: int, warmup_epochs: int = 5,
    min_lr: float = 0.0,
) -> EpochSchedule:
    """Linear warmup into a cosine decay (reference :683-702)."""

    def fn(epoch):
        epoch = jnp.asarray(epoch, jnp.float32)
        warm = jnp.clip(epoch / max(warmup_epochs, 1e-8), 0.0, 1.0)
        t = jnp.clip(
            (epoch - warmup_epochs) / max(total_epochs - warmup_epochs, 1e-8),
            0.0,
            1.0,
        )
        cos = min_lr + 0.5 * (base_lr - min_lr) * (1.0 + jnp.cos(math.pi * t))
        return jnp.where(epoch < warmup_epochs, base_lr * warm, cos)

    return fn


def resolve(
    name: str,
    base_lr: float,
    dataset: str = "cifar10",
    max_epochs: int = 141,
    warmup_epochs: int = 5,
) -> EpochSchedule:
    """Schedule dispatcher (reference :704-709 `adjust_learning_rate`)."""
    name = (name or "auto").lower()
    if name == "auto" or name == "step":
        milestones = (
            IMAGENET_MILESTONES if dataset == "imagenet" else CIFAR_MILESTONES
        )
        return warmup_step(base_lr, milestones, warmup_epochs=warmup_epochs)
    if name == "cosine":
        return cosine_warmup(base_lr, max_epochs, warmup_epochs)
    if name == "ptb":
        return ptb_staircase(base_lr)
    if name == "anneal":
        return anneal(base_lr)
    if name == "vgg":
        return vgg_halving(base_lr)
    if name == "const":
        return constant(base_lr)
    raise ValueError(f"unknown lr schedule {name!r}")


def as_step_fn(
    schedule: EpochSchedule,
    num_batches_per_epoch: int,
    step_offset: int = 0,
    epoch_offset: float = 0.0,
) -> Callable[[jnp.ndarray], jnp.ndarray]:
    """`step -> lr` for use inside the jitted train step.

    The (step_offset, epoch_offset) anchor supports elastic resizes: after a
    worker-count change alters batches-per-epoch, the epoch position must
    CONTINUE from where training stood rather than re-deriving it from the
    total carried-over step count with the new divisor (which would jump the
    schedule discontinuously)."""

    def fn(step):
        epoch = epoch_offset + (
            jnp.asarray(step, jnp.float32) - step_offset
        ) / max(num_batches_per_epoch, 1)
        return schedule(epoch)

    return fn
