"""Flash attention as a Pallas TPU kernel.

The transformer's attention (models/transformer.py) is the framework's one
O(T^2) hot op; XLA materializes the (T, T) score matrix in HBM, while this
kernel streams K/V blocks through VMEM with the standard online-softmax
recurrence — scores never leave on-chip memory, HBM traffic drops from
O(T^2) to O(T * D), and the MXU sees back-to-back (block_q x D) @
(D x block_k) matmuls.

Grid: one program per (batch*head, q-block); each program loops over K/V
blocks with running (m, l, acc) carried as values. Compute is float32
regardless of input dtype (bf16 inputs upcast per block — same policy as
parallel/ringattn.py). Causal masking is by global position, so for causal
attention blocks strictly above the diagonal are skipped entirely.

`flash_attention` is numerically equivalent to `ringattn.local_attention`
(same online-softmax math); tests pin them against each other. On CPU the
kernel runs in interpreter mode (slow but exact), so the suite exercises
the real kernel logic without a TPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def flash_supported(t: int, d: int, block_q: int = 128, block_k: int = 128) -> bool:
    """Shapes the kernel handles: sequence divisible into whole blocks and
    a head dim that fits a lane tile."""
    bq = min(block_q, t)
    bk = min(block_k, t)
    return t % bq == 0 and t % bk == 0 and d <= 256


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
    *, scale, causal, block_q, block_k,
):
    """One (bh, q-block, k-block) grid step. K is the INNERMOST grid dim so
    Pallas double-buffers the K/V block DMAs against compute; the running
    (acc, m, l) live in VMEM scratch across the k sweep of one q-block."""
    qi = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: a K block strictly above the diagonal contributes nothing
    live = (j * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale  # (bq, d)
        k_blk = k_ref[0].astype(jnp.float32)  # (bk, d)
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bq, bk)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            mask = k_pos <= q_pos
            s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_ref[:, :1]  # (bq, 1)
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)
        a_old = jnp.exp(m_prev - m_new)  # (bq, 1)
        l_new = l_prev * a_old + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bq, d)
        acc_ref[:] = acc_ref[:] * a_old + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        out = acc_ref[:] / jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "block_q", "block_k",
                              "interpret")
)
def _flash_bhtd(q, k, v, causal, scale, block_q, block_k, interpret):
    """(BH, T, D) flash attention via pallas_call."""
    bh, t, d = q.shape
    bq = min(block_q, t)
    bk = min(block_k, t)
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        grid=(bh, t // bq, t // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, qi, j: (i, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda i, qi, j: (i, j, 0)),
            pl.BlockSpec((1, bk, d), lambda i, qi, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda i, qi, j: (i, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Blockwise-softmax attention over (B, T, H, D) tensors.

    Drop-in equivalent of `ringattn.local_attention`; raises ValueError for
    unsupported shapes (callers guard with `flash_supported`). `interpret`
    defaults to True off-TPU so the kernel logic runs everywhere.

    Sharding contract: operates on LOCAL (per-device) arrays. Inside the
    framework's train step this holds by construction (the whole model runs
    under shard_map, so the kernel sees each device's shard). Do NOT call
    it under a bare `jit` with GSPMD-sharded inputs — pallas_call carries
    no partitioning rule, so XLA would gather the global batch to every
    device and replicate the compute.
    """
    b, t, h, d = q.shape
    if not flash_supported(t, d, block_q, block_k):
        raise ValueError(
            f"flash_attention: unsupported shape T={t}, D={d} for blocks "
            f"({block_q}, {block_k})"
        )
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    def to_bhtd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    out = _flash_bhtd(
        to_bhtd(q), to_bhtd(k), to_bhtd(v), causal, float(scale),
        int(block_q), int(block_k), bool(interpret),
    )
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
