"""Custom TPU kernels (Pallas) for hot ops.

The compute path of this framework is XLA-compiled JAX; Pallas kernels are
reserved for ops where manual VMEM blocking beats XLA's fusions. The first
resident: flash attention (ops/flashattn.py), used by the transformer's
attention when enabled. Every kernel has a pure-jnp reference
implementation and dispatch helpers that fall back when shapes don't
qualify or the backend lacks Mosaic support.
"""

from mgwfbp_tpu.ops.flashattn import flash_attention, flash_supported

__all__ = ["flash_attention", "flash_supported"]
