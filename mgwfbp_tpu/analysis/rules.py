"""Rule registry, findings, and suppression for the static-analysis suite.

Two rule families share this framework:
  * JIT0xx — AST lint rules for tracing-unsafe Python inside jitted/scanned
    code (`analysis.ast_lint`);
  * SCH0xx — jaxpr-level merge-schedule invariants checked against the
    lowered train step (`analysis.jaxpr_check`).

Findings print as ``file:line RULE message``. A finding on a source line
carrying ``# graft: noqa`` (all rules) or ``# graft: noqa[JIT001]`` /
``# graft: noqa[JIT001,SCH004]`` (listed rules only) is suppressed —
jaxpr-level findings have no meaningful source line and cannot be noqa'd.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Iterable, Optional, Sequence

ERROR = "error"
WARNING = "warning"


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    severity: str  # ERROR | WARNING
    summary: str


@dataclasses.dataclass(frozen=True)
class Finding:
    file: str
    line: int  # 1-based; 0 = whole-program finding (jaxpr pass)
    rule_id: str
    message: str

    @property
    def rule(self) -> Rule:
        return RULES[self.rule_id]

    @property
    def severity(self) -> str:
        return self.rule.severity

    def format(self) -> str:
        return f"{self.file}:{self.line} {self.rule_id} {self.message}"


RULES: dict[str, Rule] = {}


def _register(id: str, severity: str, summary: str) -> Rule:
    if id in RULES:
        raise ValueError(f"duplicate rule id {id!r}")
    r = Rule(id, severity, summary)
    RULES[id] = r
    return r


# --- AST lint rules (tracing-unsafe Python in jitted code) -----------------
_register("JIT000", ERROR,
          "lint target missing, unreadable, or unparseable")
_register("JIT001", ERROR,
          "wall-clock call inside traced code (runs once at trace time)")
_register("JIT002", ERROR,
          "numpy RNG inside traced code (frozen at trace time; use jax.random)")
_register("JIT003", ERROR,
          "host round-trip on a traced value (.item()/float()/int()/bool())")
_register("JIT004", WARNING,
          "Python-level branch on a traced value (use lax.cond/jnp.where)")
_register("JIT005", ERROR,
          "mutable default argument on a jitted function (shared across traces)")
_register("JIT006", ERROR,
          "telemetry/logging call inside traced code (host I/O runs once at "
          "trace time and never per step — emit spans outside jit)")

# --- jaxpr schedule-verifier rules -----------------------------------------
_register("SCH001", ERROR,
          "merged-collective count differs from MergeSchedule.num_groups")
_register("SCH002", ERROR,
          "bucket collective dtype differs from the layout's bucket dtype")
_register("SCH003", ERROR,
          "bucket layout does not cover every gradient leaf exactly once")
_register("SCH004", ERROR,
          "unexpected collective in the hot path")
_register("SCH005", ERROR,
          "host callback / debug print in the hot path")
_register("SCH006", ERROR,
          "state buffers not donated to the train step")
_register("SCH007", ERROR,
          "bucket collective payload size differs from the layout's group size")
_register("SCH008", ERROR,
          "non-finite-gradient guard presence differs from the step's "
          "configuration (is_finite check missing, or present when disabled)")
_register("SCH009", ERROR,
          "hierarchical (hier) nested-schedule contract violated: inner "
          "RS/AG leg shape, DCN-group collective count/payload/dtype, or "
          "a cross-pod collective outside its declared scope")
_register("SCH010", ERROR,
          "training-health statistics changed the step's collective "
          "footprint (the stats must ride the EXISTING metrics psum — "
          "zero new collectives or host callbacks)")


_NOQA = re.compile(r"#\s*graft:\s*noqa(?:\[(?P<ids>[A-Za-z0-9_,\s]+)\])?")


def suppressed_ids(source_line: str) -> Optional[frozenset[str]]:
    """Rule ids a ``# graft: noqa`` comment on this line suppresses.

    Returns None when the line has no noqa marker; an EMPTY frozenset means
    a bare marker (suppress every rule); otherwise the listed ids.
    """
    m = _NOQA.search(source_line)
    if m is None:
        return None
    ids = m.group("ids")
    if ids is None:
        return frozenset()
    return frozenset(s.strip() for s in ids.split(",") if s.strip())


def filter_suppressed(
    findings: Iterable[Finding], source_lines: Sequence[str]
) -> list[Finding]:
    """Drop findings whose source line carries a matching noqa marker."""
    out = []
    for f in findings:
        if 1 <= f.line <= len(source_lines):
            ids = suppressed_ids(source_lines[f.line - 1])
            if ids is not None and (not ids or f.rule_id in ids):
                continue
        out.append(f)
    return out


def has_errors(findings: Iterable[Finding]) -> bool:
    return any(f.severity == ERROR for f in findings)
