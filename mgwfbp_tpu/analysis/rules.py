"""Rule registry, findings, and suppression for the static-analysis suite.

Five rule families share this framework:
  * JIT0xx — AST lint rules for tracing-unsafe Python inside jitted/scanned
    code (`analysis.ast_lint`);
  * SCH0xx — jaxpr-level merge-schedule invariants checked against the
    lowered train step (`analysis.jaxpr_check`);
  * RUN0xx — SPMD lockstep rules for the host-side multi-host coordination
    protocol (`analysis.spmd_check`): every process must execute the
    identical group-operation sequence, statically;
  * THR0xx — host-concurrency race rules (`analysis.race_check`): shared
    state and lock discipline across the discovered thread / executor /
    HTTP-handler / observer / signal contexts;
  * ANA0xx — meta rules about the analysis annotations themselves
    (a suppression that suppresses nothing, a suppression without a
    reason).
TRC000 is the odd one out: not a protocol violation but the jaxpr pass
failing to TRACE the step at all — kept separate so CI can distinguish
"the protocol is broken" from "the model failed to build".

Findings print as ``file:line RULE message``. A finding on a source line
carrying ``# graft: noqa`` (all rules) or ``# graft: noqa[JIT001]`` /
``# graft: noqa[JIT001,SCH004]`` (listed rules only) is suppressed —
jaxpr-level findings have no meaningful source line and cannot be noqa'd.
A suppression should carry a reason: ``# graft: noqa[RUN003] -- cadence
vars are group-uniform (supervisor exports one env)``.

Exit codes are stable per family (`FAMILY_BITS` / `exit_code`): CI can
tell WHICH family failed from the code alone.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Iterable, Optional, Sequence

ERROR = "error"
WARNING = "warning"


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    severity: str  # ERROR | WARNING
    summary: str


@dataclasses.dataclass(frozen=True)
class Finding:
    file: str
    line: int  # 1-based; 0 = whole-program finding (jaxpr pass)
    rule_id: str
    message: str

    @property
    def rule(self) -> Rule:
        return RULES[self.rule_id]

    @property
    def severity(self) -> str:
        return self.rule.severity

    def format(self) -> str:
        return f"{self.file}:{self.line} {self.rule_id} {self.message}"


RULES: dict[str, Rule] = {}


def _register(id: str, severity: str, summary: str) -> Rule:
    if id in RULES:
        raise ValueError(f"duplicate rule id {id!r}")
    r = Rule(id, severity, summary)
    RULES[id] = r
    return r


# --- AST lint rules (tracing-unsafe Python in jitted code) -----------------
_register("JIT000", ERROR,
          "lint target missing, unreadable, or unparseable")
_register("JIT001", ERROR,
          "wall-clock call inside traced code (runs once at trace time)")
_register("JIT002", ERROR,
          "numpy RNG inside traced code (frozen at trace time; use jax.random)")
_register("JIT003", ERROR,
          "host round-trip on a traced value (.item()/float()/int()/bool())")
_register("JIT004", WARNING,
          "Python-level branch on a traced value (use lax.cond/jnp.where)")
_register("JIT005", ERROR,
          "mutable default argument on a jitted function (shared across traces)")
_register("JIT006", ERROR,
          "telemetry/logging call inside traced code (host I/O runs once at "
          "trace time and never per step — emit spans outside jit)")

# --- jaxpr schedule-verifier rules -----------------------------------------
_register("SCH001", ERROR,
          "merged-collective count differs from MergeSchedule.num_groups")
_register("SCH002", ERROR,
          "bucket collective dtype differs from the layout's bucket dtype")
_register("SCH003", ERROR,
          "bucket layout does not cover every gradient leaf exactly once")
_register("SCH004", ERROR,
          "unexpected collective in the hot path")
_register("SCH005", ERROR,
          "host callback / debug print in the hot path")
_register("SCH006", ERROR,
          "state buffers not donated to the train step")
_register("SCH007", ERROR,
          "bucket collective payload size differs from the layout's group size")
_register("SCH008", ERROR,
          "non-finite-gradient guard presence differs from the step's "
          "configuration (is_finite check missing, or present when disabled)")
_register("SCH009", ERROR,
          "hierarchical (hier) nested-schedule contract violated: inner "
          "RS/AG leg shape, DCN-group collective count/payload/dtype, or "
          "a cross-pod collective outside its declared scope")
_register("SCH010", ERROR,
          "training-health statistics changed the step's collective "
          "footprint (the stats must ride the EXISTING metrics psum — "
          "zero new collectives or host callbacks)")

# --- SPMD lockstep rules (host-side multi-host protocol) --------------------
_register("RUN001", ERROR,
          "group operation control-dependent on a process-local value "
          "(process identity, local RNG/clock/filesystem, a local flag) — "
          "processes take different arms and the group deadlocks")
_register("RUN002", ERROR,
          "branch arms execute different group-operation sequences under a "
          "condition not proven group-uniform (join-point sequence "
          "mismatch)")
_register("RUN003", ERROR,
          "early return/raise/continue skips a group operation another "
          "path still executes (the skipped-barrier hang)")
_register("RUN004", ERROR,
          "primary-only side effect (process-0-gated write) not followed "
          "by a commit barrier / group operation on all paths — peers can "
          "proceed before the commit is durable")
_register("RUN005", ERROR,
          "group operation inside a try whose handler swallows the "
          "exception and proceeds — one process drops out of lockstep "
          "while its peers wait")
_register("RUN006", ERROR,
          "blocking group operation reachable while holding a lock the "
          "serving plane also takes (HTTP handler <-> step-loop deadlock)")

# --- host-concurrency race rules (analysis.race_check) ----------------------
_register("THR001", ERROR,
          "shared attribute written from two or more concurrency contexts "
          "with no common lock held across the writes (torn/lost update)")
_register("THR002", ERROR,
          "lock-order inversion: two locks acquired in opposite orders by "
          "concurrent contexts (classic ABBA deadlock)")
_register("THR003", ERROR,
          "blocking operation (group op / file I/O / sleep / HTTP) while "
          "holding a lock a serving-plane handler also takes — one slow "
          "or wedged call freezes the observability plane (generalizes "
          "RUN006 beyond group ops)")
_register("THR004", ERROR,
          "signal handler doing non-async-signal-safe work (lock "
          "acquisition, blocking I/O, group op) — the handler can run "
          "while the interrupted thread holds the very lock it wants")
_register("THR005", ERROR,
          "stream written without the lock its close() holds — a "
          "daemon-thread write can race close() and land on a closed "
          "file (or be torn mid-record)")

# --- annotation meta rules --------------------------------------------------
_register("ANA001", ERROR,
          "dead or reason-less suppression: a '# graft: noqa[...]' that "
          "suppresses nothing, a '# graft: group-uniform' the checker "
          "never consulted, a '# graft: thread-safe' the race checker "
          "never consulted, or a RUN-family / value-annotation "
          "suppression without a '-- reason' string")

# --- trace failures (not a protocol violation) ------------------------------
_register("TRC000", ERROR,
          "jaxpr pass could not trace the step (model/build failure — "
          "distinct from a lint or schedule violation)")


# exit-code bits, one per family: CI distinguishes WHICH gate failed from
# the exit code alone (documented in README "Static analysis")
FAMILY_BITS = {"JIT": 1, "SCH": 2, "RUN": 4, "ANA": 8, "TRC": 16, "THR": 32}


def family(rule_id: str) -> str:
    return rule_id.rstrip("0123456789")


def exit_code(
    findings: Iterable[Finding], warnings_as_errors: bool = False
) -> int:
    """Bitwise-OR of the FAMILY_BITS of every error finding (warnings too
    under `warnings_as_errors`); 0 when nothing qualifies."""
    code = 0
    for f in findings:
        if f.severity == ERROR or warnings_as_errors:
            code |= FAMILY_BITS.get(family(f.rule_id), 1)
    return code


_NOQA = re.compile(r"#\s*graft:\s*noqa(?:\[(?P<ids>[A-Za-z0-9_,\s]+)\])?")
# value annotation: the fact on this line the analysis cannot see — the
# condition/assigned value IS group-uniform (see spmd_check). A reason
# string after ' -- ' is required for RUN-family noqa and group-uniform
# markers (ANA001 enforces it).
_GROUP_UNIFORM = re.compile(r"#\s*graft:\s*group-uniform\b")
# value annotation for the race checker: the shared state / blocking
# call on (or under the `def` carrying) this line is DELIBERATELY
# lock-free and the author accepts the interleavings — e.g. the
# watchdog's torn-read-tolerant heartbeat. Always requires a reason.
_THREAD_SAFE = re.compile(r"#\s*graft:\s*thread-safe\b")
_REASON = re.compile(
    r"#\s*graft:\s*(?:noqa(?:\[[^\]]*\])?|group-uniform|thread-safe)"
    r"\s*--\s*\S"
)


def suppressed_ids(source_line: str) -> Optional[frozenset[str]]:
    """Rule ids a ``# graft: noqa`` comment on this line suppresses.

    Returns None when the line has no noqa marker; an EMPTY frozenset means
    a bare marker (suppress every rule); otherwise the listed ids.
    """
    m = _NOQA.search(source_line)
    if m is None:
        return None
    ids = m.group("ids")
    if ids is None:
        return frozenset()
    return frozenset(s.strip() for s in ids.split(",") if s.strip())


def has_group_uniform_marker(source_line: str) -> bool:
    """True when the line carries a ``# graft: group-uniform`` value
    annotation (spmd_check treats the condition/assigned value on that
    line as group-uniform)."""
    return _GROUP_UNIFORM.search(source_line) is not None


def has_thread_safe_marker(source_line: str) -> bool:
    """True when the line carries a ``# graft: thread-safe`` annotation
    (race_check accepts the lock-free access/blocking call it marks)."""
    return _THREAD_SAFE.search(source_line) is not None


def has_reason(source_line: str) -> bool:
    """True when the line's graft marker carries a ``-- reason`` string."""
    return _REASON.search(source_line) is not None


def comment_lines(source: str) -> Optional[dict[int, str]]:
    """{lineno: comment_text} for every REAL comment token — docstrings
    quoting the annotation grammar must not register as markers. None
    when the source does not tokenize (caller falls back to line scan).
    """
    import io
    import tokenize

    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError,
            UnicodeDecodeError):
        return None
    return out


class SuppressionTracker:
    """Accounting for the annotation surface, feeding ANA001.

    The passes report every suppression they CONSUME (`note_used`) and
    every suppressed finding (kept, marked, for ``--json``); the tracker
    independently scans the analyzed files for markers, so after all
    passes ran, a marker nobody consumed is dead (`unused_findings`).
    `note_uniform_used` is the same contract for ``group-uniform`` value
    annotations (consumed by spmd_check when one actually informs a
    classification).
    """

    def __init__(self) -> None:
        # (file, line) -> frozenset of listed ids (empty = bare noqa)
        self.markers: dict[tuple[str, int], frozenset[str]] = {}
        # (file, line) of group-uniform value annotations
        self.uniform_markers: set[tuple[str, int]] = set()
        # (file, line) of thread-safe value annotations (race_check)
        self.threadsafe_markers: set[tuple[str, int]] = set()
        # (file, line) lines whose marker carries a reason string
        self._reasoned: set[tuple[str, int]] = set()
        # consumed: (file, line, rule_id) for noqa, (file, line) for uniform
        self.used: set[tuple[str, int, str]] = set()
        self.uniform_used: set[tuple[str, int]] = set()
        self.threadsafe_used: set[tuple[str, int]] = set()
        self.suppressed_findings: list[Finding] = []
        self._scanned: set[str] = set()
        # grammar -> files its consuming pass actually analyzed this run.
        # A value annotation is only provably DEAD when the pass that
        # could consume it ran over the file it sits in — an SPMD-only
        # run must not call the race checker's thread-safe pins dead
        # (and vice versa), and a paths-restricted run must not condemn
        # pins in files it never analyzed.
        self._value_pass_files: dict[str, set[str]] = {}

    def scan_source(self, path: str, source: str) -> None:
        if path in self._scanned:
            return
        self._scanned.add(path)
        comments = comment_lines(source)
        if comments is None:  # unparseable: every line is fair game
            comments = dict(enumerate(source.splitlines(), start=1))
        for i, line in comments.items():
            ids = suppressed_ids(line)
            if ids is not None:
                self.markers[(path, i)] = ids
            if has_group_uniform_marker(line):
                self.uniform_markers.add((path, i))
            if has_thread_safe_marker(line):
                self.threadsafe_markers.add((path, i))
            if has_reason(line):
                self._reasoned.add((path, i))

    def scan_lines(self, path: str, source_lines: Sequence[str]) -> None:
        self.scan_source(path, "\n".join(source_lines))

    def scan_file(self, path: str) -> None:
        try:
            with open(path, "r", encoding="utf-8") as f:
                self.scan_source(path, f.read())
        except (OSError, UnicodeDecodeError):
            pass

    def note_used(self, finding: Finding) -> None:
        self.used.add((finding.file, finding.line, finding.rule_id))
        self.suppressed_findings.append(finding)

    def note_uniform_used(self, path: str, line: int) -> None:
        self.uniform_used.add((path, line))

    def note_threadsafe_used(self, path: str, line: int) -> None:
        self.threadsafe_used.add((path, line))

    def note_value_pass(self, grammar: str, paths: Iterable[str]) -> None:
        """Record that `grammar`'s consuming pass analyzed `paths`."""
        self._value_pass_files.setdefault(grammar, set()).update(paths)

    def unused_findings(self) -> list[Finding]:
        """ANA001 findings: dead noqa ids, dead group-uniform markers, and
        RUN-family / group-uniform markers without a reason string."""
        out: list[Finding] = []
        for (path, line), ids in sorted(self.markers.items()):
            if ids:
                dead = [
                    rid for rid in sorted(ids)
                    if (path, line, rid) not in self.used
                ]
                if dead:
                    out.append(Finding(
                        path, line, "ANA001",
                        "noqa[" + ",".join(dead) + "] suppresses nothing "
                        "on this line — remove the dead suppression",
                    ))
                if any(
                    family(rid) == "RUN" for rid in ids
                ) and (path, line) not in self._reasoned:
                    out.append(Finding(
                        path, line, "ANA001",
                        "RUN-family suppression without a reason — append "
                        "'-- <why this is safe>'",
                    ))
            else:
                if not any(
                    (f, ln) == (path, line) for (f, ln, _r) in self.used
                ):
                    out.append(Finding(
                        path, line, "ANA001",
                        "bare noqa suppresses nothing on this line — "
                        "remove the dead suppression",
                    ))
        uniform_scope = self._value_pass_files.get("group-uniform", set())
        for (path, line) in sorted(self.uniform_markers):
            if path not in uniform_scope:
                continue
            if (path, line) not in self.uniform_used:
                out.append(Finding(
                    path, line, "ANA001",
                    "group-uniform annotation the checker never consulted "
                    "— remove it or move it to the condition/assignment "
                    "it describes",
                ))
            elif (path, line) not in self._reasoned:
                out.append(Finding(
                    path, line, "ANA001",
                    "group-uniform annotation without a reason — append "
                    "'-- <why this value is identical on every process>'",
                ))
        threadsafe_scope = self._value_pass_files.get("thread-safe", set())
        for (path, line) in sorted(self.threadsafe_markers):
            if path not in threadsafe_scope:
                continue
            if (path, line) not in self.threadsafe_used:
                out.append(Finding(
                    path, line, "ANA001",
                    "thread-safe annotation the race checker never "
                    "consulted — remove it or move it to the access / "
                    "blocking call (or its enclosing def) it describes",
                ))
            elif (path, line) not in self._reasoned:
                out.append(Finding(
                    path, line, "ANA001",
                    "thread-safe annotation without a reason — append "
                    "'-- <why the lock-free interleaving is acceptable>'",
                ))
        return out


def filter_suppressed(
    findings: Iterable[Finding],
    source_lines: Sequence[str],
    tracker: Optional[SuppressionTracker] = None,
) -> list[Finding]:
    """Drop findings whose source line carries a matching noqa marker;
    consumed suppressions (and the findings they hid) are recorded on
    `tracker` when given, so ANA001 can prove the rest dead."""
    out = []
    for f in findings:
        if 1 <= f.line <= len(source_lines):
            ids = suppressed_ids(source_lines[f.line - 1])
            if ids is not None and (not ids or f.rule_id in ids):
                if tracker is not None:
                    tracker.note_used(f)
                continue
        out.append(f)
    return out


def has_errors(findings: Iterable[Finding]) -> bool:
    return any(f.severity == ERROR for f in findings)
