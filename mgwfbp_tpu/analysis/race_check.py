"""Host-concurrency race checker: the THR rule family.

The host side of the runtime is deliberately multi-threaded: the HTTP
serving plane (`telemetry/serve.py`), the fleet watcher
(`telemetry/fleet.py`), the watchdog (`utils/watchdog.py`), the loader
prefetch thread (`data/loader.py`), the async checkpoint writer
(`checkpoint.py`), EventWriter observer callbacks, and SIGTERM/SIGINT
handlers all share mutable trainer/telemetry state.  This pass proves
that sharing disciplined, statically:

1. **Context discovery** — thread entry points are read off the AST:
   ``threading.Thread(target=...)``, ``ThreadPoolExecutor.submit/map``,
   ``do_*`` methods on ``BaseHTTPRequestHandler`` subclasses,
   EventWriter ``observer=`` callbacks (including ``tee_observers``
   fan-out and ``x.observer = fn`` rebinds), ``signal.signal`` handlers
   (an async-signal context, stricter than a thread), and ``_watch``
   poll loops (merged into the main context when reachable by a
   synchronous call, as the supervisor's is).

2. **Effect signatures** — reusing the SPMD checker's interprocedural
   machinery (`spmd_check.Checker` call resolution + class/attr type
   inference), each function gets, to fixpoint: the locks it is
   guaranteed to hold on entry (must-hold intersection over analyzed
   call sites), the class-qualified shared attributes it writes and the
   locks held at each write, the blocking operations it reaches
   (``@group_op`` calls, file I/O, ``sleep``, HTTP), and stream
   write/close sites.

3. **THR rules** —
   THR001  shared attribute written from >= 2 concurrency contexts with
           no common lock across the writes (torn/lost update)
   THR002  lock-order inversion across contexts (ABBA deadlock)
   THR003  blocking op while holding a lock a serving-plane handler
           also takes (generalizes RUN006 beyond group ops)
   THR004  signal handler doing non-async-signal-safe work
   THR005  stream written without the lock its close() holds

Suppression is the ``# graft: thread-safe -- reason`` marker (on the
access line, the comment line directly above, or on/above the enclosing
``def`` for a function-level pin); consumption is tracked so ANA001
flags dead or reason-less pins.  ``# graft: noqa[THR00x]`` works too,
with the same honesty accounting.

Known holes (deliberate, to keep the pass fast and the FP rate near
zero): lambdas are not treated as entry points, callbacks stored in
plain attributes (``self.on_stall``) are not traced, and the must-hold
lock intersection under-reports locks held on only *some* call paths.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterable, Optional, Sequence

from mgwfbp_tpu.analysis.rules import (
    Finding,
    SuppressionTracker,
    filter_suppressed,
    has_thread_safe_marker,
)
from mgwfbp_tpu.analysis.spmd_check import (
    _FS_WRITE_TAILS,
    _PKG_ROOT,
    TRANSPORT_PATH,
    Checker,
    FuncInfo,
    ModuleInfo,
    _dotted,
    _expand_targets,
    _is_lock_expr,
    _load_module,
    _walk_no_defs,
    discover_group_ops,
)

# the host-concurrency surfaces (package-relative)
DEFAULT_THR_TARGETS = (
    "runtime",
    "serving",
    os.path.join("train", "trainer.py"),
    "checkpoint.py",
    os.path.join("telemetry", "serve.py"),
    os.path.join("telemetry", "fleet.py"),
    os.path.join("telemetry", "events.py"),
    os.path.join("telemetry", "recorder.py"),
    os.path.join("utils", "watchdog.py"),
    os.path.join("data", "loader.py"),
)

# constructors whose instances ARE synchronization primitives: calling
# their mutator methods (Event.set, Queue.put, ...) is synchronization,
# not a racy write — direct reassignment of the attribute still is
_SYNC_CTORS = {
    "Lock", "RLock", "Condition", "Event", "Semaphore",
    "BoundedSemaphore", "Barrier", "Queue", "SimpleQueue", "LifoQueue",
    "PriorityQueue", "deque",
}
_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_THREAD_CTORS = {"Thread", "Timer"}

# method tails that mutate their receiver in place
_MUTATOR_TAILS = {
    "append", "appendleft", "extend", "add", "update", "pop", "popleft",
    "clear", "remove", "discard", "insert", "setdefault", "put",
    "put_nowait",
}
_STREAM_W_TAILS = {"write", "writelines", "flush"}
_HTTP_TAILS = {"urlopen", "getresponse", "request"}
_MULTI_INSTANCE = ("handler:", "executor:")


@dataclasses.dataclass(frozen=True)
class _Site:
    fnid: int
    path: str
    line: int
    locks: frozenset  # lexically-held lock keys at the site


@dataclasses.dataclass
class _FnEff:
    """Own (non-interprocedural) effects of one function body."""
    writes: dict = dataclasses.field(default_factory=dict)    # key->[Site]
    blocking: list = dataclasses.field(default_factory=list)  # (kind,name,Site)
    acquires: list = dataclasses.field(default_factory=list)  # (lock,Site)
    pairs: list = dataclasses.field(default_factory=list)     # (a,b,Site)
    stream_w: dict = dataclasses.field(default_factory=dict)  # key->[Site]
    stream_c: dict = dataclasses.field(default_factory=dict)  # key->[Site]
    calls: list = dataclasses.field(default_factory=list)     # (fi,locks,line)


def _modtail(mod: ModuleInfo) -> str:
    base = os.path.basename(mod.path)
    return base[:-3] if base.endswith(".py") else base


def _concurrent(a: Iterable[str], b: Iterable[str]) -> bool:
    """Can code running under context set `a` interleave with code under
    `b`?  Yes when the union spans two distinct contexts, or when they
    share a multi-instance context (several handler/executor threads run
    the same code simultaneously)."""
    sa, sb = set(a), set(b)
    if not sa or not sb:
        return False
    if len(sa | sb) > 1:
        return True
    return any(c.startswith(_MULTI_INSTANCE) for c in sa & sb)


class RaceChecker:
    """Whole-program host-concurrency analysis over `modules`."""

    def __init__(
        self,
        modules: Sequence[ModuleInfo],
        ops: dict,
        tracker: Optional[SuppressionTracker] = None,
        transport_base: str = "coordination.py",
    ):
        # the SPMD checker is the resolution substrate: class/function
        # indexes, call resolution, transport-primitive marking
        self.base = Checker(
            list(modules), ops, (), None, transport_base=transport_base
        )
        self.modules = self.base.modules
        self.tracker = tracker
        self._mod_by_path = {m.path: m for m in self.modules}
        self.fns: dict[int, FuncInfo] = {}
        self.all_funcs: list[FuncInfo] = []
        self.local_defs: dict[int, dict[str, FuncInfo]] = {}
        self.lock_attrs: set[tuple[str, str]] = set()
        self.sync_attrs: set[tuple[str, str]] = set()
        self.thread_attrs: set[tuple[str, str]] = set()
        self.eff: dict[int, _FnEff] = {}
        # (label, fi, lineno) — real concurrency contexts
        self.entries: list[tuple[str, FuncInfo, int]] = []
        # poll loops: listed as discovered, merged into main if reachable
        self.poll_entries: list[tuple[str, FuncInfo, int]] = []
        self.merged_polls: set[str] = set()
        self.ctx: dict[int, set] = {}
        self.inherited: dict[int, Optional[frozenset]] = {}
        self.findings: list[Finding] = []
        self._reported: set[tuple] = set()

    # -- model construction -------------------------------------------
    def _fill_types(self) -> None:
        """Constructor-based attribute typing (`self.x = ClassName(...)`)
        plus the sync-primitive / lock / thread attr registries."""
        for mod in self.modules:
            for fi in mod.functions.values():
                if fi.classname is None:
                    continue
                entry = self.base.class_index.get(fi.classname)
                if entry is None:
                    continue
                ci = entry[1]
                for node in _walk_no_defs(fi.node, skip_root_def=True):
                    if not (
                        isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                    ):
                        continue
                    cname = (_dotted(node.value.func) or "").rsplit(
                        ".", 1
                    )[-1]
                    for t in node.targets:
                        d = _dotted(t)
                        if not (
                            d and d.startswith("self.")
                            and d.count(".") == 1
                        ):
                            continue
                        attr = d.split(".", 1)[1]
                        if cname in self.base.class_index:
                            ci.attr_types.setdefault(attr, cname)
                        if cname in _LOCK_CTORS:
                            self.lock_attrs.add((fi.classname, attr))
                        if cname in _SYNC_CTORS:
                            self.sync_attrs.add((fi.classname, attr))
                        if cname in _THREAD_CTORS:
                            self.thread_attrs.add((fi.classname, attr))

    def _collect_funcs(self) -> None:
        roots = [
            fi for mod in self.modules for fi in mod.functions.values()
        ]
        for fi in roots:
            self._register_fn(fi)
            self._collect_nested(fi)

    def _register_fn(self, fi: FuncInfo) -> None:
        self.fns[id(fi)] = fi
        self.all_funcs.append(fi)

    def _collect_nested(self, parent: FuncInfo) -> None:
        """Nested defs (loader's `feed`/`job` pattern) get their own
        pseudo-FuncInfo so thread/executor targets resolve to them and
        their bodies are analyzed in their own context."""
        for node in self._immediate_nested(parent.node):
            fi = FuncInfo(
                f"{parent.qualname}.{node.name}", node, parent.module,
                parent.classname,
            )
            self.local_defs.setdefault(id(parent), {})[node.name] = fi
            self._register_fn(fi)
            self._collect_nested(fi)

    @staticmethod
    def _immediate_nested(root) -> list:
        out, stack = [], list(ast.iter_child_nodes(root))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(n)
            elif not isinstance(n, (ast.Lambda, ast.ClassDef)):
                stack.extend(ast.iter_child_nodes(n))
        return out

    def _discover_class_entries(self) -> None:
        for mod in self.modules:
            for cname, ci in mod.classes.items():
                bases = [
                    (_dotted(b) or "").rsplit(".", 1)[-1]
                    for b in ci.node.bases
                ]
                if any(b.endswith("RequestHandler") for b in bases):
                    # every method of a handler class runs on a serving
                    # thread — do_* are the entries, the rest helpers
                    for mname, mnode in ci.methods.items():
                        fi = mod.functions.get(f"{cname}.{mname}")
                        if fi is not None:
                            self.entries.append(
                                (f"handler:{cname}", fi, mnode.lineno)
                            )
                fi = mod.functions.get(f"{cname}._watch")
                if fi is not None:
                    self.poll_entries.append(
                        (f"poll:{cname}._watch", fi, fi.node.lineno)
                    )

    # -- lock / attr keys ---------------------------------------------
    def _shared_key(
        self, dotted: str, fi: FuncInfo, vt: dict, globals_decl: set,
        local_ctors: frozenset = frozenset(),
    ) -> Optional[str]:
        """Class-qualified key for a shared mutable target, or None for
        locals/unresolvables.  `self.X` -> `Class.X`; `self.Y.Z` and
        `var.Z` resolve the receiver class via constructor typing.
        Writes through a variable the function itself constructed
        (`out = Thing(); out.field = x`) are construction-before-
        publication — the builder pattern — and not shared."""
        parts = dotted.split(".")
        if parts[0] == "self" and fi.classname:
            if len(parts) == 2:
                return f"{fi.classname}.{parts[1]}"
            if len(parts) == 3:
                entry = self.base.class_index.get(fi.classname)
                tc = (
                    entry[1].attr_types.get(parts[1])
                    if entry else None
                )
                if tc:
                    return f"{tc}.{parts[2]}"
            return None
        if len(parts) == 2 and parts[0] in vt:
            if parts[0] in local_ctors:
                return None
            return f"{vt[parts[0]]}.{parts[1]}"
        if len(parts) == 1:
            if parts[0] in globals_decl or parts[0] in fi.module.consts:
                return f"{_modtail(fi.module)}.{parts[0]}"
        return None

    def _lock_key(
        self, node: ast.AST, fi: FuncInfo, vt: dict
    ) -> Optional[str]:
        """Class-qualified identity of a lock-like with-item (avoids
        conflating every class's `_lock` into one token)."""
        name = _dotted(node)
        if name is None and isinstance(node, ast.Call):
            name = _dotted(node.func)
        if name is None:
            return None
        parts = name.split(".")
        lockish = _is_lock_expr(node) is not None
        if parts[0] == "self" and fi.classname:
            if len(parts) == 2:
                if lockish or (fi.classname, parts[1]) in self.lock_attrs:
                    return f"{fi.classname}.{parts[1]}"
                return None
            if len(parts) == 3:
                entry = self.base.class_index.get(fi.classname)
                tc = (
                    entry[1].attr_types.get(parts[1])
                    if entry else None
                )
                if tc and (lockish or (tc, parts[2]) in self.lock_attrs):
                    return f"{tc}.{parts[2]}"
            return None
        if len(parts) == 2 and parts[0] in vt:
            if lockish or (vt[parts[0]], parts[1]) in self.lock_attrs:
                return f"{vt[parts[0]]}.{parts[1]}"
            return None
        if lockish:
            if len(parts) == 1 and parts[0] in fi.module.consts:
                return f"{_modtail(fi.module)}.{parts[0]}"
            if len(parts) >= 2:
                return ".".join(parts[-2:])
            return f"{fi.qualname}.{parts[0]}"
        return None

    def _var_types(self, fi: FuncInfo) -> tuple[dict, frozenset]:
        """Function-local `var -> ClassName` from `v = self.X`,
        `v = getattr(self, "X", ...)`, `v = ClassName(...)`, and
        `with ClassName(...) as v:` bindings.  Second return: the vars
        bound by a constructor call here (function-owned objects)."""
        vt: dict[str, str] = {}
        ctor_bound: set[str] = set()
        entry = (
            self.base.class_index.get(fi.classname)
            if fi.classname else None
        )
        attr_types = entry[1].attr_types if entry else {}

        def bind(name: str, value) -> None:
            attr = None
            if isinstance(value, ast.Attribute):
                d = _dotted(value)
                if d and d.startswith("self.") and d.count(".") == 1:
                    attr = d.split(".", 1)[1]
            elif isinstance(value, ast.Call):
                fnd = _dotted(value.func) or ""
                tail = fnd.rsplit(".", 1)[-1]
                if (
                    fnd == "getattr" and len(value.args) >= 2
                    and isinstance(value.args[0], ast.Name)
                    and value.args[0].id == "self"
                    and isinstance(value.args[1], ast.Constant)
                ):
                    attr = value.args[1].value
                elif tail in self.base.class_index:
                    vt[name] = tail
                    ctor_bound.add(name)
                    return
            if attr is not None and attr in attr_types:
                vt[name] = attr_types[attr]
                ctor_bound.discard(name)

        for node in _walk_no_defs(fi.node, skip_root_def=True):
            if (
                isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                bind(node.targets[0].id, node.value)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if isinstance(item.optional_vars, ast.Name):
                        bind(item.optional_vars.id, item.context_expr)
        return vt, frozenset(ctor_bound)

    # -- the per-function effect walk ---------------------------------
    def _walk_fn(self, fi: FuncInfo) -> None:
        eff = _FnEff()
        self.eff[id(fi)] = eff
        if fi.is_op is not None:
            return  # transport primitives are atomic leaves
        mod = fi.module
        # `__init__` bodies contribute no shared writes: construction
        # happens-before publication of the object to any other thread
        is_init = fi.node.name == "__init__"
        globals_decl = {
            n for node in _walk_no_defs(fi.node, skip_root_def=True)
            if isinstance(node, ast.Global) for n in node.names
        }
        vt, local_ctors = self._var_types(fi)
        ldefs = self.local_defs.get(id(fi), {})

        def site(line: int, held) -> _Site:
            return _Site(id(fi), mod.path, line, frozenset(held))

        def record_write(key: Optional[str], line: int, held) -> None:
            if key is None or is_init:
                return
            sites = eff.writes.setdefault(key, [])
            if len(sites) < 8:
                sites.append(site(line, held))

        def resolve_callable(e) -> Optional[FuncInfo]:
            if isinstance(e, ast.Call):
                d = (_dotted(e.func) or "").rsplit(".", 1)[-1]
                if d == "partial" and e.args:
                    return resolve_callable(e.args[0])
                return None
            d = _dotted(e)
            if d is None:
                return None
            parts = d.split(".")
            if len(parts) == 1:
                if parts[0] in ldefs:
                    return ldefs[parts[0]]
                f2 = mod.functions.get(parts[0])
                if f2 is not None:
                    return f2
                src = mod.from_imports.get(parts[0])
                if src is not None:
                    return self.base._find_module_func(src[0], src[1])
                return None
            if parts[0] == "self" and fi.classname:
                if len(parts) == 2:
                    return self.base._lookup_method(
                        fi.classname, parts[1]
                    )
                if len(parts) == 3:
                    entry = self.base.class_index.get(fi.classname)
                    tc = (
                        entry[1].attr_types.get(parts[1])
                        if entry else None
                    )
                    if tc:
                        return self.base._lookup_method(tc, parts[2])
                return None
            if len(parts) == 2:
                if parts[0] in vt:
                    return self.base._lookup_method(
                        vt[parts[0]], parts[1]
                    )
                mt = mod.module_aliases.get(parts[0])
                if mt:
                    return self.base._find_module_func(mt, parts[1])
            return None

        def reg_entry(label_kind: str, e) -> None:
            tfi = resolve_callable(e)
            if tfi is not None:
                self.entries.append((
                    f"{label_kind}:{tfi.qualname}", tfi,
                    getattr(e, "lineno", fi.node.lineno),
                ))

        def reg_observers(e) -> None:
            if isinstance(e, ast.IfExp):
                reg_observers(e.body)
                reg_observers(e.orelse)
                return
            if isinstance(e, ast.Constant):
                return
            if isinstance(e, ast.Call):
                tail = (_dotted(e.func) or "").rsplit(".", 1)[-1]
                if tail == "tee_observers":
                    for a in e.args:
                        reg_observers(a)
                return
            reg_entry("observer", e)

        def mutable_receiver_key(recv: str) -> Optional[str]:
            key = self._shared_key(
                recv, fi, vt, globals_decl, local_ctors
            )
            if key is None:
                return None
            cls, _, attr = key.rpartition(".")
            if (cls, attr) in self.sync_attrs:
                return None  # Event.set / Queue.put are synchronization
            return key

        def handle_call(n: ast.Call, held) -> None:
            fn = _dotted(n.func)
            line = n.lineno
            tail = fn.rsplit(".", 1)[-1] if fn else ""
            recv = fn[: -(len(tail) + 1)] if fn and "." in fn else ""
            # -- entry-point registrations
            if tail in _THREAD_CTORS:
                for kw in n.keywords:
                    if kw.arg == "target" or (
                        tail == "Timer" and kw.arg == "function"
                    ):
                        reg_entry("thread", kw.value)
            elif tail == "submit" and n.args:
                reg_entry("executor", n.args[0])
            elif tail == "map" and n.args and any(
                h in recv.rsplit(".", 1)[-1].lower()
                for h in ("pool", "executor", "ex")
            ):
                reg_entry("executor", n.args[0])
            elif tail == "signal" and len(n.args) >= 2 and (
                recv.split(".")[0] in ("signal",)
                or mod.module_aliases.get(recv.split(".")[0])
                == "signal"
            ):
                reg_entry("signal", n.args[1])
            elif tail == "EventWriter":
                for kw in n.keywords:
                    if kw.arg == "observer":
                        reg_observers(kw.value)
            elif tail == "tee_observers":
                reg_observers(n)
            # -- resolution: group ops and analyzed-call edges
            res = self.base.resolve_call(n, mod, fi.classname)
            if res is None and fn and "." not in fn and fn in ldefs:
                res = ("fn", ldefs[fn])
            if res is not None:
                kind, obj = res
                if kind == "op":
                    if obj.blocking:
                        eff.blocking.append(
                            ("group_op", obj.name, site(line, held))
                        )
                    return
                if obj.is_op is not None:
                    if obj.is_op.blocking:
                        eff.blocking.append((
                            "group_op", obj.is_op.name,
                            site(line, held),
                        ))
                    return
                eff.calls.append((obj, frozenset(held), line))
                return
            if not fn:
                return
            # -- in-place mutators are writes to their receiver
            if tail in _MUTATOR_TAILS and recv:
                record_write(mutable_receiver_key(recv), line, held)
            # -- blocking operations
            root = recv.split(".")[0] if recv else ""
            if tail == "sleep":
                eff.blocking.append(("sleep", fn, site(line, held)))
            elif tail in _HTTP_TAILS:
                eff.blocking.append(("http", fn, site(line, held)))
            elif fn == "open":
                eff.blocking.append(("fs", fn, site(line, held)))
            elif tail in _FS_WRITE_TAILS and (
                root in mod.module_aliases or root in ("os", "np")
            ):
                eff.blocking.append(("fs", fn, site(line, held)))
            elif tail in _STREAM_W_TAILS and recv:
                eff.blocking.append(("fs", fn, site(line, held)))
                skey = mutable_receiver_key(recv)
                if skey is not None and not is_init:
                    eff.stream_w.setdefault(skey, []).append(
                        site(line, held)
                    )
            elif tail == "close" and recv:
                skey = mutable_receiver_key(recv)
                if skey is not None and not is_init:
                    eff.stream_c.setdefault(skey, []).append(
                        site(line, held)
                    )
            elif tail == "acquire" and isinstance(
                n.func, ast.Attribute
            ):
                lk = self._lock_key(n.func.value, fi, vt)
                if lk is not None:
                    s = site(line, held)
                    eff.acquires.append((lk, s))
                    for h in held:
                        if h != lk:
                            eff.pairs.append((h, lk, s))
            elif tail in ("wait", "join") and recv:
                key = self._shared_key(recv, fi, vt, globals_decl)
                if key is not None:
                    cls, _, attr = key.rpartition(".")
                    if (cls, attr) in self.sync_attrs | self.thread_attrs:
                        eff.blocking.append(
                            ("sync-wait", fn, site(line, held))
                        )

        def scan_expr(node, held) -> None:
            stack = [node]
            while stack:
                n = stack.pop()
                if isinstance(n, ast.Call):
                    handle_call(n, held)
                for c in ast.iter_child_nodes(n):
                    if not isinstance(c, (
                        ast.FunctionDef, ast.AsyncFunctionDef,
                        ast.Lambda, ast.ClassDef,
                    )):
                        stack.append(c)

        def write_target(t, line: int, held) -> None:
            if isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    write_target(e, line, held)
            elif isinstance(t, ast.Starred):
                write_target(t.value, line, held)
            elif isinstance(t, ast.Attribute):
                d = _dotted(t)
                if d is not None:
                    record_write(
                        self._shared_key(
                            d, fi, vt, globals_decl, local_ctors
                        ),
                        line, held,
                    )
            elif isinstance(t, ast.Subscript):
                d = _dotted(t.value)
                if d is not None:
                    record_write(
                        self._shared_key(
                            d, fi, vt, globals_decl, local_ctors
                        ),
                        line, held,
                    )
            elif isinstance(t, ast.Name):
                if t.id in globals_decl:
                    record_write(
                        f"{_modtail(mod)}.{t.id}", line, held
                    )

        def visit(stmts, held) -> None:
            for stmt in stmts:
                if isinstance(stmt, (
                    ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                )):
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    newheld = list(held)
                    for item in stmt.items:
                        scan_expr(item.context_expr, tuple(newheld))
                        lk = self._lock_key(item.context_expr, fi, vt)
                        if lk is not None:
                            s = site(stmt.lineno, newheld)
                            eff.acquires.append((lk, s))
                            for h in newheld:
                                if h != lk:
                                    eff.pairs.append((h, lk, s))
                            newheld.append(lk)
                    visit(stmt.body, tuple(newheld))
                    continue
                if isinstance(stmt, ast.If):
                    scan_expr(stmt.test, held)
                    visit(stmt.body, held)
                    visit(stmt.orelse, held)
                    continue
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    scan_expr(stmt.iter, held)
                    visit(stmt.body, held)
                    visit(stmt.orelse, held)
                    continue
                if isinstance(stmt, ast.While):
                    scan_expr(stmt.test, held)
                    visit(stmt.body, held)
                    visit(stmt.orelse, held)
                    continue
                if isinstance(stmt, ast.Try):
                    visit(stmt.body, held)
                    for h in stmt.handlers:
                        visit(h.body, held)
                    visit(stmt.orelse, held)
                    visit(stmt.finalbody, held)
                    continue
                if hasattr(ast, "Match") and isinstance(
                    stmt, ast.Match
                ):
                    scan_expr(stmt.subject, held)
                    for case in stmt.cases:
                        visit(case.body, held)
                    continue
                # simple statement: calls anywhere inside, then targets
                scan_expr(stmt, held)
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        write_target(t, stmt.lineno, held)
                        if (
                            isinstance(t, ast.Attribute)
                            and t.attr == "observer"
                        ):
                            reg_observers(stmt.value)
                elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                    write_target(stmt.target, stmt.lineno, held)

        visit(fi.node.body, ())

    # -- interprocedural fixpoints ------------------------------------
    def _build_graph(self) -> None:
        edges: dict[int, list[tuple[int, frozenset]]] = {}
        callers: dict[int, set[int]] = {}
        for fi in self.all_funcs:
            for callee, held, _line in self.eff[id(fi)].calls:
                if id(callee) not in self.fns:
                    continue
                edges.setdefault(id(fi), []).append((id(callee), held))
                callers.setdefault(id(callee), set()).add(id(fi))
        entry_ids = {id(fi) for _l, fi, _ in self.entries}
        # public-API assumption: an analyzed function nobody analyzed
        # calls and that is not a thread entry runs on the main thread
        main_seeds = [
            fi for fi in self.all_funcs
            if id(fi) not in entry_ids and not callers.get(id(fi))
            and fi.is_op is None
        ]
        # main-reachability fixpoint (to merge synchronous _watch polls)
        reach_main: set[int] = {id(fi) for fi in main_seeds}
        changed = True
        while changed:
            changed = False
            for src, outs in edges.items():
                if src in reach_main:
                    for dst, _h in outs:
                        if dst not in reach_main:
                            reach_main.add(dst)
                            changed = True
        live_entries = list(self.entries)
        for label, fi, line in self.poll_entries:
            if id(fi) in reach_main or id(fi) in entry_ids:
                self.merged_polls.add(label)
            else:
                live_entries.append((label, fi, line))
        self.entries = live_entries
        # context fixpoint: labels flow down call edges
        ctx: dict[int, set] = {}
        for fi in main_seeds:
            ctx.setdefault(id(fi), set()).add("main")
        for label, fi, _line in self.entries:
            ctx.setdefault(id(fi), set()).add(label)
        changed = True
        while changed:
            changed = False
            for src, outs in edges.items():
                src_ctx = ctx.get(src)
                if not src_ctx:
                    continue
                for dst, _h in outs:
                    d = ctx.setdefault(dst, set())
                    if not src_ctx <= d:
                        d |= src_ctx
                        changed = True
        self.ctx = ctx
        # must-hold inherited locks: intersection over analyzed call
        # sites of (caller's inherited | locks lexically held at the
        # call); entries and main seeds start lock-free
        inh: dict[int, Optional[frozenset]] = {
            id(fi): None for fi in self.all_funcs
        }
        for fi in main_seeds:
            inh[id(fi)] = frozenset()
        for _label, fi, _line in self.entries:
            inh[id(fi)] = frozenset()
        for _ in range(24):
            changed = False
            for src, outs in edges.items():
                got = inh.get(src)
                if got is None:
                    continue
                for dst, held in outs:
                    cand = got | held
                    prev = inh.get(dst)
                    new = cand if prev is None else prev & cand
                    if new != prev:
                        inh[dst] = new
                        changed = True
            if not changed:
                break
        self.inherited = inh

    def _eff_locks(self, s: _Site) -> frozenset:
        return s.locks | (self.inherited.get(s.fnid) or frozenset())

    def _site_ctx(self, s: _Site) -> set:
        return self.ctx.get(s.fnid, set())

    # -- thread-safe pins ---------------------------------------------
    def _pin_line(self, mod: ModuleInfo, lineno: int) -> Optional[int]:
        own = mod.comments.get(lineno)
        if own is not None and has_thread_safe_marker(own):
            return lineno
        # a contiguous comment block directly above the line: reasons
        # long enough to be honest rarely fit one line, so the marker
        # may open a multi-line block
        ln = lineno - 1
        while (
            2 <= ln <= len(mod.lines)
            and mod.lines[ln - 1].strip().startswith("#")
        ):
            cm = mod.comments.get(ln)
            if cm is not None and has_thread_safe_marker(cm):
                return ln
            ln -= 1
        return None

    def _find_pin(
        self, sites: Iterable[_Site]
    ) -> Optional[tuple[str, int]]:
        """A `# graft: thread-safe` marker covering any of `sites`: on
        the access line, the comment line directly above it, or on/above
        the enclosing `def` (a function-level pin)."""
        for s in sites:
            mod = self._mod_by_path.get(s.path)
            if mod is None:
                continue
            fi = self.fns.get(s.fnid)
            cands = [s.line]
            if fi is not None:
                cands.append(fi.node.lineno)
            for line in cands:
                ml = self._pin_line(mod, line)
                if ml is not None:
                    return (s.path, ml)
        return None

    def _report(
        self, s: _Site, rule: str, msg: str,
        pin_sites: Iterable[_Site],
    ) -> None:
        key = (s.path, s.line, rule)
        if key in self._reported:
            return
        pin = self._find_pin(pin_sites)
        if pin is not None:
            self._reported.add(key)
            if self.tracker is not None:
                self.tracker.note_threadsafe_used(*pin)
                # retained for --json: the finding existed and a
                # documented pin hid it (same contract as noqa)
                self.tracker.suppressed_findings.append(
                    Finding(s.path, s.line, rule, msg)
                )
            return
        self._reported.add(key)
        self.findings.append(Finding(s.path, s.line, rule, msg))

    # -- rule evaluation ----------------------------------------------
    def _evaluate(self) -> None:
        self._eval_thr001()
        self._eval_thr002()
        self._eval_thr003()
        self._eval_thr004()
        self._eval_thr005()

    def _live(self, sites: Iterable[_Site]) -> list[_Site]:
        return [s for s in sites if self._site_ctx(s)]

    def _eval_thr001(self) -> None:
        agg: dict[str, list[_Site]] = {}
        for fi in self.all_funcs:
            for key, sites in self.eff[id(fi)].writes.items():
                dst = agg.setdefault(key, [])
                for s in sites:
                    if len(dst) < 24:
                        dst.append(s)
        for key in sorted(agg):
            live = self._live(agg[key])
            hit = None
            for i, a in enumerate(live):
                for b in live[i:]:
                    if not _concurrent(
                        self._site_ctx(a), self._site_ctx(b)
                    ):
                        continue
                    if self._eff_locks(a) & self._eff_locks(b):
                        continue
                    hit = (a, b)
                    break
                if hit:
                    break
            if hit is None:
                continue
            a, b = hit
            labels = sorted(self._site_ctx(a) | self._site_ctx(b))
            other = (
                f"also written at {os.path.basename(a.path)}:{a.line}"
                if a is not b else "a single site two contexts reach"
            )
            self._report(
                b, "THR001",
                f"shared state '{key}' written from concurrency "
                f"contexts {{{', '.join(labels)}}} with no common lock "
                f"({other}) — torn/lost update; add locking or pin "
                "with '# graft: thread-safe -- <reason>'",
                live,
            )

    def _eval_thr002(self) -> None:
        ordered: dict[tuple[str, str], list[_Site]] = {}
        for fi in self.all_funcs:
            e = self.eff[id(fi)]
            pairs = list(e.pairs)
            inherited = self.inherited.get(id(fi)) or frozenset()
            for lk, s in e.acquires:
                for h in inherited:
                    if h != lk and h not in s.locks:
                        pairs.append((h, lk, s))
            for a, b, s in pairs:
                dst = ordered.setdefault((a, b), [])
                if len(dst) < 4:
                    dst.append(s)
        seen: set[frozenset] = set()
        for (a, b), sites in sorted(ordered.items()):
            rev = ordered.get((b, a))
            if rev is None or frozenset((a, b)) in seen:
                continue
            hit = None
            for s1 in self._live(sites):
                for s2 in self._live(rev):
                    if _concurrent(
                        self._site_ctx(s1), self._site_ctx(s2)
                    ):
                        hit = (s1, s2)
                        break
                if hit:
                    break
            if hit is None:
                continue
            seen.add(frozenset((a, b)))
            s1, s2 = hit
            self._report(
                s1, "THR002",
                f"lock-order inversion: '{a}' then '{b}' here, but "
                f"'{b}' then '{a}' at "
                f"{os.path.basename(s2.path)}:{s2.line} — concurrent "
                "contexts can deadlock (ABBA); pick one global order",
                [s1, s2],
            )

    def _eval_thr003(self) -> None:
        handler_locks: set[str] = set()
        for fi in self.all_funcs:
            if any(
                c.startswith("handler:")
                for c in self.ctx.get(id(fi), ())
            ):
                for lk, _s in self.eff[id(fi)].acquires:
                    handler_locks.add(lk)
        if not handler_locks:
            return
        for fi in self.all_funcs:
            for kind, name, s in self.eff[id(fi)].blocking:
                if not self._site_ctx(s):
                    continue
                inter = sorted(self._eff_locks(s) & handler_locks)
                if not inter:
                    continue
                self._report(
                    s, "THR003",
                    f"blocking {kind} '{name}' while holding "
                    f"'{inter[0]}', a lock the serving-plane handlers "
                    "also take — one slow or wedged call here freezes "
                    "the observability plane; move the call outside "
                    "the lock",
                    [s],
                )

    def _eval_thr004(self) -> None:
        for fi in self.all_funcs:
            sigs = sorted(
                c for c in self.ctx.get(id(fi), ())
                if c.startswith("signal:")
            )
            if not sigs:
                continue
            e = self.eff[id(fi)]
            for lk, s in e.acquires:
                self._report(
                    s, "THR004",
                    f"signal handler ({sigs[0]}) acquires '{lk}' — the "
                    "interrupted thread may already hold it (self-"
                    "deadlock); handlers must only set flags",
                    [s],
                )
            for kind, name, s in e.blocking:
                self._report(
                    s, "THR004",
                    f"signal handler ({sigs[0]}) performs {kind} "
                    f"'{name}' — not async-signal-safe; set a flag and "
                    "let the step loop act on it",
                    [s],
                )

    def _eval_thr005(self) -> None:
        agg_w: dict[str, list[_Site]] = {}
        agg_c: dict[str, list[_Site]] = {}
        for fi in self.all_funcs:
            e = self.eff[id(fi)]
            for key, sites in e.stream_w.items():
                agg_w.setdefault(key, []).extend(sites[:8])
            for key, sites in e.stream_c.items():
                agg_c.setdefault(key, []).extend(sites[:8])
        for key in sorted(set(agg_w) & set(agg_c)):
            hit = None
            for w in self._live(agg_w[key]):
                for c in self._live(agg_c[key]):
                    if not _concurrent(
                        self._site_ctx(w), self._site_ctx(c)
                    ):
                        continue
                    if self._eff_locks(w) & self._eff_locks(c):
                        continue
                    hit = (w, c)
                    break
                if hit:
                    break
            if hit is None:
                continue
            w, c = hit
            self._report(
                w, "THR005",
                f"stream '{key}' written without the lock its close() "
                f"holds (closed at {os.path.basename(c.path)}:{c.line})"
                " — a concurrent close can land mid-record or after "
                "the file is gone; take the same lock",
                [w, c],
            )

    # -- driver --------------------------------------------------------
    def run(self) -> list[Finding]:
        self._fill_types()
        self._collect_funcs()
        self._discover_class_entries()
        for fi in self.all_funcs:
            self._walk_fn(fi)
        self._build_graph()
        self._evaluate()
        out: list[Finding] = []
        by_mod: dict[str, list[Finding]] = {}
        for f in self.findings:
            by_mod.setdefault(f.file, []).append(f)
        if self.tracker is not None:
            self.tracker.note_value_pass(
                "thread-safe", (m.path for m in self.modules),
            )
        for mod in self.modules:
            if self.tracker is not None:
                self.tracker.scan_lines(mod.path, mod.lines)
            out.extend(filter_suppressed(
                sorted(
                    by_mod.get(mod.path, []),
                    key=lambda f: (f.line, f.rule_id),
                ),
                mod.lines, self.tracker,
            ))
        return out

    def discovered_contexts(self) -> list[tuple[str, str, str, int]]:
        """(label, qualname, path, line) per discovered entry, for the
        README's threading-model table and the tests; merged `_watch`
        polls are labelled explicitly."""
        out = []
        for label, fi, line in self.entries:
            out.append((label, fi.qualname, fi.module.path, line))
        for label, fi, line in self.poll_entries:
            if label in self.merged_polls:
                out.append((
                    f"{label} (merged into main)", fi.qualname,
                    fi.module.path, line,
                ))
        return sorted(set(out))


# --- entry points ----------------------------------------------------------

def _build(
    paths: Optional[Sequence[str]],
    transport_path: Optional[str],
    tracker: Optional[SuppressionTracker],
) -> RaceChecker:
    if paths is None:
        paths = [os.path.join(_PKG_ROOT, t) for t in DEFAULT_THR_TARGETS]
    ops = discover_group_ops(transport_path)
    modules = [
        m for m in (_load_module(p) for p in _expand_targets(paths))
        if m is not None
    ]
    return RaceChecker(
        modules, ops, tracker,
        transport_base=os.path.basename(
            transport_path or TRANSPORT_PATH
        ),
    )


def check_paths(
    paths: Optional[Sequence[str]] = None,
    transport_path: Optional[str] = None,
    tracker: Optional[SuppressionTracker] = None,
) -> list[Finding]:
    """Run the THR family over the host-concurrency surfaces
    (`DEFAULT_THR_TARGETS` when `paths` is None)."""
    return _build(paths, transport_path, tracker).run()


def check_sources(
    sources: dict[str, str],
    transport_path: Optional[str] = None,
    tracker: Optional[SuppressionTracker] = None,
) -> list[Finding]:
    """Test hook: run the checker over in-memory sources ({path: src})."""
    ops = discover_group_ops(transport_path)
    modules = [ModuleInfo(p, s) for p, s in sources.items()]
    return RaceChecker(
        modules, ops, tracker,
        transport_base=os.path.basename(
            transport_path or TRANSPORT_PATH
        ),
    ).run()


def discover_contexts(
    paths: Optional[Sequence[str]] = None,
    transport_path: Optional[str] = None,
) -> list[tuple[str, str, str, int]]:
    """Discovered concurrency contexts over `paths` (defaults to the
    shipped THR surfaces)."""
    rc = _build(paths, transport_path, None)
    rc._fill_types()
    rc._collect_funcs()
    rc._discover_class_entries()
    for fi in rc.all_funcs:
        rc._walk_fn(fi)
    rc._build_graph()
    return rc.discovered_contexts()
