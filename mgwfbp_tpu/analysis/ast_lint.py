"""AST lint for tracing-unsafe Python inside jitted/scanned code.

A jitted function's Python body runs ONCE, at trace time; anything that
reads the host environment (clocks, numpy RNG) is frozen into the compiled
program, and anything that forces a traced value to a Python scalar either
fails under jit or silently de-optimizes. These bugs tend to survive
review because the first (tracing) call looks correct.

Scope model — deliberately conservative to keep false positives near zero:
a function is considered TRACED when
  * it is decorated with jit/pmap (bare, dotted, or via
    ``partial(jax.jit, ...)``), or
  * its name is passed as the first argument to a tracing combinator
    anywhere in the module (``jax.jit(f)``, ``shard_map(f, ...)``,
    ``lax.scan(f, ...)``, ``jax.grad(f)``, ``jax.vmap(f)``, ...), or
  * it is lexically nested inside a traced function.
Helpers merely CALLED from traced code are not chased (no interprocedural
taint); rule JIT003's float()/int()/bool() form only fires when the
argument is rooted at one of the traced function's own parameters, so
Python-level config scalars stay flaggable-free.
"""

from __future__ import annotations

import ast
from typing import Optional, Sequence

from mgwfbp_tpu.analysis.rules import (
    Finding,
    SuppressionTracker,
    filter_suppressed,
)

# call names (rightmost dotted segment) whose first function-valued argument
# becomes traced code
_TRACING_COMBINATORS = {
    "jit", "pmap", "vmap", "grad", "value_and_grad", "shard_map",
    "scan", "cond", "while_loop", "fori_loop", "checkpoint", "remat",
    "custom_vjp", "custom_jvp", "eval_shape", "make_jaxpr", "xmap",
}

_WALLCLOCK_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time", "time.time_ns", "time.perf_counter_ns",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow",
}

_TRACED_MODULE_ROOTS = ("jnp.", "jax.", "lax.")

# JIT006: telemetry/logging emitters — host I/O that a traced body would
# run exactly once (at trace time) instead of per step, silently dropping
# every later record; real telemetry belongs OUTSIDE the jitted step
# (telemetry/events.py). The sets are deliberately shaped like the
# project's emitters: stdlib/logging-style method calls on a logger-ish
# receiver, the ScalarWriter surface, and EventWriter.emit on a
# telemetry-ish receiver. `jax.debug.print` (a traced callback) is NOT
# matched — only the bare Python `print`.
_LOGGING_METHODS = {
    "debug", "info", "warning", "error", "critical", "exception", "log",
}
_LOGGING_ROOT_SEGMENTS = {"log", "logger", "logging"}
_TELEMETRY_METHODS = {"add_scalar", "add_scalars"}
_TELEMETRY_EMIT_SEGMENTS = {"telemetry", "tel", "writer", "events"}

# jax APIs that operate on pytree STRUCTURE, not traced values — a Python
# branch on these is static and legal (e.g. `if tree_leaves(bstats):`)
_STRUCTURAL_PREFIXES = (
    "jax.tree_util.", "jax.tree.", "jax.dtypes.", "jnp.dtype",
    "jnp.issubdtype", "jax.eval_shape", "jnp.shape", "jnp.ndim",
)


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_decorator(dec: ast.AST) -> bool:
    """@jit / @jax.jit / @partial(jax.jit, ...) / @functools.partial(jit,..)."""
    name = _dotted(dec)
    if name is not None:
        return name.rsplit(".", 1)[-1] in ("jit", "pmap")
    if isinstance(dec, ast.Call):
        fn = _dotted(dec.func)
        if fn is not None:
            tail = fn.rsplit(".", 1)[-1]
            if tail in ("jit", "pmap"):
                return True
            if tail == "partial" and dec.args:
                inner = _dotted(dec.args[0])
                if inner is not None and inner.rsplit(".", 1)[-1] in (
                    "jit", "pmap"
                ):
                    return True
    return False


class _TracedNameCollector(ast.NodeVisitor):
    """Names passed by reference into tracing combinators, module-wide."""

    def __init__(self) -> None:
        self.names: set[str] = set()

    def visit_Call(self, node: ast.Call) -> None:
        fn = _dotted(node.func)
        if fn is not None and fn.rsplit(".", 1)[-1] in _TRACING_COMBINATORS:
            for arg in node.args[:1]:  # the function operand is leading
                name = _dotted(arg)
                if name is not None and "." not in name:
                    self.names.add(name)
        self.generic_visit(node)


def _param_names(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> set[str]:
    a = fn.args
    params = [*a.posonlyargs, *a.args, *a.kwonlyargs]
    if a.vararg:
        params.append(a.vararg)
    if a.kwarg:
        params.append(a.kwarg)
    return {p.arg for p in params}


def _static_param_names(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> set[str]:
    """Params declared static via static_argnums/static_argnames on a jit
    decorator — these are concrete Python values, so host conversions of
    them (int()/float()/bool()) are legal and must not trip JIT003."""
    positional = [*fn.args.posonlyargs, *fn.args.args]
    static: set[str] = set()

    def const_values(node: ast.AST) -> list:
        if isinstance(node, ast.Constant):
            return [node.value]
        if isinstance(node, (ast.Tuple, ast.List)):
            return [e.value for e in node.elts if isinstance(e, ast.Constant)]
        return []

    for dec in fn.decorator_list:
        if not (isinstance(dec, ast.Call) and _is_jit_decorator(dec)):
            continue
        for kw in dec.keywords:
            if kw.arg == "static_argnums":
                for v in const_values(kw.value):
                    if isinstance(v, int) and 0 <= v < len(positional):
                        static.add(positional[v].arg)
            elif kw.arg == "static_argnames":
                for v in const_values(kw.value):
                    if isinstance(v, str):
                        static.add(v)
    return static


def _rooted_at(node: ast.AST, names: set[str]) -> bool:
    """Expression is a Name/Attribute/Subscript chain rooted at `names`."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return isinstance(node, ast.Name) and node.id in names


def _contains_traced_call(node: ast.AST) -> bool:
    """Subtree contains a call into jnp./jax./lax. — a traced producer."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            fn = _dotted(sub.func)
            if (
                fn is not None
                and fn.startswith(_TRACED_MODULE_ROOTS)
                and not fn.startswith(_STRUCTURAL_PREFIXES)
            ):
                return True
    return False


class _TracedBodyChecker(ast.NodeVisitor):
    """Rule checks inside one traced function body (without nested defs —
    those are visited as traced functions in their own right)."""

    def __init__(self, path: str, fn: ast.FunctionDef, findings: list):
        self.path = path
        self.fn = fn
        self.params = _param_names(fn) - _static_param_names(fn)
        self.findings = findings

    def _add(self, node: ast.AST, rule_id: str, msg: str) -> None:
        self.findings.append(
            Finding(self.path, getattr(node, "lineno", 0), rule_id, msg)
        )

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node is not self.fn:
            return  # nested def: checked separately with its own params
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def _is_telemetry_call(self, fn: str) -> bool:
        parts = fn.split(".")
        tail = parts[-1]
        receiver = parts[:-1]
        if fn == "print":
            return True
        if tail in _LOGGING_METHODS and any(
            p in _LOGGING_ROOT_SEGMENTS for p in receiver
        ):
            return True
        if tail in _TELEMETRY_METHODS:
            return True
        if tail == "emit" and any(
            p in _TELEMETRY_EMIT_SEGMENTS for p in receiver
        ):
            return True
        return False

    def visit_Call(self, node: ast.Call) -> None:
        fn = _dotted(node.func)
        if fn is not None:
            tail2 = ".".join(fn.split(".")[-2:])
            if fn in _WALLCLOCK_CALLS or tail2 in _WALLCLOCK_CALLS:
                self._add(node, "JIT001",
                          f"'{fn}()' inside traced '{self.fn.name}'")
            elif fn.startswith(("np.random.", "numpy.random.")):
                self._add(node, "JIT002",
                          f"'{fn}()' inside traced '{self.fn.name}'")
            elif self._is_telemetry_call(fn):
                self._add(
                    node, "JIT006",
                    f"'{fn}()' inside traced '{self.fn.name}' — host I/O "
                    "runs once at trace time, not per step; emit telemetry "
                    "outside jit",
                )
            elif fn in ("float", "int", "bool") and node.args:
                if _rooted_at(node.args[0], self.params) or (
                    _contains_traced_call(node.args[0])
                ):
                    self._add(
                        node, "JIT003",
                        f"'{fn}()' forces a traced value to host in "
                        f"'{self.fn.name}'",
                    )
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item":
            self._add(node, "JIT003",
                      f"'.item()' forces a traced value to host in "
                      f"'{self.fn.name}'")
        self.generic_visit(node)

    def _check_branch(self, node: ast.If | ast.While, kind: str) -> None:
        if _contains_traced_call(node.test):
            self._add(
                node, "JIT004",
                f"Python '{kind}' on a traced expression in "
                f"'{self.fn.name}' — the branch is frozen at trace time",
            )

    def visit_If(self, node: ast.If) -> None:
        self._check_branch(node, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_branch(node, "while")
        self.generic_visit(node)


def _mutable_default_findings(
    path: str, fn: ast.FunctionDef, findings: list
) -> None:
    for default in [*fn.args.defaults, *fn.args.kw_defaults]:
        if default is None:
            continue
        mutable = isinstance(default, (ast.List, ast.Dict, ast.Set))
        if isinstance(default, ast.Call):
            callee = _dotted(default.func)
            mutable = callee in ("list", "dict", "set")
        if mutable:
            findings.append(Finding(
                path, default.lineno, "JIT005",
                f"mutable default argument on jitted '{fn.name}'",
            ))


def lint_source(
    source: str, path: str = "<string>",
    tracker: Optional[SuppressionTracker] = None,
) -> list:
    """Lint one module's source; returns noqa-filtered findings.
    Consumed suppressions land on `tracker` (ANA001 accounting)."""
    if tracker is not None:
        tracker.scan_lines(path, source.splitlines())
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, "JIT000",
                        f"unparseable module: {e.msg}")]
    collector = _TracedNameCollector()
    collector.visit(tree)
    traced_names = collector.names

    findings: list = []

    def visit_functions(node: ast.AST, inside_traced: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                decorated = any(
                    _is_jit_decorator(d) for d in child.decorator_list
                )
                traced = (
                    inside_traced
                    or decorated
                    or child.name in traced_names
                )
                if traced:
                    _TracedBodyChecker(path, child, findings).visit(child)
                    if decorated:
                        _mutable_default_findings(path, child, findings)
                visit_functions(child, traced)
            else:
                visit_functions(child, inside_traced)

    visit_functions(tree, False)
    return filter_suppressed(findings, source.splitlines(), tracker)


def lint_file(
    path: str, tracker: Optional[SuppressionTracker] = None
) -> list:
    try:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
    except OSError as e:
        return [Finding(path, 0, "JIT000", f"cannot read lint target: {e}")]
    except UnicodeDecodeError as e:
        return [Finding(path, 0, "JIT000", f"cannot decode lint target: {e}")]
    return lint_source(source, path, tracker)


def lint_paths(
    paths: Sequence[str], tracker: Optional[SuppressionTracker] = None
) -> list:
    """Lint .py files (recursing into directories), sorted findings.

    A target that is neither a directory nor an existing .py file yields a
    JIT000 error finding rather than being dropped — a mistyped path must
    not turn the CI gate green by linting nothing.
    """
    import os

    files: list[str] = []
    findings: list = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                files.extend(
                    os.path.join(root, n) for n in names if n.endswith(".py")
                )
        elif p.endswith(".py") and os.path.isfile(p):
            files.append(p)
        else:
            findings.append(Finding(
                p, 0, "JIT000",
                "lint target is not a directory or existing .py file",
            ))
    for f in sorted(files):
        findings.extend(lint_file(f, tracker))
    return findings
