"""CLI: run both analysis passes and exit non-zero on errors.

    python -m mgwfbp_tpu.analysis                 # lint package + verify step
    python -m mgwfbp_tpu.analysis --skip-jaxpr    # AST lint only (fast)
    python -m mgwfbp_tpu.analysis path/to/file.py # lint specific targets

The jaxpr pass traces the jitted MG-WFBP train step on an 8-device virtual
CPU mesh — pure tracing, no computation, no accelerator needed — once per
merge policy, so the schedule-realization invariants are checked across the
whole policy surface (wfbp / single / mgwfbp), not just the default.
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mgwfbp_tpu.analysis",
        description="MG-WFBP static analysis: jit-safety lint + "
        "jaxpr merge-schedule verification",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: the mgwfbp_tpu package)",
    )
    parser.add_argument("--skip-lint", action="store_true",
                        help="skip the AST lint pass")
    parser.add_argument("--skip-jaxpr", action="store_true",
                        help="skip the jaxpr schedule-verification pass")
    parser.add_argument("--model", default="lenet",
                        help="model to trace in the jaxpr pass")
    parser.add_argument(
        "--policies", default="wfbp,single,mgwfbp",
        help="comma-separated merge policies to verify (jaxpr pass)",
    )
    parser.add_argument(
        "--comm-ops", dest="comm_ops",
        default="all_reduce,rs_opt_ag,rs_fwd_ag,hier",
        help="comma-separated bucket lowerings to verify; each policy is "
        "traced under each (rs_opt_ag/rs_fwd_ag are verified with "
        "global-norm clipping on, so the cross-group clip psum is covered "
        "too; rs_fwd_ag traces TWO consecutive steps — the cross-step "
        "contract: each group's reduce-scatter in step N, its all-gather "
        "in step N+1's forward; hier traces on an (ici, dcn) virtual mesh "
        "under a slow-DCN two-level cost model — the SCH009 nested "
        "contract: per-group inner RS/AG plus one outer collective per "
        "DCN group, no stray cross-pod collectives)",
    )
    parser.add_argument("--warnings-as-errors", action="store_true",
                        help="exit non-zero on warnings too")
    args = parser.parse_args(argv)

    from mgwfbp_tpu.analysis.rules import ERROR, WARNING

    findings = []
    if not args.skip_lint:
        from mgwfbp_tpu.analysis.ast_lint import lint_paths

        targets = args.paths or [os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))]
        findings.extend(lint_paths(targets))

    if not args.skip_jaxpr:
        from mgwfbp_tpu.analysis.jaxpr_check import verify_train_step

        ops = [c.strip() for c in args.comm_ops.split(",") if c.strip()]
        for policy in [p.strip() for p in args.policies.split(",") if p.strip()]:
            for comm_op in ops:
                findings.extend(verify_train_step(
                    args.model, policy, comm_op=comm_op,
                    # clipping on the sharded paths also verifies the
                    # declared clip-psum scope stays the only extra
                    # collective
                    norm_clip=(
                        1.0 if comm_op in ("rs_opt_ag", "rs_fwd_ag")
                        else None
                    ),
                ))
        # one guard-off trace pins SCH008's other direction: disabling the
        # non-finite guard must actually remove the finite_check eqns
        findings.extend(verify_train_step(
            args.model, "wfbp", grad_guard=False,
        ))
        # SCH010: the training-health statistics (ISSUE 12) must not
        # change the step's collective footprint — stats-on and stats-off
        # traces compared on the flat and the sharded-optimizer lowerings
        # (the two distinct collective shapes)
        from mgwfbp_tpu.analysis.jaxpr_check import (
            verify_health_stats_footprint,
        )

        for comm_op in ("all_reduce", "rs_opt_ag"):
            findings.extend(verify_health_stats_footprint(
                args.model, "mgwfbp", comm_op=comm_op,
            ))

    errors = sum(1 for f in findings if f.severity == ERROR)
    warnings = sum(1 for f in findings if f.severity == WARNING)
    for f in findings:
        print(f.format())
    print(
        f"mgwfbp_tpu.analysis: {errors} error(s), {warnings} warning(s)",
        file=sys.stderr,
    )
    if errors or (warnings and args.warnings_as_errors):
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
