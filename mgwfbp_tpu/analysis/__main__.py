"""CLI: run the analysis passes and exit with a family-coded status.

    python -m mgwfbp_tpu.analysis                 # lint + spmd + jaxpr
    python -m mgwfbp_tpu.analysis --skip-jaxpr    # fast passes only
    python -m mgwfbp_tpu.analysis --json          # machine-readable output
    python -m mgwfbp_tpu.analysis path/to/file.py # lint specific targets

Pass order is cheapest-first so protocol bugs fail in seconds: the AST
jit-safety lint, then the host-concurrency race checker (THR001..THR005
over the thread/handler/observer/signal surfaces — runtime/,
train/trainer.py, checkpoint.py, telemetry/{serve,fleet,events,
recorder}.py, utils/watchdog.py, data/loader.py), then the SPMD
lockstep checker (RUN001..RUN006 over the multi-host protocol surfaces
— runtime/, train/trainer.py, checkpoint.py, parallel/autotune.py,
telemetry/drift.py), then ANA001 (dead-suppression accounting over
everything the earlier passes saw), then the jaxpr pass, which traces the jitted MG-WFBP train step on an
8-device virtual CPU mesh — pure tracing, no computation, no
accelerator needed — once per merge policy, so the schedule-realization
invariants are checked across the whole policy surface (wfbp / single /
mgwfbp), not just the default.

Exit codes are stable per rule family (CI can tell WHICH gate failed):
bit 1 = JIT lint errors, bit 2 = SCH schedule-verifier errors, bit 4 =
RUN lockstep errors, bit 8 = ANA annotation errors, bit 16 = the jaxpr
pass failed to TRACE (TRC000 — a model/build failure, not a protocol
violation), bit 32 = THR host-concurrency race errors. 0 = clean.

``--json`` prints one JSON document on stdout: every finding (including
suppressed ones, marked) with rule id, severity, file, line, message,
and suppression state, plus the per-family error counts and the exit
code the process will return.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mgwfbp_tpu.analysis",
        description="MG-WFBP static analysis: jit-safety lint + SPMD "
        "lockstep checker + jaxpr merge-schedule verification",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: the mgwfbp_tpu package)",
    )
    parser.add_argument("--skip-lint", action="store_true",
                        help="skip the AST lint pass")
    parser.add_argument("--skip-spmd", action="store_true",
                        help="skip the SPMD lockstep pass (RUN rules)")
    parser.add_argument("--skip-thr", action="store_true",
                        help="skip the host-concurrency race pass "
                        "(THR rules)")
    parser.add_argument("--skip-jaxpr", action="store_true",
                        help="skip the jaxpr schedule-verification pass")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable findings on stdout "
                        "(suppressed findings included, marked)")
    parser.add_argument("--model", default="lenet",
                        help="model to trace in the jaxpr pass")
    parser.add_argument(
        "--policies", default="wfbp,single,mgwfbp",
        help="comma-separated merge policies to verify (jaxpr pass)",
    )
    parser.add_argument(
        "--comm-ops", dest="comm_ops",
        default="all_reduce,rs_opt_ag,rs_fwd_ag,hier",
        help="comma-separated bucket lowerings to verify; each policy is "
        "traced under each (rs_opt_ag/rs_fwd_ag are verified with "
        "global-norm clipping on, so the cross-group clip psum is covered "
        "too; rs_fwd_ag traces TWO consecutive steps — the cross-step "
        "contract: each group's reduce-scatter in step N, its all-gather "
        "in step N+1's forward; hier traces on an (ici, dcn) virtual mesh "
        "under a slow-DCN two-level cost model — the SCH009 nested "
        "contract: per-group inner RS/AG plus one outer collective per "
        "DCN group, no stray cross-pod collectives)",
    )
    parser.add_argument("--warnings-as-errors", action="store_true",
                        help="exit non-zero on warnings too")
    args = parser.parse_args(argv)

    from mgwfbp_tpu.analysis.rules import (
        ERROR,
        WARNING,
        SuppressionTracker,
        exit_code,
        family,
    )

    tracker = SuppressionTracker()
    findings = []

    if not args.skip_lint:
        from mgwfbp_tpu.analysis.ast_lint import lint_paths

        targets = args.paths or [os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))]
        findings.extend(lint_paths(targets, tracker))

    if not args.skip_thr:
        from mgwfbp_tpu.analysis.race_check import (
            check_paths as thr_check_paths,
        )

        # explicit paths narrow the race pass too (like the lint), so a
        # seeded single-file probe exercises THR alone in milliseconds
        findings.extend(thr_check_paths(
            paths=args.paths or None, tracker=tracker,
        ))

    if not args.skip_spmd:
        from mgwfbp_tpu.analysis.spmd_check import check_paths

        findings.extend(check_paths(tracker=tracker))

    # ANA001 runs only when EVERY consuming pass ran: lint consumes JIT
    # noqas, the race pass THR noqas + thread-safe pins, spmd RUN noqas
    # + group-uniform markers — skipping any would misreport that pass's
    # live markers as dead
    if not args.skip_lint and not args.skip_spmd and not args.skip_thr:
        findings.extend(tracker.unused_findings())

    if not args.skip_jaxpr:
        from mgwfbp_tpu.analysis.rules import Finding

        def _trace(fn, *fargs, **fkw):
            """One traced verification; a failure to trace is TRC000 —
            CI must distinguish 'the model failed to build' from 'the
            protocol/schedule is violated'."""
            try:
                return fn(*fargs, **fkw)
            except Exception as e:  # noqa: BLE001 — uniform surface
                return [Finding(
                    "<jaxpr>", 0, "TRC000",
                    f"{getattr(fn, '__name__', 'trace')}"
                    f"{fargs!r} failed to trace: {type(e).__name__}: {e}",
                )]

        from mgwfbp_tpu.analysis.jaxpr_check import (
            verify_health_stats_footprint,
            verify_train_step,
        )

        ops = [c.strip() for c in args.comm_ops.split(",") if c.strip()]
        for policy in [p.strip() for p in args.policies.split(",") if p.strip()]:
            for comm_op in ops:
                findings.extend(_trace(
                    verify_train_step,
                    args.model, policy, comm_op=comm_op,
                    # clipping on the sharded paths also verifies the
                    # declared clip-psum scope stays the only extra
                    # collective
                    norm_clip=(
                        1.0 if comm_op in ("rs_opt_ag", "rs_fwd_ag")
                        else None
                    ),
                ))
        # one guard-off trace pins SCH008's other direction: disabling the
        # non-finite guard must actually remove the finite_check eqns
        findings.extend(_trace(
            verify_train_step, args.model, "wfbp", grad_guard=False,
        ))
        # SCH010: the training-health statistics (ISSUE 12) must not
        # change the step's collective footprint — stats-on and stats-off
        # traces compared on the flat and the sharded-optimizer lowerings
        # (the two distinct collective shapes)
        for comm_op in ("all_reduce", "rs_opt_ag"):
            findings.extend(_trace(
                verify_health_stats_footprint,
                args.model, "mgwfbp", comm_op=comm_op,
            ))

    errors = sum(1 for f in findings if f.severity == ERROR)
    warnings = sum(1 for f in findings if f.severity == WARNING)
    rc = exit_code(findings, args.warnings_as_errors)

    if args.as_json:
        def doc(f, suppressed):
            return {
                "rule": f.rule_id,
                "family": family(f.rule_id),
                "severity": f.severity,
                "file": f.file,
                "line": f.line,
                "message": f.message,
                "suppressed": suppressed,
            }

        by_family: dict[str, int] = {}
        for f in findings:
            if f.severity == ERROR:
                fam = family(f.rule_id)
                by_family[fam] = by_family.get(fam, 0) + 1
        print(json.dumps({
            "findings": (
                [doc(f, False) for f in findings]
                + [doc(f, True) for f in tracker.suppressed_findings]
            ),
            "errors": errors,
            "warnings": warnings,
            "errors_by_family": by_family,
            "exit_code": rc,
        }, indent=2))
    else:
        for f in findings:
            print(f.format())
    print(
        f"mgwfbp_tpu.analysis: {errors} error(s), {warnings} warning(s)"
        + (f", exit {rc}" if rc else ""),
        file=sys.stderr,
    )
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
