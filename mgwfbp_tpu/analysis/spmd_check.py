"""SPMD lockstep checker: static deadlock-freedom for the host protocol.

The jaxpr verifier proves the JITTED step realizes the merge schedule;
this pass proves the HOST-side multi-host protocol around it stays in
lockstep. MG-WFBP's wait-free scheduling (arXiv:1811.11141) and the
DeAR-style cross-step pipelining it composes with (arXiv:2302.12445)
both assume synchronous data-parallel SGD: every process executes the
IDENTICAL sequence of group operations (`agree_any` / `agree_all` /
`barrier` / `broadcast_flag` / `gather_*` / `all_argmin` /
`agree_uniform` — anything `runtime/coordination.py` decorates with
``@group_op``). A group op reached by only SOME processes deadlocks the
group; until this pass the only gate was the 2-process live smoke's
hard timeout. This pass catches the divergence in seconds, statically.

Model
-----
Per analyzed function the checker enumerates the possible group-op
SEQUENCES along control-flow paths (branches, loops 0-or-1 unrolled,
early exits), expanding calls through per-function *effect signatures*
(a real interprocedural pass: wrappers like ``Trainer._agreed_preempt``
or ``Checkpointer._commit_barrier`` carry their callee's ops, one
fixpoint over the whole target set). Conditions are classified on a
three-point lattice:

  UNIFORM  provably identical on every process: constants, static
           config, ``process_count()``, results of group ops whose
           ``uniform_result`` is declared (the agreement sanitizers),
           env vars (the supervisor exports ONE environment — except
           the per-process identity vars), and anything annotated
           ``# graft: group-uniform -- reason``;
  LOCAL    provably process-local: ``process_index()`` /
           ``is_primary()``, MGWFBP_PROCESS_ID-style env reads, local
           RNG, wall clocks, local-filesystem probes, and
           ``self._preempt``-style flags (attributes ever assigned from
           a local source);
  UNKNOWN  everything else.

Branches explicitly comparing ``process_count()`` against 1 are
resolved to their MULTI-HOST arm — the single-process short-circuits
are not part of the protocol.

Rules
-----
  RUN001  a group op control-dependent on a LOCAL condition;
  RUN002  branch arms executing different group-op sequences under a
          condition not proven UNIFORM (join-point sequence mismatch);
  RUN003  an early ``return``/``raise``/``continue`` that skips a group
          op another path still executes (the skipped-barrier hang);
  RUN004  a primary-only (process-0-gated) filesystem side effect not
          followed by a group op (commit barrier) on all paths;
  RUN005  a group op inside a ``try`` whose broad handler swallows the
          exception and proceeds (one process drops out of lockstep);
  RUN006  a group op reachable while holding a lock the serving plane
          (telemetry/serve.py, telemetry/fleet.py) also takes — the
          HTTP-handler <-> step-loop deadlock.

Suppression: the shared ``# graft: noqa[RUNnnn] -- reason`` grammar;
``# graft: group-uniform -- reason`` on a condition or assignment
declares a fact the analysis cannot see (both accounted by ANA001, so a
dead annotation cannot mask a future regression).

Deliberate limits (documented, not accidental): nested ``def``/lambda
bodies are not entered (the protocol surfaces keep group ops at
function level), implicit exceptions (an OSError out of ``np.save``)
are not modeled as edges — RUN005 covers the swallow side and the
commit protocol itself must agree on success (see
``Checkpointer.save_sharded``), and attribute types are inferred only
from ``self.x = ClassName(...)`` construction sites.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Any, Iterable, Optional, Sequence

from mgwfbp_tpu.analysis.rules import (
    Finding,
    SuppressionTracker,
    comment_lines,
    filter_suppressed,
    has_group_uniform_marker,
)

# --- lattice ---------------------------------------------------------------
UNIFORM, UNKNOWN, LOCAL = 0, 1, 2


def _join(*states: int) -> int:
    return max(states) if states else UNIFORM


# --- group-op discovery ----------------------------------------------------

@dataclasses.dataclass(frozen=True)
class GroupOp:
    name: str
    blocking: bool = True
    uniform_result: bool = True


_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRANSPORT_PATH = os.path.join(_PKG_ROOT, "runtime", "coordination.py")

# the protocol surfaces (package-relative); runtime/ is scanned whole
DEFAULT_TARGETS = (
    "runtime",
    os.path.join("train", "trainer.py"),
    "checkpoint.py",
    os.path.join("parallel", "autotune.py"),
    os.path.join("telemetry", "drift.py"),
)
# scanned for serving-plane lock acquisitions (RUN006) only
DEFAULT_SERVING = (
    os.path.join("telemetry", "serve.py"),
    os.path.join("telemetry", "fleet.py"),
)


def discover_group_ops(
    transport_path: Optional[str] = None,
) -> dict[str, GroupOp]:
    """AST-discover ``@group_op``-decorated functions in the transport
    module. Discovery is static on purpose: the op list is read from the
    same decorations that register the runtime registry
    (`coordination.GROUP_OPS`), so neither can drift from the other —
    a new primitive is discovered the moment it is decorated."""
    path = transport_path or TRANSPORT_PATH
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    ops: dict[str, GroupOp] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = _dotted(target)
            if name is None or name.rsplit(".", 1)[-1] != "group_op":
                continue
            kw = {"blocking": True, "uniform_result": True}
            if isinstance(dec, ast.Call):
                for k in dec.keywords:
                    if k.arg in kw and isinstance(k.value, ast.Constant):
                        kw[k.arg] = bool(k.value.value)
            ops[node.name] = GroupOp(node.name, **kw)
    return ops


# --- small AST helpers -----------------------------------------------------

def _dotted(node: ast.AST) -> Optional[str]:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_WALLCLOCK_TAILS = {
    "time", "perf_counter", "monotonic", "process_time", "time_ns",
    "perf_counter_ns", "monotonic_ns", "now", "utcnow",
}
_WALLCLOCK_ROOTS = {"time", "datetime"}
_FS_PROBE_TAILS = {
    "exists", "isfile", "isdir", "listdir", "stat", "scandir", "getsize",
    "getmtime", "glob", "iglob", "walk", "load", "loadtxt", "read_text",
    "read_bytes",
}
_FS_WRITE_TAILS = {
    "save", "savez", "dump", "replace", "rename", "makedirs", "mkdir",
    "rmtree", "remove", "unlink", "move", "copy", "copyfile", "copytree",
    "write_text", "write_bytes", "fsync",
}
_LOCAL_ENV_KEYS = {
    "MGWFBP_PROCESS_ID", "SLURM_PROCID", "OMPI_COMM_WORLD_RANK",
    "JAX_PROCESS_INDEX",
}
_PASSTHROUGH_BUILTINS = {
    "int", "float", "bool", "str", "len", "min", "max", "abs", "sum",
    "sorted", "tuple", "list", "dict", "set", "frozenset", "round",
    "any", "all", "repr", "zip", "enumerate", "range", "isinstance",
    "getattr", "hasattr", "type", "divmod",
}
_PASSTHROUGH_METHODS = {
    "get", "copy", "items", "keys", "values", "strip", "split", "lower",
    "upper", "format", "join", "startswith", "endswith", "rsplit",
    "popleft", "pop",
}
_BUILTIN_NAMES = {
    "dict", "list", "tuple", "set", "str", "int", "float", "bool",
    "bytes", "object", "type", "len", "Exception", "ValueError",
    "TypeError", "KeyError", "RuntimeError", "OSError",
}
_BROAD_EXC = {
    "Exception", "BaseException", "RuntimeError", "OSError", "IOError",
    "TimeoutError", "EnvironmentError",
}
_NORETURN_CALLS = {"exit", "_exit", "abort"}  # sys.exit / os._exit / os.abort


def _is_lock_expr(node: ast.AST) -> Optional[str]:
    """A with-item context manager that looks like a lock; returns its
    token (last name segment) or None."""
    name = _dotted(node)
    if name is None and isinstance(node, ast.Call):
        name = _dotted(node.func)
    if name is None:
        return None
    tail = name.rsplit(".", 1)[-1].lower()
    if "lock" in tail or "mutex" in tail or tail in ("cond", "condition"):
        return name.rsplit(".", 1)[-1]
    return None


def _env_key_of(call: ast.Call, fn: str) -> Optional[str]:
    """The env-var name read by os.environ.get / os.getenv, when constant."""
    tail = fn.rsplit(".", 1)[-1]
    if tail not in ("get", "getenv"):
        return None
    if "environ" not in fn and tail != "getenv":
        return None
    if call.args and isinstance(call.args[0], ast.Constant) and isinstance(
        call.args[0].value, str
    ):
        return call.args[0].value
    return None


# --- per-module model ------------------------------------------------------

class ModuleInfo:
    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.coord_aliases: set[str] = set()
        self.op_imports: dict[str, str] = {}  # local name -> op name
        self.module_aliases: dict[str, str] = {}  # alias -> module tail
        # bare name -> (source module tail, original name), from
        # `from pkg.mod import name [as alias]`
        self.from_imports: dict[str, tuple[str, str]] = {}
        self.consts: dict[str, int] = {}
        self.functions: dict[str, "FuncInfo"] = {}  # qualname
        self.classes: dict[str, "ClassInfo"] = {}
        # real comment tokens only — docstrings quoting the grammar are
        # not annotations
        self.comments: dict[int, str] = comment_lines(source) or {}
        self._scan_imports()
        self._scan_consts()

    def _scan_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    tail = a.name.rsplit(".", 1)[-1]
                    bound = a.asname or a.name.split(".", 1)[0]
                    if a.name.endswith("coordination"):
                        self.coord_aliases.add(bound)
                    else:
                        self.module_aliases[bound] = tail
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for a in node.names:
                    bound = a.asname or a.name
                    if a.name == "coordination":
                        self.coord_aliases.add(bound)
                    elif mod.endswith("coordination"):
                        self.op_imports[bound] = a.name
                    else:
                        self.module_aliases[bound] = a.name
                        self.from_imports[bound] = (
                            mod.rsplit(".", 1)[-1], a.name
                        )

    def _scan_consts(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    self.consts[t.id] = UNIFORM

    def uniform_marker_line(self, lineno: int) -> Optional[int]:
        """The line carrying a ``# graft: group-uniform`` marker for the
        statement starting at `lineno`: the line itself, or — the
        own-line convention for statements too long to tag inline — the
        comment line directly above it. Returns the MARKER's line (for
        ANA001 usage accounting) or None."""
        own = self.comments.get(lineno)
        if own is not None and has_group_uniform_marker(own):
            return lineno
        prev = self.comments.get(lineno - 1)
        if prev is not None and has_group_uniform_marker(prev) and (
            2 <= lineno <= len(self.lines) + 1
            and self.lines[lineno - 2].strip().startswith("#")
        ):
            return lineno - 1
        return None

    def line_has_uniform_marker(self, lineno: int) -> bool:
        return self.uniform_marker_line(lineno) is not None


class ClassInfo:
    def __init__(self, name: str, node: ast.ClassDef):
        self.name = name
        self.node = node
        self.attr_state: dict[str, int] = {}
        self.attr_pinned: set[str] = set()  # group-uniform annotated
        # attr -> (module path, marker line): consumed when a READ would
        # otherwise classify non-uniform (a redundant pin stays unused
        # and ANA001 flags it)
        self.attr_pin_lines: dict[str, tuple[str, int]] = {}
        self.attr_types: dict[str, str] = {}  # attr -> ClassName
        self.methods: dict[str, ast.FunctionDef] = {}


@dataclasses.dataclass
class FuncInfo:
    qualname: str
    node: Any
    module: ModuleInfo
    classname: Optional[str]
    seq: tuple = ()  # representative group-op sequence (callee-expanded)
    guaranteed: bool = False  # >= 1 op on EVERY multi-host path
    returns: int = UNKNOWN
    fs_write: bool = False
    is_op: Optional[GroupOp] = None  # the transport primitives themselves
    env: dict = dataclasses.field(default_factory=dict)
    primary_vars: set = dataclasses.field(default_factory=set)
    # param name -> joined lattice state over every ANALYZED call site
    # (a param nobody calls stays absent -> UNKNOWN)
    param_states: dict = dataclasses.field(default_factory=dict)


# --- the checker -----------------------------------------------------------

_SEQ_CAP = 40          # ops kept per path sequence
_SET_CAP = 48          # path sequences kept per program point


class _SeqSet:
    """A bounded set of group-op sequences; `overflow` poisons
    comparisons (never report on truncated evidence)."""

    __slots__ = ("seqs", "overflow")

    def __init__(self, seqs: frozenset, overflow: bool = False):
        self.seqs = seqs
        self.overflow = overflow or len(seqs) > _SET_CAP
        if len(seqs) > _SET_CAP:
            self.seqs = frozenset(sorted(seqs)[:_SET_CAP])

    @staticmethod
    def single(seq: tuple = ()) -> "_SeqSet":
        return _SeqSet(frozenset([seq]))

    def prepend(self, ops: Sequence[str]) -> "_SeqSet":
        if not ops:
            return self
        ops = tuple(ops)
        return _SeqSet(
            frozenset((ops + s)[:_SEQ_CAP] for s in self.seqs),
            self.overflow,
        )

    def union(self, other: "_SeqSet") -> "_SeqSet":
        return _SeqSet(
            self.seqs | other.seqs, self.overflow or other.overflow
        )

    def all_contain_op(self) -> bool:
        return not self.overflow and all(len(s) > 0 for s in self.seqs)

    def comparable(self, other: "_SeqSet") -> bool:
        return not (self.overflow or other.overflow)


class _Cont:
    """Interned continuation: execute stmts[i:] (with loop context), then
    `nxt`. Loop contexts are (break_cont, continue_cont) pairs."""

    __slots__ = ("stmts", "i", "lctx", "nxt")

    def __init__(self, stmts, i, lctx, nxt):
        self.stmts = stmts
        self.i = i
        self.lctx = lctx
        self.nxt = nxt


class Checker:
    def __init__(
        self,
        modules: Sequence[ModuleInfo],
        ops: dict[str, GroupOp],
        serving_modules: Sequence[ModuleInfo] = (),
        tracker: Optional[SuppressionTracker] = None,
        transport_base: str = "coordination.py",
    ):
        self.modules = list(modules)
        self.ops = ops
        self.serving_modules = list(serving_modules)
        self.tracker = tracker
        self.transport_base = transport_base
        self.findings: list[Finding] = []
        self._reported: set[tuple] = set()
        # RUN004 is two-phase: candidates recorded during the per-function
        # walks, then exonerated when EVERY analyzed call site of the
        # containing helper is followed by a guaranteed group op (the
        # `_write_index` pattern: the p0 write commits at the caller)
        self._run004: list[tuple[FuncInfo, int]] = []
        self._callsites: dict[int, list[bool]] = {}  # id(FuncInfo) -> flags
        self.class_index: dict[str, tuple[ModuleInfo, ClassInfo]] = {}
        self.func_index: dict[str, FuncInfo] = {}  # "modtail.qualname"
        self.serving_locks: set[str] = set()
        self._collect()

    # -- model construction -------------------------------------------
    def _collect(self) -> None:
        for mod in self.modules:
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    ci = ClassInfo(node.name, node)
                    mod.classes[node.name] = ci
                    self.class_index.setdefault(node.name, (mod, ci))
                    for item in node.body:
                        if isinstance(
                            item, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            ci.methods[item.name] = item
                            q = f"{node.name}.{item.name}"
                            fi = FuncInfo(q, item, mod, node.name)
                            mod.functions[q] = fi
                elif isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    fi = FuncInfo(node.name, node, mod, None)
                    mod.functions[node.name] = fi
        for mod in self.modules:
            is_transport = (
                os.path.basename(mod.path) == self.transport_base
            )
            for q, fi in mod.functions.items():
                if (
                    is_transport
                    and fi.classname is None
                    and fi.node.name in self.ops
                ):
                    # the decorated primitives ARE the atomic ops: their
                    # summary is themselves, and their single-process
                    # short-circuit bodies are not re-derived
                    fi.is_op = self.ops[fi.node.name]
                    fi.seq = (fi.node.name,)
                    fi.guaranteed = True
                    fi.returns = UNIFORM
                key = self._func_key(mod, q)
                self.func_index[key] = fi
        self._collect_serving_locks()

    def _func_key(self, mod: ModuleInfo, qualname: str) -> str:
        tail = os.path.basename(mod.path)
        return f"{tail}:{qualname}"

    def _collect_serving_locks(self) -> None:
        for mod in self.serving_modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.With):
                    for item in node.items:
                        tok = _is_lock_expr(item.context_expr)
                        if tok:
                            self.serving_locks.add(tok)

    # -- call resolution ----------------------------------------------
    def resolve_call(
        self, call: ast.Call, mod: ModuleInfo, classname: Optional[str]
    ):
        """('op', GroupOp) | ('fn', FuncInfo) | None."""
        fn = _dotted(call.func)
        if fn is None:
            return None
        parts = fn.split(".")
        if parts[0] in mod.coord_aliases and len(parts) == 2:
            op = self.ops.get(parts[1])
            if op:
                return ("op", op)
            return None
        if len(parts) == 1 and parts[0] in mod.op_imports:
            op = self.ops.get(mod.op_imports[parts[0]])
            if op:
                return ("op", op)
        if parts[0] == "self" and classname:
            _m, ci = self.class_index.get(classname, (None, None))
            if ci is not None:
                if len(parts) == 2 and parts[1] in ci.methods:
                    return ("fn", self._lookup_method(classname, parts[1]))
                if len(parts) == 3 and parts[1] in ci.attr_types:
                    target_cls = ci.attr_types[parts[1]]
                    m = self._lookup_method(target_cls, parts[2])
                    if m is not None:
                        return ("fn", m)
            return None
        if len(parts) == 1:
            fi = mod.functions.get(parts[0])
            if fi is not None:
                return ("fn", fi)
            cls = self.class_index.get(parts[0])
            if cls is not None:
                init = self._lookup_method(parts[0], "__init__")
                if init is not None:
                    return ("fn", init)
            src = mod.from_imports.get(parts[0])
            if src is not None:
                mod_tail, orig = src
                target = self._find_module_func(mod_tail, orig)
                if target is not None:
                    return ("fn", target)
        if len(parts) == 2:
            target_mod_tail = mod.module_aliases.get(parts[0])
            if target_mod_tail:
                target = self._find_module_func(target_mod_tail, parts[1])
                if target is not None:
                    return ("fn", target)
        return None

    def _find_module_func(
        self, mod_tail: str, name: str
    ) -> Optional[FuncInfo]:
        for m2 in self.modules:
            if os.path.basename(m2.path) == mod_tail + ".py":
                return m2.functions.get(name)
        return None

    def _lookup_method(
        self, classname: str, method: str
    ) -> Optional[FuncInfo]:
        entry = self.class_index.get(classname)
        if entry is None:
            return None
        mod, _ci = entry
        return mod.functions.get(f"{classname}.{method}")

    def _consume_uniform_marker(self, mod: ModuleInfo, lineno: int) -> bool:
        ml = mod.uniform_marker_line(lineno)
        if ml is None:
            return False
        if self.tracker is not None:
            self.tracker.note_uniform_used(mod.path, ml)
        return True

    # -- expression classification ------------------------------------
    def classify(
        self, node: ast.AST, fi: FuncInfo, _depth: int = 0
    ) -> int:
        state = self._classify_inner(node, fi, _depth)
        if state != UNIFORM:
            # a group-uniform marker is consumed only when it actually
            # FLIPS a classification — a marker on an already-uniform
            # value is dead and ANA001 reports it
            line = getattr(node, "lineno", 0)
            if line and self._consume_uniform_marker(fi.module, line):
                return UNIFORM
        return state

    def _classify_inner(
        self, node: ast.AST, fi: FuncInfo, _depth: int = 0
    ) -> int:
        if node is None or _depth > 25:
            return UNIFORM if node is None else UNKNOWN
        mod = fi.module
        if isinstance(node, ast.Constant):
            return UNIFORM
        if isinstance(node, ast.Name):
            if node.id in fi.env:
                return fi.env[node.id]
            if node.id in mod.consts:
                return UNIFORM
            if node.id.isupper():  # imported ALL_CAPS constant
                return UNIFORM
            if node.id in _BUILTIN_NAMES:
                return UNIFORM
            return UNKNOWN
        if isinstance(node, ast.Attribute):
            full = _dotted(node)
            if full is not None:
                parts = full.split(".")
                if any(p in ("config", "cfg") for p in parts):
                    return UNIFORM
                if parts[0] == "self" and fi.classname:
                    entry = self.class_index.get(fi.classname)
                    if entry is not None:
                        _m, ci = entry
                        if parts[1] in ci.attr_pinned:
                            raw = ci.attr_state.get(parts[1], UNKNOWN)
                            if raw != UNIFORM and self.tracker is not None:
                                pin = ci.attr_pin_lines.get(parts[1])
                                if pin is not None:
                                    self.tracker.note_uniform_used(*pin)
                            return UNIFORM
                        # `self._preempt*`-style flags are set by signal
                        # handlers — the canonical process-local source
                        if "preempt" in parts[1]:
                            return LOCAL
                        st = ci.attr_state.get(parts[1])
                        if st is not None:
                            return st
                    return UNKNOWN
            return self.classify(node.value, fi, _depth + 1)
        if isinstance(node, ast.Subscript):
            return self.classify(node.value, fi, _depth + 1)
        if isinstance(node, ast.UnaryOp):
            return self.classify(node.operand, fi, _depth + 1)
        if isinstance(node, ast.BoolOp):
            return _join(*[
                self.classify(v, fi, _depth + 1) for v in node.values
            ])
        if isinstance(node, ast.BinOp):
            return _join(
                self.classify(node.left, fi, _depth + 1),
                self.classify(node.right, fi, _depth + 1),
            )
        if isinstance(node, ast.Compare):
            return _join(
                self.classify(node.left, fi, _depth + 1),
                *[self.classify(c, fi, _depth + 1) for c in node.comparators]
            )
        if isinstance(node, ast.IfExp):
            return _join(
                self.classify(node.test, fi, _depth + 1),
                self.classify(node.body, fi, _depth + 1),
                self.classify(node.orelse, fi, _depth + 1),
            )
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return _join(*[
                self.classify(e, fi, _depth + 1) for e in node.elts
            ])
        if isinstance(node, ast.Call):
            return self._classify_call(node, fi, _depth)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            it_state = _join(*[
                self.classify(g.iter, fi, _depth + 1)
                for g in node.generators
            ])
            # comprehension targets carry the iterable's state while the
            # element expression is classified
            names = [
                n.id for g in node.generators
                for n in ast.walk(g.target) if isinstance(n, ast.Name)
            ]
            saved = {n: fi.env.get(n) for n in names}
            for n in names:
                fi.env[n] = it_state
            try:
                parts = (
                    [node.key, node.value]
                    if isinstance(node, ast.DictComp) else [node.elt]
                )
                return _join(it_state, *[
                    self.classify(p, fi, _depth + 1) for p in parts
                ])
            finally:
                for n, st in saved.items():
                    if st is None:
                        fi.env.pop(n, None)
                    else:
                        fi.env[n] = st
        if isinstance(node, ast.Lambda):
            return UNIFORM
        return UNKNOWN

    def _classify_call(
        self, call: ast.Call, fi: FuncInfo, _depth: int
    ) -> int:
        fn = _dotted(call.func)
        if fn is None:
            # `(expr or "").strip()`-style: method on a non-Name chain
            if isinstance(call.func, ast.Attribute) and (
                call.func.attr in _PASSTHROUGH_METHODS
            ):
                return _join(
                    self.classify(call.func.value, fi, _depth + 1),
                    *[self.classify(a, fi, _depth + 1) for a in call.args]
                )
            return UNKNOWN
        tail = fn.rsplit(".", 1)[-1]
        root = fn.split(".", 1)[0]
        if tail == "process_count":
            return UNIFORM
        if tail in ("process_index", "is_primary", "getpid", "gethostname"):
            return LOCAL
        if root in _WALLCLOCK_ROOTS and tail in _WALLCLOCK_TAILS:
            return LOCAL
        if root in ("random",) or fn.startswith(
            ("np.random.", "numpy.random.")
        ):
            return LOCAL
        key = _env_key_of(call, fn)
        if key is not None:
            return LOCAL if key in _LOCAL_ENV_KEYS else UNIFORM
        if "environ" in fn:
            return UNKNOWN
        if fn == "open" or tail in _FS_PROBE_TAILS and root in (
            "os", "glob", "np", "numpy", "json", "shutil"
        ):
            return LOCAL
        res = self.resolve_call(call, fi.module, fi.classname)
        if res is not None:
            kind, target = res
            if kind == "op":
                # only ops DECLARED uniform_result sanitize — a future
                # primitive without the declaration must not silently
                # launder a non-uniform value into a branch condition
                return UNIFORM if target.uniform_result else UNKNOWN
            return target.returns
        args_state = _join(*[
            self.classify(a, fi, _depth + 1) for a in call.args
        ]) if call.args else UNIFORM
        if fn in _PASSTHROUGH_BUILTINS:
            return args_state
        if tail in _PASSTHROUGH_METHODS:
            return _join(
                self.classify(call.func, fi, _depth + 1), args_state
            )
        return UNKNOWN

    # -- multi-host resolution of process_count() comparisons ----------
    def _strip_mh(self, test: ast.AST):
        """('const', bool) when the test is decided by multi-host
        (process_count() vs 1 comparisons), ('nodes', [remaining])
        otherwise — remaining terms classify the residual condition."""
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            kind, payload = self._strip_mh(test.operand)
            if kind == "const":
                return ("const", not payload)
            return ("nodes", [test])
        if isinstance(test, ast.Compare) and len(test.ops) == 1:
            left, right = test.left, test.comparators[0]
            pc_left = (
                isinstance(left, ast.Call)
                and (_dotted(left.func) or "").endswith("process_count")
            )
            pc_right = (
                isinstance(right, ast.Call)
                and (_dotted(right.func) or "").endswith("process_count")
            )
            const = None
            if pc_left and isinstance(right, ast.Constant):
                const = right.value
                op = test.ops[0]
            elif pc_right and isinstance(left, ast.Constant):
                const = left.value
                op = {
                    ast.Gt: ast.Lt, ast.Lt: ast.Gt, ast.GtE: ast.LtE,
                    ast.LtE: ast.GtE,
                }.get(type(test.ops[0]), type(test.ops[0]))()
            if const is not None and isinstance(const, int):
                # evaluate with process_count >= 2
                if isinstance(op, ast.Eq):
                    return ("const", False) if const <= 1 else (
                        "nodes", [test]
                    )
                if isinstance(op, ast.NotEq):
                    return ("const", True) if const <= 1 else (
                        "nodes", [test]
                    )
                if isinstance(op, ast.Gt):
                    return ("const", True) if const <= 1 else (
                        "nodes", [test]
                    )
                if isinstance(op, ast.GtE):
                    return ("const", True) if const <= 2 else (
                        "nodes", [test]
                    )
                if isinstance(op, ast.Lt):
                    return ("const", False) if const <= 2 else (
                        "nodes", [test]
                    )
                if isinstance(op, ast.LtE):
                    return ("const", False) if const <= 1 else (
                        "nodes", [test]
                    )
        if isinstance(test, ast.BoolOp):
            is_and = isinstance(test.op, ast.And)
            remaining: list[ast.AST] = []
            for v in test.values:
                kind, payload = self._strip_mh(v)
                if kind == "const":
                    if is_and and payload is False:
                        return ("const", False)
                    if not is_and and payload is True:
                        return ("const", True)
                    continue  # neutral term drops out
                remaining.extend(payload)
            if not remaining:
                return ("const", is_and)
            return ("nodes", remaining)
        return ("nodes", [test])

    def _classify_test(self, test: ast.AST, fi: FuncInfo) -> Optional[int]:
        """None when multi-host-resolved (caller already pruned);
        otherwise lattice state of the residual condition."""
        kind, payload = self._strip_mh(test)
        if kind == "const":
            return None
        return _join(*[self.classify(n, fi) for n in payload])

    # -- env / attribute passes ----------------------------------------
    def _env_pass(self, fi: FuncInfo, ci: Optional[ClassInfo]) -> None:
        """Variable environment (last-write-wins, so the canonical
        sanitize-rebind `x = coord.agree_all(x)` lowers x to UNIFORM) +
        self.X attribute joins + call-site-inferred parameter states."""
        fi.env = {}
        for p, st in fi.param_states.items():
            fi.env[p] = st
        fi.primary_vars = set()
        mod = fi.module

        def is_primary_expr(expr) -> bool:
            if isinstance(expr, ast.Call):
                t = (_dotted(expr.func) or "").rsplit(".", 1)[-1]
                return t == "is_primary"
            if isinstance(expr, ast.Compare) and len(expr.ops) == 1:
                if isinstance(expr.ops[0], ast.Eq):
                    sides = [expr.left, expr.comparators[0]]
                    has_zero = any(
                        isinstance(s, ast.Constant) and s.value == 0
                        for s in sides
                    )
                    has_pidx = any(
                        isinstance(s, ast.Call)
                        and (_dotted(s.func) or "").endswith("process_index")
                        for s in sides
                    )
                    return has_zero and has_pidx
            return False

        for node in _walk_no_defs(fi.node, skip_root_def=True):
            if isinstance(node, ast.Assign):
                state = self.classify(node.value, fi)
                if mod.uniform_marker_line(node.lineno) is not None:
                    state = UNIFORM
                for t in node.targets:
                    self._bind_target(t, state, fi, ci, node)
                if is_primary_expr(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            fi.primary_vars.add(t.id)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                state = self.classify(node.value, fi)
                if mod.uniform_marker_line(node.lineno) is not None:
                    state = UNIFORM
                self._bind_target(node.target, state, fi, ci, node)
            elif isinstance(node, ast.AugAssign):
                state = _join(
                    self.classify(node.target, fi),
                    self.classify(node.value, fi),
                )
                if mod.uniform_marker_line(node.lineno) is not None:
                    state = UNIFORM
                self._bind_target(node.target, state, fi, ci, node)
            elif isinstance(node, ast.For):
                state = self.classify(node.iter, fi)
                self._bind_target(node.target, state, fi, ci, node)

    def _bind_target(
        self, target, state: int, fi: FuncInfo,
        ci: Optional[ClassInfo], stmt,
    ) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind_target(e, state, fi, ci, stmt)
            return
        if isinstance(target, ast.Name):
            fi.env[target.id] = state  # last write wins (see _env_pass)
            return
        if isinstance(target, ast.Attribute) and ci is not None:
            full = _dotted(target)
            if full and full.startswith("self.") and full.count(".") == 1:
                attr = full.split(".", 1)[1]
                ml = fi.module.uniform_marker_line(stmt.lineno)
                if ml is not None:
                    ci.attr_pinned.add(attr)
                    ci.attr_pin_lines.setdefault(
                        attr, (fi.module.path, ml)
                    )
                    state = UNIFORM  # the marker asserts THIS value too
                prev = ci.attr_state.get(attr, UNIFORM)
                ci.attr_state[attr] = _join(prev, state)
                # constructor-based attribute type inference
                if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, ast.Call
                ):
                    cname = (_dotted(stmt.value.func) or "").rsplit(
                        ".", 1
                    )[-1]
                    if cname in self.class_index:
                        ci.attr_types[attr] = cname

    # -- effect summaries (fixpoint) -----------------------------------
    def compute_summaries(self, rounds: int = 4) -> None:
        funcs = [
            fi for mod in self.modules for fi in mod.functions.values()
        ]
        for _ in range(rounds):
            changed = False
            for fi in funcs:
                ci = (
                    self.class_index[fi.classname][1]
                    if fi.classname else None
                )
                self._env_pass(fi, ci)
                if fi.is_op is not None:
                    continue
                seq = tuple(self._struct_seq(fi.node.body, fi))[:_SEQ_CAP]
                guaranteed = self._guaranteed(list(fi.node.body), fi)
                returns = self._returns_state(fi)
                fs_write = self._has_fs_write(fi.node.body, fi)
                new = (seq, guaranteed, returns, fs_write)
                if new != (fi.seq, fi.guaranteed, fi.returns, fi.fs_write):
                    fi.seq, fi.guaranteed = seq, guaranteed
                    fi.returns, fi.fs_write = returns, fs_write
                    changed = True
            if self._infer_param_states(funcs):
                changed = True
            if not changed:
                break

    def _infer_param_states(self, funcs: Sequence[FuncInfo]) -> bool:
        """Join every ANALYZED call site's argument states into the
        callee's parameter states (interprocedural taint: a cadence flag
        passed only as a literal is group-uniform at the callee too).
        Joins are monotone, so the enclosing fixpoint converges."""
        changed = False
        for fi in funcs:
            for call in _walk_no_defs(fi.node, skip_root_def=True):
                if not isinstance(call, ast.Call):
                    continue
                res = self.resolve_call(call, fi.module, fi.classname)
                if res is None or res[0] != "fn":
                    continue
                callee = res[1]
                a = callee.node.args
                params = [p.arg for p in [*a.posonlyargs, *a.args]]
                if callee.classname is not None and params[:1] == ["self"]:
                    params = params[1:]
                bound: dict[str, int] = {}
                for i, arg in enumerate(call.args):
                    if isinstance(arg, ast.Starred):
                        break
                    if i < len(params):
                        bound[params[i]] = self.classify(arg, fi)
                for kw in call.keywords:
                    if kw.arg is not None:
                        bound[kw.arg] = self.classify(kw.value, fi)
                # unpassed params take their default's state
                defaults = a.defaults
                if defaults:
                    for p, d in zip(params[-len(defaults):], defaults):
                        if p not in bound:
                            bound[p] = self.classify(d, callee)
                for p, kwd in zip(
                    [k.arg for k in a.kwonlyargs], a.kw_defaults
                ):
                    if p not in bound and kwd is not None:
                        bound[p] = self.classify(kwd, callee)
                for p, st in bound.items():
                    prev = callee.param_states.get(p)
                    nxt = st if prev is None else _join(prev, st)
                    if nxt != prev:
                        callee.param_states[p] = nxt
                        changed = True
        return changed

    def _call_ops(self, call: ast.Call, fi: FuncInfo) -> tuple:
        res = self.resolve_call(call, fi.module, fi.classname)
        if res is None:
            return ()
        kind, target = res
        if kind == "op":
            return (target.name,)
        return tuple(target.seq)

    def _stmt_ops(self, stmt, fi: FuncInfo) -> list[str]:
        """Group ops issued by the statement's OWN expressions (compound
        bodies excluded — they flow through continuations)."""
        out: list[str] = []
        for expr in _own_exprs(stmt):
            if expr is None:
                continue
            for sub in _walk_no_defs(expr):
                if isinstance(sub, ast.Call):
                    out.extend(self._call_ops(sub, fi))
        return out

    def _struct_seq(self, stmts, fi: FuncInfo, depth: int = 0) -> list[str]:
        """Representative op sequence (for call-site expansion)."""
        if depth > 40:
            return []
        out: list[str] = []
        for stmt in stmts:
            out.extend(self._stmt_ops(stmt, fi))
            if isinstance(stmt, ast.If):
                kind, _ = self._strip_mh(stmt.test)
                if kind == "const":
                    arm = stmt.body if _ else stmt.orelse
                    out.extend(self._struct_seq(arm, fi, depth + 1))
                else:
                    t = self._struct_seq(stmt.body, fi, depth + 1)
                    e = self._struct_seq(stmt.orelse, fi, depth + 1)
                    out.extend(t if len(t) >= len(e) else e)
            elif isinstance(stmt, (ast.For, ast.While)):
                out.extend(self._struct_seq(stmt.body, fi, depth + 1))
                out.extend(self._struct_seq(stmt.orelse, fi, depth + 1))
            elif isinstance(stmt, ast.Try):
                out.extend(self._struct_seq(stmt.body, fi, depth + 1))
                out.extend(self._struct_seq(stmt.orelse, fi, depth + 1))
                out.extend(self._struct_seq(stmt.finalbody, fi, depth + 1))
            elif isinstance(stmt, ast.With):
                out.extend(self._struct_seq(stmt.body, fi, depth + 1))
            if isinstance(stmt, (ast.Return, ast.Raise)):
                break
            if len(out) >= _SEQ_CAP:
                break
        return out[:_SEQ_CAP]

    def _stmt_guaranteed(self, stmt, fi: FuncInfo) -> bool:
        for expr in _own_exprs(stmt):
            if expr is None:
                continue
            for sub in _walk_no_defs(expr):
                if isinstance(sub, ast.Call):
                    res = self.resolve_call(sub, fi.module, fi.classname)
                    if res is None:
                        continue
                    kind, target = res
                    if kind == "op" or target.guaranteed:
                        return True
        return False

    def _guaranteed(self, stmts: list, fi: FuncInfo, depth: int = 0) -> bool:
        """>= 1 group op on every path through `stmts` (multi-host arms)."""
        if depth > 60:
            return False
        for i, stmt in enumerate(stmts):
            rest = stmts[i + 1:]
            if self._stmt_guaranteed(stmt, fi):
                return True
            if isinstance(stmt, (ast.Return, ast.Raise)):
                return False
            if isinstance(stmt, ast.If):
                kind, _ = self._strip_mh(stmt.test)
                if kind == "const":
                    arm = stmt.body if _ else stmt.orelse
                    return self._guaranteed(
                        list(arm) + rest, fi, depth + 1
                    )
                return self._guaranteed(
                    list(stmt.body) + rest, fi, depth + 1
                ) and self._guaranteed(
                    list(stmt.orelse) + rest, fi, depth + 1
                )
            if isinstance(stmt, ast.With):
                return self._guaranteed(
                    list(stmt.body) + rest, fi, depth + 1
                )
            if isinstance(stmt, ast.Try):
                return self._guaranteed(
                    list(stmt.body) + list(stmt.orelse)
                    + list(stmt.finalbody) + rest, fi, depth + 1,
                )
            if isinstance(stmt, (ast.For, ast.While)):
                continue  # loop may run zero times; scan the rest
            if isinstance(stmt, (ast.Break, ast.Continue)):
                return False
        return False

    def _returns_state(self, fi: FuncInfo) -> int:
        """Join of reachable MULTI-HOST return expressions — returns
        inside `process_count() == 1` short-circuits are not part of the
        protocol (`_agreed_preempt` returns its raw local flag there but
        the agreed value on every multi-host path)."""
        states: list[int] = []

        def visit(stmts) -> None:
            for stmt in stmts:
                if isinstance(stmt, ast.Return):
                    states.append(
                        self.classify(stmt.value, fi)
                        if stmt.value is not None else UNIFORM
                    )
                elif isinstance(stmt, ast.If):
                    kind, payload = self._strip_mh(stmt.test)
                    if kind == "const":
                        visit(stmt.body if payload else stmt.orelse)
                    else:
                        visit(stmt.body)
                        visit(stmt.orelse)
                elif isinstance(stmt, (ast.For, ast.While)):
                    visit(stmt.body)
                    visit(stmt.orelse)
                elif isinstance(stmt, ast.Try):
                    visit(stmt.body)
                    for h in stmt.handlers:
                        visit(h.body)
                    visit(stmt.orelse)
                    visit(stmt.finalbody)
                elif isinstance(stmt, ast.With):
                    visit(stmt.body)

        visit(list(fi.node.body))
        if not states:
            return UNIFORM  # implicit None
        return _join(*states)

    def _has_fs_write(self, stmts, fi: FuncInfo) -> bool:
        for stmt in stmts:
            for node in _walk_no_defs(stmt):
                if not isinstance(node, ast.Call):
                    continue
                fn = _dotted(node.func)
                if fn is None:
                    continue
                tail = fn.rsplit(".", 1)[-1]
                if tail in _FS_WRITE_TAILS:
                    return True
                if fn == "open":
                    mode = None
                    if len(node.args) >= 2 and isinstance(
                        node.args[1], ast.Constant
                    ):
                        mode = node.args[1].value
                    for k in node.keywords:
                        if k.arg == "mode" and isinstance(
                            k.value, ast.Constant
                        ):
                            mode = k.value.value
                    if isinstance(mode, str) and any(
                        c in mode for c in "wax+"
                    ):
                        return True
                res = self.resolve_call(node, fi.module, fi.classname)
                if res is not None and res[0] == "fn" and res[1].fs_write:
                    return True
        return False

    # -- findings ------------------------------------------------------
    def _report(
        self, fi: FuncInfo, line: int, rule: str, msg: str
    ) -> None:
        key = (fi.module.path, line, rule)
        if key in self._reported:
            return
        self._reported.add(key)
        self.findings.append(Finding(fi.module.path, line, rule, msg))

    def check(self) -> list[Finding]:
        self.compute_summaries()
        for mod in self.modules:
            for fi in mod.functions.values():
                if fi.is_op is not None:
                    continue
                self._check_function(fi)
                self._check_locks(fi)
        self._resolve_run004()
        out: list[Finding] = []
        by_mod: dict[str, list[Finding]] = {}
        for f in self.findings:
            by_mod.setdefault(f.file, []).append(f)
        if self.tracker is not None:
            self.tracker.note_value_pass(
                "group-uniform", (m.path for m in self.modules),
            )
        for mod in self.modules:
            fs = by_mod.get(mod.path, [])
            if self.tracker is not None:
                self.tracker.scan_lines(mod.path, mod.lines)
            out.extend(filter_suppressed(
                sorted(fs, key=lambda f: (f.line, f.rule_id)),
                mod.lines, self.tracker,
            ))
        for mod in self.serving_modules:
            if self.tracker is not None:
                self.tracker.scan_lines(mod.path, mod.lines)
        return out

    # continuation machinery ------------------------------------------
    def _check_function(self, fi: FuncInfo) -> None:
        memo: dict[tuple, _SeqSet] = {}
        conts: dict[tuple, _Cont] = {}

        def make_cont(stmts, i, lctx, nxt) -> Optional[_Cont]:
            key = (id(stmts), i, lctx, id(nxt) if nxt else 0)
            c = conts.get(key)
            if c is None:
                c = _Cont(tuple(stmts), i, lctx, nxt)
                conts[key] = c
            return c

        def seqs(cont: Optional[_Cont]) -> _SeqSet:
            if cont is None:
                return _SeqSet.single()
            key = (id(cont.stmts), cont.i, cont.lctx,
                   id(cont.nxt) if cont.nxt else 0)
            hit = memo.get(key)
            if hit is not None:
                return hit
            memo[key] = _SeqSet.single()  # cycle guard (shouldn't occur)
            result = self._seqs_step(fi, cont, seqs, make_cont)
            memo[key] = result
            return result

        body = list(fi.node.body)
        seqs(make_cont(body, 0, (), None))

    def _seqs_step(self, fi, cont, seqs, make_cont) -> _SeqSet:
        stmts, i, lctx, nxt = cont.stmts, cont.i, cont.lctx, cont.nxt
        if i >= len(stmts):
            return seqs(nxt)
        stmt = stmts[i]
        rest = make_cont(stmts, i + 1, lctx, nxt)
        ops = self._stmt_ops(stmt, fi)

        if isinstance(stmt, (ast.Return, ast.Raise)):
            self._record_callsites(fi, stmt, _SeqSet.single())
            return _SeqSet.single(tuple(ops))
        if isinstance(stmt, ast.Break):
            return seqs(lctx[-1][0]).prepend(ops) if lctx else (
                _SeqSet.single(tuple(ops))
            )
        if isinstance(stmt, ast.Continue):
            return seqs(lctx[-1][1]).prepend(ops) if lctx else (
                _SeqSet.single(tuple(ops))
            )
        if isinstance(stmt, ast.If):
            return self._seqs_if(fi, stmt, ops, rest, lctx, seqs, make_cont)
        if isinstance(stmt, (ast.For, ast.While)):
            after = (
                make_cont(stmt.orelse, 0, lctx, rest)
                if stmt.orelse else rest
            )
            lctx2 = lctx + ((rest, after),)
            body_c = make_cont(stmt.body, 0, lctx2, after)
            return seqs(after).union(seqs(body_c)).prepend(ops)
        if isinstance(stmt, ast.Try):
            return self._seqs_try(fi, stmt, ops, rest, lctx, seqs, make_cont)
        if isinstance(stmt, ast.With):
            return seqs(make_cont(stmt.body, 0, lctx, rest)).prepend(ops)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return seqs(rest)
        self._record_callsites(fi, stmt, seqs(rest))
        return seqs(rest).prepend(ops)

    def _seqs_if(
        self, fi, stmt, ops, rest, lctx, seqs, make_cont
    ) -> _SeqSet:
        kind, _payload = self._strip_mh(stmt.test)
        if kind == "const":
            arm = stmt.body if _payload else stmt.orelse
            c = make_cont(arm, 0, lctx, rest) if arm else rest
            return seqs(c).prepend(ops)
        t = seqs(make_cont(stmt.body, 0, lctx, rest))
        e = (
            seqs(make_cont(stmt.orelse, 0, lctx, rest))
            if stmt.orelse else seqs(rest)
        )
        if t.comparable(e) and t.seqs != e.seqs:
            state = self._classify_test(stmt.test, fi)
            if state is not None and state != UNIFORM and (
                self._consume_uniform_marker(fi.module, stmt.lineno)
            ):
                # marker on the `if` line of a multi-line condition whose
                # non-uniform term sits on a continuation line
                state = UNIFORM
            if state == LOCAL:
                diff = _diff_ops(t.seqs, e.seqs)
                self._report(
                    fi, stmt.lineno, "RUN001",
                    f"in '{fi.qualname}': group op(s) {diff} are "
                    "control-dependent on a process-local condition — "
                    "processes will take different arms and issue "
                    "mismatched collectives (agree on the decision "
                    "first: agree_any/agree_all/broadcast_flag)",
                )
            elif state == UNKNOWN:
                exit_stmt = _trailing_exit(stmt.body) or (
                    _trailing_exit(stmt.orelse) if stmt.orelse else None
                )
                if exit_stmt is not None:
                    diff = _diff_ops(t.seqs, e.seqs)
                    self._report(
                        fi, exit_stmt.lineno, "RUN003",
                        f"in '{fi.qualname}': this early "
                        f"{_exit_kind(exit_stmt)} skips group op(s) "
                        f"{diff} that another path still executes — a "
                        "process leaving here deadlocks peers waiting in "
                        "the op (prove the condition group-uniform or "
                        "restructure so every path balances)",
                    )
                else:
                    diff = _diff_ops(t.seqs, e.seqs)
                    self._report(
                        fi, stmt.lineno, "RUN002",
                        f"in '{fi.qualname}': branch arms execute "
                        f"different group-op sequences ({diff}) under a "
                        "condition not proven group-uniform — annotate "
                        "'# graft: group-uniform -- reason' if it is, or "
                        "agree on it first",
                    )
        # RUN004: primary-gated filesystem side effect needs a commit
        # barrier (any group op) downstream on every path
        self._check_primary_write(fi, stmt, rest, seqs)
        return t.union(e).prepend(ops)

    def _record_callsites(self, fi, stmt, rest_seqs: _SeqSet) -> None:
        """Note, for every resolved function call in this statement,
        whether a guaranteed group op follows at THIS call site (feeds
        RUN004 exoneration)."""
        follows = (not rest_seqs.overflow) and rest_seqs.all_contain_op()
        for expr in _own_exprs(stmt):
            if expr is None:
                continue
            for sub in _walk_no_defs(expr):
                if isinstance(sub, ast.Call):
                    res = self.resolve_call(sub, fi.module, fi.classname)
                    if res is not None and res[0] == "fn":
                        self._callsites.setdefault(
                            id(res[1]), []
                        ).append(follows)

    def _check_primary_write(self, fi, stmt, rest, seqs) -> None:
        arm = self._primary_arm(stmt, fi)
        if arm is None:
            return
        if arm == "rest":
            # `if not is_primary(): return` — the p0 side is the block
            # remainder
            arm_stmts = list(rest.stmts[rest.i:])
        else:
            arm_stmts = list(arm)
        if not self._has_fs_write(arm_stmts, fi):
            return
        if self._arm_has_op(arm_stmts, fi):
            return  # RUN001's territory (op inside a local-gated arm)
        cont_seqs = seqs(rest)
        if cont_seqs.overflow:
            return
        if arm != "rest" and self._guaranteed(arm_stmts, fi):
            return
        if arm == "rest" or not cont_seqs.all_contain_op():
            if arm == "rest" and self._guaranteed(arm_stmts, fi):
                return
            self._run004.append((fi, stmt.lineno))

    def _resolve_run004(self) -> None:
        for fi, line in self._run004:
            flags = self._callsites.get(id(fi))
            if flags and all(flags):
                continue  # every analyzed caller commits after the call
            self._report(
                fi, line, "RUN004",
                f"in '{fi.qualname}': primary-only side effect "
                "(process-0-gated write) is not followed by a commit "
                "barrier / group op on every path — peers can race past "
                "the uncommitted write (or exit before it is durable)",
            )

    def _primary_arm(self, stmt: ast.If, fi: FuncInfo):
        """The statements executed ONLY on process 0, when the branch is
        primary-gated; None otherwise."""
        def test_primary(test) -> Optional[bool]:
            # True -> body is the p0 arm; False -> orelse is
            if isinstance(test, ast.UnaryOp) and isinstance(
                test.op, ast.Not
            ):
                inner = test_primary(test.operand)
                return None if inner is None else (not inner)
            if isinstance(test, ast.Call):
                t = (_dotted(test.func) or "").rsplit(".", 1)[-1]
                if t == "is_primary":
                    return True
            if isinstance(test, ast.Name) and test.id in fi.primary_vars:
                return True
            if isinstance(test, ast.Compare) and len(test.ops) == 1:
                sides = [test.left, test.comparators[0]]
                has_zero = any(
                    isinstance(s, ast.Constant) and s.value == 0
                    for s in sides
                )
                has_pidx = any(
                    isinstance(s, ast.Call)
                    and (_dotted(s.func) or "").endswith("process_index")
                    for s in sides
                )
                if has_zero and has_pidx:
                    if isinstance(test.ops[0], ast.Eq):
                        return True
                    if isinstance(test.ops[0], ast.NotEq):
                        return False
            if isinstance(test, ast.BoolOp) and isinstance(
                test.op, ast.And
            ):
                for v in test.values:
                    r = test_primary(v)
                    if r is True:
                        return True
            return None

        which = test_primary(stmt.test)
        if which is True:
            return stmt.body
        if which is False and stmt.orelse:
            return stmt.orelse
        if which is False and not stmt.orelse and (
            _trailing_exit(stmt.body) is not None
        ):
            return "rest"  # `if not is_primary(): return` guard form
        return None

    def _arm_has_op(self, stmts, fi: FuncInfo) -> bool:
        return len(self._struct_seq(list(stmts), fi)) > 0

    def _seqs_try(
        self, fi, stmt, ops, rest, lctx, seqs, make_cont
    ) -> _SeqSet:
        final_c = (
            make_cont(stmt.finalbody, 0, lctx, rest)
            if stmt.finalbody else rest
        )
        orelse_c = (
            make_cont(stmt.orelse, 0, lctx, final_c)
            if stmt.orelse else final_c
        )
        body_c = make_cont(stmt.body, 0, lctx, orelse_c)
        body_ops = self._struct_seq(list(stmt.body), fi)
        for handler in stmt.handlers:
            # analyze the handler flow for nested findings (results are
            # not unioned into the main flow: the no-exception path is
            # the protocol path)
            seqs(make_cont(handler.body, 0, lctx, final_c))
            if body_ops and self._broad_handler(handler) and (
                self._handler_swallows(handler)
            ):
                self._report(
                    fi, handler.lineno, "RUN005",
                    f"in '{fi.qualname}': this handler swallows a "
                    f"failure around group op(s) "
                    f"{sorted(set(body_ops))} and proceeds — the "
                    "failing process drops out of lockstep while peers "
                    "wait in the op (re-raise, or exit so the "
                    "supervisor tears the group down)",
                )
        return seqs(body_c).prepend(ops)

    def _broad_handler(self, handler: ast.ExceptHandler) -> bool:
        if handler.type is None:
            return True
        types = (
            handler.type.elts
            if isinstance(handler.type, ast.Tuple) else [handler.type]
        )
        for t in types:
            name = _dotted(t)
            if name is not None and name.rsplit(".", 1)[-1] in _BROAD_EXC:
                return True
        return False

    def _handler_swallows(self, handler: ast.ExceptHandler) -> bool:
        for node in _walk_no_defs_stmts(handler.body):
            if isinstance(node, ast.Raise):
                return False
            if isinstance(node, ast.Call):
                fn = _dotted(node.func) or ""
                tail = fn.rsplit(".", 1)[-1]
                if tail in _NORETURN_CALLS and fn.split(".", 1)[0] in (
                    "sys", "os", tail
                ):
                    return False
        return True

    # RUN006 ----------------------------------------------------------
    def _check_locks(self, fi: FuncInfo) -> None:
        if not self.serving_locks:
            return

        def walk(stmts, held: tuple):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                if held:
                    for expr in _own_exprs(stmt):
                        if expr is None:
                            continue
                        for sub in _walk_no_defs(expr):
                            if isinstance(sub, ast.Call):
                                opseq = self._call_ops(sub, fi)
                                if opseq:
                                    shared = [
                                        t for t in held
                                        if t in self.serving_locks
                                    ]
                                    if shared:
                                        self._report(
                                            fi, stmt.lineno, "RUN006",
                                            f"in '{fi.qualname}': group "
                                            f"op(s) {sorted(set(opseq))} "
                                            "issued while holding lock "
                                            f"'{shared[0]}', which the "
                                            "serving plane also takes — "
                                            "an HTTP handler blocking on "
                                            "it deadlocks against a "
                                            "process parked in the "
                                            "collective",
                                        )
                if isinstance(stmt, ast.With):
                    toks = tuple(
                        t for t in (
                            _is_lock_expr(it.context_expr)
                            for it in stmt.items
                        ) if t
                    )
                    walk(stmt.body, held + toks)
                elif isinstance(stmt, ast.If):
                    walk(stmt.body, held)
                    walk(stmt.orelse, held)
                elif isinstance(stmt, (ast.For, ast.While)):
                    walk(stmt.body, held)
                    walk(stmt.orelse, held)
                elif isinstance(stmt, ast.Try):
                    walk(stmt.body, held)
                    for h in stmt.handlers:
                        walk(h.body, held)
                    walk(stmt.orelse, held)
                    walk(stmt.finalbody, held)

        walk(list(fi.node.body), ())


# --- statement/expression iteration helpers --------------------------------

def _own_exprs(stmt) -> Iterable[Optional[ast.AST]]:
    """The statement's own (non-body) expressions, in evaluation order."""
    if isinstance(stmt, ast.Expr):
        yield stmt.value
    elif isinstance(stmt, ast.Assign):
        yield stmt.value
    elif isinstance(stmt, ast.AnnAssign):
        yield stmt.value
    elif isinstance(stmt, ast.AugAssign):
        yield stmt.value
    elif isinstance(stmt, ast.Return):
        yield stmt.value
    elif isinstance(stmt, ast.Raise):
        yield stmt.exc
        yield stmt.cause
    elif isinstance(stmt, ast.If):
        yield stmt.test
    elif isinstance(stmt, ast.While):
        yield stmt.test
    elif isinstance(stmt, ast.For):
        yield stmt.iter
    elif isinstance(stmt, ast.With):
        for it in stmt.items:
            yield it.context_expr
    elif isinstance(stmt, ast.Assert):
        yield stmt.test
        yield stmt.msg
    elif isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            yield t


def _walk_no_defs(node, skip_root_def: bool = False):
    """ast.walk in DOCUMENT (preorder) order that does not descend into
    nested function/class defs — source order matters for the
    last-write-wins environment."""
    stack = [node]
    first = True
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            if not (first and skip_root_def):
                first = False
                continue
        first = False
        yield n
        stack.extend(reversed(list(ast.iter_child_nodes(n))))


def _walk_no_defs_stmts(stmts):
    for s in stmts:
        yield from _walk_no_defs(s)


def _trailing_exit(stmts) -> Optional[ast.AST]:
    """The exit statement when every path through `stmts` leaves the
    normal flow (return/raise/continue/break); None otherwise."""
    if not stmts:
        return None
    last = stmts[-1]
    if isinstance(last, (ast.Return, ast.Raise, ast.Continue, ast.Break)):
        return last
    if isinstance(last, ast.If) and last.orelse:
        a = _trailing_exit(last.body)
        b = _trailing_exit(last.orelse)
        if a is not None and b is not None:
            return a
    if isinstance(last, ast.With):
        return _trailing_exit(last.body)
    return None


def _exit_kind(stmt) -> str:
    return {
        ast.Return: "return", ast.Raise: "raise",
        ast.Continue: "continue", ast.Break: "break",
    }.get(type(stmt), "exit")


def _diff_ops(a: frozenset, b: frozenset) -> list[str]:
    """Ops appearing in one side's sequences but not the other's — the
    human-readable core of a sequence mismatch."""
    ops_a = {op for s in a for op in s}
    ops_b = {op for s in b for op in s}
    d = sorted(ops_a ^ ops_b)
    if d:
        return d
    return sorted(ops_a | ops_b)


# --- entry points ----------------------------------------------------------

def _load_module(path: str) -> Optional[ModuleInfo]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return ModuleInfo(path, f.read())
    except (OSError, SyntaxError, UnicodeDecodeError):
        return None


def _expand_targets(roots: Sequence[str]) -> list[str]:
    files: list[str] = []
    for r in roots:
        if os.path.isdir(r):
            for base, _dirs, names in os.walk(r):
                files.extend(
                    os.path.join(base, n)
                    for n in sorted(names) if n.endswith(".py")
                )
        elif os.path.isfile(r):
            files.append(r)
    return files


def check_paths(
    paths: Optional[Sequence[str]] = None,
    transport_path: Optional[str] = None,
    serving_paths: Optional[Sequence[str]] = None,
    tracker: Optional[SuppressionTracker] = None,
) -> list[Finding]:
    """Run the RUN-family pass over the protocol surfaces.

    Defaults: `DEFAULT_TARGETS` under the installed package, ops
    discovered from `runtime/coordination.py`, serving-plane locks from
    `DEFAULT_SERVING`. Suppressed findings and consumed annotations are
    recorded on `tracker` for ANA001.
    """
    if paths is None:
        paths = [os.path.join(_PKG_ROOT, t) for t in DEFAULT_TARGETS]
    if serving_paths is None:
        serving_paths = [os.path.join(_PKG_ROOT, t) for t in DEFAULT_SERVING]
    ops = discover_group_ops(transport_path)
    modules = [
        m for m in (_load_module(p) for p in _expand_targets(paths))
        if m is not None
    ]
    serving = [
        m for m in (_load_module(p) for p in _expand_targets(serving_paths))
        if m is not None
    ]
    checker = Checker(
        modules, ops, serving, tracker,
        transport_base=os.path.basename(transport_path or TRANSPORT_PATH),
    )
    return checker.check()


def check_sources(
    sources: dict[str, str],
    transport_path: Optional[str] = None,
    serving_sources: Optional[dict[str, str]] = None,
    tracker: Optional[SuppressionTracker] = None,
) -> list[Finding]:
    """Test hook: run the checker over in-memory sources ({path: src})."""
    ops = discover_group_ops(transport_path)
    modules = [ModuleInfo(p, s) for p, s in sources.items()]
    serving = [
        ModuleInfo(p, s) for p, s in (serving_sources or {}).items()
    ]
    return Checker(
        modules, ops, serving, tracker,
        transport_base=os.path.basename(transport_path or TRANSPORT_PATH),
    ).check()
