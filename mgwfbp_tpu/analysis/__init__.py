"""Static analysis for the MG-WFBP hot path AND the host-side protocol.

Four passes, one CLI (`python -m mgwfbp_tpu.analysis`), cheapest first:

  * `ast_lint` — AST rules for tracing-unsafe Python inside jitted code
    (wall clocks, numpy RNG, host round-trips, Python branches on traced
    values, mutable defaults, telemetry-in-jit). Rule ids JIT000..JIT006.
  * `spmd_check` — the SPMD lockstep checker: statically proves the
    host-side multi-host coordination protocol deadlock-free. Group
    operations are discovered from the ``@group_op`` decorations in
    `runtime/coordination.py`; interprocedural effect signatures +
    a group-uniformity lattice enforce that every process executes the
    identical group-op sequence. Rule ids RUN001..RUN006.
  * ANA001 — annotation accounting (ruff's unused-noqa semantics): a
    suppression or ``group-uniform`` marker that changes nothing, or a
    RUN-family suppression without a reason, is itself an error.
  * `jaxpr_check` — trace the jitted train step on abstract inputs and
    verify the lowered program realizes the merge schedule (group count,
    bucket sizes/dtypes, no stray collectives or host callbacks, buffer
    donation, guard/health footprints). Rule ids SCH001..SCH010; a
    failure to TRACE at all is TRC000 (exit bit 16), distinct from any
    rule violation.

Exit codes are family-stable (rules.FAMILY_BITS): JIT=1, SCH=2, RUN=4,
ANA=8, TRC=16. ``--json`` emits machine-readable findings. Findings
print as ``file:line RULE message``; suppress in-line with
``# graft: noqa[RULE] -- reason``. See README "Static analysis".
"""

from mgwfbp_tpu.analysis.rules import (  # noqa: F401
    ERROR,
    FAMILY_BITS,
    WARNING,
    Finding,
    Rule,
    RULES,
    SuppressionTracker,
    exit_code,
    filter_suppressed,
    has_errors,
    suppressed_ids,
)
from mgwfbp_tpu.analysis.ast_lint import (  # noqa: F401
    lint_file,
    lint_paths,
    lint_source,
)
from mgwfbp_tpu.analysis.spmd_check import (  # noqa: F401
    check_paths,
    check_sources,
    discover_group_ops,
)
from mgwfbp_tpu.analysis.jaxpr_check import (  # noqa: F401
    collect_collectives,
    find_donated,
    iter_eqns,
    trace_train_step,
    verify_jaxpr_against_reducer,
    verify_train_step,
)
