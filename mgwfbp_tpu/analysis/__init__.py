"""Static analysis for the MG-WFBP hot path.

Two passes, one CLI (`python -m mgwfbp_tpu.analysis`):

  * `jaxpr_check` — trace the jitted train step on abstract inputs and
    verify the lowered program realizes the merge schedule (group count,
    bucket sizes/dtypes, no stray collectives or host callbacks, buffer
    donation). Rule ids SCH001..SCH007.
  * `ast_lint` — AST rules for tracing-unsafe Python inside jitted code
    (wall clocks, numpy RNG, host round-trips, Python branches on traced
    values, mutable defaults). Rule ids JIT000..JIT005.

Findings print as ``file:line RULE message``; suppress a lint finding
in-line with ``# graft: noqa[RULE]``. See README "Static analysis".
"""

from mgwfbp_tpu.analysis.rules import (  # noqa: F401
    ERROR,
    WARNING,
    Finding,
    Rule,
    RULES,
    filter_suppressed,
    has_errors,
    suppressed_ids,
)
from mgwfbp_tpu.analysis.ast_lint import (  # noqa: F401
    lint_file,
    lint_paths,
    lint_source,
)
from mgwfbp_tpu.analysis.jaxpr_check import (  # noqa: F401
    collect_collectives,
    find_donated,
    iter_eqns,
    trace_train_step,
    verify_jaxpr_against_reducer,
    verify_train_step,
)
