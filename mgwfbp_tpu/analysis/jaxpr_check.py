"""Jaxpr-level verifier: does the lowered train step realize the schedule?

MG-WFBP's value proposition is that the merge schedule the solver emits is
ACTUALLY issued as N dtype-homogeneous fused collectives overlapping the
backward pass. Nothing at runtime checks that — a refactor of the step, a
jax upgrade, or an overeager XLA pass can silently degrade collective
granularity (the failure mode DeAR, arXiv:2302.12445, documents) while
training still converges. This pass traces the jitted step on ABSTRACT
inputs (`jax.make_jaxpr`; no devices execute anything) and statically
asserts, against the `MergedAllreduce` that built it:

  SCH003  the bucket layout covers every gradient leaf exactly once, with
          dtype-homogeneous groups and consistent offsets
          (`BucketLayout.validate`);
  SCH001  the traced program contains exactly `layout.num_groups` merged
          reduction collectives (matched via the `mgwfbp_groupNNNN` name
          scopes `parallel.allreduce` stamps on them);
  SCH007  each group's collective carries exactly the group's element count;
  SCH002  ... at the layout's bucket dtype (or the comm_dtype wire cast);
  SCH004  no OTHER collective appears outside the declared scopes
          (metrics_reduce / bstats_reduce / flat_grad_reduce) — a stray
          all_gather/all_to_all or an unscoped psum is granularity silently
          leaking away;
  SCH005  no host callbacks / debug prints ride the hot path;
  SCH006  the step donates its input buffers (params/opt-state aliasing —
          without it every step round-trips a full model copy through HBM);
  SCH008  the non-finite-gradient guard (resilience layer) is realized as
          configured: a guard-enabled step must carry the `is_finite`
          reduction feeding the metrics psum (its count rides the EXISTING
          metrics_reduce collective — the guard adds no collective of its
          own, which SCH001/SCH004 already pin), and a guard-disabled step
          must not;
  SCH009  the hierarchical (comm_op='hier') contract: per inner group one
          reduce-scatter then one all-gather over the INNER (ICI) axis
          only, per DCN group exactly one OUTER-axis collective under its
          ``mgwfbp_dcngroupNNNN`` scope moving exactly its members'
          concatenated shards at the wire dtype, the DCN partition
          covering every inner group exactly once, no cross-pod (outer-
          axis) collective anywhere else, and the DCN scope never
          appearing on a non-hier path;
  SCH010  the training-health statistics (ISSUE 12) are FREE at the
          collective layer: tracing the same step with health_stats on
          and off must yield identical collective footprints (same
          collective primitives, same counts — the stats ride the
          EXISTING metrics psum) and zero host callbacks either way. A
          stats build that grows the footprint is a new collective (or a
          host sync) smuggled into the hot path.
"""

from __future__ import annotations

import re
from typing import Any, Iterator, Optional, Sequence

import numpy as np

from mgwfbp_tpu.analysis.rules import Finding

# --- primitive taxonomy (names as of jax 0.4.x; matching is by name so the
# verifier needs no private jax imports) ------------------------------------
REDUCTION_PRIMS = frozenset({"psum", "reduce_scatter", "psum_scatter"})
OTHER_COLLECTIVE_PRIMS = frozenset({
    "all_gather", "all_to_all", "pmax", "pmin", "ppermute", "pgather",
})
COLLECTIVE_PRIMS = REDUCTION_PRIMS | OTHER_COLLECTIVE_PRIMS
CALLBACK_PRIMS = frozenset({
    "debug_callback", "pure_callback", "io_callback", "outside_call",
    "host_callback_call", "python_callback",
})

# scopes the train step declares for its OWN auxiliary collectives
# (train/step.py); anything else collective-shaped must be a merge group.
# "sharded_clip_norm" is the rs_opt_ag lowering's one cross-group psum of
# shard squared norms (global-norm clipping while every bucket is
# scattered) — parallel/allreduce.py CLIP_NORM_SCOPE, keep in sync.
# "runtime_coord" is the multi-host runtime's agreement psum/pmax
# (runtime/coordination.py COORD_SCOPE, keep in sync): today those run as
# standalone host-decision programs, but a step that ever traces one in
# stays verifier-clean by declaration instead of tripping SCH004.
DEFAULT_ALLOWED_SCOPES = (
    "metrics_reduce", "bstats_reduce", "flat_grad_reduce",
    "sharded_clip_norm", "runtime_coord",
)


def _group_scope_re() -> "re.Pattern[str]":
    """Regex for the merge-group scope, derived from the prefix constant
    `parallel.allreduce` stamps (import deferred: the lint-only CLI path
    must not pull jax in through this module)."""
    from mgwfbp_tpu.parallel.allreduce import GROUP_SCOPE_PREFIX

    return re.compile(re.escape(GROUP_SCOPE_PREFIX) + r"(\d+)")


def _dcn_scope_re() -> "re.Pattern[str]":
    """Regex for the hier lowering's DCN-group scope
    (`parallel.allreduce.DCN_GROUP_SCOPE_PREFIX`)."""
    from mgwfbp_tpu.parallel.allreduce import DCN_GROUP_SCOPE_PREFIX

    return re.compile(re.escape(DCN_GROUP_SCOPE_PREFIX) + r"(\d+)")


def _eqn_axes(eqn: Any) -> tuple:
    """Named mesh axes a collective eqn reduces/gathers over (psum and
    psum_scatter carry `axes`, all_gather `axis_name`); empty when the
    param shape is unrecognized."""
    for key in ("axes", "axis_name"):
        v = eqn.params.get(key)
        if v is None:
            continue
        if isinstance(v, str):
            return (v,)
        try:
            return tuple(a for a in v if isinstance(a, str))
        except TypeError:
            return ()
    return ()


def _scope_segments(scope: str) -> list[str]:
    """Name-stack entries of a rendered scope string, transformation
    wrappers stripped: 'transpose(jvp(metrics_reduce))/foo' ->
    ['metrics_reduce', 'foo']. Segment-exact matching keeps a scope like
    'extra_metrics_reduce_v2' from whitelisting stray collectives."""
    out = []
    for seg in scope.split("/"):
        while True:
            m = re.fullmatch(r"\w+\((.*)\)", seg)
            if m is None:
                break
            seg = m.group(1)
        out.append(seg)
    return out


def iter_eqns(jaxpr: Any) -> Iterator[Any]:
    """Depth-first walk of a jaxpr's eqns, recursing into every sub-jaxpr
    found in eqn params (pjit/shard_map/scan/cond/custom_* all carry their
    bodies under different param keys; duck-type instead of enumerating)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from iter_eqns(sub)


def _sub_jaxprs(v: Any) -> Iterator[Any]:
    if hasattr(v, "eqns"):  # core.Jaxpr
        yield v
    elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):  # ClosedJaxpr
        yield v.jaxpr
    elif isinstance(v, (tuple, list)):
        for u in v:
            yield from _sub_jaxprs(u)


def _scope_of(eqn: Any) -> str:
    try:
        return str(eqn.source_info.name_stack)
    except Exception:
        return ""


def _numel(aval: Any) -> int:
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    return n


def collect_collectives(closed_jaxpr: Any) -> dict[str, list]:
    """Classify every collective/callback eqn in the traced program.

    Returns {"groups": {gi: [eqn, ...]}, "dcn_groups": {di: [eqn, ...]},
    "allowed": [...], "stray": [...], "callbacks": [...]} where group
    membership comes from the `mgwfbp_groupNNNN` (and, for the hier
    lowering's outer collectives, `mgwfbp_dcngroupNNNN`) name scopes
    stamped by `parallel.allreduce`.
    """
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    group_re = _group_scope_re()
    dcn_re = _dcn_scope_re()
    groups: dict[int, list] = {}
    dcn_groups: dict[int, list] = {}
    allowed: list = []
    stray: list = []
    callbacks: list = []
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name in CALLBACK_PRIMS:
            callbacks.append(eqn)
            continue
        if name not in COLLECTIVE_PRIMS:
            continue
        scope = _scope_of(eqn)
        dm = dcn_re.search(scope)
        m = group_re.search(scope)
        if dm is not None:
            dcn_groups.setdefault(int(dm.group(1)), []).append(eqn)
        elif m is not None:
            groups.setdefault(int(m.group(1)), []).append(eqn)
        elif any(
            seg in DEFAULT_ALLOWED_SCOPES for seg in _scope_segments(scope)
        ):
            allowed.append(eqn)
        else:
            stray.append(eqn)
    return {
        "groups": groups, "dcn_groups": dcn_groups, "allowed": allowed,
        "stray": stray, "callbacks": callbacks,
    }


def find_donated(closed_jaxpr: Any) -> Optional[tuple[bool, ...]]:
    """donated_invars of the outermost pjit eqn, or None when untraceable."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pjit":
            d = eqn.params.get("donated_invars")
            if d is not None:
                return tuple(bool(x) for x in d)
    return None


def _check_rs_opt_ag_group(reducer: Any, gi: int, eqns: list, add) -> None:
    """The rs_opt_ag per-group collective contract: exactly ONE
    reduce-scatter (the padded grad bucket, at the wire dtype) and ONE
    all-gather (the UPDATED param shard, 1/world of the padded bucket, at
    the bucket dtype) under the group's scope — nothing else. A second
    reduction, a missing gather, or a full-bucket gather operand all mean
    the sharded-update seam silently degenerated (e.g. back to gathering
    gradients, or to a replicated update)."""
    layout = reducer.layout
    optim = reducer.optim
    comm_dtype = getattr(reducer, "comm_dtype", None)
    reductions = [e for e in eqns if e.primitive.name in REDUCTION_PRIMS]
    gathers = [e for e in eqns if e.primitive.name == "all_gather"]
    extra = [e for e in eqns if e not in reductions and e not in gathers]
    if len(reductions) != 1 or len(gathers) != 1:
        add("SCH001",
            f"rs_opt_ag group {gi}: expected exactly 1 reduce-scatter + 1 "
            f"all-gather under its scope, found {len(reductions)} "
            f"reduction(s) + {len(gathers)} gather(s)")
        return
    for e in extra:
        add("SCH004",
            f"rs_opt_ag group {gi}: unexpected '{e.primitive.name}' in "
            "the group scope")
    padded = optim.padded_size(gi)
    shard = optim.shard_size(gi)
    rs, ag = reductions[0], gathers[0]
    rs_elems = _numel(rs.invars[0].aval)
    if rs_elems != padded:
        add("SCH007",
            f"rs_opt_ag group {gi}: reduce-scatter moves {rs_elems} "
            f"elements, padded bucket is {padded}")
    ag_elems = _numel(ag.invars[0].aval)
    if ag_elems != shard:
        add("SCH007",
            f"rs_opt_ag group {gi}: all-gather operand is {ag_elems} "
            f"elements, the 1/world shard is {shard}")
    want_wire = comm_dtype if comm_dtype is not None else layout.dtypes[gi]
    if np.dtype(rs.invars[0].aval.dtype) != np.dtype(want_wire):
        add("SCH002",
            f"rs_opt_ag group {gi}: reduce-scatter runs at dtype "
            f"{np.dtype(rs.invars[0].aval.dtype).name}, wire dtype is "
            f"{np.dtype(want_wire).name}")
    if np.dtype(ag.invars[0].aval.dtype) != np.dtype(layout.dtypes[gi]):
        add("SCH002",
            f"rs_opt_ag group {gi}: param all-gather runs at dtype "
            f"{np.dtype(ag.invars[0].aval.dtype).name}, bucket dtype is "
            f"{np.dtype(layout.dtypes[gi]).name}")


def _check_rs_fwd_ag_group(reducer: Any, gi: int, eqns: list, add) -> None:
    """The cross-step per-group collective contract, per STEP: exactly ONE
    all-gather (the carried param shard, 1/world of the padded bucket, at
    the bucket dtype — the PREVIOUS step's deferred gather landing in this
    step's forward) followed, later in the program, by exactly ONE
    reduce-scatter (the padded grad bucket, at the wire dtype) whose
    updated shard carries out to the NEXT step. `eqns` preserves program
    order (iter_eqns walks the jaxpr depth-first in sequence), so
    AG-before-RS is exactly 'the gather sits in the forward region, the
    scatter in the backward' — an in-step RS..AG pair (the rs_opt_ag
    shape, i.e. the deferral silently degenerated) fails the order
    check."""
    layout = reducer.layout
    optim = reducer.optim
    comm_dtype = getattr(reducer, "comm_dtype", None)
    reductions = [e for e in eqns if e.primitive.name in REDUCTION_PRIMS]
    gathers = [e for e in eqns if e.primitive.name == "all_gather"]
    extra = [e for e in eqns if e not in reductions and e not in gathers]
    if len(reductions) != 1 or len(gathers) != 1:
        add("SCH001",
            f"rs_fwd_ag group {gi}: expected exactly 1 all-gather + 1 "
            f"reduce-scatter under its scope per step, found "
            f"{len(gathers)} gather(s) + {len(reductions)} reduction(s)")
        return
    for e in extra:
        add("SCH004",
            f"rs_fwd_ag group {gi}: unexpected '{e.primitive.name}' in "
            "the group scope")
    rs, ag = reductions[0], gathers[0]
    if eqns.index(ag) > eqns.index(rs):
        add("SCH004",
            f"rs_fwd_ag group {gi}: the all-gather follows the "
            "reduce-scatter in program order — the gather was NOT "
            "deferred across the step boundary (this is the in-step "
            "rs_opt_ag shape)")
    padded = optim.padded_size(gi)
    shard = optim.shard_size(gi)
    rs_elems = _numel(rs.invars[0].aval)
    if rs_elems != padded:
        add("SCH007",
            f"rs_fwd_ag group {gi}: reduce-scatter moves {rs_elems} "
            f"elements, padded bucket is {padded}")
    ag_elems = _numel(ag.invars[0].aval)
    if ag_elems != shard:
        add("SCH007",
            f"rs_fwd_ag group {gi}: all-gather operand is {ag_elems} "
            f"elements, the carried 1/world shard is {shard}")
    want_wire = comm_dtype if comm_dtype is not None else layout.dtypes[gi]
    if np.dtype(rs.invars[0].aval.dtype) != np.dtype(want_wire):
        add("SCH002",
            f"rs_fwd_ag group {gi}: reduce-scatter runs at dtype "
            f"{np.dtype(rs.invars[0].aval.dtype).name}, wire dtype is "
            f"{np.dtype(want_wire).name}")
    if np.dtype(ag.invars[0].aval.dtype) != np.dtype(layout.dtypes[gi]):
        add("SCH002",
            f"rs_fwd_ag group {gi}: param all-gather runs at dtype "
            f"{np.dtype(ag.invars[0].aval.dtype).name}, bucket dtype is "
            f"{np.dtype(layout.dtypes[gi]).name}")


def _check_hier_group(
    reducer: Any, gi: int, eqns: list, add
) -> Optional[int]:
    """The hier per-inner-group collective contract: exactly ONE
    reduce-scatter (the padded grad bucket at the wire dtype) followed by
    ONE all-gather (the slice shard, post-DCN) under the group's scope —
    both over the INNER (ICI) axis only. A cross-pod (outer-axis)
    collective inside a group scope means the lowering silently routed
    bucket traffic over the slow link the schedule never priced; AG
    before RS means the leg order degenerated. Returns the group's shard
    element count (the DCN contract's payload unit), or None when the
    shape is too broken to measure."""
    layout = reducer.layout
    comm_dtype = getattr(reducer, "comm_dtype", None)
    inner = reducer.axis_name[0]
    outer = reducer.axis_name[1] if len(reducer.axis_name) > 1 else None
    for e in eqns:
        axes = _eqn_axes(e)
        if outer is not None and outer in axes:
            add("SCH009",
                f"hier group {gi}: '{e.primitive.name}' over the OUTER "
                f"(DCN) axis {outer!r} inside an inner-group scope — "
                "cross-pod traffic belongs under mgwfbp_dcngroupNNNN")
    reductions = [e for e in eqns if e.primitive.name in REDUCTION_PRIMS]
    gathers = [e for e in eqns if e.primitive.name == "all_gather"]
    extra = [e for e in eqns if e not in reductions and e not in gathers]
    if len(reductions) != 1 or len(gathers) != 1:
        add("SCH001",
            f"hier group {gi}: expected exactly 1 reduce-scatter + 1 "
            f"all-gather under its scope, found {len(reductions)} "
            f"reduction(s) + {len(gathers)} gather(s)")
        return None
    for e in extra:
        add("SCH004",
            f"hier group {gi}: unexpected '{e.primitive.name}' in the "
            "group scope")
    rs, ag = reductions[0], gathers[0]
    if eqns.index(ag) < eqns.index(rs):
        add("SCH009",
            f"hier group {gi}: the all-gather precedes the reduce-scatter "
            "in program order — the inner RS -> outer AR -> inner AG leg "
            "order degenerated")
    for e, leg in ((rs, "reduce-scatter"), (ag, "all-gather")):
        axes = _eqn_axes(e)
        if axes and tuple(axes) != (inner,):
            add("SCH009",
                f"hier group {gi}: {leg} runs over axes {axes}, the inner "
                f"leg must ride {inner!r} only")
    want_elems = layout.group_sizes[gi]
    rs_elems = _numel(rs.invars[0].aval)
    if rs_elems < want_elems:
        add("SCH007",
            f"hier group {gi}: reduce-scatter moves {rs_elems} elements, "
            f"layout says >= {want_elems}")
    shard_elems = _numel(rs.outvars[0].aval)
    ag_elems = _numel(ag.invars[0].aval)
    if ag_elems != shard_elems:
        add("SCH007",
            f"hier group {gi}: all-gather operand is {ag_elems} elements, "
            f"the inner shard is {shard_elems}")
    want_wire = comm_dtype if comm_dtype is not None else layout.dtypes[gi]
    for e, leg in ((rs, "reduce-scatter"), (ag, "all-gather")):
        if np.dtype(e.invars[0].aval.dtype) != np.dtype(want_wire):
            add("SCH002",
                f"hier group {gi}: {leg} runs at dtype "
                f"{np.dtype(e.invars[0].aval.dtype).name}, wire dtype is "
                f"{np.dtype(want_wire).name}")
    return shard_elems


def _check_hier_dcn(
    reducer: Any, info: dict, shard_elems: dict, add
) -> None:
    """The hier DCN contract (SCH009): the nested partition covers every
    inner group exactly once, each DCN group issues exactly ONE psum over
    the OUTER axis moving exactly its members' concatenated shards at the
    wire dtype — no more DCN collectives than the schedule promised
    (merging on DCN exists to amortize the slow link's startup; a split
    the verifier misses silently doubles it)."""
    from mgwfbp_tpu.parallel.solver import check_dcn_partition

    layout = reducer.layout
    schedule = reducer.schedule
    comm_dtype = getattr(reducer, "comm_dtype", None)
    inner = reducer.axis_name[0]
    outer = reducer.axis_name[1] if len(reducer.axis_name) > 1 else None
    dcn_part = [list(d) for d in schedule.dcn_groups] or [
        [gi] for gi in range(layout.num_groups)
    ]
    try:
        check_dcn_partition(dcn_part, layout.num_groups)
    except ValueError as e:
        add("SCH009", f"hier: {e}")
        return
    observed = info["dcn_groups"]
    if sorted(observed) != list(range(len(dcn_part))):
        add("SCH009",
            f"hier: traced step issues DCN collectives for scopes "
            f"{sorted(observed)}, the nested schedule promises "
            f"{len(dcn_part)} DCN group(s)")
        return
    for di, members in enumerate(dcn_part):
        eqns = observed[di]
        if len(eqns) != 1 or eqns[0].primitive.name != "psum":
            add("SCH009",
                f"hier dcn group {di}: expected exactly 1 outer-axis psum "
                f"under its scope, found "
                f"{[e.primitive.name for e in eqns]}")
            continue
        eqn = eqns[0]
        axes = _eqn_axes(eqn)
        if axes and (
            (outer is not None and tuple(axes) != (outer,))
            or inner in axes
        ):
            add("SCH009",
                f"hier dcn group {di}: psum runs over axes {axes}, the "
                f"cross-slice leg must ride {outer!r} only")
        want = sum(
            shard_elems.get(gi) or 0 for gi in members
        )
        got = _numel(eqn.invars[0].aval)
        if all(shard_elems.get(gi) for gi in members) and got != want:
            add("SCH009",
                f"hier dcn group {di}: outer collective moves {got} "
                f"elements, members {members} shard to {want}")
        dtypes = {layout.dtypes[gi] for gi in members}
        want_wire = comm_dtype if comm_dtype is not None else (
            next(iter(dtypes)) if len(dtypes) == 1 else None
        )
        if want_wire is not None and (
            np.dtype(eqn.invars[0].aval.dtype) != np.dtype(want_wire)
        ):
            add("SCH009",
                f"hier dcn group {di}: outer collective runs at dtype "
                f"{np.dtype(eqn.invars[0].aval.dtype).name}, wire dtype "
                f"is {np.dtype(want_wire).name}")


def verify_jaxpr_against_reducer(
    closed_jaxpr: Any,
    reducer: Any,
    grad_leaves: Sequence[Any],
    *,
    expect_donation: bool = True,
    expect_finite_guard: Optional[bool] = None,
    file: str = "<traced step>",
) -> list[Finding]:
    """Check the MG-WFBP invariants of a traced step against its reducer.

    closed_jaxpr: `jax.make_jaxpr(step)(...)` output for the jitted step.
    reducer: the `MergedAllreduce` the step was built with.
    grad_leaves: gradient-leaf avals in ARRIVAL order (i.e. the layout's
        leaf order — `[leaves[j] for j in reducer.perm]`).
    expect_finite_guard: None skips the SCH008 check; True/False asserts
        the traced program does/does not realize the non-finite-gradient
        guard (matched via the `finite_check`-scoped `is_finite` eqns).
    """
    layout = reducer.layout
    schedule = reducer.schedule
    out: list[Finding] = []

    def add(rule_id: str, msg: str) -> None:
        out.append(Finding(file, 0, rule_id, msg))

    # --- structural pass: layout vs leaves (SCH003) ------------------------
    for problem in layout.validate(grad_leaves):
        add("SCH003", problem)
    if layout.num_groups != schedule.num_groups:
        add("SCH003",
            f"layout has {layout.num_groups} groups but the schedule "
            f"promises {schedule.num_groups}")

    # --- lowered program vs layout -----------------------------------------
    info = collect_collectives(closed_jaxpr)
    groups = info["groups"]
    if len(groups) != layout.num_groups:
        add("SCH001",
            f"traced step issues {len(groups)} merged collectives, "
            f"schedule promises {layout.num_groups}")
    comm_dtype = getattr(reducer, "comm_dtype", None)
    comm_op = getattr(reducer, "comm_op", "all_reduce")
    # the hier/rs_ag lowerings pad buckets to scatter-axis divisibility, so
    # their payload check is >=; the monolithic all-reduce is exact; a
    # sparsifying compressor moves k <= n elements chosen at trace time, so
    # no static payload expectation exists and the size check is skipped
    padded = comm_op != "all_reduce"
    sparsified = getattr(reducer, "compressor", None) is not None
    hier_shards: dict[int, Optional[int]] = {}
    for gi in sorted(groups):
        if gi >= layout.num_groups:
            add("SCH001",
                f"collective scoped to group {gi} but the layout only has "
                f"{layout.num_groups} groups")
            continue
        if comm_op == "rs_opt_ag":
            _check_rs_opt_ag_group(reducer, gi, groups[gi], add)
            continue
        if comm_op == "rs_fwd_ag":
            _check_rs_fwd_ag_group(reducer, gi, groups[gi], add)
            continue
        if comm_op == "hier":
            hier_shards[gi] = _check_hier_group(reducer, gi, groups[gi], add)
            continue
        eqn = groups[gi][0]  # primary reduction (rs_ag/hier add gathers)
        aval = eqn.invars[0].aval
        want_elems = layout.group_sizes[gi]
        got_elems = _numel(aval)
        ok = sparsified or (
            got_elems >= want_elems if padded else got_elems == want_elems
        )
        if not ok:
            add("SCH007",
                f"group {gi} collective moves {got_elems} elements, layout "
                f"says {want_elems}")
        want_dtype = comm_dtype if comm_dtype is not None else (
            layout.dtypes[gi]
        )
        if np.dtype(aval.dtype) != np.dtype(want_dtype):
            add("SCH002",
                f"group {gi} collective runs at dtype "
                f"{np.dtype(aval.dtype).name}, layout bucket is "
                f"{np.dtype(want_dtype).name}")

    # the DCN-group scope is the hier lowering's alone: on any other path
    # a collective hiding under it is scope abuse (SCH009), exactly like
    # the clip-norm scope below — and on the hier path the full nested
    # contract applies (count/payload/dtype per DCN group)
    if comm_op == "hier":
        _check_hier_dcn(reducer, info, hier_shards, add)
    else:
        for di in sorted(info["dcn_groups"]):
            for eqn in info["dcn_groups"][di]:
                add("SCH009",
                    f"'{eqn.primitive.name}' under scope "
                    f"mgwfbp_dcngroup{di:04d} but comm_op is {comm_op!r} "
                    "(scope reserved for the hierarchical lowering)")
    for eqn in info["stray"]:
        add("SCH004",
            f"unexpected '{eqn.primitive.name}' outside declared scopes "
            f"(scope: {_scope_of(eqn) or '<none>'})")
    # the sharded_clip_norm scope is not a blanket whitelist: it exists
    # only for the sharded-update lowerings (rs_opt_ag / rs_fwd_ag), and
    # there its contract is exactly one psum of the shard squared norms —
    # and only when the spec clips
    clip_eqns = [
        e for e in info["allowed"]
        if "sharded_clip_norm" in _scope_segments(_scope_of(e))
    ]
    if comm_op not in ("rs_opt_ag", "rs_fwd_ag"):
        for eqn in clip_eqns:
            add("SCH004",
                f"'{eqn.primitive.name}' under scope sharded_clip_norm "
                f"but comm_op is {comm_op!r} (scope reserved for the "
                "sharded-update lowerings)")
    else:
        clips = getattr(reducer.optim.spec, "norm_clip", None) is not None
        for eqn in clip_eqns:
            if eqn.primitive.name != "psum":
                add("SCH004",
                    f"'{eqn.primitive.name}' under scope sharded_clip_norm "
                    "(only the clip-norm psum belongs there)")
        psums = [e for e in clip_eqns if e.primitive.name == "psum"]
        want = 1 if clips else 0
        if len(psums) != want:
            add("SCH004",
                f"sharded_clip_norm scope carries {len(psums)} psum(s); "
                f"the spec (norm_clip="
                f"{getattr(reducer.optim.spec, 'norm_clip', None)!r}) "
                f"calls for exactly {want}")
    for eqn in info["callbacks"]:
        add("SCH005",
            f"host callback '{eqn.primitive.name}' in the hot path "
            f"(scope: {_scope_of(eqn) or '<none>'})")

    if expect_donation:
        donated = find_donated(closed_jaxpr)
        if donated is None or not any(donated):
            add("SCH006",
                "no donated input buffers on the jitted step "
                "(params/opt-state copy every iteration)")

    if expect_finite_guard is not None:
        jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
        finite_eqns = [
            e for e in iter_eqns(jaxpr)
            if e.primitive.name == "is_finite"
            and "finite_check" in _scope_segments(_scope_of(e))
        ]
        if expect_finite_guard and not finite_eqns:
            add("SCH008",
                "step built with the non-finite-gradient guard but the "
                "traced program carries no finite_check-scoped is_finite "
                "reduction — the guard silently compiled away")
        if not expect_finite_guard and finite_eqns:
            add("SCH008",
                f"guard disabled but {len(finite_eqns)} finite_check-"
                "scoped is_finite eqn(s) remain in the hot path")
    return out


def collective_footprint(closed_jaxpr: Any) -> dict[str, int]:
    """Collective/callback primitive counts of a traced program — the
    SCH010 comparison unit. Counting by primitive NAME (not scope) makes
    the footprint insensitive to where the stats sit in the program and
    sensitive to exactly what the rule forbids: any additional
    collective or host callback."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    counts: dict[str, int] = {}
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS or name in CALLBACK_PRIMS:
            counts[name] = counts.get(name, 0) + 1
    return counts


def compare_collective_footprints(
    base: Any,
    stats: Any,
    *,
    file: str = "<health-stats trace>",
) -> list[Finding]:
    """SCH010: the stats-on program's collective footprint must equal the
    stats-off program's, and neither may carry a host callback. `base`
    and `stats` are the two traced programs (`jax.make_jaxpr` output)."""
    out: list[Finding] = []

    def add(rule_id: str, msg: str) -> None:
        out.append(Finding(file, 0, rule_id, msg))

    fp_base = collective_footprint(base)
    fp_stats = collective_footprint(stats)
    for prim in sorted(set(fp_base) | set(fp_stats)):
        b, s = fp_base.get(prim, 0), fp_stats.get(prim, 0)
        if prim in CALLBACK_PRIMS:
            if s or b:
                add("SCH005",
                    f"host callback '{prim}' in the hot path "
                    f"(stats-off x{b}, stats-on x{s})")
            continue
        if s > b:
            add("SCH010",
                f"health statistics added {s - b} '{prim}' "
                f"collective(s) ({b} -> {s}) — the stats must ride the "
                "EXISTING metrics psum, not new collectives")
        elif s < b:
            add("SCH010",
                f"health statistics REMOVED {b - s} '{prim}' "
                f"collective(s) ({b} -> {s}) — the stats build no longer "
                "realizes the same schedule as the plain step")
    return out


def verify_health_stats_footprint(
    model_name: str = "lenet",
    policy: str = "mgwfbp",
    *,
    comm_op: str = "all_reduce",
) -> list[Finding]:
    """Trace one representative step with health statistics off and on
    and apply SCH010. The rs_fwd_ag lowering compares its two-step
    programs (the deferred gathers live across the boundary)."""
    kw: dict[str, Any] = dict(comm_op=comm_op)
    if comm_op in ("rs_opt_ag", "rs_fwd_ag"):
        kw["norm_clip"] = 1.0
    if comm_op == "rs_fwd_ag":
        kw["steps"] = 2
    base, _, _ = trace_train_step(model_name, policy, **kw)
    stats, _, _ = trace_train_step(
        model_name, policy, health_stats=True, **kw
    )
    return compare_collective_footprints(
        base, stats,
        file=f"<health-stats {model_name}/{policy}/{comm_op}>",
    )


# ---------------------------------------------------------------------------
# Self-contained verification target: build a representative train step and
# check it. Used by the CLI and by the analyzer's own clean-on-HEAD test.
# ---------------------------------------------------------------------------

def _ensure_cpu_devices(n: int = 8) -> None:
    """Force an n-device virtual CPU platform if jax has not initialized yet
    (tracing needs a mesh, not real hardware)."""
    from mgwfbp_tpu.utils.platform import (
        already_initialized_platforms,
        apply_platform_overrides,
        force_host_device_count,
    )

    if already_initialized_platforms():
        return  # too late to change; use whatever devices exist
    force_host_device_count(n)
    apply_platform_overrides("cpu")


def trace_train_step(
    model_name: str = "lenet",
    policy: str = "mgwfbp",
    *,
    comm_op: str = "all_reduce",
    comm_dtype: Any = None,
    donate: bool = True,
    batch_size: int = 16,
    norm_clip: Optional[float] = None,
    grad_guard: bool = True,
    steps: int = 1,
    dcn_slices: Optional[int] = None,
    dcn_groups: Optional[Any] = None,
    health_stats: bool = False,
) -> tuple[Any, Any, list]:
    """Build and trace a representative jitted MG-WFBP train step.

    health_stats traces the ISSUE-12 training-health-statistics build —
    `verify_health_stats_footprint` compares it against the plain trace
    (rule SCH010: the stats may not change the collective footprint).

    Returns (closed_jaxpr, reducer, grad_leaves_in_arrival_order) — the
    exact inputs `verify_jaxpr_against_reducer` wants. Tracing only: state
    is built with `jax.eval_shape`, the batch is ShapeDtypeStructs, nothing
    executes on any device. Exposed separately from `verify_train_step` so
    the analyzer's mutation tests can verify a REAL traced program against
    a deliberately doctored expectation.

    comm_op='rs_opt_ag' traces the sharded-optimizer path (opt state as
    1/world shard buffers, params gathered post-update); norm_clip then
    additionally exercises the cross-group clip psum. comm_op='rs_fwd_ag'
    carries params as cross-step shards (`params_struct`).

    steps > 1 chains that many consecutive jitted step calls with the
    carried state threaded through — one top-level pjit eqn per call,
    which is what `verify_cross_step_jaxpr` splits on (steps=2 is the
    cross-step two-step contract's program).

    comm_op='hier' traces on an (ici, dcn)-shaped virtual mesh
    (`dcn_slices` outer slices; default 2) under a two-level cost model
    with a deliberately slow DCN link, so the nested-schedule machinery
    is exercised, not just the single-link fallback; `dcn_groups`
    optionally pins an explicit DCN partition (the mutation tests' hook).
    """
    _ensure_cpu_devices()
    import jax
    import jax.numpy as jnp

    from mgwfbp_tpu import models as zoo
    from mgwfbp_tpu.optim import OptimSpec
    from mgwfbp_tpu.parallel.allreduce import make_merged_allreduce
    from mgwfbp_tpu.parallel.costmodel import AlphaBeta, TwoLevelAlphaBeta
    from mgwfbp_tpu.parallel.mesh import (
        DATA_AXIS,
        DCN_AXIS,
        MeshSpec,
        make_mesh,
    )
    from mgwfbp_tpu.train.step import create_train_state, make_train_step

    if comm_op == "hier" and not dcn_slices:
        dcn_slices = 2
    dcn = int(dcn_slices or 1)
    mesh = make_mesh(
        MeshSpec(data=len(jax.devices()) // dcn, seq=1, dcn=dcn)
    )
    axis_name: Any = (
        (DATA_AXIS, DCN_AXIS) if dcn > 1 else DATA_AXIS
    )
    model, meta = zoo.create_model(model_name)
    spec = OptimSpec(lr=0.1, kind="sgd", momentum=0.9, norm_clip=norm_clip)
    tx = spec.make_tx()
    # abstract state: full init math traced, nothing executed
    state = jax.eval_shape(
        lambda: create_train_state(
            jax.random.PRNGKey(0), model, jnp.zeros((1,) + meta.input_shape),
            tx,
        )
    )
    full_params = state.params  # canonical tree (pre any sharded carry)
    kw: dict[str, Any] = {}
    if policy in ("mgwfbp", "auto"):
        if comm_op == "hier":
            # slow-DCN two-level prior: the nested solve must actually
            # price two links here, or the hier contract only ever sees
            # the degenerate one-DCN-collective-per-group shape
            kw = dict(cost_model=TwoLevelAlphaBeta(
                ici=AlphaBeta(1e-5, 2e-11),
                dcn=AlphaBeta(2.5e-3, 6e-10),
                ici_size=len(jax.devices()) // dcn,
                dcn_size=dcn,
            ))
        else:
            kw = dict(cost_model=AlphaBeta(1e-4, 1e-9))
    if comm_op in ("rs_opt_ag", "rs_fwd_ag"):
        kw.update(optim_spec=spec, world_size=len(jax.devices()))
    if dcn_groups is not None:
        kw.update(dcn_groups=dcn_groups)
    reducer = make_merged_allreduce(
        state.params, axis_name=axis_name, policy=policy,
        comm_dtype=comm_dtype, comm_op=comm_op, **kw,
    )
    if comm_op in ("rs_opt_ag", "rs_fwd_ag"):
        state = state.replace(
            opt_state=jax.eval_shape(reducer.optim.init)
        )
    if comm_op == "rs_fwd_ag":
        # params ride as the cross-step sharded carry
        state = state.replace(params=reducer.optim.params_struct())
    step = make_train_step(
        model, meta, tx, mesh, reducer, axis_name=axis_name,
        donate=donate, grad_guard=grad_guard, health_stats=health_stats,
    )
    batch = {
        "x": jax.ShapeDtypeStruct(
            (1, batch_size) + meta.input_shape, jnp.float32
        ),
        "y": jax.ShapeDtypeStruct((1, batch_size), jnp.int32),
    }
    if steps == 1:
        closed = jax.make_jaxpr(step)(state, batch)
    else:
        def chained(state, *batches):
            metrics = None
            for b in batches:
                state, metrics = step(state, b)
            return state, metrics

        closed = jax.make_jaxpr(chained)(state, *([batch] * steps))
    leaves = jax.tree_util.tree_leaves(full_params)
    arr = [leaves[j] for j in reducer.perm]
    return closed, reducer, arr


def step_subjaxprs(closed_jaxpr: Any) -> list:
    """Top-level pjit eqns of a multi-step trace, program order — one per
    jitted step call (the step boundary marker the cross-step verifier
    splits on; named scopes cannot mark it, because pjit caches the first
    call's trace and would stamp both steps with the first scope)."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    return [e for e in jaxpr.eqns if e.primitive.name == "pjit"]


def verify_cross_step_jaxpr(
    closed_jaxpr: Any,
    reducer: Any,
    grad_leaves: Sequence[Any],
    *,
    expect_donation: bool = True,
    expect_finite_guard: Optional[bool] = None,
    file: str = "<cross-step trace>",
) -> list[Finding]:
    """The TWO-STEP contract of the rs_fwd_ag lowering (ISSUE 7).

    closed_jaxpr must trace two CONSECUTIVE jitted steps with the carried
    state threaded through (`trace_cross_step`). Each step is verified
    against the reducer independently (SCH001/2/3/7 via the rs_fwd_ag
    group contract, SCH004 strays, SCH005 callbacks, SCH008 finite
    guard), which pins exactly the cross-step shape: within EVERY step,
    each group's all-gather sits in the forward region (before its
    reduce-scatter in program order) and consumes the carried shard the
    PREVIOUS step's reduce-scatter + update produced — the carry is the
    only dataflow path between the two pjit calls, so full per-step
    coverage + in-step ordering IS 'RS in step N, AG in step N+1's
    forward, no strays'. Donation is checked per step call (SCH006)."""
    out: list[Finding] = []
    steps = step_subjaxprs(closed_jaxpr)
    if len(steps) != 2:
        out.append(Finding(
            file, 0, "SCH001",
            f"cross-step trace carries {len(steps)} jitted step call(s); "
            "the two-step contract needs exactly 2",
        ))
        return out
    for si, eqn in enumerate(steps):
        sub = eqn.params.get("jaxpr")
        findings = verify_jaxpr_against_reducer(
            sub, reducer, grad_leaves,
            expect_donation=False,  # donation lives on the pjit eqn here
            expect_finite_guard=expect_finite_guard,
            file=f"{file}#step{si}",
        )
        out.extend(findings)
        if expect_donation:
            donated = eqn.params.get("donated_invars")
            if donated is None or not any(donated):
                out.append(Finding(
                    f"{file}#step{si}", 0, "SCH006",
                    "no donated input buffers on the jitted step "
                    "(params/opt-state copy every iteration)",
                ))
    return out


def trace_cross_step(
    model_name: str = "lenet",
    policy: str = "mgwfbp",
    *,
    comm_dtype: Any = None,
    donate: bool = True,
    batch_size: int = 16,
    norm_clip: Optional[float] = None,
    grad_guard: bool = True,
) -> tuple[Any, Any, list]:
    """Trace TWO consecutive jitted rs_fwd_ag train steps with the carried
    state threaded through — the two-step program `verify_cross_step_jaxpr`
    checks. Thin alias of `trace_train_step(..., comm_op='rs_fwd_ag',
    steps=2)` so the trace protocol has exactly one owner."""
    return trace_train_step(
        model_name, policy, comm_op="rs_fwd_ag", comm_dtype=comm_dtype,
        donate=donate, batch_size=batch_size, norm_clip=norm_clip,
        grad_guard=grad_guard, steps=2,
    )


def verify_cross_step_train_step(
    model_name: str = "lenet",
    policy: str = "mgwfbp",
    *,
    comm_dtype: Any = None,
    donate: bool = True,
    expect_donation: Optional[bool] = None,
    batch_size: int = 16,
    norm_clip: Optional[float] = None,
    grad_guard: bool = True,
    expect_finite_guard: Optional[bool] = None,
) -> list[Finding]:
    """Trace + verify the representative two-step rs_fwd_ag program."""
    closed, reducer, arr = trace_cross_step(
        model_name, policy, comm_dtype=comm_dtype, donate=donate,
        batch_size=batch_size, norm_clip=norm_clip, grad_guard=grad_guard,
    )
    return verify_cross_step_jaxpr(
        closed, reducer, arr,
        expect_donation=donate if expect_donation is None else expect_donation,
        expect_finite_guard=(
            grad_guard if expect_finite_guard is None else expect_finite_guard
        ),
        file=f"<cross-step {model_name}/{policy}/rs_fwd_ag>",
    )


def verify_train_step(
    model_name: str = "lenet",
    policy: str = "mgwfbp",
    *,
    comm_op: str = "all_reduce",
    comm_dtype: Any = None,
    donate: bool = True,
    expect_donation: Optional[bool] = None,
    batch_size: int = 16,
    norm_clip: Optional[float] = None,
    grad_guard: bool = True,
    expect_finite_guard: Optional[bool] = None,
    dcn_slices: Optional[int] = None,
) -> list[Finding]:
    """Trace one representative jitted train step and verify it (the
    finite guard is expected exactly as built unless overridden — the
    override exists for the analyzer's own mutation tests). The cross-step
    rs_fwd_ag lowering dispatches to the TWO-step trace: its contract
    spans a step boundary (RS in step N, AG in step N+1's forward). The
    hier lowering traces on an (ici, dcn) virtual mesh
    (`trace_train_step`'s dcn_slices default)."""
    if comm_op == "rs_fwd_ag":
        return verify_cross_step_train_step(
            model_name, policy, comm_dtype=comm_dtype, donate=donate,
            expect_donation=expect_donation, batch_size=batch_size,
            norm_clip=norm_clip, grad_guard=grad_guard,
            expect_finite_guard=expect_finite_guard,
        )
    closed, reducer, arr = trace_train_step(
        model_name, policy, comm_op=comm_op, comm_dtype=comm_dtype,
        donate=donate, batch_size=batch_size, norm_clip=norm_clip,
        grad_guard=grad_guard, dcn_slices=dcn_slices,
    )
    tag = f"{model_name}/{policy}" + (
        f"/{comm_op}" if comm_op != "all_reduce" else ""
    )
    return verify_jaxpr_against_reducer(
        closed, reducer, arr,
        expect_donation=donate if expect_donation is None else expect_donation,
        expect_finite_guard=(
            grad_guard if expect_finite_guard is None else expect_finite_guard
        ),
        file=f"<train step {tag}>",
    )
