"""ServePlane: the assembled serving subsystem behind one seam.

Composition only — model plane (ServingModel) + reload watcher +
request dispatcher (PredictService) + shadow scorer, wired to one
``emit(event, fields)`` sink and optionally attached to an existing
TelemetryServer's POST /predict route. The trainer embeds one in-process
(``--serve-shadow``); the standalone CLI (serving/__main__.py) runs one
per replica.
"""

from __future__ import annotations

from typing import Callable, Optional

from mgwfbp_tpu.serving.model import ServingModel
from mgwfbp_tpu.serving.service import PredictService
from mgwfbp_tpu.serving.shadow import ShadowScorer
from mgwfbp_tpu.serving.watch import DEFAULT_POLL_S, ReloadWatcher
from mgwfbp_tpu.utils.logging import get_logger

log = get_logger("mgwfbp.serving.plane")


class ServePlane:
    def __init__(
        self,
        model: ServingModel,
        checkpoint_dir: str,
        *,
        emit: Optional[Callable[[str, dict], None]] = None,
        server=None,
        shadow: bool = True,
        poll_s: float = DEFAULT_POLL_S,
        flush_ms: Optional[float] = None,
        queue_limit: Optional[int] = None,
        train_loss_fn: Optional[Callable[[], Optional[float]]] = None,
    ):
        self.model = model
        self.service = PredictService(
            model, flush_ms=flush_ms, queue_limit=queue_limit, emit=emit
        )
        self.scorer = (
            ShadowScorer(
                model, emit=emit, train_loss_fn=train_loss_fn
            ) if shadow else None
        )
        self.watcher = ReloadWatcher(
            model,
            checkpoint_dir,
            poll_s=poll_s,
            emit=emit,
            on_reload=(
                self.scorer.score if self.scorer is not None else None
            ),
        )
        self._server = server
        if server is not None:
            server.attach_predict(self.service)
        self._closed = False

    def start(self) -> None:
        """Open for business: dispatcher first (requests already routed
        here 503 until a snapshot lands), then the reload watcher."""
        self.service.start()
        self.watcher.start()

    def poll_now(self) -> Optional[int]:
        """Synchronous reload check (startup waits and tests)."""
        return self.watcher.poll_once()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._server is not None:
            self._server.attach_predict(None)  # /predict answers 503 again
        self.watcher.close()
        self.service.close()
