"""Serving model plane: jitted forward + hot-reloadable param snapshot.

The load path is the manifest-addressed ``ShardSource`` reader from the
shard-native checkpoint format (ISSUE 13): one full leaf at a time off
the memmapped shard files — never a world-sized buffer — regardless of
whether the saver stored params sharded (rs_opt_ag / rs_fwd_ag carries)
or replicated. The swap is one reference store of an immutable
``LiveSnapshot`` behind a lock: a request thread that grabbed the old
snapshot keeps computing on the old params, a request after the swap
sees the new ones — there is no state in between, which is exactly the
torn-read guarantee the concurrency hammer test pins.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from mgwfbp_tpu.checkpoint import (
    MANIFEST_FILE,
    SHARD_FORMAT_VERSION,
    SHARD_SUBDIR,
    CheckpointRestoreError,
    ShardSource,
)
from mgwfbp_tpu.parallel.mesh import DATA_AXIS, MeshSpec, make_mesh
from mgwfbp_tpu.utils.logging import get_logger

SERVE_MAX_BATCH_ENV = "MGWFBP_SERVE_MAX_BATCH"
DEFAULT_MAX_BATCH = 8

log = get_logger("mgwfbp.serving.model")


def committed_sharded_steps(directory: str) -> list[int]:
    """Committed shard-native steps under a checkpoint directory, sorted.
    Commit is the atomic manifest rename, so manifest-present == safely
    readable; orbax-format steps are NOT listed (the serving reader is
    manifest-addressed by design — no orbax manager in the request
    path)."""
    shard_root = os.path.join(directory, SHARD_SUBDIR)
    out = []
    try:
        names = os.listdir(shard_root)
    except OSError:
        return []
    for name in names:
        if name.isdigit() and os.path.exists(
            os.path.join(shard_root, name, MANIFEST_FILE)
        ):
            out.append(int(name))
    return sorted(out)


def open_committed_step(directory: str, step: int) -> tuple[ShardSource, float]:
    """Validated reader over one committed shard-native step WITHOUT
    constructing a Checkpointer (no orbax manager — the watcher must not
    contend with the training process's own manager on the same
    directory). Returns (source, commit wall time) where the commit time
    is the manifest's mtime — the atomic-rename instant that made the
    step visible, i.e. the start of the reload-lag clock."""
    step_dir = os.path.join(directory, SHARD_SUBDIR, f"{int(step):08d}")
    path = os.path.join(step_dir, MANIFEST_FILE)
    try:
        with open(path) as f:
            manifest = json.load(f)
        commit_wall = os.path.getmtime(path)
    except (OSError, ValueError) as e:
        raise CheckpointRestoreError(
            f"shard-native checkpoint step {step} in {directory!r} has no "
            f"readable manifest ({e}) — the save never committed or the "
            "directory is torn"
        ) from e
    if manifest.get("format_version") != SHARD_FORMAT_VERSION:
        raise CheckpointRestoreError(
            f"shard-native checkpoint step {step} in {directory!r} has "
            f"format_version {manifest.get('format_version')!r}; this "
            f"build reads version {SHARD_FORMAT_VERSION}"
        )
    src = ShardSource(step_dir, manifest)
    src.validate()
    return src, commit_wall


@dataclasses.dataclass(frozen=True)
class LiveSnapshot:
    """One served checkpoint: immutable by construction, swapped whole.
    `step` is the train step the params came from — every response built
    against this snapshot reports it as ``served_step``."""

    params: Any
    batch_stats: Any
    step: int
    commit_wall: float  # manifest commit instant (wall clock)
    loaded_wall: float  # when the swap landed


class ServingModel:
    """The jitted forward on an inference mesh + the hot-reload seam.

    ``run_padded`` is the ONLY compute path: the dispatcher packs every
    flush into the same fixed ``max_batch`` slot (one compiled shape),
    and the bitwise acceptance test calls it directly with the same
    padding — so a served answer and a direct forward on the same
    checkpoint cannot differ.
    """

    def __init__(
        self,
        module,
        meta,
        mesh=None,
        max_batch: Optional[int] = None,
    ):
        if meta.has_carry:
            raise ValueError(
                f"model {meta.name!r} carries BPTT state; stateful "
                "serving is not supported (serve a carry-free model)"
            )
        if meta.task == "ctc":
            raise ValueError(
                f"model {meta.name!r} is a CTC audio model; /predict "
                "serves classify and carry-free lm tasks only"
            )
        if max_batch is None:
            max_batch = int(
                os.environ.get(SERVE_MAX_BATCH_ENV) or DEFAULT_MAX_BATCH
            )
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.module = module
        self.meta = meta
        self.max_batch = int(max_batch)
        self.mesh = mesh if mesh is not None else make_mesh(MeshSpec())
        dummy = jnp.zeros(
            (self.max_batch,) + tuple(meta.input_shape), meta.input_dtype
        )
        self.input_np_dtype = np.dtype(np.asarray(dummy).dtype)
        variables = module.init(
            {"params": jax.random.PRNGKey(0)}, dummy, train=False
        )
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        self._has_batch_stats = bool(
            jax.tree_util.tree_leaves(batch_stats)
        )
        self._params_treedef = jax.tree_util.tree_structure(params)
        self._param_leaves = [
            jax.ShapeDtypeStruct(leaf.shape, leaf.dtype)
            for leaf in jax.tree_util.tree_leaves(params)
        ]
        self._bs_treedef = jax.tree_util.tree_structure(batch_stats)
        self._bs_leaves = [
            jax.ShapeDtypeStruct(leaf.shape, leaf.dtype)
            for leaf in jax.tree_util.tree_leaves(batch_stats)
        ]
        # replicate-onto-mesh shardings: params replicated; the batch
        # rides the data axis when the fixed slot divides it (the
        # "sharded inference mesh"), else replicated too. Neither path
        # issues a collective on load — device_put only.
        self._rep = NamedSharding(self.mesh, PartitionSpec())
        data_extent = int(self.mesh.shape[DATA_AXIS])
        if self.max_batch % data_extent == 0 and data_extent > 1:
            self._x_sharding = NamedSharding(
                self.mesh, PartitionSpec(DATA_AXIS)
            )
        else:
            self._x_sharding = self._rep
        self._fwd = jax.jit(self._forward)
        self._lock = threading.Lock()
        self._live: Optional[LiveSnapshot] = None

    def _forward(self, params, batch_stats, x):
        variables = {"params": params}
        if self._has_batch_stats:
            variables["batch_stats"] = batch_stats
        out = self.module.apply(variables, x, train=False)
        if isinstance(out, tuple):  # aux-logit heads (googlenet style)
            out = out[0]
        return out

    # -- hot-reload seam ---------------------------------------------------
    def snapshot(self) -> Optional[LiveSnapshot]:
        with self._lock:
            return self._live

    def served_step(self) -> Optional[int]:
        snap = self.snapshot()
        return None if snap is None else snap.step

    def install_source(
        self, src: ShardSource, step: int, commit_wall: float
    ) -> LiveSnapshot:
        """Load one committed step's params off the manifest reader and
        swap it live. Leaf order is the tree_leaves order of this
        module's init — the same order the trainer's ``_params_template``
        gave the saver, so index j addresses the same leaf on both
        sides; shapes/dtypes are still checked leaf-by-leaf to fail a
        wrong---dnn mismatch loudly instead of serving garbage."""
        params = self._read_section(
            src, "params", self._param_leaves, self._params_treedef
        )
        if self._has_batch_stats:
            if src.section_kind("batch_stats") == "none":
                raise CheckpointRestoreError(
                    f"checkpoint step {step}: model "
                    f"{self.meta.name!r} has batch_stats but the "
                    "manifest carries none — saved from a different "
                    "model"
                )
            batch_stats = self._read_section(
                src, "batch_stats", self._bs_leaves, self._bs_treedef
            )
        else:
            batch_stats = jax.tree_util.tree_unflatten(
                self._bs_treedef, []
            )
        snap = LiveSnapshot(
            params=params,
            batch_stats=batch_stats,
            step=int(step),
            commit_wall=float(commit_wall),
            loaded_wall=time.time(),
        )
        with self._lock:
            self._live = snap
        return snap

    def load_step(self, directory: str, step: int) -> LiveSnapshot:
        src, commit_wall = open_committed_step(directory, step)
        return self.install_source(src, step, commit_wall)

    def _read_section(self, src, section, template, treedef):
        docs = src.section_docs(section)
        if len(docs) != len(template):
            raise CheckpointRestoreError(
                f"checkpoint {src.step_dir!r}: {section} has "
                f"{len(docs)} leaves, model {self.meta.name!r} expects "
                f"{len(template)} — saved from a different model"
            )
        leaves = []
        for j, (doc, ref) in enumerate(zip(docs, template)):
            if tuple(doc.get("shape", ())) != tuple(ref.shape):
                raise CheckpointRestoreError(
                    f"checkpoint {src.step_dir!r}: {section} leaf {j} "
                    f"has shape {tuple(doc.get('shape', ()))}, model "
                    f"expects {tuple(ref.shape)} — saved from a "
                    "different model"
                )
            host = src.read_leaf(section, j)
            leaves.append(jax.device_put(host, self._rep))
        return jax.tree_util.tree_unflatten(treedef, leaves)

    # -- the one compute path ----------------------------------------------
    def run_padded(self, x: np.ndarray) -> tuple[np.ndarray, int]:
        """Forward `x` (n <= max_batch examples) through the live
        snapshot: pads to the fixed slot, runs the single compiled
        forward, slices the padding back off. Returns (outputs, the
        served train step). The snapshot is read ONCE — every example in
        the call is answered by the same checkpoint."""
        snap = self.snapshot()
        if snap is None:
            raise RuntimeError("no checkpoint served yet")
        x = np.asarray(x, self.input_np_dtype)
        want = tuple(self.meta.input_shape)
        if x.ndim != len(want) + 1 or tuple(x.shape[1:]) != want:
            raise ValueError(
                f"inputs must be (n, {', '.join(map(str, want))}), "
                f"got {tuple(x.shape)}"
            )
        n = int(x.shape[0])
        if not 1 <= n <= self.max_batch:
            raise ValueError(
                f"batch of {n} examples exceeds the serve slot "
                f"({self.max_batch}); split the request"
            )
        if n < self.max_batch:
            pad = np.zeros(
                (self.max_batch - n,) + want, self.input_np_dtype
            )
            x = np.concatenate([x, pad], axis=0)
        xd = jax.device_put(x, self._x_sharding)
        out = self._fwd(snap.params, snap.batch_stats, xd)
        return np.asarray(jax.device_get(out))[:n], snap.step
