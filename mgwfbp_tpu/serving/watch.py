"""Hot-reload watcher: committed checkpoint steps -> live param swaps.

Polls the checkpoint directory for newly COMMITTED shard-native steps
(manifest present — the atomic-rename commit from ISSUE 13/16 is the
visibility barrier, so a step this watcher sees is always fully
readable) and installs the newest one into the ServingModel. Every
device interaction on this thread is device_put + jit — no collectives —
so running it off the step loop is safe in-process too.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from mgwfbp_tpu.checkpoint import CheckpointRestoreError
from mgwfbp_tpu.serving.model import (
    LiveSnapshot,
    ServingModel,
    committed_sharded_steps,
    open_committed_step,
)
from mgwfbp_tpu.utils.logging import get_logger

DEFAULT_POLL_S = 0.25

# a step that failed to load this many times is skipped for good (the
# next committed step supersedes it anyway); without the cap a corrupt
# directory would hot-loop the watcher forever
_MAX_LOAD_ATTEMPTS = 3

log = get_logger("mgwfbp.serving.watch")


class ReloadWatcher:
    """Background poller driving ServingModel hot-reloads.

    ``poll_once`` is also the synchronous entry point (tests and the
    standalone CLI's startup wait call it directly); the background
    thread just runs it on a cadence.
    """

    def __init__(
        self,
        model: ServingModel,
        directory: str,
        *,
        poll_s: float = DEFAULT_POLL_S,
        emit: Optional[Callable[[str, dict], None]] = None,
        on_reload: Optional[Callable[[LiveSnapshot], None]] = None,
    ):
        self.model = model
        self.directory = directory
        self._poll_s = float(poll_s)
        self._emit = emit
        self._on_reload = on_reload
        # load-failure ledger; only ever touched by whichever single
        # caller drives poll_once (the watcher thread once started)
        self._failed: dict[int, int] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="mgwfbp-serve-reload", daemon=True
        )
        self._started = False

    def start(self) -> None:
        if not self._started:
            self._started = True
            self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._started:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — the watcher must
                # survive any single bad poll (torn directory, transient
                # I/O); the next committed step gets a fresh attempt
                log.warning("reload poll failed: %s", e)

    def poll_once(self) -> Optional[int]:
        """Install the newest committed step if it is newer than the one
        being served. Returns the newly served step, or None when
        nothing changed."""
        steps = committed_sharded_steps(self.directory)
        current = self.model.served_step()
        target = None
        for step in reversed(steps):
            if current is not None and step <= current:
                break
            if self._failed.get(step, 0) < _MAX_LOAD_ATTEMPTS:
                target = step
                break
        if target is None:
            return None
        t0 = time.monotonic()
        try:
            src, commit_wall = open_committed_step(self.directory, target)
            snap = self.model.install_source(src, target, commit_wall)
        except CheckpointRestoreError as e:
            # graft: thread-safe -- retry ledger with one effective
            # writer: the watcher thread owns poll_once after start();
            # poll_now() callers (tests, startup waits) run before or
            # around it, and the worst lost-update is one extra load
            # attempt of an already-failing step
            self._failed[target] = self._failed.get(target, 0) + 1
            log.warning(
                "hot-reload of step %d failed (attempt %d/%d): %s",
                target, self._failed[target], _MAX_LOAD_ATTEMPTS, e,
            )
            return None
        duration = time.monotonic() - t0
        lag = max(0.0, time.time() - snap.commit_wall)
        log.info(
            "hot-reloaded step %d (lag %.3fs, load %.3fs)",
            target, lag, duration,
        )
        if self._emit is not None:
            try:
                self._emit("reload", {
                    "step": int(target),
                    "lag_s": round(lag, 6),
                    "duration_s": round(duration, 6),
                })
            except Exception as e:  # noqa: BLE001 — telemetry must not
                # block the swap
                log.warning("reload emit failed: %s", e)
        if self._on_reload is not None:
            try:
                self._on_reload(snap)
            except Exception as e:  # noqa: BLE001 — shadow-eval is
                # advisory; a scoring failure must not stall reloads
                log.warning("on_reload hook failed: %s", e)
        return int(target)
