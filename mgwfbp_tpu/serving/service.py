"""Request plane: bounded queue + dispatcher thread (micro-batching).

Continuous micro-batching in the MG-WFBP spirit — never compute with an
idle slot you could have filled, never wait longer than the deadline to
fill it: handler threads park requests on a bounded queue; one
dispatcher thread packs them into the next fixed ``max_batch`` slot and
flushes when the slot is full OR the oldest parked request has waited
``flush_ms`` (deadline-or-full). One compiled forward shape, one live
snapshot per flush — every response in a batch carries the same
``served_step`` by construction.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable, Optional

import numpy as np

from mgwfbp_tpu.serving.model import ServingModel
from mgwfbp_tpu.utils.logging import get_logger

SERVE_FLUSH_MS_ENV = "MGWFBP_SERVE_FLUSH_MS"
SERVE_QUEUE_ENV = "MGWFBP_SERVE_QUEUE"
DEFAULT_FLUSH_MS = 20.0
DEFAULT_QUEUE_LIMIT = 64

# a request parked longer than this has lost its client; the bound also
# keeps handler threads from accumulating forever if the dispatcher dies
_REQUEST_TIMEOUT_S = 30.0

# serve_stats cadence: the dispatcher emits at most one snapshot per
# interval, so a hot request plane cannot flood the telemetry stream
_STATS_INTERVAL_S = 1.0

# latency quantile window (recent requests)
_LATENCY_WINDOW = 256

log = get_logger("mgwfbp.serving.service")


def _env_float(name: str, default: float) -> float:
    raw = (os.environ.get(name) or "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


class _Pending:
    """One parked request: the handler thread blocks on `done` until the
    dispatcher fills (code, doc) and sets it."""

    __slots__ = ("x", "n", "t0", "done", "code", "doc")

    def __init__(self, x: np.ndarray):
        self.x = x
        self.n = int(x.shape[0])
        self.t0 = time.monotonic()
        self.done = threading.Event()
        self.code = 500
        self.doc: dict = {"error": "dispatcher dropped the request"}


class PredictService:
    """The POST /predict backend (TelemetryServer.attach_predict)."""

    def __init__(
        self,
        model: ServingModel,
        *,
        flush_ms: Optional[float] = None,
        queue_limit: Optional[int] = None,
        emit: Optional[Callable[[str, dict], None]] = None,
    ):
        self.model = model
        self.max_batch = model.max_batch
        self._flush_s = (
            flush_ms if flush_ms is not None
            else _env_float(SERVE_FLUSH_MS_ENV, DEFAULT_FLUSH_MS)
        ) / 1000.0
        limit = int(
            queue_limit if queue_limit is not None
            else _env_float(SERVE_QUEUE_ENV, DEFAULT_QUEUE_LIMIT)
        )
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, limit))
        self._emit = emit
        # a request the packer pulled but could not fit into the flushing
        # slot; owned by the dispatcher thread alone (never touched by a
        # handler thread), so it needs no lock
        self._carry: Optional[_Pending] = None
        # rolling stats shared between the dispatcher (writer) and the
        # handler/report threads (`stats()` readers)
        self._stats_lock = threading.Lock()
        self._requests = 0
        self._batches = 0
        self._fill_sum = 0.0
        self._fill_n = 0
        self._latencies: list[float] = []
        self._last_stats_emit = 0.0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="mgwfbp-serve-dispatch", daemon=True
        )
        self._started = False

    def start(self) -> None:
        if not self._started:
            self._started = True
            self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._started:
            self._thread.join(timeout=5)
        # fail anything still parked so no handler thread waits out the
        # full request timeout against a dead dispatcher
        drained = []
        if self._carry is not None:
            drained.append(self._carry)
            # graft: thread-safe -- _carry is dispatcher-owned; this
            # write runs after _stop.set() + thread.join(), so the
            # dispatcher has exited (or, past the join timeout, is
            # wedged inside a jit call and will never touch _carry
            # again before process exit)
            self._carry = None
        while True:
            try:
                drained.append(self._queue.get_nowait())
            except queue.Empty:
                break
        for p in drained:
            p.code, p.doc = 503, {"error": "serving plane shut down"}
            p.done.set()

    # -- handler-thread side -----------------------------------------------
    def handle(self, inputs) -> tuple[int, dict]:
        """One /predict request (runs on an HTTP handler thread).
        Returns (http status, response doc)."""
        if self.model.snapshot() is None:
            return 503, {"error": "no checkpoint served yet"}
        try:
            x = np.asarray(inputs, self.model.input_np_dtype)
        except (TypeError, ValueError) as e:
            return 400, {"error": f"inputs not coercible to a batch: {e}"}
        want = tuple(self.model.meta.input_shape)
        if x.ndim == len(want) and tuple(x.shape) == want:
            x = x[None]  # single example rides as a batch of one
        if x.ndim != len(want) + 1 or tuple(x.shape[1:]) != want:
            return 400, {
                "error": f"inputs must be (n, {', '.join(map(str, want))})"
                         f" or a single example, got {tuple(x.shape)}"
            }
        if not 1 <= x.shape[0] <= self.max_batch:
            return 400, {
                "error": f"batch of {x.shape[0]} exceeds the serve slot "
                         f"({self.max_batch}); split the request"
            }
        pending = _Pending(x)
        try:
            self._queue.put_nowait(pending)
        except queue.Full:
            return 429, {
                "error": "request queue full; retry with backoff",
                "queue_limit": self._queue.maxsize,
            }
        if not pending.done.wait(_REQUEST_TIMEOUT_S):
            return 504, {"error": "request timed out in the batch queue"}
        return pending.code, pending.doc

    # -- dispatcher thread ---------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            batch = self._gather()
            if batch:
                self._flush(batch)

    def _gather(self) -> list[_Pending]:
        """Deadline-or-full packing: block for a first request, then keep
        pulling until the slot is full or `flush_ms` has passed since the
        first arrival. A request that would overflow the slot is carried
        into the NEXT batch (never split, never reordered)."""
        batch: list[_Pending] = []
        n = 0
        if self._carry is not None:
            batch.append(self._carry)
            n = self._carry.n
            self._carry = None
        else:
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                return []
            batch.append(first)
            n = first.n
        deadline = time.monotonic() + self._flush_s
        while n < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                nxt = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            if n + nxt.n > self.max_batch:
                self._carry = nxt
                break
            batch.append(nxt)
            n += nxt.n
        return batch

    def _flush(self, batch: list[_Pending]) -> None:
        now = time.monotonic()
        n = sum(p.n for p in batch)
        try:
            outs, step = self.model.run_padded(
                np.concatenate([p.x for p in batch], axis=0)
            )
        except Exception as e:  # noqa: BLE001 — a bad batch must answer,
            # not kill the dispatcher thread (the request plane outlives
            # any single failed flush)
            log.warning("predict flush failed: %s", e)
            for p in batch:
                p.code, p.doc = 500, {"error": f"forward failed: {e}"}
                p.done.set()
            return
        off = 0
        done = time.monotonic()
        for p in batch:
            p.code = 200
            p.doc = {
                "outputs": outs[off:off + p.n].tolist(),
                "served_step": int(step),
            }
            off += p.n
            p.done.set()
        with self._stats_lock:
            self._requests += len(batch)
            self._batches += 1
            self._fill_sum += n / self.max_batch
            self._fill_n += 1
            for p in batch:
                self._latencies.append(done - p.t0)
            del self._latencies[:-_LATENCY_WINDOW]
            snap = (
                self._stats_locked()
                if (self._emit is not None
                    and now - self._last_stats_emit >= _STATS_INTERVAL_S)
                else None
            )
            if snap is not None:
                self._last_stats_emit = now
        if snap is not None:
            try:
                self._emit("serve_stats", snap)
            except Exception as e:  # noqa: BLE001 — telemetry must not
                # take down the request plane
                log.warning("serve_stats emit failed: %s", e)

    # -- stats ---------------------------------------------------------------
    def _stats_locked(self) -> dict:
        lats = sorted(self._latencies)

        def q(p: float) -> float:
            if not lats:
                return 0.0
            return lats[min(len(lats) - 1, int(p * len(lats)))]

        fill = self._fill_sum / self._fill_n if self._fill_n else 0.0
        return {
            "requests": int(self._requests),
            "queue_depth": int(self._queue.qsize()),
            "batch_fill": round(fill, 4),
            "batches": int(self._batches),
            "latency_p50_s": round(q(0.50), 6),
            "latency_p95_s": round(q(0.95), 6),
            "latency_p99_s": round(q(0.99), 6),
        }

    def stats(self) -> dict:
        with self._stats_lock:
            return self._stats_locked()
