"""Standalone serving replica: ``python -m mgwfbp_tpu.serving``.

One process = one replica: builds the ServingModel for a named model,
watches a checkpoint directory for committed shard-native steps, and
serves POST /predict (plus the usual /metrics /healthz /status) on the
role-aware metrics port (``base + serve offset + replica``). The
supervisor spawns N of these under ``supervise --serve-replicas N`` and
folds them into the fleet console under the ``serve`` role.
"""

from __future__ import annotations

import argparse
import os
import signal
import threading
import time
from typing import Optional

from mgwfbp_tpu.serving.watch import DEFAULT_POLL_S
from mgwfbp_tpu.utils.logging import get_logger

SERVE_REPLICA_ENV = "MGWFBP_SERVE_REPLICA"

log = get_logger("mgwfbp.serving")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m mgwfbp_tpu.serving",
        description="standalone serving replica (hot-reload + /predict)",
    )
    p.add_argument("--dnn", required=True, help="model name (models registry)")
    p.add_argument("--dataset", default=None,
                   help="dataset override (retargets input shape/classes)")
    p.add_argument("--checkpoint-dir", required=True,
                   help="checkpoint directory to watch for committed steps")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="base metrics port (default: MGWFBP_METRICS_PORT; "
                        "the replica serves base + serve offset + replica)")
    p.add_argument("--replica", type=int, default=None,
                   help=f"replica index (default: {SERVE_REPLICA_ENV} or 0)")
    p.add_argument("--max-batch", type=int, default=None,
                   help="batch slot size (default: MGWFBP_SERVE_MAX_BATCH)")
    p.add_argument("--flush-ms", type=float, default=None,
                   help="micro-batch flush deadline "
                        "(default: MGWFBP_SERVE_FLUSH_MS)")
    p.add_argument("--queue-limit", type=int, default=None,
                   help="bounded request queue size "
                        "(default: MGWFBP_SERVE_QUEUE)")
    p.add_argument("--poll-s", type=float, default=DEFAULT_POLL_S,
                   help="checkpoint poll interval")
    p.add_argument("--shadow", action="store_true",
                   help="score the held-out shadow stream on every reload")
    p.add_argument("--telemetry-dir", default=None,
                   help="write this replica's own telemetry stream here")
    p.add_argument("--max-seconds", type=float, default=None,
                   help="exit after this long (smokes/tests; default: run "
                        "until SIGTERM/SIGINT)")
    return p


def main(argv: Optional[list] = None) -> int:
    args = build_parser().parse_args(argv)
    from mgwfbp_tpu import models
    from mgwfbp_tpu.serving.model import ServingModel
    from mgwfbp_tpu.serving.plane import ServePlane
    from mgwfbp_tpu.telemetry.serve import (
        METRICS_PORT_ENV,
        MetricsAggregator,
        start_metrics_server,
    )

    replica = (
        args.replica if args.replica is not None
        else int(os.environ.get(SERVE_REPLICA_ENV) or 0)
    )
    module, meta = models.create_model(args.dnn, dataset=args.dataset)
    model = ServingModel(module, meta, max_batch=args.max_batch)

    run = {
        "role": "serve",
        "replica": int(replica),
        "dnn": meta.name,
        "dataset": meta.dataset,
        "checkpoint_dir": args.checkpoint_dir,
        "max_batch": model.max_batch,
    }
    agg = MetricsAggregator(run=run)
    writer = None
    if args.telemetry_dir:
        from mgwfbp_tpu.telemetry.events import EventWriter

        writer = EventWriter(
            os.path.join(args.telemetry_dir, "telemetry.jsonl"),
            run=run, observer=agg.observe,
        )

    def emit(event: str, fields: dict) -> None:
        if writer is not None:
            writer.emit(event, **fields)  # tees to the aggregator
        else:
            agg.observe(event, fields)

    base_port = (
        args.metrics_port if args.metrics_port is not None
        else (int(os.environ[METRICS_PORT_ENV])
              if os.environ.get(METRICS_PORT_ENV) else None)
    )
    server = start_metrics_server(agg, base_port, replica, role="serve")
    plane = ServePlane(
        model,
        args.checkpoint_dir,
        emit=emit,
        server=server,
        shadow=bool(args.shadow),
        poll_s=args.poll_s,
        flush_ms=args.flush_ms,
        queue_limit=args.queue_limit,
    )

    stop = threading.Event()
    if threading.current_thread() is threading.main_thread():
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda *_: stop.set())

    plane.start()
    log.info(
        "serving replica %d: %s watching %r (slot %d)%s",
        replica, meta.name, args.checkpoint_dir, model.max_batch,
        f" on port {server.port}" if server is not None else "",
    )
    deadline = (
        time.monotonic() + args.max_seconds
        if args.max_seconds is not None else None
    )
    try:
        while not stop.wait(0.2):
            if deadline is not None and time.monotonic() >= deadline:
                break
    finally:
        plane.close()
        if server is not None:
            server.close()
        if writer is not None:
            writer.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
