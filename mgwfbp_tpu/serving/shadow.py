"""Shadow-eval: score a deterministic held-out stream per reload.

The de-risking stage of ``--serve-shadow``: before (or while) a model
answers real traffic, every newly served checkpoint is scored against
the SAME fixed synthetic held-out batches — seeded host-side, so two
replicas (or two runs) score identical data and their `shadow_eval`
series are comparable. The score rides the normal telemetry stream and
renders as the served-vs-training loss gauge
(``mgwfbp_shadow_eval_loss`` / ``_delta``).
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from mgwfbp_tpu.serving.model import LiveSnapshot, ServingModel
from mgwfbp_tpu.utils.logging import get_logger

DEFAULT_SHADOW_BATCHES = 2
DEFAULT_SHADOW_SEED = 20190227  # MG-WFBP's INFOCOM day; any fixed value

log = get_logger("mgwfbp.serving.shadow")


class ShadowScorer:
    """Cross-entropy over fixed synthetic batches (classify models).

    Non-classify tasks are not scored (logged once, `score` returns
    None) — /predict still serves them; shadow-eval is simply dark.
    """

    def __init__(
        self,
        model: ServingModel,
        *,
        batches: int = DEFAULT_SHADOW_BATCHES,
        seed: int = DEFAULT_SHADOW_SEED,
        emit: Optional[Callable[[str, dict], None]] = None,
        train_loss_fn: Optional[Callable[[], Optional[float]]] = None,
    ):
        self.model = model
        self._emit = emit
        self._train_loss_fn = train_loss_fn
        self.supported = model.meta.task == "classify"
        if not self.supported:
            log.info(
                "shadow-eval dark for task %r (classify only); "
                "/predict serves regardless", model.meta.task,
            )
            self._data: list = []
            return
        rng = np.random.default_rng(seed)
        b = model.max_batch
        shape = (b,) + tuple(model.meta.input_shape)
        self._data = [
            (
                rng.standard_normal(shape).astype(model.input_np_dtype),
                rng.integers(0, model.meta.num_classes, size=b),
            )
            for _ in range(max(1, int(batches)))
        ]

    def score(self, snap: LiveSnapshot) -> Optional[float]:
        """Mean cross-entropy of the held-out stream against the served
        snapshot; emits the `shadow_eval` event (train_loss riding along
        when the provider knows it)."""
        if not self.supported:
            return None
        losses = []
        for x, labels in self._data:
            logits, step = self.model.run_padded(x)
            if step != snap.step:
                # a newer reload landed mid-score; the fresher snapshot
                # will be scored by its own reload callback
                return None
            logits = np.asarray(logits, np.float64)
            m = logits.max(axis=-1, keepdims=True)
            lse = m[:, 0] + np.log(np.exp(logits - m).sum(axis=-1))
            losses.append(
                float(np.mean(lse - logits[np.arange(len(labels)), labels]))
            )
        loss = float(np.mean(losses))
        fields: dict = {"step": int(snap.step), "loss": round(loss, 6)}
        if self._train_loss_fn is not None:
            train_loss = self._train_loss_fn()
            if train_loss is not None:
                fields["train_loss"] = float(train_loss)
        if self._emit is not None:
            try:
                self._emit("shadow_eval", fields)
            except Exception as e:  # noqa: BLE001 — scoring is advisory
                log.warning("shadow_eval emit failed: %s", e)
        return loss
