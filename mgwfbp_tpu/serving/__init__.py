"""Online inference plane riding the training runtime (ISSUE 19).

The training side already built everything a serving plane needs: a
committed, manifest-addressed checkpoint format readable one leaf at a
time (checkpoint.ShardSource, ISSUE 13/16), a per-process HTTP plane
with a single metric registry (telemetry/serve.py, ISSUE 9/10), and a
supervisor that owns child lifecycles (runtime/supervisor.py). This
package adds the missing consumer:

  * ``ServingModel`` (model.py) — the jitted ``apply_fn`` on a sharded
    inference mesh plus an atomically-swapped live snapshot of the
    newest committed checkpoint's params (hot-reload).
  * ``ReloadWatcher`` (watch.py) — polls the checkpoint directory's
    committed steps and drives the swap; emits ``reload`` events.
  * ``PredictService`` (service.py) — bounded request queue + dispatcher
    thread packing requests into fixed batch slots (continuous
    micro-batching, deadline-or-full flush); answers POST ``/predict``
    on the existing telemetry server.
  * ``ShadowScorer`` (shadow.py) — scores a deterministic held-out
    stream against each newly served checkpoint (``shadow_eval``
    events, served-vs-training loss gauge).
  * ``ServePlane`` (plane.py) — wires the four together; the trainer
    embeds one under ``--serve-shadow``, ``python -m mgwfbp_tpu.serving``
    runs one standalone, and ``supervise --serve-replicas N`` scales
    them.

No code in this package ever issues a collective: every device
interaction is replicate-onto-mesh ``device_put`` plus a jitted forward,
so any thread (watcher, dispatcher) may run it without violating the
PR-16 owning-thread discipline for collectives.
"""

from mgwfbp_tpu.serving.model import ServingModel, committed_sharded_steps
from mgwfbp_tpu.serving.plane import ServePlane
from mgwfbp_tpu.serving.service import PredictService
from mgwfbp_tpu.serving.shadow import ShadowScorer
from mgwfbp_tpu.serving.watch import ReloadWatcher

__all__ = [
    "PredictService",
    "ReloadWatcher",
    "ServePlane",
    "ServingModel",
    "ShadowScorer",
    "committed_sharded_steps",
]
