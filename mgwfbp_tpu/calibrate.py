"""Communication calibration CLI: measure alpha-beta on the live topology.

Parity target: the reference's CommunicationProfiler + LinearRegression fit
(reference profiling.py:150-183, distributed_optimizer.py:105-127) — present
there but dead in the default path, which falls back to hardcoded cluster
tables. Here calibration is a first-class step: run once per topology,
persist the profile, and point training at it with --comm-profile.

Usage:
  python -m mgwfbp_tpu.calibrate --out profiles/v5e8.json
  python -m mgwfbp_tpu.train_cli --dnn resnet50 --comm-profile profiles/v5e8.json
"""

from __future__ import annotations

import argparse
import json
from typing import Optional


def main(argv: Optional[list[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="mgwfbp-calibrate")
    p.add_argument("--out", required=True, help="output profile json path")
    p.add_argument("--min-log2", type=int, default=13,
                   help="smallest payload (log2 elements)")
    p.add_argument("--max-log2", type=int, default=24,
                   help="largest payload (log2 elements)")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--warmup", type=int, default=5)
    args = p.parse_args(argv)

    from mgwfbp_tpu.utils.platform import apply_platform_overrides

    apply_platform_overrides()
    from mgwfbp_tpu.parallel.costmodel import save_profile
    from mgwfbp_tpu.parallel.mesh import MeshSpec, make_mesh
    from mgwfbp_tpu.profiling import profile_allreduce

    import jax

    mesh = make_mesh(MeshSpec())
    sizes = tuple(2**k for k in range(args.min_log2, args.max_log2 + 1))
    prof = profile_allreduce(
        mesh, sizes=sizes, warmup=args.warmup, iters=args.iters
    )
    import os

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    save_profile(
        args.out,
        prof.model,
        meta={
            "device_kind": jax.devices()[0].device_kind,
            "n_devices": len(jax.devices()),
            "payload_log2_range": [args.min_log2, args.max_log2],
            "iters": args.iters,
        },
    )
    print(
        json.dumps(
            {
                "alpha_s": prof.model.alpha,
                "beta_s_per_byte": prof.model.beta,
                "samples": len(prof.sizes_bytes),
                "out": args.out,
            }
        )
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
