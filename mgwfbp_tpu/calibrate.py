"""Communication calibration CLI: measure alpha-beta on the live topology.

Parity target: the reference's CommunicationProfiler + LinearRegression fit
(reference profiling.py:150-183, distributed_optimizer.py:105-127) — present
there but dead in the default path, which falls back to hardcoded cluster
tables. Here calibration is a first-class step: run once per topology,
persist the profile, and point training at it with --comm-profile.

Usage:
  python -m mgwfbp_tpu.calibrate --out profiles/v5e8.json
  python -m mgwfbp_tpu.train_cli --dnn resnet50 --comm-profile profiles/v5e8.json
"""

from __future__ import annotations

import argparse
import json
from typing import Optional


def main(argv: Optional[list[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="mgwfbp-calibrate")
    p.add_argument("--out", required=True, help="output profile json path")
    p.add_argument("--min-log2", type=int, default=13,
                   help="smallest payload (log2 elements)")
    p.add_argument("--max-log2", type=int, default=24,
                   help="largest payload (log2 elements)")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--no-gamma", action="store_true",
                   help="skip the bucket-path microbenches: the "
                        "per-collective overhead (gamma) fit, the "
                        "per-byte bucketization (pack_beta) fit AND the "
                        "rs_opt_ag update-in-the-middle (update_beta) fit "
                        "— all save as 0.0, reverting the solver to the "
                        "pure alpha-beta objective")
    p.add_argument("--no-overlap", action="store_true",
                   help="skip the comm/compute overlap-capability probe")
    p.add_argument("--allgather", action="store_true",
                   help="also sweep a tiled all-gather at the same payload "
                        "sizes and fit ag_fraction — the measured RS/AG "
                        "phase split the cross-step rs_fwd_ag solver uses "
                        "instead of halving the full-collective predictor "
                        "(persisted in the profile, schema v3; older "
                        "profiles load with the historical 0.5 split)")
    p.add_argument("--gamma-total-log2", type=int, default=22,
                   help="fixed total payload for the gamma fit (log2 elems)")
    p.add_argument("--world-sizes", default=None,
                   help="comma list of data-axis extents to calibrate (e.g. "
                        "2,4,8): produces a 'family' profile whose per-P "
                        "alpha-beta-gamma replace the invented alpha-vs-hops "
                        "prior with measured trend")
    p.add_argument("--prior-extend", default=None, metavar="CONN",
                   help="single-chip mode (VERDICT r4 #5): calibrate the "
                        "chip-measurable constants at the available world "
                        "size (gamma = dispatch per extra collective, "
                        "pack_beta = bucketization copy, overlap — all real "
                        "at world 1, where the collective itself is "
                        "identity) and emit a FAMILY profile whose larger "
                        "extents carry the named alpha-beta prior ('ici' / "
                        "'dcn') combined with the measured "
                        "gamma/pack_beta/overlap. Meta separates "
                        "measured_fields from prior_fields per entry.")
    p.add_argument("--prior-world-sizes", default="2,4,8,16",
                   help="extents for the prior-extended entries")
    p.add_argument("--two-level", dest="two_level", action="store_true",
                   help="per-AXIS calibration of an (ici x dcn) two-axis "
                        "mesh (needs --dcn > 1): sweep a pmean over ONLY "
                        "the inner axis and ONLY the outer axis, fit each "
                        "link's alpha-beta, and persist a schema-stamped "
                        "two-level profile (kind='two_level', SampledCost "
                        "curves per link) — the cost model the two-link "
                        "hier solver schedules against. Combine with "
                        "--allgather to also fit the ICI link's RS/AG "
                        "split. tools/two_level_validation.py consumes "
                        "this calibration and validates the composition "
                        "AND the solved hier schedule against measurement.")
    p.add_argument("--ici", type=int, default=None,
                   help="inner-axis extent for --two-level (default: "
                        "devices / dcn)")
    p.add_argument("--dcn", type=int, default=2,
                   help="outer-axis extent (slices) for --two-level")
    p.add_argument("--forward", action="store_true",
                   help="LAYER-profile mode (needs --model): benchmark the "
                        "model's per-layer backward AND forward durations "
                        "on one device and write a layer profile "
                        "(tb_profile.json format, schema_version=2 with "
                        "tf_s) to --out — the forward timeline the "
                        "cross-step rs_fwd_ag solver prices deferred "
                        "all-gathers against. Unstamped legacy profiles "
                        "without tf_s still load (forward times default "
                        "to 0 with a warning; see "
                        "profiling.load_layer_profile).")
    p.add_argument("--model", default=None,
                   help="model to benchmark in --forward mode (e.g. lenet)")
    p.add_argument("--batch-size", type=int, default=8,
                   help="per-device batch for the --forward benchmark")
    args = p.parse_args(argv)
    if args.prior_extend and args.world_sizes:
        p.error("--prior-extend and --world-sizes are mutually exclusive: "
                "the former measures ONE world size and prior-fills the "
                "rest, the latter measures each listed extent")
    if args.forward and not args.model:
        p.error("--forward needs --model (the layer profile is per-model)")
    if args.two_level and (
        args.world_sizes or args.prior_extend or args.forward
    ):
        p.error("--two-level is its own calibration mode; it does not "
                "combine with --world-sizes/--prior-extend/--forward")
    if args.forward:
        return _forward_main(args)
    if args.two_level:
        return _two_level_main(args)

    from mgwfbp_tpu.utils.platform import apply_platform_overrides

    apply_platform_overrides()
    import dataclasses

    from mgwfbp_tpu.parallel.costmodel import (
        ProfileFamily,
        SampledCost,
        save_profile,
    )
    from mgwfbp_tpu.parallel.mesh import MeshSpec, make_mesh
    from mgwfbp_tpu.profiling import (
        fit_ag_fraction,
        profile_allgather,
        profile_allreduce,
        profile_group_overhead,
        profile_overlap_capability,
        profile_pack_overhead,
        profile_update_beta,
    )

    import jax

    sizes = tuple(2**k for k in range(args.min_log2, args.max_log2 + 1))

    def calibrate_mesh(mesh):
        prof = profile_allreduce(
            mesh, sizes=sizes, warmup=args.warmup, iters=args.iters
        )
        gamma, gsamples = 0.0, None
        if not args.no_gamma:
            gamma, gsamples = profile_group_overhead(
                mesh, alpha=prof.model.alpha,
                total_elems=2**args.gamma_total_log2,
            )
        overlap = 1.0
        if not args.no_overlap:
            overlap = profile_overlap_capability(mesh)
        pack_beta = 0.0
        update_beta = 0.0
        if not args.no_gamma:  # same bucket-path microbench family
            pack_beta = profile_pack_overhead(mesh)
            # the rs_opt_ag update-in-the-middle term (ROADMAP PR-2
            # follow-up): rs_ag vs rs_opt_ag on an identical payload
            update_beta = profile_update_beta(mesh)
        ag_fraction = 0.5
        if args.allgather:
            # measured RS/AG phase split (ROADMAP PR-7 follow-up b): a
            # dedicated tiled-all-gather sweep at the SAME payload sizes;
            # the median AG/full ratio replaces the halved-split prior
            ag_prof = profile_allgather(
                mesh, sizes=sizes, warmup=args.warmup, iters=args.iters
            )
            ag_fraction = fit_ag_fraction(prof, ag_prof)
        # the sampled curve (not just the 2-parameter fit) is the persisted
        # predictor: one flat beta cannot describe payload-dependent
        # per-byte cost (cache regimes on CPU, DMA pipelining on TPU)
        model = SampledCost(
            sizes_bytes=tuple(prof.sizes_bytes),
            times_s=tuple(prof.times_s),
            ab=prof.model,
            gamma=gamma,
            overlap=overlap,
            pack_beta=pack_beta,
            update_beta=update_beta,
            ag_fraction=ag_fraction,
        )
        return model, prof, gsamples

    meta = {
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": len(jax.devices()),
        "payload_log2_range": [args.min_log2, args.max_log2],
        "iters": args.iters,
    }
    if args.prior_extend:
        from mgwfbp_tpu.parallel.costmodel import (
            AlphaBeta,
            lookup_alpha_beta,
        )

        avail = len(jax.devices())
        mesh = make_mesh(MeshSpec(data=avail), devices=jax.devices())
        measured, _, gamma_samples = calibrate_mesh(mesh)
        prior_sizes = sorted(
            {int(s) for s in args.prior_world_sizes.split(",")} - {avail}
        )
        entries: dict = {avail: measured}
        for n in prior_sizes:
            ab = lookup_alpha_beta(args.prior_extend, n)
            entries[n] = AlphaBeta(
                alpha=ab.alpha, beta=ab.beta, gamma=measured.gamma,
                overlap=measured.overlap, pack_beta=measured.pack_beta,
                update_beta=measured.update_beta,
                ag_fraction=measured.ag_fraction,
            )
        out_model = ProfileFamily(entries=entries)
        meta["measured_fields"] = {
            str(avail): "all (sampled curve + gamma + pack_beta + overlap)",
            **{
                str(n): "gamma, pack_beta, update_beta, overlap "
                        f"(chip-measured at world={avail})"
                for n in prior_sizes
            },
        }
        meta["prior_fields"] = {
            str(n): f"alpha, beta ({args.prior_extend} prior — no "
                    "multi-chip fabric available to measure)"
            for n in prior_sizes
        }
        if gamma_samples:
            meta["gamma_samples_s"] = [
                [k, round(t, 6)] for k, t in gamma_samples
            ]
        report = {
            "measured_world": avail,
            "alpha_s": measured.alpha,
            "beta_s_per_byte": measured.beta,
            "gamma_s": measured.gamma,
            "overlap": measured.overlap,
            "pack_beta_s_per_byte": measured.pack_beta,
            "update_beta_s_per_byte": measured.update_beta,
            "ag_fraction": measured.ag_fraction,
            "prior_extended": prior_sizes,
            "out": args.out,
        }
    elif args.world_sizes:
        extents = sorted({int(s) for s in args.world_sizes.split(",")})
        avail = len(jax.devices())
        entries = {}
        summary = {}
        for n in extents:
            if n > avail:
                raise SystemExit(
                    f"--world-sizes {n}: only {avail} devices available"
                )
            mesh = make_mesh(MeshSpec(data=n), devices=jax.devices()[:n])
            model, _, _ = calibrate_mesh(mesh)
            entries[n] = model
            summary[str(n)] = {
                "alpha_s": model.alpha,
                "beta_s_per_byte": model.beta,
                "gamma_s": model.gamma,
                "overlap": model.overlap,
                "pack_beta_s_per_byte": model.pack_beta,
                "update_beta_s_per_byte": model.update_beta,
                "ag_fraction": model.ag_fraction,
            }
        out_model = ProfileFamily(entries=entries)
        meta["world_sizes"] = extents
        report = {"family": summary, "out": args.out}
    else:
        mesh = make_mesh(MeshSpec())
        out_model, prof, gamma_samples = calibrate_mesh(mesh)
        if gamma_samples:
            meta["gamma_samples_s"] = [
                [k, round(t, 6)] for k, t in gamma_samples
            ]
        report = {
            "alpha_s": out_model.alpha,
            "beta_s_per_byte": out_model.beta,
            "gamma_s": out_model.gamma,
            "overlap": out_model.overlap,
            "pack_beta_s_per_byte": out_model.pack_beta,
            "update_beta_s_per_byte": out_model.update_beta,
            "ag_fraction": out_model.ag_fraction,
            "samples": len(prof.sizes_bytes),
            "out": args.out,
        }
    import os

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    save_profile(args.out, out_model, meta=meta)
    print(json.dumps(report))
    return 0


def _two_level_main(args) -> int:
    """--two-level: per-axis (ici, dcn) calibration -> two_level profile
    (`profiling.profile_two_level`; schema-stamped via save_profile)."""
    from mgwfbp_tpu.utils.platform import apply_platform_overrides

    apply_platform_overrides()
    import os

    import jax

    from mgwfbp_tpu.parallel.costmodel import save_profile
    from mgwfbp_tpu.profiling import profile_two_level

    dcn = int(args.dcn)
    if dcn <= 1:
        raise SystemExit("--two-level needs --dcn > 1")
    avail = len(jax.devices())
    ici = int(args.ici) if args.ici else avail // dcn
    if ici < 1 or ici * dcn > avail:
        raise SystemExit(
            f"--two-level: {ici} x {dcn} does not fit the {avail} "
            "available device(s)"
        )
    sizes = tuple(2**k for k in range(args.min_log2, args.max_log2 + 1))
    model, raw = profile_two_level(
        ici, dcn, sizes=sizes, warmup=args.warmup, iters=args.iters,
        allgather=args.allgather,
    )
    meta = {
        "device_kind": jax.devices()[0].device_kind,
        "mesh": {"ici": ici, "dcn": dcn},
        "payload_log2_range": [args.min_log2, args.max_log2],
        "iters": args.iters,
        "fit": raw["fit"],
        "ag_fraction": raw["ag_fraction"],
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    save_profile(args.out, model, meta=meta)
    print(json.dumps({
        "ici": {
            "alpha_s": model.ici.alpha, "beta_s_per_byte": model.ici.beta,
            "ag_fraction": raw["ag_fraction"],
        },
        "dcn": {
            "alpha_s": model.dcn.alpha, "beta_s_per_byte": model.dcn.beta,
        },
        "mesh": {"ici": ici, "dcn": dcn},
        "samples": len(raw["sizes_bytes"]),
        "out": args.out,
    }))
    return 0


def _forward_main(args) -> int:
    """--forward: per-layer backward + forward benchmark -> layer profile
    (the tb_profile.json format trainers persist, schema_version=2)."""
    from mgwfbp_tpu.utils.platform import apply_platform_overrides

    apply_platform_overrides()
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mgwfbp_tpu import models as zoo
    from mgwfbp_tpu.parallel.allreduce import arrival_order
    from mgwfbp_tpu.profiling import (
        LAYER_PROFILE_SCHEMA_VERSION,
        benchmark_trainer_backward,
        benchmark_trainer_forward,
    )
    from mgwfbp_tpu.train.step import create_train_state

    model, meta = zoo.create_model(args.model)
    rng = jax.random.PRNGKey(0)
    import optax

    state = create_train_state(
        rng, model,
        jnp.zeros((1,) + tuple(meta.input_shape), meta.input_dtype),
        optax.sgd(0.1),
    )
    b = max(args.batch_size, 1)
    rs = np.random.RandomState(0)
    if meta.task == "lm":
        t = int(meta.input_shape[0])
        batch = {
            "x": jnp.asarray(
                rs.randint(0, meta.num_classes, (b, t)), jnp.int32
            ),
            "y": jnp.asarray(
                rs.randint(0, meta.num_classes, (b, t)), jnp.int32
            ),
        }
    elif meta.task == "ctc":
        # speech batch shape: (b, time, feat) float inputs with per-sample
        # lengths, label ids with label lengths (make_loss_fn's ctc branch
        # reads all four keys)
        t = int(meta.input_shape[0])
        label_t = max(t // 8, 4)
        batch = {
            "x": jnp.asarray(rs.randn(b, *meta.input_shape), jnp.float32),
            "input_lengths": jnp.full((b,), t, jnp.int32),
            "y": jnp.asarray(
                rs.randint(1, meta.num_classes, (b, label_t)), jnp.int32
            ),
            "label_lengths": jnp.full((b,), label_t, jnp.int32),
        }
    else:
        batch = {
            "x": jnp.asarray(
                rs.randn(b, *meta.input_shape), jnp.float32
            ),
            "y": jnp.asarray(rs.randint(0, meta.num_classes, (b,)), jnp.int32),
        }
    paths = jax.tree_util.tree_flatten_with_path(state.params)[0]
    names = [jax.tree_util.keystr(kp) for kp, _ in paths]
    perm = arrival_order(len(names), names=names)
    tb = benchmark_trainer_backward(
        model, meta, state.params, state.batch_stats, batch, perm,
        warmup=args.warmup, iters=args.iters, names=names,
    )
    tf = benchmark_trainer_forward(
        model, meta, state.params, state.batch_stats, batch, perm,
        warmup=args.warmup, iters=args.iters, names=names,
    )
    doc = {
        "schema_version": LAYER_PROFILE_SCHEMA_VERSION,
        "tb_s": list(tb),
        "tf_s": list(tf),
        "arrival_names": [names[j] for j in perm],
        "total_s": sum(tb),
        "tf_total_s": sum(tf),
        "source": getattr(tb, "source", "volume-prior"),
        "tf_source": getattr(tf, "source", "volume-prior"),
        "meta": {"model": args.model, "batch_size": b},
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(doc, f)
    print(json.dumps({
        "model": args.model,
        "tb_total_s": doc["total_s"],
        "tf_total_s": doc["tf_total_s"],
        "layers": len(doc["tb_s"]),
        "out": args.out,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
