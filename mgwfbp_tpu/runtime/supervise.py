"""`python -m mgwfbp_tpu.runtime.supervise` — launch a coordinated
multi-process training group under the auto-resubmit supervisor.

    python -m mgwfbp_tpu.runtime.supervise --processes 2 -- \
        --dnn lenet --synthetic --telemetry --logdir logs \
        --checkpoint-dir checkpoints --ckpt-every-steps 25

Everything after ``--`` goes to `mgwfbp_tpu.train_cli` verbatim; the
supervisor exports MGWFBP_COORDINATOR / MGWFBP_NUM_PROCESSES /
MGWFBP_PROCESS_ID per child. Exit-code policy (README "Multi-host
runtime"): rc 75 resubmits the whole group with bounded exponential
backoff, rc 86 (watchdog abort) stops and points at the stack dumps.
Hard failures SELF-HEAL by default (ISSUE 20): crashes relaunch at the
same world, OOM-style SIGKILLs shrink to the survivor count (elastic
resume), wedged children are detected by the liveness monitor and the
group is drained and relaunched — all under per-class budgets;
``--no-heal`` restores the old teardown-and-propagate policy.
"""

from __future__ import annotations

import argparse
import shlex
import sys
from typing import Optional

from mgwfbp_tpu.runtime.supervisor import (
    Supervisor,
    default_serve_cmd,
    default_train_cmd,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mgwfbp-supervise",
        description="multi-process training group supervisor "
                    "(auto-resubmit on rc 75 / EX_TEMPFAIL)",
    )
    p.add_argument("--processes", type=int, required=True,
                   help="process-group size (MGWFBP_NUM_PROCESSES)")
    p.add_argument("--max-restarts", dest="max_restarts", type=int,
                   default=3,
                   help="resubmission budget for preempted (rc 75) groups")
    p.add_argument("--backoff-base", dest="backoff_base", type=float,
                   default=1.0,
                   help="first resubmit delay in seconds (doubles per "
                        "restart, capped by --backoff-max)")
    p.add_argument("--backoff-max", dest="backoff_max", type=float,
                   default=60.0)
    p.add_argument("--grace", type=float, default=10.0,
                   help="seconds between SIGTERM and SIGKILL when tearing "
                        "down stragglers")
    p.add_argument("--drain-grace", dest="drain_grace", type=float,
                   default=120.0,
                   help="seconds peers get to finish their agreed drain "
                        "after the first rc-75 exit")
    p.add_argument("--log-dir", dest="log_dir", default=None,
                   help="capture each child's stdout+stderr to "
                        "<log-dir>/p<idx>.i<incarnation>.log (default: "
                        "inherit this terminal)")
    p.add_argument("--port", type=int, default=None,
                   help="coordinator port (default: pick a free one per "
                        "incarnation)")
    p.add_argument("--fleet-port", dest="fleet_port", type=int,
                   default=None,
                   help="serve the group-level fan-in here "
                        "(/fleet/metrics merges every child's registry "
                        "metrics under a process label, /fleet/status "
                        "the live straggler table + group alarms; 0 = "
                        "ephemeral). Needs MGWFBP_METRICS_PORT exported "
                        "for the children")
    p.add_argument("--fleet-file", dest="fleet_file", default=None,
                   help="persist the children's ACTUAL metrics endpoints "
                        "here in Prometheus http_sd format (default: "
                        "<log-dir>/fleet.json when --log-dir is set)")
    p.add_argument("--resize-to", dest="resize_to", type=int, default=None,
                   help="elastic resize: relaunch the group at this many "
                        "processes at the next drain. With "
                        "MGWFBP_METRICS_PORT set the supervisor initiates "
                        "the drain itself (SIGTERM once a child reports a "
                        "completed step); the relaunched incarnation "
                        "resumes from the exact step — shard-native "
                        "checkpoints re-shard onto the new world size")
    p.add_argument("--serve-replicas", dest="serve_replicas", type=int,
                   default=0,
                   help="spawn this many hot-reload serving replicas "
                        "(python -m mgwfbp_tpu.serving) alongside the "
                        "training group; replicas live for the whole "
                        "supervisor run (resubmits/resizes do not churn "
                        "them) and join the fleet under the serve role "
                        "on role-offset metrics ports")
    p.add_argument("--serve-args", dest="serve_args", default=None,
                   help="arguments for the serving CLI, one shell-quoted "
                        "string (e.g. --serve-args '--dnn lenet "
                        "--checkpoint-dir ckpts --shadow')")
    p.add_argument("--no-heal", dest="heal", action="store_false",
                   default=True,
                   help="disable self-healing: any hard child failure "
                        "(crash/OOM/wedge) tears the group down and "
                        "propagates, the pre-ISSUE-20 policy")
    p.add_argument("--heal-max-restarts", dest="heal_max_restarts",
                   type=int, default=2,
                   help="per-failure-class healing budget (crash, "
                        "oom_kill, wedge, ... each get this many "
                        "relaunches before the supervisor gives up)")
    p.add_argument("--liveness-grace", dest="liveness_grace", type=float,
                   default=None,
                   help="seconds a child's /status step may stay frozen "
                        "(or its endpoint unreachable) before it is "
                        "declared wedged and the group is healed "
                        "(default: MGWFBP_LIVENESS_GRACE_S or 120)")
    p.add_argument("--serve-max-restarts", dest="serve_max_restarts",
                   type=int, default=3,
                   help="per-replica respawn budget for crashed serve "
                        "replicas (backoff-spaced; budget spent = the "
                        "replica stays down)")
    p.add_argument("train_args", nargs=argparse.REMAINDER,
                   help="arguments for mgwfbp_tpu.train_cli (prefix "
                        "with --)")
    return p


def main(argv: Optional[list[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    train_args = args.train_args
    if train_args and train_args[0] == "--":
        train_args = train_args[1:]
    sup = Supervisor(
        default_train_cmd(train_args),
        args.processes,
        max_restarts=args.max_restarts,
        backoff_base_s=args.backoff_base,
        backoff_max_s=args.backoff_max,
        grace_s=args.grace,
        drain_grace_s=args.drain_grace,
        log_dir=args.log_dir,
        port=args.port,
        fleet_port=args.fleet_port,
        fleet_file=args.fleet_file,
        resize_to=args.resize_to,
        serve_replicas=args.serve_replicas,
        serve_cmd=(
            default_serve_cmd(shlex.split(args.serve_args or ""))
            if args.serve_replicas else None
        ),
        heal=args.heal,
        heal_max_restarts=args.heal_max_restarts,
        liveness_grace_s=args.liveness_grace,
        serve_max_restarts=args.serve_max_restarts,
    )
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
