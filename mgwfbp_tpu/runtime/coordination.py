"""Cross-process agreement primitives for the multi-host runtime.

Synchronous data-parallel SGD means every host-side decision that changes
which jitted program runs next — drain on preemption, roll back after K
bad steps, commit an autotune winner — must be IDENTICAL on every
process, or the processes issue mismatched collectives and the group
deadlocks (the failure mode the PR-3 autotuner refused multi-host over).
These primitives make that identity explicit and cheap:

  agree_any / agree_all   boolean consensus over one flag per process
  broadcast_flag          process-`source`'s value, everywhere
  all_argmin              per-candidate times -> one agreed winner index
                          (each candidate priced at its SLOWEST process —
                          a sync group can't run faster than its straggler)
  barrier                 named rendezvous with a real timeout

Transport: one tiny jitted psum/pmax over a throwaway 1-axis mesh of all
global devices (the `jax.experimental.multihost_utils` building block,
re-implemented here because `process_allgather`'s single-device reshard
is unimplemented on the CPU backend this repo's tier-1 runs on). Each
process contributes its payload on its FIRST local device and the
reduction identity elsewhere, so the psum sums exactly once per process.
The collectives carry the `runtime_coord` name scope — declared in
`analysis/jaxpr_check.py` DEFAULT_ALLOWED_SCOPES, so a future step that
traces an agreement into a jitted program stays verifier-clean (SCH004).

Every primitive is a LOCKSTEP COLLECTIVE when `process_count() > 1`:
all processes must call the same primitives in the same order with
same-shaped payloads (the same invariant their jitted steps already
obey). Single-process calls short-circuit on the host — zero device
work, so these are safe to leave in single-host hot paths.

Payloads ride float32 on the device (jax x64 is off): exact for flags,
counts below 2**24, and wall-clock seconds — the only things routed
through here.

Every multi-process call is BOUNDED: the device-transport primitives run
under `MGWFBP_COORD_TIMEOUT_S` (default = the barrier timeout) and the
barrier under `MGWFBP_BARRIER_TIMEOUT_S`; a miss or transport error
raises `CoordinationTimeout` so a dead/wedged peer surfaces as a clean
restart-friendly exit instead of an indefinite hang.
"""

from __future__ import annotations

import collections
import functools
import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mgwfbp_tpu.utils.platform import (
    env_float,
    get_shard_map,
    run_with_deadline,
)

# 1-axis mesh over every global device, used only by these primitives
COORD_AXIS = "coord"
# name scope stamped on the agreement collectives (jaxpr_check SCH004
# allowed scope — keep in sync with analysis/jaxpr_check.py)
COORD_SCOPE = "runtime_coord"

# default barrier timeout; a peer that never arrives means a dead or
# wedged process — fail so the supervisor can tear down and resubmit
BARRIER_TIMEOUT_ENV = "MGWFBP_BARRIER_TIMEOUT_S"
DEFAULT_BARRIER_TIMEOUT_S = 600.0

# real-deadline contract for the DEVICE-transport primitives (ISSUE 20):
# agree_any / agree_all / broadcast_flag / gather_* / agree_uniform /
# all_argmin block inside a gloo/ICI collective when a peer is dead or
# wedged — exactly the hang the barrier's timeout already refuses. The
# same deadline bounds them all; a miss raises CoordinationTimeout so
# the trainer can convert an opaque distributed hang into a clean
# rc-75-style exit the supervisor's healer understands.
COORD_TIMEOUT_ENV = "MGWFBP_COORD_TIMEOUT_S"


class CoordinationTimeout(RuntimeError):
    """A lockstep group operation did not complete within its real
    deadline (or its transport failed outright): a peer process is dead
    or wedged, so the collective can NEVER complete. The process is
    tainted (an abandoned worker thread may hold transport locks) — the
    caller must exit promptly and restart-friendly; train_cli converts
    this to rc 75 (drain-less: no checkpoint barrier can complete
    either) so the supervisor heals the group from the last committed
    step."""

    def __init__(self, op: str, timeout_s: float, detail: str = ""):
        super().__init__(
            f"coordination op {op!r} did not complete within "
            f"{timeout_s:.0f}s{f' ({detail})' if detail else ''}; a peer "
            "process is dead or wedged — exiting restart-friendly so the "
            "supervisor can heal the group"
        )
        self.op = op
        self.timeout_s = timeout_s


def _coord_timeout_s() -> float:
    return env_float(COORD_TIMEOUT_ENV, DEFAULT_BARRIER_TIMEOUT_S)


# ---------------------------------------------------------------------------
# group-operation registry
# ---------------------------------------------------------------------------

# name -> {"blocking": bool, "uniform_result": bool}. Populated by the
# @group_op decorator below; the SPMD lockstep checker
# (analysis/spmd_check.py) discovers its op list from these decorations —
# the checker and the transport cannot drift, because a new primitive is
# a new decoration, and the decoration IS the registration.
GROUP_OPS: dict[str, dict] = {}


def group_op(fn=None, *, blocking: bool = True, uniform_result: bool = True):
    """Mark a function as a LOCKSTEP GROUP OPERATION: when
    ``process_count() > 1`` every process must call it, in the same
    order, with same-shaped payloads, or the group deadlocks.

    ``blocking`` — the call cannot return until every process arrives
    (true for every primitive here: psum/pmax rendezvous on the device,
    barrier on the coordination service). ``uniform_result`` — the return
    value is bitwise-identical on every process, so host decisions
    branching on it keep the group in lockstep (the checker treats such
    results as group-uniform sanitizers).
    """
    def register(f):
        GROUP_OPS[f.__name__] = {
            "blocking": bool(blocking),
            "uniform_result": bool(uniform_result),
        }
        return f

    if fn is not None:
        return register(fn)
    return register


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def is_primary() -> bool:
    """True on the process that owns exactly-once side effects (sidecar
    index writes, autotune cache persistence, ...)."""
    return jax.process_index() == 0


# ---------------------------------------------------------------------------
# device transport
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _coord_mesh() -> Mesh:
    return Mesh(np.asarray(jax.devices()), (COORD_AXIS,))


@functools.lru_cache(maxsize=None)
def _reduce_prog(kind: str):
    """Jitted (n_devices, k) -> replicated (k,) reduction program."""
    mesh = _coord_mesh()
    shard_map = get_shard_map()

    def body(x):
        with jax.named_scope(COORD_SCOPE):
            if kind == "sum":
                return lax.psum(jnp.sum(x, axis=0), COORD_AXIS)
            return lax.pmax(jnp.max(x, axis=0), COORD_AXIS)

    return jax.jit(
        shard_map(body, mesh=mesh, in_specs=P(COORD_AXIS), out_specs=P())
    )


def _device_reduce(
    vals: Sequence[float], kind: str, op: str = "device_reduce",
) -> np.ndarray:
    """Reduce a per-process float vector across ALL processes ("sum" or
    "max"); returns the identical reduced vector on every process.

    Each process contributes `vals` on its first local device and the
    reduction identity (0 / -inf) on the rest, so device multiplicity
    never double-counts a process. Works single-process too (the tests
    exercise the device path directly); the public primitives
    short-circuit before reaching here when there is nothing to agree.

    Multi-process, the blocking collective runs under the same real
    deadline the barrier already has (MGWFBP_COORD_TIMEOUT_S, default
    the barrier default): a dead or wedged peer means the rendezvous can
    never complete, and a deadline miss — or the transport erroring
    outright (a peer's death can also surface as a connection reset from
    the collective instead of a hang) — raises CoordinationTimeout
    naming `op` so the caller exits restart-friendly instead of hanging
    until the supervisor's hard teardown."""
    row = np.asarray(vals, np.float32).reshape(-1)
    fill = 0.0 if kind == "sum" else -np.inf
    local = np.full((jax.local_device_count(), row.size), fill, np.float32)
    local[0] = row
    sharding = NamedSharding(_coord_mesh(), P(COORD_AXIS))
    garr = jax.make_array_from_process_local_data(sharding, local)
    if jax.process_count() == 1:
        # nothing to rendezvous with: no deadline thread per call on the
        # single-host hot path (and the direct-call unit tests)
        return np.asarray(_reduce_prog(kind)(garr))
    timeout_s = _coord_timeout_s()
    try:
        return run_with_deadline(
            lambda: np.asarray(_reduce_prog(kind)(garr)),
            timeout_s, what=f"coordination op {op!r}",
        )
    except Exception as e:  # noqa: BLE001 — deadline miss and transport
        # failure are ONE structured surface: both mean a peer is gone
        raise CoordinationTimeout(op, timeout_s, detail=str(e)) from e


# ---------------------------------------------------------------------------
# agreement primitives
# ---------------------------------------------------------------------------

@group_op
def agree_any(flag: bool) -> bool:
    """True everywhere iff ANY process passed True (preempt drain: one
    signaled host drains the whole group)."""
    if process_count() == 1:
        return bool(flag)
    return bool(
        _device_reduce([1.0 if flag else 0.0], "sum", op="agree_any")[0]
        > 0.0
    )


@group_op
def agree_all(flag: bool) -> bool:
    """True everywhere iff EVERY process passed True (rollback: only when
    every host can restore; autotune cache hit: only when every host has
    the entry)."""
    if process_count() == 1:
        return bool(flag)
    total = _device_reduce(
        [1.0 if flag else 0.0], "sum", op="agree_all",
    )[0]
    return bool(total >= float(process_count()))


@group_op
def broadcast_flag(value: float, source: int = 0) -> float:
    """Process `source`'s scalar, identical everywhere (the tb-profile
    broadcast pattern, for host decisions: restore-target steps,
    agreed winner indices, ...)."""
    if process_count() == 1:
        return float(value)
    contrib = float(value) if process_index() == source else 0.0
    return float(_device_reduce([contrib], "sum", op="broadcast_flag")[0])


@group_op
def gather_values(value: float) -> list[float]:
    """Every process's scalar, in process order, identical everywhere
    (the live straggler probe: each process contributes its window step
    time; everyone sees the full per-process vector and agrees on who is
    slow). One-hot rows summed — same transport, same lockstep contract
    as every other primitive here."""
    if process_count() == 1:
        return [float(value)]
    row = [0.0] * process_count()
    row[process_index()] = float(value)
    return [
        float(t) for t in _device_reduce(row, "sum", op="gather_values")
    ]


@group_op
def gather_vectors(values: Sequence[float]) -> list[list[float]]:
    """Every process's float VECTOR, in process order, identical
    everywhere — `gather_values` for per-group payloads (the on-demand
    deep-profile window gathers each process's trace-attributed per-group
    device seconds). Every process must pass the SAME length (the
    lockstep-shape contract all primitives here carry; merge-group count
    is group-uniform by construction). One-hot block rows summed through
    the same transport."""
    row = [float(v) for v in values]
    n = process_count()
    if n == 1:
        return [row]
    k = len(row)
    if k == 0:
        return [[] for _ in range(n)]
    flat = [0.0] * (n * k)
    start = process_index() * k
    flat[start:start + k] = row
    reduced = _device_reduce(flat, "sum", op="gather_vectors")
    return [
        [float(t) for t in reduced[i * k:(i + 1) * k]] for i in range(n)
    ]


@group_op
def agree_uniform(value: float) -> bool:
    """True iff every process passed the SAME scalar (max == min across
    the group). The cheap divergence guard for values that MUST be
    group-uniform before a collective side effect — e.g. the step key a
    shard-native checkpoint commit is about to write: processes saving
    different steps means the lockstep invariant already broke, and
    writing a torn manifest would bake the divergence into disk."""
    if process_count() == 1:
        return True
    v = float(value)
    mx = float(_device_reduce([v], "max", op="agree_uniform")[0])
    mn = -float(_device_reduce([-v], "max", op="agree_uniform")[0])
    return mx == mn


@group_op
def all_argmin(values: Sequence[Optional[float]]) -> tuple[int, list[float]]:
    """Agreed argmin over per-candidate timings.

    `values[i]` is this process's measured time for candidate i (None =
    not measured here). Each candidate is reduced to its MAX across
    processes — a synchronous group runs at its straggler's pace, and a
    candidate unmeasured anywhere prices as +inf — then every process
    computes the same argmin over the same reduced vector.

    Returns (winner_index, reduced_times); reduced_times[winner] is
    +inf iff NO candidate was measured on every process.
    """
    vals = [
        float("inf") if v is None or not np.isfinite(v) else float(v)
        for v in values
    ]
    if not vals:
        raise ValueError("all_argmin: empty candidate list")
    if process_count() > 1:
        vals = [
            float(t) for t in _device_reduce(vals, "max", op="all_argmin")
        ]
    return int(np.argmin(vals)), vals


# per-name use counters: barrier keys must be unique per rendezvous, and
# every process mints the same sequence as long as its call order matches
# (the same lockstep invariant every primitive here already requires)
_barrier_seq: collections.Counter = collections.Counter()


@group_op(uniform_result=False)
def barrier(name: str, timeout_s: Optional[float] = None) -> None:
    """Named rendezvous across all processes, with a real timeout.

    Uses the jax.distributed coordination-service barrier (timeout
    enforced server-side); a missing client degrades to
    `multihost_utils.sync_global_devices` under a thread deadline. A
    timeout raises CoordinationTimeout (a RuntimeError) — the caller
    should treat the process group as broken and exit so the supervisor
    can heal it.
    """
    if process_count() == 1:
        return
    if timeout_s is None:
        raw = (os.environ.get(BARRIER_TIMEOUT_ENV) or "").strip()
        if raw:
            try:
                timeout_s = float(raw)
            except ValueError:
                # a garbage value must fail with the variable named, not
                # a bare float() traceback mid-drain
                raise ValueError(
                    f"{BARRIER_TIMEOUT_ENV}={raw!r} is not a number"
                ) from None
        else:
            timeout_s = DEFAULT_BARRIER_TIMEOUT_S
    key = f"mgwfbp:{name}:{_barrier_seq[name]}"
    _barrier_seq[name] += 1
    try:
        from jax._src import distributed

        client = distributed.global_state.client
    except Exception:  # noqa: BLE001 — private module moved; use fallback
        client = None
    try:
        if client is not None:
            client.wait_at_barrier(key, int(timeout_s * 1000))
        else:
            from jax.experimental import multihost_utils

            run_with_deadline(
                lambda: multihost_utils.sync_global_devices(key),
                timeout_s, what=f"barrier {name!r}",
            )
    except Exception as e:  # noqa: BLE001 — uniform failure surface
        raise CoordinationTimeout(
            f"barrier:{name}", timeout_s, detail=str(e)
        ) from e
