"""Process-group supervisor: launch, watch, resubmit.

The resilience layer (PR 5) made a preempted training process exit rc 75
(EX_TEMPFAIL) after draining to a step-indexed checkpoint — but nothing
restarted it, so "preemption-safe" ended at the process boundary. This
module closes the loop for a LOCAL multi-process group (one host driving
N coordinated processes; on a real pod each host runs its own train_cli
under the cluster's scheduler and only the rc contract below applies):

  rc 0   (all)   the run finished; exit 0.
  rc 75  (any)   graceful preemption drain: progress is checkpointed and
                 the whole group agreed to exit (runtime/coordination) —
                 resubmit the ENTIRE group after bounded exponential
                 backoff, until the restart budget is spent.
  rc 86  (any)   watchdog abort: a wedged device/runtime; the aborting
                 process faulthandler-dumped every thread's stack first.
                 Restarting a wedged grant loops forever, so STOP and
                 surface where the dumps are.
  other  (any)   a HARD failure. With healing on (the default, ISSUE
                 20): classify it (classify_rc — crash / oom_kill /
                 term), SIGTERM the survivors so they drain through the
                 agreed-preempt path (or their coordination deadline),
                 then relaunch — same world when the slot looks
                 recoverable, SHRUNK to the survivor count for an
                 OOM-style SIGKILL (elastic resume re-shards off the
                 last committed shard-native step) — under per-class
                 restart budgets and a same-step crash-loop detector.
                 With heal=False: tear down the stragglers (SIGTERM,
                 grace, SIGKILL) and exit with the failing rc.

Self-healing also covers failures with NO exit code: a liveness monitor
in the `_watch` poll scrapes each child's /status (hard timeout — a hung
child can never hang the monitor) and declares a child *wedged* when its
step counter freezes past MGWFBP_LIVENESS_GRACE_S (or /healthz goes
503-sticky that long), *unreachable* when a previously-seen endpoint
stops answering; either verdict SIGTERMs the group and heals it the same
way. Every failure/heal decision is appended to the supervisor's own
telemetry stream (`telemetry.supervisor.jsonl`, process_index -1).

Launch contract (what each child sees): MGWFBP_COORDINATOR,
MGWFBP_NUM_PROCESSES, MGWFBP_PROCESS_ID — the env chain train_cli's
`resolve_multihost` reads. Everything else (fault plans, platform
overrides) is inherited, so `MGWFBP_FAULT_PLAN='preempt@step=4,proc=1'`
preempts exactly one process of the group and exercises the agreed
drain end to end.

Live observability plane (ISSUE 9): with MGWFBP_METRICS_PORT set, each
child serves /metrics /healthz /status on port + process_index
(telemetry/serve.py); the supervisor logs each child's port at launch,
and an rc-86 stop (a wedged grant the watchdog aborted) includes every
still-reachable child's last /status snapshot in the stop message — the
dead group's final state lands in the supervisor log next to the stack
dumps it points at.

Fleet console (ISSUE 10): the supervisor exports a per-child
MGWFBP_METRICS_PORT_FILE so every child persists its ACTUAL bound port
(covering the MGWFBP_METRICS_PORT=0 ephemeral case, where the
port+process_index convention is simply wrong); the resolved targets are
persisted to a `fleet.json` sidecar in Prometheus http_sd/file_sd format,
and — with ``fleet_port`` set (`supervise --fleet-port`) — served live as
the group-level fan-in: /fleet/metrics merges every child's registry
metrics under a ``process`` label, /fleet/status synthesizes the live
straggler table, slowest-process attribution, and the group's active
alarms (telemetry/fleet.py).

Elastic resize (ISSUE 13): ``--resize-to M`` relaunches the NEXT
incarnation at M processes instead of N. With the live plane configured
the supervisor initiates the drain itself — SIGTERM to the whole group
once a child reports a completed step over /status (the agreed-preempt
path checkpoints shard-native and exits rc 75); without it the resize
applies at the next natural preemption. Children get
MGWFBP_ELASTIC_RESUME=1 so a relaunch at a new size finds the old
world's checkpoints under their sibling tag and re-shards
(train.trainer._resume_cross_world); /fleet/status carries the
transition as a ``resize`` view while it happens.

`python -m mgwfbp_tpu.runtime.supervise --processes 2 -- <train args>`
is the CLI (see runtime/supervise.py).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Callable, Optional, Sequence

from mgwfbp_tpu.utils.faults import PREEMPT_RC
from mgwfbp_tpu.utils.logging import get_logger
from mgwfbp_tpu.utils.platform import env_float

# utils/watchdog.py exits the process with os._exit(86) after dumping all
# thread stacks; keep in sync (the watchdog predates this constant)
WATCHDOG_RC = 86

# self-healing (ISSUE 20): how long a child's /status step may stay
# frozen (or its endpoint unreachable after having been seen) before the
# liveness monitor declares it wedged/unreachable and heals the group
LIVENESS_GRACE_ENV = "MGWFBP_LIVENESS_GRACE_S"
DEFAULT_LIVENESS_GRACE_S = 120.0

# failure classes a child exit decodes to (classify_rc) — the healing
# policy and the `failure` telemetry event share this vocabulary
HEAL_CLASSES = (
    "crash", "oom_kill", "wedge", "unreachable", "term",
)


def classify_rc(rc: int) -> str:
    """Decode one child returncode into the rc-policy vocabulary.

    Popen returncodes are negative for signal deaths (-N = killed by
    signal N); a shell-style 128+N is decoded the same way so the table
    holds for rcs relayed through an intermediate shell. SIGKILL is
    'oom_kill' — on Linux the OOM killer delivers exactly SIGKILL, and a
    sibling that was SIGKILLed by an operator heals identically (the
    slot's memory demand is suspect either way, so the healer SHRINKS
    rather than relaunching the same footprint). SIGTERM is 'term': an
    external/preempt-style stop that never drained — recoverable at the
    same world.
    """
    if rc == 0:
        return "ok"
    if rc == PREEMPT_RC:
        return "preempt"
    if rc == WATCHDOG_RC:
        return "watchdog"
    sig = -rc if rc < 0 else (rc - 128 if 128 < rc < 160 else None)
    if sig == int(signal.SIGKILL):
        return "oom_kill"
    if sig in (int(signal.SIGTERM), int(signal.SIGINT)):
        return "term"
    return "crash"


class _LivenessTracker:
    """Per-child liveness state machine for the `_watch` poll.

    Fed one `/status` scrape (or None) per child per poll; classifies
    each child as 'running', 'wedged' (alive but its step counter froze
    past the grace, or /status reports sticky-unhealthy past the grace),
    'unreachable' (endpoint stopped answering after having been seen),
    or 'unknown' (never seen — still booting/compiling; pre-step hangs
    are the in-process watchdog's domain, not ours). Pure host state
    driven by an injected clock — unit-testable without processes.
    """

    def __init__(self) -> None:
        self._step: dict[int, int] = {}
        self._step_t: dict[int, float] = {}
        self._seen: set[int] = set()
        self._unhealthy_t: dict[int, float] = {}
        self._unreachable_t: dict[int, float] = {}

    def observe(self, idx: int, status, now: float) -> None:
        if status is None:
            # only a child that HAS answered can become unreachable —
            # never-seen children are booting, not lost
            if idx in self._seen:
                self._unreachable_t.setdefault(idx, now)
            return
        self._seen.add(idx)
        self._unreachable_t.pop(idx, None)
        step = int(status.get("step") or 0)
        if step != self._step.get(idx):
            self._step[idx] = step
            self._step_t[idx] = now
        elif idx not in self._step_t:
            self._step_t[idx] = now
        if status.get("healthy") is False:
            self._unhealthy_t.setdefault(idx, now)
        else:
            self._unhealthy_t.pop(idx, None)

    def classify(self, idx: int, now: float, grace_s: float) -> str:
        if idx not in self._seen:
            return "unknown"
        t = self._unreachable_t.get(idx)
        if t is not None and now - t > grace_s:
            return "unreachable"
        t = self._unhealthy_t.get(idx)
        if t is not None and now - t > grace_s:
            return "wedged"
        # a frozen step only counts once the child has EVER stepped:
        # compile/bootstrap legitimately sits at step 0 for a long time
        if (
            self._step.get(idx, 0) >= 1
            and now - self._step_t.get(idx, now) > grace_s
        ):
            return "wedged"
        return "running"

    def max_step(self) -> int:
        """Highest step any child ever reported (crash-loop detection:
        the same max step across consecutive healed incarnations means
        the group is dying at the same point every life)."""
        return max(self._step.values(), default=0)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@dataclasses.dataclass
class GroupResult:
    """Outcome of one incarnation of the process group."""

    incarnation: int
    returncodes: list[int]

    @property
    def ok(self) -> bool:
        return all(rc == 0 for rc in self.returncodes)

    @property
    def preempted(self) -> bool:
        """Restart-friendly: at least one drain, nothing worse."""
        return (
            any(rc == PREEMPT_RC for rc in self.returncodes)
            and all(rc in (0, PREEMPT_RC) for rc in self.returncodes)
        )

    @property
    def watchdog_abort(self) -> bool:
        return any(rc == WATCHDOG_RC for rc in self.returncodes)


class Supervisor:
    """Launch a coordinated N-process group and apply the rc policy.

    `base_cmd` is the per-process command (default: this interpreter's
    train_cli); process index, count, and coordinator land in the child
    ENV, not argv, so the same command line serves every slot and every
    incarnation. Injectable `sleep` keeps the backoff testable.
    """

    def __init__(
        self,
        base_cmd: Sequence[str],
        processes: int,
        *,
        max_restarts: int = 3,
        backoff_base_s: float = 1.0,
        backoff_max_s: float = 60.0,
        grace_s: float = 10.0,
        drain_grace_s: float = 120.0,
        log_dir: Optional[str] = None,
        env: Optional[dict] = None,
        port: Optional[int] = None,
        fleet_port: Optional[int] = None,
        fleet_file: Optional[str] = None,
        resize_to: Optional[int] = None,
        serve_replicas: int = 0,
        serve_cmd: Optional[Sequence[str]] = None,
        heal: bool = True,
        heal_max_restarts: int = 2,
        heal_same_step_limit: int = 3,
        liveness_grace_s: Optional[float] = None,
        serve_max_restarts: int = 3,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if processes < 1:
            raise ValueError(f"processes must be >= 1, got {processes}")
        if resize_to is not None and resize_to < 1:
            raise ValueError(f"resize_to must be >= 1, got {resize_to}")
        if serve_replicas < 0:
            raise ValueError(
                f"serve_replicas must be >= 0, got {serve_replicas}"
            )
        if serve_replicas and not serve_cmd:
            raise ValueError("serve_replicas > 0 needs a serve_cmd")
        self.base_cmd = list(base_cmd)
        self.processes = int(processes)
        self.max_restarts = int(max_restarts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.grace_s = float(grace_s)
        self.drain_grace_s = float(drain_grace_s)
        self.log_dir = log_dir
        self.env = dict(env if env is not None else os.environ)
        self.port = port
        self.sleep = sleep
        self.log = get_logger("mgwfbp.supervisor")
        self.results: list[GroupResult] = []
        # last /status of each still-alive peer, captured by _watch at
        # the moment an rc-86 exit is first observed (None = no abort
        # seen yet this incarnation)
        self._status_snapshots: Optional[dict] = None
        # fleet console (ISSUE 10): fan-in server port (None = off,
        # 0 = ephemeral), http_sd sidecar path, port-file directory
        self.fleet_port = fleet_port
        self._fleet_file_explicit = fleet_file is not None
        self.fleet_file = fleet_file or (
            os.path.join(log_dir, "fleet.json") if log_dir else None
        )
        self.fleet_server = None
        self._ports_dir: Optional[str] = None
        self._last_fleet_targets: Optional[dict] = None
        # supervisor-driven elastic resize (ISSUE 13): relaunch the next
        # incarnation at `resize_to` processes once the current one
        # drains. With the live plane configured the supervisor TRIGGERS
        # the drain itself (SIGTERM to the whole group as soon as a child
        # reports a completed step — the agreed-preempt path takes it
        # from there); otherwise the resize applies at the next natural
        # preemption.
        self.resize_to = resize_to
        self._initial_processes = int(processes)
        self._resize_signaled = False
        self._resize_poll_t = 0.0
        self._resize_no_metrics_warned = False
        # serving replicas (ISSUE 19): spawned ONCE for the supervisor's
        # lifetime — they hot-reload checkpoints across incarnations, so
        # a training-group resubmit/resize must not churn them. Excluded
        # from the rc policy (a dead replica degrades serving, never the
        # training job); folded into the fleet under the `serve` role.
        self.serve_replicas = int(serve_replicas)
        self.serve_cmd = list(serve_cmd) if serve_cmd else None
        self._serve_procs: list = []
        self._serve_logs: list = []
        self._serve_exit_warned: set = set()
        # self-healing (ISSUE 20): hard failures (crash/oom/wedge/
        # unreachable) heal the group instead of tearing it down —
        # relaunch at the same world when the slot looks recoverable,
        # SHRINK to the survivor count (elastic resume) when not, under
        # per-failure-class restart budgets. heal=False keeps the old
        # teardown-and-propagate policy verbatim.
        self.heal = bool(heal)
        self.heal_max_restarts = int(heal_max_restarts)
        self.heal_same_step_limit = int(heal_same_step_limit)
        # garbage in the env knob must fail NOW, naming the variable —
        # not mid-heal (env_float = the MGWFBP_BARRIER_TIMEOUT_S contract)
        self.liveness_grace_s = (
            float(liveness_grace_s)
            if liveness_grace_s is not None
            else env_float(
                LIVENESS_GRACE_ENV, DEFAULT_LIVENESS_GRACE_S,
                environ=self.env,
            )
        )
        self._liveness = _LivenessTracker()
        self._liveness_poll_t = 0.0
        # the failure the current incarnation is dying of: set by the
        # liveness monitor (wedge/unreachable — it SIGTERMs the group,
        # so every child exits 75 and the rc vector alone would look
        # like a plain preempt) or by the hard-exit path in _watch
        self._pending_failure: Optional[dict] = None
        # slot index -> rc for children that exited HARD this
        # incarnation, captured before teardown pollutes the rc vector
        # with its own -15/-9
        self._failed_slots: dict[int, int] = {}
        self._heal_restarts: dict[str, int] = {}
        # max observed step per healed incarnation (crash-loop detection)
        self._crash_steps: list[int] = []
        self._postmortem_paths: list[str] = []
        # serve-replica restart policy (satellite): respawn with backoff
        # under an own budget instead of the old spawn-once
        self.serve_max_restarts = int(serve_max_restarts)
        self._serve_restarts: list[int] = []
        self._serve_respawn_at: dict[int, float] = {}
        self._incarnation = 0
        self._events = None  # lazy supervisor-stream EventWriter

    # -- launch ------------------------------------------------------------
    def _metrics_base_port(self) -> Optional[int]:
        """The group's configured metrics base port (child i serves
        base + i — telemetry/serve.resolve_metrics_port), or None when
        the plane is off or the base is ephemeral (0: per-child ports are
        unknowable from outside)."""
        raw = (self.env.get("MGWFBP_METRICS_PORT") or "").strip()
        if not raw:
            return None
        try:
            base = int(raw)
        except ValueError:
            return None
        return base if base > 0 else None

    def _metrics_enabled(self) -> bool:
        """True when the group's live plane is configured at all
        (MGWFBP_METRICS_PORT set to anything, including 0/ephemeral)."""
        raw = (self.env.get("MGWFBP_METRICS_PORT") or "").strip()
        if not raw:
            return False
        try:
            return int(raw) >= 0
        except ValueError:
            return False

    def _port_file(self, idx: int, role: str = "train") -> str:
        """Per-child metrics port-file sidecar path (the child's
        telemetry/serve writes its ACTUAL bound port there). Role-aware:
        serve replicas get their own `metrics_port.serve{i}.json`
        namespace so replica i never clobbers training child i's file."""
        if self._ports_dir is None:
            if self.log_dir:
                self._ports_dir = self.log_dir
                os.makedirs(self._ports_dir, exist_ok=True)
            else:
                import tempfile

                self._ports_dir = tempfile.mkdtemp(
                    prefix="mgwfbp_fleet_ports_"
                )
        stem = f"serve{idx}" if role == "serve" else f"p{idx}"
        return os.path.join(self._ports_dir, f"metrics_port.{stem}.json")

    def _child_targets(self) -> dict:
        """process index -> (host, port) of every currently-resolvable
        child metrics endpoint: the child-written port file (the ACTUAL
        bound port — authoritative, and the only source in the ephemeral
        base==0 case), falling back to the base+index convention for
        children that have not bound yet."""
        if not self._metrics_enabled():
            return {}
        import json as _json

        base = self._metrics_base_port()
        targets: dict = {}
        for i in range(self.processes):
            path = self._port_file(i)
            try:
                with open(path) as f:
                    doc = _json.load(f)
                targets[i] = (
                    str(doc.get("host") or "127.0.0.1"),
                    int(doc["port"]),
                )
                continue
            except (OSError, ValueError, KeyError, TypeError):
                pass
            if base is not None:
                targets[i] = ("127.0.0.1", base + i)
        if self.serve_replicas:
            from mgwfbp_tpu.telemetry.serve import resolve_metrics_port

            for i in range(self.serve_replicas):
                key = f"serve{i}"
                path = self._port_file(i, role="serve")
                try:
                    with open(path) as f:
                        doc = _json.load(f)
                    targets[key] = (
                        str(doc.get("host") or "127.0.0.1"),
                        int(doc["port"]),
                    )
                    continue
                except (OSError, ValueError, KeyError, TypeError):
                    pass
                if base is not None:
                    # the serve offset keeps replica ports disjoint from
                    # the training children's base+index band
                    targets[key] = (
                        "127.0.0.1",
                        resolve_metrics_port(base, i, role="serve"),
                    )
        return targets

    @staticmethod
    def _target_role(key) -> str:
        return "serve" if isinstance(key, str) else "train"

    def _refresh_fleet(self) -> None:
        """Re-resolve the child target map; persist `fleet.json`
        (Prometheus http_sd format) whenever it changes. Called from the
        `_watch` poll loop — targets appear as children bind their
        (possibly ephemeral) ports and write their port files."""
        if not self._metrics_enabled():
            return
        targets = self._child_targets()
        if targets == self._last_fleet_targets:
            return
        if self.fleet_file and targets:
            from mgwfbp_tpu.telemetry.fleet import write_fleet_sd

            try:
                write_fleet_sd(
                    self.fleet_file, targets,
                    roles={k: self._target_role(k) for k in targets},
                )
            except OSError as e:
                # do NOT record the targets: the sidecar is stale, and a
                # stable group would otherwise never retry the write
                self.log.warning(
                    "could not write fleet sidecar %s: %s",
                    self.fleet_file, e,
                )
                return
            self.log.info(
                "fleet targets -> %s (%s)", self.fleet_file,
                ", ".join(
                    f"{'' if isinstance(i, str) else 'p'}{i}={h}:{p}"
                    for i, (h, p) in sorted(
                        targets.items(), key=lambda kv: str(kv[0])
                    )
                ),
            )
        self._last_fleet_targets = dict(targets)

    def _emit(self, event: str, **fields) -> None:
        """Append one record to the supervisor's OWN telemetry stream
        (`telemetry.supervisor.jsonl` — deliberately outside
        find_stream_paths' per-process pattern, so per-run merges only
        see it when asked for explicitly). process_index -1 marks the
        emitter as nobody's training rank. Best-effort: telemetry must
        never be what kills the healer."""
        if not self.log_dir:
            return
        try:
            if self._events is None:
                from mgwfbp_tpu.telemetry.events import EventWriter

                os.makedirs(self.log_dir, exist_ok=True)
                self._events = EventWriter(
                    os.path.join(
                        self.log_dir, "telemetry.supervisor.jsonl"
                    ),
                    run={"process_index": -1, "role": "supervisor"},
                )
            self._events.emit(event, **fields)
        except Exception as e:  # noqa: BLE001 — observability best-effort
            self.log.warning(
                "could not emit %s telemetry event: %s", event, e
            )

    def _fleet_meta(self) -> dict:
        """Supervisor-level fields for /fleet/status."""
        meta = {
            "incarnation": len(self.results),
            "processes_configured": self.processes,
        }
        meta["heal"] = {
            "enabled": self.heal,
            "restarts": dict(self._heal_restarts),
            "budget": self.heal_max_restarts,
            "liveness_grace_s": self.liveness_grace_s,
        }
        if self._pending_failure is not None:
            meta["heal"]["pending_failure"] = dict(self._pending_failure)
        if self.serve_replicas:
            meta["serving"] = {
                "replicas": self.serve_replicas,
                "alive": sum(
                    1 for p in self._serve_procs if p.poll() is None
                ),
                "restarts": list(self._serve_restarts),
                "restart_budget": self.serve_max_restarts,
            }
        if self.resize_to is not None:
            # the transition is fleet-visible: pending while the group
            # still runs at the old size, done once an incarnation
            # launched at the target
            meta["resize"] = {
                "from": self._initial_processes,
                "to": self.resize_to,
                "state": (
                    "done"
                    if self.processes == self.resize_to
                    else "pending"
                ),
                "triggered": bool(self._resize_signaled),
            }
        return meta

    def _resize_pending(self) -> bool:
        return (
            self.resize_to is not None
            and self.resize_to != self.processes
        )

    def _maybe_trigger_resize(self, procs) -> None:
        """--resize-to with a healthy group: initiate the drain ourselves
        — SIGTERM the whole group once any child reports a COMPLETED step
        over /status (signal handlers are armed by then; an earlier
        signal would kill a child mid-bootstrap instead of draining it).
        Needs the live plane; without it the resize waits for the next
        natural preemption."""
        if not self._resize_pending() or self._resize_signaled:
            return
        if not self._metrics_enabled():
            if not self._resize_no_metrics_warned:
                self._resize_no_metrics_warned = True
                self.log.warning(
                    "--resize-to %d: MGWFBP_METRICS_PORT is not set, so "
                    "the supervisor cannot see training progress to time "
                    "the drain; the resize will apply at the next "
                    "preemption (rc 75) instead", self.resize_to,
                )
            return
        now = time.monotonic()
        if now - self._resize_poll_t < 0.5:  # throttle the /status polls
            return
        self._resize_poll_t = now
        for i in range(self.processes):
            st = self._child_status(i)
            if st and int(st.get("step") or 0) >= 1:
                self.log.warning(
                    "resize %d -> %d: draining the group (SIGTERM; the "
                    "agreed-preempt path checkpoints and exits rc 75)",
                    self.processes, self.resize_to,
                )
                self._resize_signaled = True
                for p in procs:
                    if p.poll() is None:
                        try:
                            p.send_signal(signal.SIGTERM)
                        except OSError:
                            pass
                return

    def _start_fleet_server(self) -> None:
        """One fan-in server for the supervisor's lifetime (targets
        re-resolve per request, so resubmitted incarnations with fresh
        ephemeral ports keep being reachable through the same URL)."""
        if self.fleet_port is None or self.fleet_server is not None:
            return
        if not self._metrics_enabled():
            self.log.warning(
                "fleet fan-in requested but MGWFBP_METRICS_PORT is not "
                "set for the children; /fleet endpoints disabled"
            )
            return
        from mgwfbp_tpu.telemetry.fleet import start_fleet_server

        self.fleet_server = start_fleet_server(
            self._child_targets, self.fleet_port,
            meta_provider=self._fleet_meta,
        )

    def _child_status(self, idx: int, timeout_s: float = 2.0):
        """Last /status snapshot of child `idx`, or None when the plane
        is off / the child is gone. Resolves the child's REAL endpoint
        through the port-file map (ephemeral ports included)."""
        target = self._child_targets().get(idx)
        if target is None:
            return None
        import json as _json
        import urllib.request

        host, port = target
        try:
            with urllib.request.urlopen(
                f"http://{host}:{port}/status", timeout=timeout_s
            ) as resp:
                return _json.loads(resp.read().decode())
        except Exception:  # noqa: BLE001 — a dead child's port refusing
            # is the expected case; the snapshot is best-effort
            return None

    def _child_env(self, idx: int, port: int, incarnation: int = 0) -> dict:
        env = dict(self.env)
        env["MGWFBP_COORDINATOR"] = f"127.0.0.1:{port}"
        env["MGWFBP_NUM_PROCESSES"] = str(self.processes)
        env["MGWFBP_PROCESS_ID"] = str(idx)
        # which life this is: the fault plan's HARD kinds (kill/wedge —
        # drain-less, so a healed relaunch resumes BELOW the fault step)
        # key on this so a chaos fault fires in exactly one incarnation
        # instead of re-firing every life (faults.for_incarnation)
        env["MGWFBP_INCARNATION"] = str(incarnation)
        # supervised groups may resume across world-size changes: a
        # relaunch at a new --processes finds the old world's checkpoints
        # under their sibling tag and re-shards (trainer
        # _resume_cross_world). Explicit operator values win.
        env.setdefault("MGWFBP_ELASTIC_RESUME", "1")
        if self._metrics_enabled():
            # the child persists its ACTUAL bound metrics port here
            # (telemetry/serve.write_port_file) — the fleet fan-in and
            # fleet.json read real ports, never the base+index guess
            env["MGWFBP_METRICS_PORT_FILE"] = self._port_file(idx)
            if self.fleet_port is not None or self._fleet_file_explicit:
                # cross-host seam: with the fleet plane armed (a fan-in
                # server or a fleet.json sidecar for an external
                # Prometheus) the children default to a ROUTABLE bind so
                # off-host consumers can reach them, and the port file
                # advertises the resolved routable address. Scoped to
                # the armed-fleet case deliberately: the endpoints are
                # unauthenticated, so a plain supervised run keeps the
                # loopback default (and explicit operator values always
                # win).
                env.setdefault("MGWFBP_METRICS_HOST", "0.0.0.0")
        return env

    def _spawn(self, idx: int, incarnation: int, port: int):
        stdout = stderr = None
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            path = os.path.join(
                self.log_dir, f"p{idx}.i{incarnation}.log"
            )
            stdout = open(path, "w", buffering=1)
            stderr = subprocess.STDOUT
        return subprocess.Popen(
            self.base_cmd,
            env=self._child_env(idx, port, incarnation),
            stdout=stdout,
            stderr=stderr,
        ), stdout

    # -- serving replicas (ISSUE 19) ---------------------------------------
    def _serve_env(self, idx: int) -> dict:
        """A serve replica is NOT a member of the training group: it gets
        no coordinator contract (and any inherited one is stripped so a
        replica never tries to join jax.distributed), just its replica
        index and the role-aware port file."""
        env = dict(self.env)
        for k in (
            "MGWFBP_COORDINATOR",
            "MGWFBP_NUM_PROCESSES",
            "MGWFBP_PROCESS_ID",
        ):
            env.pop(k, None)
        env["MGWFBP_SERVE_REPLICA"] = str(idx)
        if self._metrics_enabled():
            env["MGWFBP_METRICS_PORT_FILE"] = self._port_file(
                idx, role="serve"
            )
            if self.fleet_port is not None or self._fleet_file_explicit:
                env.setdefault("MGWFBP_METRICS_HOST", "0.0.0.0")
        return env

    def _spawn_serve(self, i: int) -> None:
        """(Re)spawn serve replica `i` into slot `i`. The log file is
        opened append so a respawned replica's output lands after its
        previous life's instead of erasing the evidence."""
        if self._metrics_enabled():
            try:
                os.unlink(self._port_file(i, role="serve"))
            except OSError:
                pass
        stdout = stderr = None
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            stdout = open(
                os.path.join(self.log_dir, f"serve{i}.log"),
                "a", buffering=1,
            )
            stderr = subprocess.STDOUT
        proc = subprocess.Popen(
            self.serve_cmd,
            env=self._serve_env(i),
            stdout=stdout,
            stderr=stderr,
        )
        if i < len(self._serve_procs):
            old = self._serve_logs[i]
            if old is not None:
                old.close()
            self._serve_procs[i] = proc
            self._serve_logs[i] = stdout
        else:
            self._serve_procs.append(proc)
            self._serve_logs.append(stdout)

    def _start_serve_replicas(self) -> None:
        """Spawn the serve replicas for the supervisor's lifetime
        (training-group resubmits and resizes must not churn them — each
        replica hot-reloads committed checkpoints on its own)."""
        if not self.serve_replicas or self._serve_procs:
            return
        self._serve_restarts = [0] * self.serve_replicas
        base = self._metrics_base_port()
        for i in range(self.serve_replicas):
            self._spawn_serve(i)
            if base is not None:
                from mgwfbp_tpu.telemetry.serve import resolve_metrics_port

                self.log.info(
                    "serve replica %d metrics at http://127.0.0.1:%d "
                    "(/metrics /status, POST /predict)",
                    i, resolve_metrics_port(base, i, role="serve"),
                )

    def _reap_serve_replicas(self, now: Optional[float] = None) -> None:
        """Serve-replica restart policy (ISSUE 20 satellite): a dead
        replica degrades serving capacity but never the training job —
        respawn it after bounded exponential backoff, under the
        replicas' OWN restart budget. Budget spent -> warn once and
        leave the slot dead (the old spawn-once behavior, now the
        endpoint of a policy instead of the whole policy)."""
        if now is None:
            now = time.monotonic()
        for i, p in enumerate(self._serve_procs):
            if p.poll() is None:
                self._serve_respawn_at.pop(i, None)
                continue
            used = self._serve_restarts[i]
            if used >= self.serve_max_restarts:
                if i not in self._serve_exit_warned:
                    self._serve_exit_warned.add(i)
                    self.log.warning(
                        "serve replica %d exited rc %d and its restart "
                        "budget (%d) is spent; replica stays down "
                        "(training continues%s)",
                        i, p.returncode, self.serve_max_restarts,
                        f" — see {self.log_dir}/serve{i}.log"
                        if self.log_dir else "",
                    )
                continue
            due = self._serve_respawn_at.get(i)
            if due is None:
                self._emit(
                    "failure",
                    **{"class": classify_rc(p.returncode)},
                    target=f"serve{i}", rc=int(p.returncode),
                )
                delay = self.backoff_s(used + 1)
                self._serve_respawn_at[i] = now + delay
                self.log.warning(
                    "serve replica %d exited rc %d; respawning in %.1fs "
                    "(restart %d/%d)", i, p.returncode, delay,
                    used + 1, self.serve_max_restarts,
                )
                continue
            if now >= due:
                self._serve_respawn_at.pop(i, None)
                self._serve_restarts[i] += 1
                self._spawn_serve(i)
                self._emit(
                    "heal", action="respawn_serve", target=f"serve{i}",
                    restarts=self._serve_restarts[i],
                )
                self.log.info(
                    "serve replica %d respawned (restart %d/%d)",
                    i, self._serve_restarts[i], self.serve_max_restarts,
                )

    def _stop_serve_replicas(self) -> None:
        if self._serve_procs:
            self._teardown(self._serve_procs)
        for f in self._serve_logs:
            if f is not None:
                f.close()
        self._serve_procs = []
        self._serve_logs = []

    def _run_group(self, incarnation: int) -> GroupResult:
        self._status_snapshots = None  # fresh capture per incarnation
        # fresh failure/liveness state per incarnation (the PREVIOUS
        # incarnation's verdicts were consumed by the rc policy already)
        self._failed_slots = {}
        self._pending_failure = None
        self._liveness = _LivenessTracker()
        self._liveness_poll_t = 0.0
        port = self.port if self.port is not None else free_port()
        self.log.info(
            "incarnation %d: launching %d process(es) (coordinator "
            "127.0.0.1:%d)", incarnation, self.processes, port,
        )
        if self._metrics_enabled():
            # stale port files describe the PREVIOUS incarnation's
            # (possibly ephemeral) binds; drop them so the fan-in never
            # scrapes a dead port as live
            for i in range(self.processes):
                try:
                    os.unlink(self._port_file(i))
                except OSError:
                    pass
            self._last_fleet_targets = None
            self._start_fleet_server()
        metrics_base = self._metrics_base_port()
        if metrics_base is not None:
            for i in range(self.processes):
                self.log.info(
                    "incarnation %d: process %d metrics at "
                    "http://127.0.0.1:%d (/metrics /healthz /status)",
                    incarnation, i, metrics_base + i,
                )
        procs, logs = [], []
        for i in range(self.processes):
            p, f = self._spawn(i, incarnation, port)
            procs.append(p)
            logs.append(f)
        try:
            rcs = self._watch(procs)
        finally:
            for f in logs:
                if f is not None:
                    f.close()
        result = GroupResult(incarnation, rcs)
        self.results.append(result)
        self.log.info(
            "incarnation %d: exit codes %s", incarnation, rcs,
        )
        return result

    def _capture_snapshots(self, procs) -> None:
        """Last /status of every still-alive peer, captured the moment a
        hard/watchdog exit is first observed — by the time run() applies
        the rc policy every child is torn down and the ports refuse."""
        if self._status_snapshots is not None:
            return
        self._status_snapshots = {
            i: s for i, p in enumerate(procs)
            if p.poll() is None
            and (s := self._child_status(i)) is not None
        }
        for i, s in sorted(self._status_snapshots.items()):
            for b in (s.get("postmortems") or {}).get("recent", []):
                if b.get("path"):
                    self._postmortem_paths.append(
                        f"p{i}: {b['path']}"
                    )

    def _poll_liveness(self, procs) -> None:
        """The wedge/unreachable detector (ISSUE 20): feed each alive
        child's /status scrape (hard-timeout, same as the fleet fan-in's)
        into the liveness tracker; the first child classified wedged or
        unreachable marks the incarnation's pending failure and SIGTERMs
        the whole group — survivors drain through the agreed-preempt
        path (or their coordination deadline) and the rc policy heals."""
        if (
            not self.heal
            or self._pending_failure is not None
            or self._failed_slots
            or not self._metrics_enabled()
        ):
            return
        now = time.monotonic()
        if now - self._liveness_poll_t < 1.0:  # throttle the scrapes
            return
        self._liveness_poll_t = now
        # sweep EVERY alive child before passing a verdict: a single
        # wedged process freezes its peers at the next merged collective
        # within the same grace window, so the step-freeze signal cannot
        # root-cause which peer wedged first — the honest verdict names
        # the whole frozen set
        culprits: list[tuple[int, str, int]] = []
        for i, p in enumerate(procs):
            if p.poll() is not None:
                continue
            self._liveness.observe(i, self._child_status(i), now)
            verdict = self._liveness.classify(
                i, now, self.liveness_grace_s
            )
            if verdict in ("wedged", "unreachable"):
                culprits.append(
                    (i, verdict, self._liveness._step.get(i, 0))
                )
        if not culprits:
            return
        cls = culprits[0][1]
        target = ",".join(f"p{i}" for i, _, _ in culprits)
        step = max(s for _, _, s in culprits)
        self._pending_failure = {
            "class": cls, "target": target, "step": step,
        }
        self.log.warning(
            "%s is %s (step frozen at %d past %.0fs liveness grace); "
            "SIGTERMing the group to drain and heal",
            target, cls, step, self.liveness_grace_s,
        )
        self._emit(
            "failure", **{"class": cls}, target=target, step=step,
        )
        self._capture_snapshots(procs)
        for q in procs:
            if q.poll() is None:
                try:
                    q.send_signal(signal.SIGTERM)
                except OSError:
                    pass

    def _watch(self, procs) -> list[int]:
        """Poll until every process exits; once ANY process exits,
        stragglers get a bounded window before teardown. A group member
        that outlives its peers is wedged — once a peer is gone its next
        collective can never complete (a clean rc-0 exit takes the
        coordination service down just as surely as a crash) — so
        waiting forever would hang the supervisor exactly the way the
        job hung."""
        deadline = None  # armed on the first exit of any kind
        grace = None
        while True:
            # lazily resolve child metrics endpoints as they bind and
            # keep the fleet.json sidecar current (no-op when the live
            # plane is off or nothing changed)
            self._refresh_fleet()
            self._reap_serve_replicas()
            # --resize-to: drain a healthy group once it is stepping
            self._maybe_trigger_resize(procs)
            # wedge/unreachable detection (no-op once a failure is known)
            self._poll_liveness(procs)
            pending = [p for p in procs if p.poll() is None]
            done = [p.returncode for p in procs if p.returncode is not None]
            if WATCHDOG_RC in done and self._status_snapshots is None:
                self._capture_snapshots(procs)
            hard = {
                i: int(p.returncode) for i, p in enumerate(procs)
                if p.returncode is not None
                and p.returncode not in (0, PREEMPT_RC, WATCHDOG_RC)
            }
            if (
                self.heal
                and hard
                and not self._failed_slots
                and WATCHDOG_RC not in done
            ):
                # hard exit(s): capture the failed slots NOW (teardown
                # pollutes the rc vector with its own -15/-9 later) and
                # SIGTERM the survivors — blocked in a collective their
                # dead peer will never join, they drain via the agreed
                # preempt path or their coordination deadline (rc 75)
                self._failed_slots = dict(hard)
                self._capture_snapshots(procs)
                for i, rc in sorted(hard.items()):
                    cls = classify_rc(rc)
                    self.log.warning(
                        "process %d exited HARD (rc %d, class %s); "
                        "SIGTERMing survivors to drain for healing",
                        i, rc, cls,
                    )
                    self._emit(
                        "failure", **{"class": cls}, target=f"p{i}",
                        rc=rc, step=self._liveness.max_step(),
                    )
                for p in procs:
                    if p.poll() is None:
                        try:
                            p.send_signal(signal.SIGTERM)
                        except OSError:
                            pass
            if not pending:
                return [int(p.returncode) for p in procs]
            if done and deadline is None:
                # rc 0/75: peers are finishing up or drain-agreeing and
                # checkpointing — give them the drain window. A hard
                # exit under healing gets the SAME window: survivors
                # must ride out their coordination deadline to exit
                # clean. Anything else: broken group, short fuse.
                clean = all(rc in (0, PREEMPT_RC) for rc in done)
                grace = (
                    self.drain_grace_s
                    if clean or (self.heal and self._failed_slots)
                    else self.grace_s
                )
                deadline = time.monotonic() + grace
            if deadline is not None and time.monotonic() > deadline:
                self.log.warning(
                    "tearing down %d straggler(s) %.0fs after first "
                    "failure", len(pending), grace,
                )
                self._teardown(pending)
                return [
                    int(p.returncode) if p.returncode is not None else -9
                    for p in procs
                ]
            time.sleep(0.05)

    def _teardown(self, procs) -> None:
        for p in procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        t0 = time.monotonic()
        while any(p.poll() is None for p in procs):
            if time.monotonic() - t0 > self.grace_s:
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                for p in procs:
                    p.wait()
                return
            time.sleep(0.05)

    # -- policy ------------------------------------------------------------
    def backoff_s(self, restart: int) -> float:
        """Bounded exponential: base * 2^(restart-1), capped."""
        return min(
            self.backoff_base_s * (2.0 ** max(restart - 1, 0)),
            self.backoff_max_s,
        )

    def run(self) -> int:
        try:
            self._start_serve_replicas()
            return self._run_policy()
        finally:
            self._stop_serve_replicas()
            if self.fleet_server is not None:
                self.fleet_server.close()
                self.fleet_server = None

    def _heal_exit_rc(self) -> int:
        """The rc a give-up heal stop propagates: the failed child's own
        positive rc when it had one, the conventional 128+signal for a
        signal death, 1 for a wedge/unreachable (no child rc to speak
        of — the group was SIGTERMed by the monitor)."""
        rcs = sorted(self._failed_slots.values())
        pos = [rc for rc in rcs if rc > 0]
        if pos:
            return pos[0]
        neg = [rc for rc in rcs if rc < 0]
        if neg:
            return 128 + abs(neg[0])
        return 1

    def _heal_or_stop(self, result: GroupResult) -> Optional[int]:
        """Apply the healing policy to one hard-failed incarnation.

        Returns None when the group was healed (caller relaunches) or
        the final exit rc when the policy gives up. The policy matrix:

          oom_kill     -> SHRINK to the survivor count (the slot's
                          memory footprint is suspect; elastic resume
                          re-shards off the last committed step)
          crash/term   -> relaunch at the SAME world (slot recoverable)
          wedge/
          unreachable  -> relaunch at the SAME world
          any class    -> bounded by its own restart budget
                          (heal_max_restarts per class) and a crash-loop
                          detector (same max step heal_same_step_limit
                          consecutive lives -> stop, postmortems named)
        """
        if self._pending_failure is not None:
            cls = str(self._pending_failure["class"])
            target = str(self._pending_failure["target"])
        else:
            idx = min(self._failed_slots)
            cls = classify_rc(self._failed_slots[idx])
            target = f"p{idx}"
        step = self._liveness.max_step()
        bundles = (
            " Postmortem bundle(s): " + "; ".join(self._postmortem_paths)
            if self._postmortem_paths else ""
        )
        self._crash_steps.append(step)
        tail = self._crash_steps[-self.heal_same_step_limit:]
        if (
            len(tail) >= self.heal_same_step_limit
            and len(set(tail)) == 1
        ):
            self.log.error(
                "crash loop: %d consecutive incarnation(s) died at step "
                "%d (last failure: %s on %s) — the fault is "
                "deterministic, healing cannot fix it; stopping.%s",
                len(tail), step, cls, target, bundles,
            )
            self._emit(
                "heal", action="stop", reason="crash_loop",
                **{"class": cls}, target=target, step=step,
            )
            return self._heal_exit_rc()
        used = self._heal_restarts.get(cls, 0)
        if used >= self.heal_max_restarts:
            self.log.error(
                "%s on %s but the %r heal budget (%d) is spent; "
                "stopping.%s",
                cls, target, cls, self.heal_max_restarts, bundles,
            )
            self._emit(
                "heal", action="stop", reason="budget",
                **{"class": cls}, target=target, restarts=used,
            )
            return self._heal_exit_rc()
        self._heal_restarts[cls] = used + 1
        survivors = self.processes - len(self._failed_slots)
        shrink = cls == "oom_kill" and 1 <= survivors < self.processes
        delay = self.backoff_s(self._heal_restarts[cls])
        if shrink:
            self.log.warning(
                "healing %s on %s: SHRINKING %d -> %d process(es) "
                "(elastic resume off the last committed shard-native "
                "step) in %.1fs (%s heal %d/%d)",
                cls, target, self.processes, survivors, delay, cls,
                self._heal_restarts[cls], self.heal_max_restarts,
            )
            self._emit(
                "heal", action="shrink", **{"class": cls},
                target=target, old_world=self.processes,
                world=survivors, restarts=self._heal_restarts[cls],
            )
            self.processes = survivors
        else:
            self.log.warning(
                "healing %s on %s: relaunching at the same world (%d) "
                "in %.1fs (%s heal %d/%d)",
                cls, target, self.processes, delay, cls,
                self._heal_restarts[cls], self.heal_max_restarts,
            )
            self._emit(
                "heal", action="relaunch", **{"class": cls},
                target=target, world=self.processes,
                restarts=self._heal_restarts[cls],
            )
        self.sleep(delay)
        return None

    def _run_policy(self) -> int:
        restarts = 0
        incarnation = 0
        while True:
            result = self._run_group(incarnation)
            if result.ok:
                if restarts:
                    self.log.info(
                        "group completed after %d resubmission(s)", restarts,
                    )
                return 0
            if result.watchdog_abort:
                where = (
                    f" (per-process logs under {self.log_dir})"
                    if self.log_dir else " (see the group's stderr)"
                )
                # the dead group's final state: _watch captured every
                # still-alive peer's /status at the moment the rc-86
                # exit was observed (the group is fully torn down by
                # now), so the post-mortem starts from the supervisor
                # log, not from N scattered ports that no longer answer
                snapshots = self._status_snapshots or {}
                detail = ""
                if snapshots:
                    import json as _json

                    detail = " Last /status snapshot(s): " + "; ".join(
                        f"p{i}: {_json.dumps(s)}"
                        for i, s in sorted(snapshots.items())
                    )
                    # the flight recorder's evidence (ISSUE 12): any
                    # postmortem bundles the children wrote before the
                    # abort are the post-mortem's starting point — name
                    # them explicitly next to the stack-dump pointer
                    bundles = [
                        f"p{i}: {b.get('path')}"
                        for i, s in sorted(snapshots.items())
                        for b in (s.get("postmortems") or {}).get(
                            "recent", []
                        )
                        if b.get("path")
                    ]
                    if bundles:
                        detail += (
                            " Postmortem bundle(s): " + "; ".join(bundles)
                        )
                self.log.error(
                    "watchdog abort (rc %d): a process dumped all thread "
                    "stacks before exiting%s. A wedged device grant does "
                    "not heal on restart — NOT resubmitting.%s",
                    WATCHDOG_RC, where, detail,
                )
                return WATCHDOG_RC
            # self-healing (ISSUE 20): a hard failure this incarnation —
            # a slot that exited crash/oom/term, or a wedge/unreachable
            # verdict from the liveness monitor (whose SIGTERM made the
            # rc vector look like a plain preempt) — takes the healing
            # policy, NOT the free preempt resubmit below
            if self.heal and (
                self._pending_failure is not None or self._failed_slots
            ):
                rc = self._heal_or_stop(result)
                if rc is not None:
                    return rc
                incarnation += 1
                continue
            if not result.preempted:
                bad = [
                    rc for rc in result.returncodes
                    if rc not in (0, PREEMPT_RC)
                ]
                self.log.error(
                    "group failed (exit codes %s); stragglers torn down, "
                    "not resubmitting", result.returncodes,
                )
                # prefer a child's real rc over a signal-killed straggler's
                # negative Popen code; a pure-signal group maps to the
                # conventional 128+signal so the shell status stays honest
                pos = [rc for rc in bad if rc > 0]
                if pos:
                    return pos[0]
                return 128 + abs(bad[0]) if bad else 1
            resize_relaunch = self._resize_pending()
            if resize_relaunch:
                # realizing --resize-to is not failure recovery: the
                # relaunch at the new size neither consumes the restart
                # budget nor gets blocked by an already-spent one (the
                # supervisor may itself have SIGTERMed a healthy group to
                # drain it — refusing to relaunch would strand the job)
                self.log.warning(
                    "elastic resize: relaunching the group at %d "
                    "process(es) (was %d); the job continues from the "
                    "drained step", self.resize_to, self.processes,
                )
                self.processes = int(self.resize_to)
                delay = self.backoff_base_s
            else:
                if restarts >= self.max_restarts:
                    self.log.error(
                        "preempted again but the restart budget (%d) is "
                        "spent; progress is checkpointed — resubmit "
                        "manually or raise --max-restarts",
                        self.max_restarts,
                    )
                    return PREEMPT_RC
                restarts += 1
                delay = self.backoff_s(restarts)
            self.log.warning(
                "group preempted (rc %d): resubmitting in %.1fs "
                "(restart %d/%d) — resumed run restores from the drained "
                "checkpoint", PREEMPT_RC, delay, restarts,
                self.max_restarts,
            )
            self.sleep(delay)
            incarnation += 1


def default_train_cmd(train_args: Sequence[str]) -> list[str]:
    """The per-process command for a training group: this interpreter,
    this repo's launcher, the user's args verbatim."""
    return [sys.executable, "-m", "mgwfbp_tpu.train_cli", *train_args]


def default_serve_cmd(serve_args: Sequence[str]) -> list[str]:
    """The per-replica command for `--serve-replicas`: the standalone
    serving CLI; the replica index rides in MGWFBP_SERVE_REPLICA."""
    return [sys.executable, "-m", "mgwfbp_tpu.serving", *serve_args]
