"""Multi-host production runtime (ISSUE 6).

MG-WFBP is synchronous data-parallel SGD across many workers
(arXiv:1811.11141); one process per host, every merge-group collective a
barrier. That shape makes every HOST-side decision a distributed-consensus
problem: if two processes disagree on "drain now?", "roll back?", or
"which autotune candidate won?", they issue different collective programs
and the whole group deadlocks. This package is the substrate that makes
the `MGWFBP_NUM_PROCESSES>1` path production-real:

  coordination  small agreement primitives (broadcast_flag, all_argmin,
                agree_all/agree_any, barrier) every cross-process decision
                in the trainer/checkpointer/autotuner routes through;
  supervisor    process-group launcher + auto-resubmit policy: rc 75
                (EX_TEMPFAIL, graceful preemption drain) resubmits the
                whole group with bounded exponential backoff, rc 86
                (watchdog abort) stops and surfaces the stack dumps, any
                other death tears down the stragglers.

`python -m mgwfbp_tpu.runtime.supervise -- <train_cli args>` is the
entry point (README "Multi-host runtime").
"""

from __future__ import annotations


class ResizeUnsupported(RuntimeError):
    """Elastic resize was requested in a configuration that only supports
    resize-by-relaunch (multi-host process groups, multi-slice meshes).

    The supported path: drain (checkpoints are step-indexed and bitwise
    resumable), then relaunch the whole group at the new size under the
    supervisor — the message carries the recipe.
    """

    def __init__(self, reason: str, nworkers: int):
        super().__init__(
            f"{reason}. Elastic resize on this configuration is "
            "resize-by-relaunch, and the supervisor automates it "
            "(ISSUE 13): launch with\n"
            "  python -m mgwfbp_tpu.runtime.supervise --processes <N> "
            "--resize-to <M> -- <same train args>\n"
            "— the group drains via the agreed-preempt path (rc 75), "
            "relaunches at <M> processes with MGWFBP_ELASTIC_RESUME=1, "
            "and the job continues from the exact step (shard-native "
            "checkpoints re-shard per leaf onto the new world; no "
            "world-sized buffer is ever materialized). Manual recipe: "
            "SIGTERM the group, then relaunch at the new size "
            f"(requested worker count: {nworkers})."
        )
        self.nworkers = nworkers


__all__ = ["ResizeUnsupported"]
