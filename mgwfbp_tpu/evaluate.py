"""Offline evaluation over saved checkpoints.

Parity target (SURVEY.md §3.4): reference evaluate.py (:20-57 — rebuild the
trainer from hyperparameters encoded in the checkpoint dir name, load each
epoch's checkpoint, run test(): top1/top5 for CNNs, perplexity for PTB, WER
for AN4) and scripts/eval.sh. Here the checkpoint directory is the
config-tagged dir the Trainer writes; model/dataset come from CLI flags
(explicit beats dir-name parsing).

Usage:
  python -m mgwfbp_tpu.evaluate --dnn resnet20 --checkpoint-dir ckpts/... \
      [--epoch N] [--synthetic]
"""

from __future__ import annotations

import argparse
import json
from typing import Optional

from mgwfbp_tpu.config import make_config


def _install_and_eval(trainer, state) -> dict:
    """Re-replicate a restored train state over the trainer's mesh (the
    reference's post-load broadcast_parameters, dist_trainer.py:66) and run
    the eval loop. Single seam shared by the per-epoch and model-average
    paths."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    trainer.state = jax.device_put(
        state, NamedSharding(trainer.mesh, PartitionSpec())
    )
    return trainer.evaluate()


def _restore_or_raise(
    ckpt, root: str, template, epoch: Optional[int], carry_template=None
):
    if epoch is None:
        # prefer the newest epoch BOUNDARY: with --ckpt-every-steps the
        # raw latest snapshot may be mid-epoch, and evaluation semantics
        # are per-epoch; fall back to the latest of any kind for dirs
        # holding only step checkpoints
        epoch = ckpt.latest_epoch()
    snap = ckpt.restore(template, epoch=epoch, carry_template=carry_template)
    if snap is None:
        raise FileNotFoundError(
            f"no checkpoint under {root!r}"
            + (f" at epoch {epoch}" if epoch is not None else "")
        )
    return snap


def _eval_snapshots(
    dnn: str,
    checkpoint_root: str,
    pick_epochs,
    synthetic: Optional[bool] = None,
    **config_overrides,
):
    """Shared driver: build ONE trainer, then restore + re-replicate +
    evaluate each epoch `pick_epochs(ckpt)` selects, yielding metrics
    incrementally (a failure at epoch k does not discard earlier results)."""
    from mgwfbp_tpu.checkpoint import Checkpointer
    from mgwfbp_tpu.train.trainer import Trainer

    cfg = make_config(dnn, checkpoint_dir=None, **config_overrides)
    trainer = Trainer(cfg, profile_backward=False, synthetic_data=synthetic)
    ckpt = Checkpointer(checkpoint_root)
    try:
        epochs = pick_epochs(ckpt)
        for e in epochs:
            snap = _restore_or_raise(
                ckpt, checkpoint_root, trainer.state, e,
                carry_template=trainer._carry_template(),
            )
            metrics = _install_and_eval(trainer, snap.state)
            metrics["epoch"] = snap.epoch
            yield metrics
    finally:
        ckpt.close()
        trainer.close()


def evaluate(
    dnn: str,
    checkpoint_root: str,
    epoch: Optional[int] = None,
    synthetic: Optional[bool] = None,
    **config_overrides,
) -> dict:
    """Evaluate one checkpoint (latest by default); returns metrics dict."""
    for metrics in _eval_snapshots(
        dnn, checkpoint_root, lambda ckpt: [epoch],
        synthetic=synthetic, **config_overrides,
    ):
        return metrics
    raise FileNotFoundError(f"no checkpoint under {checkpoint_root!r}")


def evaluate_all(
    dnn: str,
    checkpoint_root: str,
    synthetic: Optional[bool] = None,
    **config_overrides,
):
    """Yield metrics for EVERY saved epoch in a run dir, in order (the
    reference's scripts/eval.sh + evaluate.py loop over per-epoch
    checkpoints)."""

    def pick(ckpt):
        epochs = ckpt.all_epochs()
        if not epochs:
            raise FileNotFoundError(
                f"no checkpoints under {checkpoint_root!r}"
            )
        return epochs

    yield from _eval_snapshots(
        dnn, checkpoint_root, pick, synthetic=synthetic, **config_overrides
    )


def model_average_evaluate(
    dnn: str,
    checkpoint_roots: list[str],
    epoch: Optional[int] = None,
    synthetic: Optional[bool] = None,
    **config_overrides,
) -> dict:
    """Average model weights across several runs' checkpoints, then evaluate
    the averaged model (reference evaluate.py:10-18 `model_average` —
    elementwise state-dict mean over per-rank checkpoints, shipped there
    behind a disabled branch at :36; live here).

    Each root is one run's tagged checkpoint directory. All roots must hold
    a checkpoint at the SAME epoch — with epoch=None each root's latest is
    restored and a mismatch (runs of different lengths, or one root's epoch
    pruned by retention) raises instead of silently averaging weights from
    different training stages."""
    import jax
    import jax.numpy as jnp

    from mgwfbp_tpu.checkpoint import Checkpointer
    from mgwfbp_tpu.train.trainer import Trainer

    if not checkpoint_roots:
        raise ValueError("model_average_evaluate: no checkpoint dirs given")
    cfg = make_config(dnn, checkpoint_dir=None, **config_overrides)
    trainer = Trainer(cfg, profile_backward=False, synthetic_data=synthetic)
    try:
        snaps = []
        for root in checkpoint_roots:
            ckpt = Checkpointer(root)
            try:
                snaps.append(
                    _restore_or_raise(
                        ckpt, root, trainer.state, epoch,
                        carry_template=trainer._carry_template(),
                    )
                )
            finally:
                ckpt.close()
        epochs = sorted({s.epoch for s in snaps})
        if len(epochs) > 1:
            raise ValueError(
                "model_average_evaluate: checkpoint roots are at different "
                f"epochs {epochs}; pass --epoch to pick a common one"
            )
        n = float(len(snaps))

        def mean(*leaves):
            acc = leaves[0].astype(jnp.float32)
            for x in leaves[1:]:
                acc = acc + x.astype(jnp.float32)
            return (acc / n).astype(leaves[0].dtype)

        params = jax.tree_util.tree_map(
            mean, *[s.state.params for s in snaps]
        )
        batch_stats = jax.tree_util.tree_map(
            mean, *[s.state.batch_stats for s in snaps]
        )
        metrics = _install_and_eval(
            trainer,
            trainer.state.replace(params=params, batch_stats=batch_stats),
        )
        metrics["epoch"] = snaps[0].epoch
        metrics["averaged_over"] = len(snaps)
        return metrics
    finally:
        trainer.close()


def main(argv: Optional[list[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="mgwfbp-evaluate")
    p.add_argument("--dnn", required=True)
    p.add_argument("--checkpoint-dir", dest="checkpoint_dir", default=None,
                   help="the run's tagged checkpoint directory (required "
                        "unless --average-dirs is used)")
    p.add_argument("--epoch", type=int, default=None,
                   help="epoch to evaluate (default: latest)")
    p.add_argument("--all-epochs", action="store_true",
                   help="evaluate every saved epoch (one JSON line each, "
                        "then a final {\"best\": ...} summary line); "
                        "mutually exclusive with --epoch")
    p.add_argument("--average-dirs", dest="average_dirs", nargs="+",
                   default=None,
                   help="average weights across these runs' checkpoints "
                        "before evaluating (reference model_average)")
    p.add_argument("--dataset", default=None)
    p.add_argument("--data-dir", dest="data_dir", default=None)
    p.add_argument("--batch-size", dest="batch_size", type=int, default=None)
    p.add_argument("--synthetic", action="store_true")
    args = p.parse_args(argv)
    from mgwfbp_tpu.utils.platform import apply_platform_overrides

    apply_platform_overrides()
    overrides = {
        k: getattr(args, k)
        for k in ("dataset", "data_dir", "batch_size")
        if getattr(args, k) is not None
    }
    if args.all_epochs and args.epoch is not None:
        p.error("--all-epochs and --epoch are mutually exclusive")
    if args.average_dirs and args.all_epochs:
        p.error("--average-dirs and --all-epochs are mutually exclusive")
    if not args.average_dirs and not args.checkpoint_dir:
        p.error("--checkpoint-dir is required (or use --average-dirs)")
    if args.average_dirs:
        metrics = model_average_evaluate(
            args.dnn,
            args.average_dirs,
            epoch=args.epoch,
            synthetic=True if args.synthetic else None,
            **overrides,
        )
        print(json.dumps(metrics))
        return 0
    if args.all_epochs:
        # running best across epochs (reference evaluate.py:47-57: higher is
        # better for accuracy, lower for lstm perplexity / an4 WER)
        best = None
        best_epoch = None
        key = lower_better = None
        for metrics in evaluate_all(
            args.dnn,
            args.checkpoint_dir,
            synthetic=True if args.synthetic else None,
            **overrides,
        ):
            print(json.dumps(metrics))
            if key is None:
                # the metric key is a property of the MODEL TASK, fixed for
                # the whole run; deriving it per line would let one epoch
                # with a missing key (e.g. failed WER decode) relabel the
                # final best summary (ADVICE r3)
                if "wer" in metrics:
                    key, lower_better = "wer", True
                elif "perplexity" in metrics:
                    key, lower_better = "perplexity", True
                else:
                    key, lower_better = "top1", False
            v = metrics.get(key)
            if v is not None and (
                best is None or (v < best if lower_better else v > best)
            ):
                best, best_epoch = v, metrics.get("epoch")
        if best is not None:
            print(json.dumps(
                {"best": {key: best, "epoch": best_epoch}}
            ))
        return 0
    metrics = evaluate(
        args.dnn,
        args.checkpoint_dir,
        epoch=args.epoch,
        synthetic=True if args.synthetic else None,
        **overrides,
    )
    print(json.dumps(metrics))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
