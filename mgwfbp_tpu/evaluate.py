"""Offline evaluation over saved checkpoints.

Parity target (SURVEY.md §3.4): reference evaluate.py (:20-57 — rebuild the
trainer from hyperparameters encoded in the checkpoint dir name, load each
epoch's checkpoint, run test(): top1/top5 for CNNs, perplexity for PTB, WER
for AN4) and scripts/eval.sh. Here the checkpoint directory is the
config-tagged dir the Trainer writes; model/dataset come from CLI flags
(explicit beats dir-name parsing).

Usage:
  python -m mgwfbp_tpu.evaluate --dnn resnet20 --checkpoint-dir ckpts/... \
      [--epoch N] [--synthetic]
"""

from __future__ import annotations

import argparse
import json
from typing import Optional

from mgwfbp_tpu.config import make_config


def _eval_snapshots(
    dnn: str,
    checkpoint_root: str,
    pick_epochs,
    synthetic: Optional[bool] = None,
    **config_overrides,
):
    """Shared driver: build ONE trainer, then restore + re-replicate +
    evaluate each epoch `pick_epochs(ckpt)` selects, yielding metrics
    incrementally (a failure at epoch k does not discard earlier results)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from mgwfbp_tpu.checkpoint import Checkpointer
    from mgwfbp_tpu.train.trainer import Trainer

    cfg = make_config(dnn, checkpoint_dir=None, **config_overrides)
    trainer = Trainer(cfg, profile_backward=False, synthetic_data=synthetic)
    ckpt = Checkpointer(checkpoint_root)
    try:
        epochs = pick_epochs(ckpt)
        for e in epochs:
            snap = ckpt.restore(trainer.state, epoch=e)
            if snap is None:
                raise FileNotFoundError(
                    f"no checkpoint under {checkpoint_root!r}"
                    + (f" at epoch {e}" if e is not None else "")
                )
            # re-replicate over the mesh (the reference's post-load
            # broadcast_parameters, dist_trainer.py:66)
            trainer.state = jax.device_put(
                snap.state, NamedSharding(trainer.mesh, PartitionSpec())
            )
            metrics = trainer.evaluate()
            metrics["epoch"] = snap.epoch
            yield metrics
    finally:
        ckpt.close()
        trainer.close()


def evaluate(
    dnn: str,
    checkpoint_root: str,
    epoch: Optional[int] = None,
    synthetic: Optional[bool] = None,
    **config_overrides,
) -> dict:
    """Evaluate one checkpoint (latest by default); returns metrics dict."""
    for metrics in _eval_snapshots(
        dnn, checkpoint_root, lambda ckpt: [epoch],
        synthetic=synthetic, **config_overrides,
    ):
        return metrics
    raise FileNotFoundError(f"no checkpoint under {checkpoint_root!r}")


def evaluate_all(
    dnn: str,
    checkpoint_root: str,
    synthetic: Optional[bool] = None,
    **config_overrides,
):
    """Yield metrics for EVERY saved epoch in a run dir, in order (the
    reference's scripts/eval.sh + evaluate.py loop over per-epoch
    checkpoints)."""

    def pick(ckpt):
        epochs = ckpt.all_epochs()
        if not epochs:
            raise FileNotFoundError(
                f"no checkpoints under {checkpoint_root!r}"
            )
        return epochs

    yield from _eval_snapshots(
        dnn, checkpoint_root, pick, synthetic=synthetic, **config_overrides
    )


def main(argv: Optional[list[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="mgwfbp-evaluate")
    p.add_argument("--dnn", required=True)
    p.add_argument("--checkpoint-dir", dest="checkpoint_dir", required=True,
                   help="the run's tagged checkpoint directory")
    p.add_argument("--epoch", type=int, default=None,
                   help="epoch to evaluate (default: latest)")
    p.add_argument("--all-epochs", action="store_true",
                   help="evaluate every saved epoch (one JSON line each); "
                        "mutually exclusive with --epoch")
    p.add_argument("--dataset", default=None)
    p.add_argument("--data-dir", dest="data_dir", default=None)
    p.add_argument("--batch-size", dest="batch_size", type=int, default=None)
    p.add_argument("--synthetic", action="store_true")
    args = p.parse_args(argv)
    from mgwfbp_tpu.utils.platform import apply_platform_overrides

    apply_platform_overrides()
    overrides = {
        k: getattr(args, k)
        for k in ("dataset", "data_dir", "batch_size")
        if getattr(args, k) is not None
    }
    if args.all_epochs and args.epoch is not None:
        p.error("--all-epochs and --epoch are mutually exclusive")
    if args.all_epochs:
        for metrics in evaluate_all(
            args.dnn,
            args.checkpoint_dir,
            synthetic=True if args.synthetic else None,
            **overrides,
        ):
            print(json.dumps(metrics))
        return 0
    metrics = evaluate(
        args.dnn,
        args.checkpoint_dir,
        epoch=args.epoch,
        synthetic=True if args.synthetic else None,
        **overrides,
    )
    print(json.dumps(metrics))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
