"""AN4 corpus acquisition: download/extract/convert/manifest, pure Python.

Parity target: reference audio_data/an4.py:19-87 + utils.py:11-37 —
wget the CMU an4_raw.bigendian tarball, sox-convert each .raw to wav,
pair fileids with transcriptions into per-utterance txt files, and write
duration-sorted (train: duration-pruned) "wav_path,txt_path" manifests.

Re-design differences (no external processes, no egress assumptions):
  * .raw -> .wav conversion is pure Python: AN4 raw files are big-endian
    signed 16-bit mono at 16 kHz (the reference shells out to
    `sox -t raw -r 16000 -b 16 -e signed-integer -B -c 1`); numpy byteswap
    + the stdlib wave module produce the identical PCM payload.
  * durations come from the wav header (the reference shells out to soxi).
  * `--source` accepts a LOCAL tarball, and extraction salvages every
    complete entry from a TRUNCATED archive (this container has no network
    egress; a partial tarball still yields a usable real-audio subset —
    the salvage count is reported so nothing is silently dropped).

Usage:
  python -m mgwfbp_tpu.data.an4_fetch --target-dir data/an4 \
      [--source /path/to/an4_raw.bigendian.tar.gz]
Then train with --dataset an4 --data-dir data/an4.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import tarfile
import wave
from typing import Optional

import numpy as np

AN4_URL = "http://www.speech.cs.cmu.edu/databases/an4/an4_raw.bigendian.tar.gz"
SAMPLE_RATE = 16000


def pcm_to_wav(pcm: np.ndarray, wav_path: str) -> float:
    """int16 mono PCM -> 16 kHz RIFF wav; returns duration (s). The one
    wav-writing contract shared by the AN4 and LibriSpeech fetchers."""
    pcm = np.asarray(pcm, "<i2")
    with wave.open(wav_path, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(SAMPLE_RATE)
        w.writeframes(pcm.tobytes())
    return len(pcm) / SAMPLE_RATE


def raw_to_wav(raw_bytes: bytes, wav_path: str) -> float:
    """Big-endian s16 mono 16 kHz raw -> RIFF wav; returns duration (s).

    Byte-identical samples to the reference's sox invocation (an4.py:40-43):
    both merely byte-swap the PCM payload into little-endian s16.
    """
    return pcm_to_wav(
        np.frombuffer(raw_bytes, dtype=">i2").astype("<i2"), wav_path
    )


def process_transcript(line: str) -> str:
    """Reference transcript normalization (an4.py:63-65): strip the
    trailing "(file-id)", the <s>/</s> sentence markers, uppercase."""
    return line.split("(")[0].strip("<s>").split("<")[0].strip().upper()


def salvage_tar(source: str) -> tuple[dict[str, bytes], bool]:
    """Extract name->bytes from a tar.gz, tolerating gzip/tar truncation.

    Returns (files, truncated). A truncated archive (e.g. an interrupted
    download) yields every entry whose payload decompressed completely.
    """
    import zlib

    with open(source, "rb") as f:
        comp = f.read()
    # incremental decompress keeps every complete chunk even when the
    # stream ends mid-payload; d.eof stays False on a cut stream that
    # happens not to raise
    d = zlib.decompressobj(16 + zlib.MAX_WBITS)
    out = []
    truncated = False
    step = 1 << 16
    try:
        for i in range(0, len(comp), step):
            out.append(d.decompress(comp[i : i + step]))
        out.append(d.flush())
    except Exception:
        truncated = True
    truncated = truncated or not d.eof
    data = b"".join(out)
    files: dict[str, bytes] = {}
    try:
        with tarfile.open(fileobj=io.BytesIO(data), mode="r|") as t:
            for m in t:
                if m.isfile():
                    fobj = t.extractfile(m)
                    if fobj is None:
                        continue
                    payload = fobj.read()
                    if len(payload) < m.size:
                        truncated = True
                        break
                    files[m.name] = payload
    except (tarfile.ReadError, EOFError):
        truncated = True
    return files, truncated


def stream_tar_entries(source: str):
    """Yield (name, bytes) per file member of a tar.gz, one at a time —
    constant memory for arbitrarily large archives (LibriSpeech tarballs
    are multi-GB; buffering them whole would OOM a typical host). Stops
    cleanly at a truncated tail: consume the generator and check
    `.truncated` on the returned iterator object."""

    class _Iter:
        truncated = False

        def __iter__(self):
            try:
                with tarfile.open(source, "r|gz") as t:
                    for m in t:
                        if not m.isfile():
                            continue
                        fobj = t.extractfile(m)
                        if fobj is None:
                            continue
                        payload = fobj.read()
                        if len(payload) < m.size:
                            self.truncated = True
                            return
                        yield m.name, payload
            except (tarfile.ReadError, EOFError, OSError):
                self.truncated = True

    return _Iter()


def _download(url: str, dest: str) -> None:
    import shutil
    import urllib.request

    with urllib.request.urlopen(url, timeout=60) as r, open(dest, "wb") as f:
        shutil.copyfileobj(r, f, length=1 << 20)  # chunked, constant memory


def fetch_an4(
    target_dir: str,
    source: Optional[str] = None,
    min_duration: float = 1.0,
    max_duration: float = 15.0,
) -> dict:
    """Build the AN4 dataset layout + manifests under target_dir.

    Layout (what data/audio.load_an4 consumes, = the reference's):
      target_dir/{train,val}/an4/wav/<utt>.wav
      target_dir/{train,val}/an4/txt/<utt>.txt
      target_dir/an4_{train,val}_manifest.csv   (duration-sorted;
          train pruned to [min_duration, max_duration] like the reference)
    """
    tarball = source
    if tarball is None:
        tarball = os.path.join(target_dir, "an4_raw.bigendian.tar.gz")
        if not os.path.exists(tarball):
            os.makedirs(target_dir, exist_ok=True)
            try:
                _download(AN4_URL, tarball)
            except Exception as e:
                raise SystemExit(
                    f"cannot download {AN4_URL} ({e}); pass --source "
                    "/path/to/an4_raw.bigendian.tar.gz instead"
                )
    files, truncated = salvage_tar(tarball)
    raws = {n: b for n, b in files.items() if n.endswith(".raw")}
    report = {
        "source": tarball,
        "truncated_archive": truncated,
        "entries": len(files),
        "raw_files": len(raws),
        "splits": {},
    }
    split_rows: dict[str, list] = {}
    for tag, split in (("train", "train"), ("test", "val")):
        ids_name = f"an4/etc/an4_{tag}.fileids"
        tr_name = f"an4/etc/an4_{tag}.transcription"
        if ids_name not in files or tr_name not in files:
            raise SystemExit(
                f"{tarball}: missing {ids_name} / {tr_name} "
                "(archive too truncated to index the corpus)"
            )
        file_ids = files[ids_name].decode().splitlines()
        transcripts = files[tr_name].decode().splitlines()
        if len(file_ids) != len(transcripts):
            raise SystemExit(
                f"{ids_name}: {len(file_ids)} ids vs "
                f"{len(transcripts)} transcripts"
            )
        wav_dir = os.path.join(target_dir, split, "an4", "wav")
        txt_dir = os.path.join(target_dir, split, "an4", "txt")
        os.makedirs(wav_dir, exist_ok=True)
        os.makedirs(txt_dir, exist_ok=True)
        rows = []  # (duration, wav_path, txt_path)
        missing = 0
        for fid, line in zip(file_ids, transcripts):
            fid = fid.strip()
            if not fid:
                continue
            raw_name = f"an4/wav/{fid}.raw"
            if raw_name not in raws:
                missing += 1  # lost to truncation
                continue
            utt = os.path.basename(fid)
            wav_path = os.path.join(wav_dir, f"{utt}.wav")
            txt_path = os.path.join(txt_dir, f"{utt}.txt")
            duration = raw_to_wav(raws[raw_name], wav_path)
            with open(txt_path, "w") as f:
                f.write(process_transcript(line))
            rows.append((duration, wav_path, txt_path))
        # duration sort always; duration pruning on train only (reference
        # an4.py:84-86 passes min/max for train, none for val)
        rows.sort(key=lambda r: r[0])
        if split == "train":
            kept = [
                r for r in rows if min_duration <= r[0] <= max_duration
            ]
            pruned = len(rows) - len(kept)
            rows = kept
        else:
            pruned = 0
        split_rows[split] = rows
        report["splits"][split] = {
            "utterances": len(rows),
            "missing_from_archive": missing,
            "duration_pruned": pruned,
        }
    if not split_rows["val"] and len(split_rows["train"]) >= 10:
        # a truncated archive can lose the whole test split (it sits at the
        # tail of the tar); hold out every 7th train utterance so eval still
        # measures held-out real audio rather than silently going synthetic
        train, val = [], []
        for i, r in enumerate(split_rows["train"]):
            (val if i % 7 == 3 else train).append(r)
        split_rows["train"], split_rows["val"] = train, val
        report["val_held_out_from_train"] = len(val)
        for split in ("train", "val"):
            report["splits"][split]["utterances"] = len(split_rows[split])
    for split, rows in split_rows.items():
        manifest = os.path.join(target_dir, f"an4_{split}_manifest.csv")
        with open(manifest, "w") as f:
            for _, wav_path, txt_path in rows:
                f.write(
                    f"{os.path.abspath(wav_path)},"
                    f"{os.path.abspath(txt_path)}\n"
                )
        report["splits"][split]["manifest"] = manifest
    return report


def main(argv: Optional[list[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--target-dir", default="data/an4")
    p.add_argument("--source", default=None,
                   help="local an4_raw.bigendian.tar.gz (skips download; "
                        "truncated archives are salvaged)")
    p.add_argument("--min-duration", type=float, default=1.0)
    p.add_argument("--max-duration", type=float, default=15.0)
    args = p.parse_args(argv)
    report = fetch_an4(
        args.target_dir, args.source, args.min_duration, args.max_duration
    )
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
