"""Raw ImageNet folder tree -> single-file HDF5 builder (CLI).

Parity target: reference scripts/create_hdf5.py:46-108 — walk
``<datadir>/{train,val}/<class>/*`` image folders, build the class-name ->
index map, resize every image to SxSx3 RGB uint8 (cv2 there, PIL here),
write the single HDF5 with train_img/train_labels/val_img/val_labels keys
(the layout datasets.load_imagenet_hdf5 reads), and emit the
``imagenet_label_mapping.csv`` class map alongside.

Re-design: images stream into pre-allocated chunked HDF5 datasets one at a
time (the reference also writes incrementally); nothing holds the corpus
in RAM. Class indices follow SORTED class-directory order (deterministic
across runs and hosts; the emitted CSV records whatever mapping was used,
exactly like the reference's output CSV).

Usage:
  python -m mgwfbp_tpu.data.imagenet_hdf5 --raw-dir /data/imagenet \
      --out-dir /data --size 224
  python -m mgwfbp_tpu.train_cli --dnn resnet50 --data-dir /data
"""

from __future__ import annotations

import argparse
import csv
import json
import os
from typing import Optional

import numpy as np

IMAGE_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def list_images(raw_dir: str, folder: str) -> list[tuple[str, str]]:
    """(path, class_name) pairs under raw_dir/folder/<class>/*, sorted."""
    root = os.path.join(raw_dir, folder)
    out: list[tuple[str, str]] = []
    if not os.path.isdir(root):
        return out
    for cls in sorted(os.listdir(root)):
        cdir = os.path.join(root, cls)
        if not os.path.isdir(cdir):
            continue
        for fn in sorted(os.listdir(cdir)):
            if fn.lower().endswith(IMAGE_EXTS):
                out.append((os.path.join(cdir, fn), cls))
    return out


def load_resized(path: str, size: int) -> np.ndarray:
    """One image -> (size, size, 3) RGB uint8 (reference _preprocess_image:
    cv2.resize INTER_CUBIC + BGR->RGB; PIL's BICUBIC is the analogue)."""
    from PIL import Image

    with Image.open(path) as im:
        im = im.convert("RGB").resize((size, size), Image.BICUBIC)
        return np.asarray(im, dtype=np.uint8)


def build_hdf5(
    raw_dir: str,
    out_dir: str,
    output: str = "imagenet.hdf5",
    size: int = 224,
) -> dict:
    import h5py

    train = list_images(raw_dir, "train")
    val = list_images(raw_dir, "val")
    if not train or not val:
        raise SystemExit(
            f"{raw_dir!r}: expected train/<class>/*.jpg and val/<class>/* "
            "image folders"
        )
    classes = sorted({c for _, c in train} | {c for _, c in val})
    class_map = {c: i for i, c in enumerate(classes)}
    os.makedirs(out_dir, exist_ok=True)
    # the reference emits its class map next to the HDF5
    # (create_hdf5.py:53-58); ours records the sorted-dir-order mapping
    csv_path = os.path.join(out_dir, "imagenet_label_mapping.csv")
    with open(csv_path, "w", newline="") as f:
        w = csv.writer(f, delimiter=" ")
        for c in classes:
            w.writerow([c, class_map[c]])
    h5path = os.path.join(out_dir, output)
    with h5py.File(h5path, "w") as hf:
        for key, files in (("train", train), ("val", val)):
            img_ds = hf.create_dataset(
                f"{key}_img",
                shape=(len(files), size, size, 3),
                dtype="uint8",
                chunks=(1, size, size, 3),
            )
            labels = np.empty((len(files),), np.int64)
            for i, (path, cls) in enumerate(files):
                img_ds[i] = load_resized(path, size)
                labels[i] = class_map[cls]
            hf.create_dataset(f"{key}_labels", data=labels)
    return {
        "out": h5path,
        "label_map": csv_path,
        "num_classes": len(classes),
        "train_images": len(train),
        "val_images": len(val),
        "size": size,
    }


def main(argv: Optional[list[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--raw-dir", required=True,
                   help="root with train/<class>/* and val/<class>/*")
    p.add_argument("--out-dir", required=True)
    p.add_argument("--output", default="imagenet.hdf5")
    p.add_argument("--size", type=int, default=224)
    args = p.parse_args(argv)
    print(json.dumps(build_hdf5(
        args.raw_dir, args.out_dir, args.output, args.size
    ), indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
