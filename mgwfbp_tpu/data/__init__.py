"""Data subsystem: dataset dispatch + sharded loading.

`data_prepare` is the analogue of the reference's per-dataset prepare methods
and dispatcher (reference dl_trainer.py:317-539): it resolves a dataset name
to sharded train/val loaders. Real files under `data_dir` are used when
present; otherwise a deterministic synthetic twin with identical
shapes/cardinalities is served (no-egress container — see data/datasets.py).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from mgwfbp_tpu.data.datasets import (
    CIFAR_MEAN,
    CIFAR_STD,
    IMAGENET_MEAN,
    IMAGENET_STD,
    MNIST_MEAN,
    MNIST_STD,
    load_cifar10,
    load_imagenet_hdf5,
    load_mnist,
    synthetic_images,
)
from mgwfbp_tpu.data.loader import (
    ArrayDataset,
    PrefetchLoader,
    ShardedLoader,
    infinite_batches,
    normalize_images,
)
from mgwfbp_tpu.data.sharding import ShardInfo


def _wrap_prefetch(train_loader):
    """Background prefetch for the TRAIN path (reference DataLoader
    num_workers + pin_memory, dl_trainer.py:353). MGWFBP_DATA_WORKERS
    tunes the pool (0 disables and returns the bare loader);
    MGWFBP_DATA_DEVICE_PUT=1 additionally commits batches to device from
    the worker threads (pin_memory analogue) — OPT-IN because device_put
    from non-main threads exercises backend thread paths that experimental
    platforms (the axon TPU tunnel here) may not handle; host-side
    assembly-ahead alone already overlaps the load with compute, and the
    actual transfer is async under jax dispatch."""
    import os

    workers = int(os.environ.get("MGWFBP_DATA_WORKERS", "2"))
    if workers <= 0:
        return train_loader
    return PrefetchLoader(
        train_loader,
        workers=workers,
        device_put=os.environ.get("MGWFBP_DATA_DEVICE_PUT", "0") == "1",
    )

# Synthetic sizes: big enough for stable throughput measurement and smoke
# convergence, small enough to build instantly. MGWFBP_SYNTH_TRAIN_N /
# MGWFBP_SYNTH_VAL_N override them (full-cardinality convergence runs), and
# MGWFBP_SYNTH_MODE=hard swaps the trivial twin for the held-out
# generalization generator (datasets.synthetic_images_hard) — the honest
# convergence substitute in this no-egress container.
_SYNTH_TRAIN = {"mnist": 4096, "cifar10": 4096, "imagenet": 512, "ptb": 512}
_SYNTH_VAL = {"mnist": 512, "cifar10": 512, "imagenet": 128, "ptb": 64}


def _synth_size(split: str, name: str) -> int:
    import os

    table = _SYNTH_TRAIN if split == "train" else _SYNTH_VAL
    env = os.environ.get(f"MGWFBP_SYNTH_{split.upper()}_N")
    return int(env) if env else table[name]


@dataclasses.dataclass
class DataBundle:
    train: ShardedLoader
    val: ShardedLoader
    num_classes: int
    synthetic: bool
    # batches per epoch over the GLOBAL batch (reference dl_trainer.py:539
    # divides by batch_size * nworkers)
    num_batches_per_epoch: int


def data_prepare(
    dataset: str,
    data_dir: str = "./data",
    batch_size: int = 32,
    shard: ShardInfo = ShardInfo(),
    seed: int = 0,
    image_hw: Optional[tuple[int, int]] = None,
    synthetic: Optional[bool] = None,
    augment: bool = True,
    num_steps: Optional[int] = None,
) -> DataBundle:
    """Build sharded train/val loaders for a dataset name.

    batch_size is PER PROCESS (weak scaling, reference dl_trainer.py:153-156).
    `synthetic=True` forces the synthetic twin; None auto-detects files.
    `image_hw` overrides the image size (inceptions need 299x299).
    `augment=False` disables training-time augmentation (benchmarking).
    `num_steps` overrides the LM window length (default: the reference's
    35-token BPTT window; seq-parallel transformers need a length divisible
    by the seq mesh extent).
    """
    name = dataset.lower()
    if name in ("mnist", "cifar10", "imagenet"):
        hw_default = {"mnist": (28, 28), "cifar10": (32, 32), "imagenet": (224, 224)}
        h, w = image_hw or hw_default[name]
        c = 1 if name == "mnist" else 3
        mean, std = {
            "mnist": (MNIST_MEAN, MNIST_STD),
            "cifar10": (CIFAR_MEAN, CIFAR_STD),
            "imagenet": (IMAGENET_MEAN, IMAGENET_STD),
        }[name]
        train = val = None
        if not synthetic:
            loader_fn = {
                "mnist": load_mnist,
                "cifar10": load_cifar10,
                "imagenet": load_imagenet_hdf5,
            }[name]
            train = loader_fn(data_dir, "train")
            val = loader_fn(data_dir, "val" if name == "imagenet" else "test")
        is_synth = train is None or val is None
        if is_synth:
            if synthetic is False:
                raise FileNotFoundError(
                    f"real {name} data not found under {data_dir!r}"
                )
            import os as _os

            nc = 1000 if name == "imagenet" else 10
            gen = synthetic_images
            if _os.environ.get("MGWFBP_SYNTH_MODE", "easy") == "hard":
                from mgwfbp_tpu.data.datasets import synthetic_images_hard

                gen = synthetic_images_hard
            train = gen(_synth_size("train", name), (h, w, c), nc, seed)
            val = gen(_synth_size("val", name), (h, w, c), nc, seed + 1)
        else:
            real_hw = tuple(train.data.shape[1:3])
            if image_hw is not None and real_hw != tuple(image_hw):
                raise ValueError(
                    f"requested image_hw {image_hw} but real {name} files "
                    f"under {data_dir!r} store {real_hw} images; rebuild the "
                    "dataset at the requested size (scripts/create_hdf5)"
                )
        normalize = normalize_images(mean, std)
        # train-split-only augmentation (reference dl_trainer.py:331-336,
        # 381-385: RandomCrop+flip for CIFAR, RandomResizedCrop+flip for
        # ImageNet; eval uses only normalize)
        train_tf = normalize
        if augment and name == "cifar10":
            # fused crop+flip+normalize: one pass over the uint8 batch via
            # the native C++ kernel (NumPy fallback is bit-identical)
            from mgwfbp_tpu.data.augment import FusedCropFlipNormalize

            train_tf = FusedCropFlipNormalize(mean, std, pad=4)
        elif augment:
            from mgwfbp_tpu.data.augment import chain, train_augment

            aug = train_augment(name)
            if aug is not None:
                train_tf = chain(aug, normalize)
        train_loader = ShardedLoader(
            train, batch_size, shard, shuffle=True, seed=seed,
            transform=train_tf,
        )
        val_loader = ShardedLoader(
            val, batch_size, shard, shuffle=False, seed=seed,
            drop_last=False, transform=normalize,
        )
        return DataBundle(
            train=_wrap_prefetch(train_loader),
            val=val_loader,
            num_classes=train.num_classes,
            synthetic=is_synth,
            # per-rank loader length already divides by nranks, so this is
            # dataset_size / (batch_size * nranks) — the reference's formula
            num_batches_per_epoch=len(train_loader),
        )
    if name == "ptb":
        from mgwfbp_tpu.data.ptb import (
            NUM_STEPS,
            VOCAB_SIZE,
            carry_layout,
            load_ptb_stream,
            synthetic_ptb_stream,
        )

        nsteps = num_steps or NUM_STEPS
        streams = None
        if not synthetic:
            streams = (load_ptb_stream(data_dir, "train"),
                       load_ptb_stream(data_dir, "valid"))
            if streams[0] is None or streams[1] is None:
                streams = None
        is_synth = streams is None
        if is_synth:
            if synthetic is False:
                raise FileNotFoundError(f"PTB files not found under {data_dir!r}")
            vocab_size = VOCAB_SIZE
            train_stream = synthetic_ptb_stream(_SYNTH_TRAIN["ptb"], seed=seed)
            val_stream = synthetic_ptb_stream(_SYNTH_VAL["ptb"], seed=seed + 1)
        else:
            (train_stream, vocab_size), (val_stream, _) = streams
        # Stateful-BPTT layout: contiguous sub-streams per batch element and
        # per rank (see ptb.carry_layout); NO shuffling, NO sample-sharding —
        # the carry must see textually consecutive windows each step.
        train = carry_layout(
            train_stream, nsteps, batch_size, shard.rank, shard.nranks,
            vocab_size,
        )
        val = carry_layout(
            val_stream, nsteps, batch_size, shard.rank, shard.nranks,
            vocab_size,
        )
        train_loader = ShardedLoader(train, batch_size, shuffle=False, seed=seed)
        val_loader = ShardedLoader(val, batch_size, shuffle=False, seed=seed)
        return DataBundle(
            train=_wrap_prefetch(train_loader),
            val=val_loader,
            num_classes=vocab_size,
            synthetic=is_synth,
            num_batches_per_epoch=len(train_loader),
        )
    if name == "an4":
        from mgwfbp_tpu.data.audio import an4_prepare

        bundle = an4_prepare(data_dir, batch_size, shard, seed, synthetic)
        bundle.train = _wrap_prefetch(bundle.train)
        return bundle
    raise ValueError(f"unknown dataset {dataset!r}")


__all__ = [
    "ArrayDataset",
    "DataBundle",
    "ShardInfo",
    "ShardedLoader",
    "data_prepare",
    "infinite_batches",
]
