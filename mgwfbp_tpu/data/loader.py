"""In-memory array dataset + sharded epoch loader.

Replaces the reference's torch `DataLoader` + `DistributedSampler` pairs
(reference dl_trainer.py:317-539) with a NumPy pipeline: datasets expose
indexable arrays; the loader owns the epoch permutation (sharded via
`sharding.shard_indices`), batching, and normalization, and yields host
numpy batches ready for device put (the trainer lays them out on the mesh).

Double-buffered prefetch happens at the trainer level via
`jax.device_put` overlap; the loader itself stays synchronous and
deterministic (same seed -> same batches, rank-disjoint).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterator, Optional

import numpy as np

from mgwfbp_tpu.data.sharding import ShardInfo, shard_indices


@dataclasses.dataclass
class ArrayDataset:
    """data[N, ...], labels[N] (+ optional per-sample aux like lengths)."""

    data: np.ndarray
    labels: np.ndarray
    num_classes: int

    def __post_init__(self):
        if len(self.data) != len(self.labels):
            raise ValueError("data/labels length mismatch")

    def __len__(self) -> int:
        return len(self.data)


class ShardedLoader:
    """Epoch-based sharded batch iterator.

    `set_epoch` reshuffles deterministically (reference
    train_sampler.set_epoch, dl_trainer.py:778-779). Batches are per-process
    (weak scaling: the reference's batch_size is per worker,
    dl_trainer.py:153-156).
    """

    def __init__(
        self,
        dataset: ArrayDataset,
        batch_size: int,
        shard: ShardInfo = ShardInfo(),
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
        transform: Optional[Callable[[np.ndarray], np.ndarray]] = None,
    ):
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shard = shard
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.transform = transform
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def set_batch_size(self, batch_size: int) -> None:
        """Re-batch the same shard (e.g. a larger eval batch,
        MGWFBP_EVAL_BATCH); batching here is lazy so the attribute IS the
        behavior."""
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.batch_size = batch_size

    @property
    def num_batches(self) -> int:
        per_rank = len(
            shard_indices(
                len(self.dataset), self.shard, 0, self.shuffle, self.seed,
                self.drop_last,
            )
        )
        if self.drop_last:
            return per_rank // self.batch_size
        return (per_rank + self.batch_size - 1) // self.batch_size

    def __len__(self) -> int:
        return self.num_batches

    def _epoch_indices(self, epoch: int) -> np.ndarray:
        if getattr(self, "_idx_epoch", None) != epoch:
            self._idx = shard_indices(
                len(self.dataset), self.shard, epoch, self.shuffle,
                self.seed, self.drop_last,
            )
            self._idx_epoch = epoch
        return self._idx

    def prime_epoch(self, epoch: int) -> None:
        """Precompute the epoch's shard permutation (PrefetchLoader calls
        this once before fanning load_batch jobs to its pool, so workers
        never race to build the cache)."""
        self._epoch_indices(epoch)

    def load_batch(self, epoch: int, b: int) -> tuple[np.ndarray, np.ndarray]:
        """Assemble batch `b` of `epoch` (gather + transform), independently
        of iterator state — the unit of work `PrefetchLoader` farms out to a
        thread pool. Deterministic: (seed, epoch, rank, batch) fully name
        the batch, so prefetched and inline assembly are bit-identical."""
        idx = self._epoch_indices(epoch)
        sel = idx[b * self.batch_size : (b + 1) * self.batch_size]
        x = _gather(self.dataset.data, sel)
        y = self.dataset.labels[sel]
        if self.transform is not None:
            if getattr(self.transform, "wants_rng", False):
                # per-(seed, epoch, rank, batch) stream: augmentation is
                # deterministic per epoch and decorrelated across ranks
                rng = np.random.default_rng(
                    [self.seed, epoch, self.shard.rank, b]
                )
                x = self.transform(x, rng)
            else:
                x = self.transform(x)
        return x, y

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        idx = self._epoch_indices(self.epoch)
        if self.drop_last:
            nb = len(idx) // self.batch_size
        else:
            nb = (len(idx) + self.batch_size - 1) // self.batch_size
        for b in range(nb):
            yield self.load_batch(self.epoch, b)


def _gather(data, sel: np.ndarray) -> np.ndarray:
    """Fancy-index `data[sel]` for ndarray OR h5py dataset backends.

    h5py only accepts strictly-increasing duplicate-free index lists, while
    shuffled/padded shard indices are neither; read the sorted unique set and
    scatter back (one HDF5 read per batch, still sequential-ish on disk).
    """
    if isinstance(data, np.ndarray):
        return data[sel]
    usel, inverse = np.unique(sel, return_inverse=True)
    return np.asarray(data[usel.tolist()])[inverse]


class PrefetchLoader:
    """Background-prefetching wrapper around an epoch loader.

    The reference feeds its GPUs through
    `DataLoader(num_workers=NUM_CPU_THREADS, pin_memory=True)` (reference
    dl_trainer.py:353, :405); this is the same role without torch: batch
    assembly (index gather + augmentation) runs in a thread pool AHEAD of
    consumption, and each ready batch is optionally `jax.device_put` early
    so the host->device transfer overlaps the previous step's compute
    (double buffering; the put is async, the jitted step just consumes the
    committed arrays). NumPy transforms release the GIL, so threads give
    real parallelism without pickling costs.

    Two modes:
      * inner exposes `load_batch(epoch, b)` (ShardedLoader): `workers`
        assemble batches concurrently, results consumed IN ORDER — output
        is bit-identical to the inline loader for any worker count.
      * otherwise (audio bucketing etc.): a single background thread runs
        the inner iterator `depth` batches ahead.
    """

    def __init__(
        self,
        inner,
        workers: int = 2,
        depth: int = 2,
        device_put: bool = False,
    ):
        self.inner = inner
        self.workers = max(int(workers), 0)
        self.depth = max(int(depth), 1)
        self.device_put = device_put

    # epoch/batch-size/len plumbing passes through to the inner loader
    def set_epoch(self, epoch: int) -> None:
        self.inner.set_epoch(epoch)

    def set_batch_size(self, batch_size: int) -> None:
        self.inner.set_batch_size(batch_size)

    @property
    def epoch(self):
        return self.inner.epoch

    @property
    def batch_size(self):
        return self.inner.batch_size

    @property
    def dataset(self):
        return self.inner.dataset

    @property
    def num_batches(self) -> int:
        return len(self.inner)

    def __len__(self) -> int:
        return len(self.inner)

    def _finalize(self, batch):
        if not self.device_put:
            return batch
        import jax

        if jax.process_count() > 1:
            # multi-host assembly pulls host numpy back out of the batch
            # (make_array_from_process_local_data); early device_put would
            # just bounce the bytes
            return batch
        return jax.device_put(batch)

    def __iter__(self):
        if self.workers == 0:
            for batch in self.inner:
                yield self._finalize(batch)
            return
        if hasattr(self.inner, "load_batch"):
            yield from self._iter_pool()
        else:
            yield from self._iter_thread()

    def _iter_pool(self):
        import collections
        from concurrent.futures import ThreadPoolExecutor

        nb = len(self.inner)
        epoch = self.inner.epoch
        # indices are epoch-cached on the inner loader; prime the cache once
        # on this thread so pool workers only read it
        if nb and hasattr(self.inner, "prime_epoch"):
            self.inner.prime_epoch(epoch)
        with ThreadPoolExecutor(max_workers=self.workers) as ex:

            def job(b):
                return self._finalize(self.inner.load_batch(epoch, b))

            ahead = self.workers + self.depth
            futs = collections.deque(
                ex.submit(job, b) for b in range(min(ahead, nb))
            )
            next_b = len(futs)
            while futs:
                out = futs.popleft().result()  # in-order consumption
                if next_b < nb:
                    futs.append(ex.submit(job, next_b))
                    next_b += 1
                yield out

    def _iter_thread(self):
        import queue
        import threading

        q: queue.Queue = queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        _END = object()

        def put(item) -> bool:
            # bounded put that gives up when the consumer abandoned the
            # iterator (otherwise an early `break` in the consumer — e.g. a
            # step-capped epoch — would leave this thread blocked on a full
            # queue forever, leaking it and its buffered batches)
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def feed():
            try:
                for batch in self.inner:
                    if not put(self._finalize(batch)):
                        return
                put(_END)
            except BaseException as e:  # propagate into the consumer
                put(e)

        t = threading.Thread(target=feed, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            t.join(timeout=5)


def infinite_batches(loader: ShardedLoader, start_epoch: int = 0):
    """Auto-restarting iterator with epoch bumping (reference `data_iter`,
    dl_trainer.py:568-576). Yields (epoch, batch)."""
    epoch = start_epoch
    while True:
        loader.set_epoch(epoch)
        for batch in loader:
            yield epoch, batch
        epoch += 1


def normalize_images(
    mean: tuple[float, ...], std: tuple[float, ...]
) -> Callable[[np.ndarray], np.ndarray]:
    """uint8 HWC images -> normalized float32 (the reference's torchvision
    transforms.Normalize equivalents, dl_trainer.py:369-409).

    uint8 batches go through the fused native kernel when available
    (mgwfbp_tpu.native.normalize_u8); the NumPy fallback uses the same
    px*scale - shift affine so both round identically in float32."""
    mean_a = np.asarray(mean, dtype=np.float32)
    std_a = np.asarray(std, dtype=np.float32)
    scale = (1.0 / (255.0 * std_a)).astype(np.float32)
    shift = (mean_a / std_a).astype(np.float32)

    def _t(x: np.ndarray) -> np.ndarray:
        if x.dtype == np.uint8 and x.ndim >= 1:
            from mgwfbp_tpu import native

            out = native.normalize_u8(x, mean_a, std_a)
            if out is not None:
                return out
        return x.astype(np.float32) * scale - shift

    return _t
