"""In-memory array dataset + sharded epoch loader.

Replaces the reference's torch `DataLoader` + `DistributedSampler` pairs
(reference dl_trainer.py:317-539) with a NumPy pipeline: datasets expose
indexable arrays; the loader owns the epoch permutation (sharded via
`sharding.shard_indices`), batching, and normalization, and yields host
numpy batches ready for device put (the trainer lays them out on the mesh).

Double-buffered prefetch happens at the trainer level via
`jax.device_put` overlap; the loader itself stays synchronous and
deterministic (same seed -> same batches, rank-disjoint).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterator, Optional

import numpy as np

from mgwfbp_tpu.data.sharding import ShardInfo, shard_indices


@dataclasses.dataclass
class ArrayDataset:
    """data[N, ...], labels[N] (+ optional per-sample aux like lengths)."""

    data: np.ndarray
    labels: np.ndarray
    num_classes: int

    def __post_init__(self):
        if len(self.data) != len(self.labels):
            raise ValueError("data/labels length mismatch")

    def __len__(self) -> int:
        return len(self.data)


class ShardedLoader:
    """Epoch-based sharded batch iterator.

    `set_epoch` reshuffles deterministically (reference
    train_sampler.set_epoch, dl_trainer.py:778-779). Batches are per-process
    (weak scaling: the reference's batch_size is per worker,
    dl_trainer.py:153-156).
    """

    def __init__(
        self,
        dataset: ArrayDataset,
        batch_size: int,
        shard: ShardInfo = ShardInfo(),
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
        transform: Optional[Callable[[np.ndarray], np.ndarray]] = None,
    ):
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shard = shard
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.transform = transform
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def set_batch_size(self, batch_size: int) -> None:
        """Re-batch the same shard (e.g. a larger eval batch,
        MGWFBP_EVAL_BATCH); batching here is lazy so the attribute IS the
        behavior."""
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.batch_size = batch_size

    @property
    def num_batches(self) -> int:
        per_rank = len(
            shard_indices(
                len(self.dataset), self.shard, 0, self.shuffle, self.seed,
                self.drop_last,
            )
        )
        if self.drop_last:
            return per_rank // self.batch_size
        return (per_rank + self.batch_size - 1) // self.batch_size

    def __len__(self) -> int:
        return self.num_batches

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        idx = shard_indices(
            len(self.dataset), self.shard, self.epoch, self.shuffle,
            self.seed, self.drop_last,
        )
        if self.drop_last:
            nb = len(idx) // self.batch_size
        else:
            nb = (len(idx) + self.batch_size - 1) // self.batch_size
        wants_rng = getattr(self.transform, "wants_rng", False)
        for b in range(nb):
            sel = idx[b * self.batch_size : (b + 1) * self.batch_size]
            x = _gather(self.dataset.data, sel)
            y = self.dataset.labels[sel]
            if self.transform is not None:
                if wants_rng:
                    # per-(seed, epoch, rank, batch) stream: augmentation is
                    # deterministic per epoch and decorrelated across ranks
                    rng = np.random.default_rng(
                        [self.seed, self.epoch, self.shard.rank, b]
                    )
                    x = self.transform(x, rng)
                else:
                    x = self.transform(x)
            yield x, y


def _gather(data, sel: np.ndarray) -> np.ndarray:
    """Fancy-index `data[sel]` for ndarray OR h5py dataset backends.

    h5py only accepts strictly-increasing duplicate-free index lists, while
    shuffled/padded shard indices are neither; read the sorted unique set and
    scatter back (one HDF5 read per batch, still sequential-ish on disk).
    """
    if isinstance(data, np.ndarray):
        return data[sel]
    usel, inverse = np.unique(sel, return_inverse=True)
    return np.asarray(data[usel.tolist()])[inverse]


def infinite_batches(loader: ShardedLoader, start_epoch: int = 0):
    """Auto-restarting iterator with epoch bumping (reference `data_iter`,
    dl_trainer.py:568-576). Yields (epoch, batch)."""
    epoch = start_epoch
    while True:
        loader.set_epoch(epoch)
        for batch in loader:
            yield epoch, batch
        epoch += 1


def normalize_images(
    mean: tuple[float, ...], std: tuple[float, ...]
) -> Callable[[np.ndarray], np.ndarray]:
    """uint8 HWC images -> normalized float32 (the reference's torchvision
    transforms.Normalize equivalents, dl_trainer.py:369-409).

    uint8 batches go through the fused native kernel when available
    (mgwfbp_tpu.native.normalize_u8); the NumPy fallback uses the same
    px*scale - shift affine so both round identically in float32."""
    mean_a = np.asarray(mean, dtype=np.float32)
    std_a = np.asarray(std, dtype=np.float32)
    scale = (1.0 / (255.0 * std_a)).astype(np.float32)
    shift = (mean_a / std_a).astype(np.float32)

    def _t(x: np.ndarray) -> np.ndarray:
        if x.dtype == np.uint8 and x.ndim >= 1:
            from mgwfbp_tpu import native

            out = native.normalize_u8(x, mean_a, std_a)
            if out is not None:
                return out
        return x.astype(np.float32) * scale - shift

    return _t
