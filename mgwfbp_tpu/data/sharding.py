"""Distributed data sharding — the `DistributedSampler` of this framework.

The reference shards every dataset with
`torch.utils.data.distributed.DistributedSampler(num_replicas=nworkers, rank)`
and reshuffles per epoch via `set_epoch` (reference dl_trainer.py:344-348,
778-779). Here the same contract is a pure index computation: a deterministic
epoch-seeded permutation, padded to a multiple of the world size, sliced
`rank::nranks`. On TPU one *process* feeds all its local devices, so `rank`
is `jax.process_index()` and the per-process batch is
`global_batch / process_count` (device-level splitting happens inside the
mesh via batch-dim sharding).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class ShardInfo:
    rank: int = 0
    nranks: int = 1

    def __post_init__(self):
        if not (0 <= self.rank < self.nranks):
            raise ValueError(f"rank {self.rank} outside [0, {self.nranks})")


def shard_indices(
    n: int,
    shard: ShardInfo,
    epoch: int = 0,
    shuffle: bool = True,
    seed: int = 0,
    drop_last: bool = False,
) -> np.ndarray:
    """Indices this rank owns for one epoch.

    Matches DistributedSampler semantics: epoch-seeded global permutation,
    wrap-around padding so every rank gets the same count, stride slicing.
    With drop_last, truncates instead of padding (all ranks equal length
    either way — a collective-deadlock-free guarantee).
    """
    if n <= 0:
        return np.empty((0,), dtype=np.int64)
    if shuffle:
        rng = np.random.RandomState((seed * 1_000_003 + epoch) % (2**31 - 1))
        order = rng.permutation(n)
    else:
        order = np.arange(n)
    if drop_last:
        total = (n // shard.nranks) * shard.nranks
        order = order[:total]
    else:
        total = ((n + shard.nranks - 1) // shard.nranks) * shard.nranks
        if total > n:
            order = np.concatenate([order, order[: total - n]])
    return order[shard.rank :: shard.nranks]


def per_process_batch(global_batch: int, nprocs: int) -> int:
    if global_batch % nprocs != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by {nprocs} processes"
        )
    return global_batch // nprocs
