"""LibriSpeech corpus acquisition: download/extract/convert/manifest.

Parity target: reference audio_data/librispeech.py — wget the openslr
tarballs (train-clean-100/360, train-other-500, dev-*, test-*), sox-convert
each .flac to 16 kHz mono wav, pair each utterance with its line in the
chapter's ``<spk>-<chap>.trans.txt`` (transcript uppercased), and write
duration-sorted manifests (train pruned to [min, max] seconds).

Re-design differences (no external processes, zero-egress friendly):
  * `--source` accepts local tarballs; downloads are attempted only when a
    URL is reachable. Truncated archives are salvaged entry-by-entry
    (shared machinery with an4_fetch).
  * .flac decode needs a decoder library (`soundfile`); this image ships
    none, so .flac entries raise an actionable error unless one is
    importable. Archives whose audio is already .wav (or raw PCM s16) are
    converted with the stdlib alone — the full pipeline is testable and
    usable without FLAC support.

Usage:
  python -m mgwfbp_tpu.data.librispeech_fetch --target-dir data/librispeech \
      --source dev-clean.tar.gz [--split val]
Then train with --dataset an4 --data-dir data/librispeech (the manifest
format and loader are shared with AN4: data/audio.load_an4 reads
``an4_{split}_manifest.csv`` naming under any data_dir).
"""

from __future__ import annotations

import argparse
import json
import os
import wave
from typing import Optional

import numpy as np

from mgwfbp_tpu.data.an4_fetch import pcm_to_wav, stream_tar_entries

LIBRISPEECH_URLS = {
    "train": [
        "http://www.openslr.org/resources/12/train-clean-100.tar.gz",
    ],
    "val": ["http://www.openslr.org/resources/12/dev-clean.tar.gz"],
}
SAMPLE_RATE = 16000


def preprocess_transcript(phrase: str) -> str:
    """Reference librispeech.py:40-41."""
    return phrase.strip().upper()


def _conform_pcm(pcm: np.ndarray, rate: int) -> np.ndarray:
    """s16 PCM at any rate/channels -> 16 kHz mono s16.

    Downmix by channel mean; nearest-sample resample (sox's -r equivalent
    in spirit; LibriSpeech is natively 16 kHz so the resample path is
    rarely taken)."""
    if pcm.ndim > 1:
        pcm = pcm.mean(axis=1).astype(np.int16)
    if rate != SAMPLE_RATE:
        idx = np.round(
            np.arange(0, len(pcm), rate / SAMPLE_RATE)
        ).astype(np.int64)
        pcm = pcm[np.minimum(idx, len(pcm) - 1)]
    return pcm


def _decode_flac(data: bytes) -> Optional[np.ndarray]:
    """FLAC -> int16 mono PCM at 16 kHz, or None when no decoder exists."""
    try:
        import io

        import soundfile  # not in this image; works where available
    except ImportError:
        return None
    pcm, rate = soundfile.read(io.BytesIO(data), dtype="int16")
    return _conform_pcm(pcm, rate)


def _audio_to_wav(name: str, data: bytes, wav_path: str) -> float:
    """Archive audio entry -> 16 kHz mono s16 wav; returns duration (s)."""
    if name.endswith(".wav"):
        # Never pass archive wavs through unchecked: a 44.1 kHz / stereo /
        # 24-bit file would silently feed wrong-rate audio into the
        # 16 kHz-mono feature pipeline (ADVICE r4 #2). Conform what we can
        # (downmix, s16 cast, nearest-sample resample); reject the rest.
        import io

        with wave.open(io.BytesIO(data)) as w:
            rate, channels, width = (
                w.getframerate(), w.getnchannels(), w.getsampwidth()
            )
            frames = w.readframes(w.getnframes())
        if width != 2:
            raise SystemExit(
                f"{name}: {8 * width}-bit wav; this pipeline expects s16 "
                "PCM — pre-convert the archive audio to 16 kHz mono s16"
            )
        pcm = np.frombuffer(frames, dtype="<i2")
        if channels > 1:
            pcm = pcm.reshape(-1, channels)
        return pcm_to_wav(_conform_pcm(pcm, rate), wav_path)
    if name.endswith(".flac"):
        pcm = _decode_flac(data)
        if pcm is None:
            raise SystemExit(
                f"{name}: .flac decoding needs the 'soundfile' library, "
                "which this environment does not ship. Either install it, "
                "or pre-convert the archive's audio to .wav (any tool; "
                "16 kHz mono s16) and re-tar — the rest of the pipeline "
                "is pure Python."
            )
    else:  # raw big-endian s16 (AN4-style) tolerated for symmetry
        pcm = np.frombuffer(data, dtype=">i2").astype("<i2")
    return pcm_to_wav(pcm, wav_path)


def fetch_librispeech(
    target_dir: str,
    sources: list[str],
    split: str = "train",
    min_duration: float = 1.0,
    max_duration: float = 15.0,
) -> dict:
    """Build wav/txt layout + manifest for one split from tarball(s).

    LibriSpeech layout inside each tarball:
      LibriSpeech/<subset>/<speaker>/<chapter>/<spk>-<chap>-<utt>.flac
      LibriSpeech/<subset>/<speaker>/<chapter>/<spk>-<chap>.trans.txt
    Output layout + manifest naming match an4_fetch (data/audio.load_an4
    consumes either corpus identically).
    """
    wav_dir = os.path.join(target_dir, split, "librispeech", "wav")
    txt_dir = os.path.join(target_dir, split, "librispeech", "txt")
    os.makedirs(wav_dir, exist_ok=True)
    os.makedirs(txt_dir, exist_ok=True)
    rows = []
    report = {
        "sources": sources, "split": split, "truncated": [],
        "missing_transcript": 0, "utterances": 0, "duration_pruned": 0,
    }
    for source in sources:
        # two STREAMING passes (constant memory — LibriSpeech tarballs are
        # multi-GB): pass 1 collects the small per-chapter transcript
        # tables, pass 2 converts audio one member at a time
        trans: dict[str, str] = {}
        it = stream_tar_entries(source)
        for name, data in it:
            if name.endswith(".trans.txt"):
                for line in data.decode().splitlines():
                    parts = line.split()
                    if parts:
                        trans[parts[0]] = " ".join(parts[1:])
        truncated = it.truncated
        it = stream_tar_entries(source)
        for name, data in it:
            base = os.path.basename(name)
            stem, ext = os.path.splitext(base)
            if ext not in (".flac", ".wav", ".raw"):
                continue
            if stem not in trans:
                report["missing_transcript"] += 1
                continue
            wav_path = os.path.join(wav_dir, stem + ".wav")
            txt_path = os.path.join(txt_dir, stem + ".txt")
            duration = _audio_to_wav(name, data, wav_path)
            with open(txt_path, "w") as f:
                f.write(preprocess_transcript(trans[stem]))
            rows.append((duration, wav_path, txt_path))
        if truncated or it.truncated:
            report["truncated"].append(os.path.basename(source))
    rows.sort(key=lambda r: r[0])
    if split == "train":
        kept = [r for r in rows if min_duration <= r[0] <= max_duration]
        report["duration_pruned"] = len(rows) - len(kept)
        rows = kept
    manifest = os.path.join(target_dir, f"an4_{split}_manifest.csv")
    with open(manifest, "w") as f:
        for _, wav_path, txt_path in rows:
            f.write(
                f"{os.path.abspath(wav_path)},{os.path.abspath(txt_path)}\n"
            )
    report["utterances"] = len(rows)
    report["manifest"] = manifest
    return report


def main(argv: Optional[list[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--target-dir", default="data/librispeech")
    p.add_argument("--source", action="append", default=None,
                   help="local tarball(s); repeatable. Without it the "
                        "openslr URLs are attempted (needs egress)")
    p.add_argument("--split", default="train", choices=["train", "val"])
    p.add_argument("--min-duration", type=float, default=1.0)
    p.add_argument("--max-duration", type=float, default=15.0)
    args = p.parse_args(argv)
    sources = args.source
    if not sources:
        import urllib.request

        sources = []
        os.makedirs(args.target_dir, exist_ok=True)
        for url in LIBRISPEECH_URLS[args.split]:
            dest = os.path.join(args.target_dir, os.path.basename(url))
            if not os.path.exists(dest):
                try:
                    with urllib.request.urlopen(url, timeout=60) as r, open(
                        dest, "wb"
                    ) as f:
                        f.write(r.read())
                except Exception as e:
                    raise SystemExit(
                        f"cannot download {url} ({e}); pass --source "
                        "/path/to/tarball instead"
                    )
            sources.append(dest)
    report = fetch_librispeech(
        args.target_dir, sources, args.split,
        args.min_duration, args.max_duration,
    )
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
