"""Training-time data augmentation (host-side NumPy, seeded).

Parity (VERDICT r2 task #6): the reference trains CIFAR with
RandomCrop(32, padding=4) + RandomHorizontalFlip (reference
dl_trainer.py:381-385) and ImageNet with RandomResizedCrop(224) +
RandomHorizontalFlip (dl_trainer.py:331-336). These run in the loader's
transform slot, TRAIN split only, on (B, H, W, C) batches before
normalization. Randomness comes from a per-batch `np.random.Generator`
handed in by `ShardedLoader` (seeded by (seed, epoch, rank, batch)), so
epochs reshuffle augmentation deterministically and ranks decorrelate.

Everything is vectorized or O(B) NumPy — no PIL/torchvision; the bilinear
resize for RandomResizedCrop is implemented directly.
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np


def random_hflip(x: np.ndarray, rng: np.random.Generator, p: float = 0.5) -> np.ndarray:
    """Flip each sample left-right with probability p. x: (B, H, W, C)."""
    flip = rng.random(x.shape[0]) < p
    if not flip.any():
        return x
    out = x.copy()
    out[flip] = out[flip, :, ::-1]
    return out


def crop_at_offsets(
    x: np.ndarray, ys: np.ndarray, xs: np.ndarray, pad: int
) -> np.ndarray:
    """Zero-pad by `pad`, crop back to the original size at the given
    per-sample offsets (0..2*pad)."""
    b, h, w, c = x.shape
    padded = np.pad(
        x, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="constant"
    )
    out = np.empty_like(x)
    for i in range(b):
        out[i] = padded[i, ys[i] : ys[i] + h, xs[i] : xs[i] + w]
    return out


def random_crop(
    x: np.ndarray, rng: np.random.Generator, pad: int = 4
) -> np.ndarray:
    """Zero-pad by `pad` on each spatial side, crop back to the original
    size at a per-sample random offset (torchvision RandomCrop(size, pad))."""
    b = x.shape[0]
    ys = rng.integers(0, 2 * pad + 1, size=b)
    xs = rng.integers(0, 2 * pad + 1, size=b)
    return crop_at_offsets(x, ys, xs, pad)


def random_resized_crop(
    x: np.ndarray,
    rng: np.random.Generator,
    scale: tuple[float, float] = (0.08, 1.0),
    ratio: tuple[float, float] = (3.0 / 4.0, 4.0 / 3.0),
    attempts: int = 10,
) -> np.ndarray:
    """torchvision RandomResizedCrop: sample an area fraction and aspect
    ratio per sample, crop, bilinear-resize back to the input size.

    Fully vectorized over the batch (the loader is synchronous, so a
    per-sample Python resize loop would stall every train step): crop
    rectangles are sampled as (B,) arrays, then one batched gather computes
    the bilinear interpolation for all samples at once. Output is float32.
    """
    b, h, w, c = x.shape
    # --- sample crop rectangles: (attempts, B) candidates, first valid wins
    area = h * w * rng.uniform(scale[0], scale[1], size=(attempts, b))
    ar = np.exp(
        rng.uniform(np.log(ratio[0]), np.log(ratio[1]), size=(attempts, b))
    )
    tw = np.round(np.sqrt(area * ar)).astype(np.int64)
    th = np.round(np.sqrt(area / ar)).astype(np.int64)
    valid = (tw > 0) & (tw <= w) & (th > 0) & (th <= h)
    first = np.argmax(valid, axis=0)  # index of first valid candidate
    any_valid = valid[first, np.arange(b)]
    cw = np.where(any_valid, tw[first, np.arange(b)], min(w, h))
    ch = np.where(any_valid, th[first, np.arange(b)], min(w, h))
    # per-sample uniform offsets within the valid range
    top = np.floor(rng.random(b) * (h - ch + 1)).astype(np.int64)
    left = np.floor(rng.random(b) * (w - cw + 1)).astype(np.int64)
    # center-crop fallback where nothing was valid (torchvision semantics)
    top = np.where(any_valid, top, (h - ch) // 2)
    left = np.where(any_valid, left, (w - cw) // 2)

    # --- batched bilinear gather back to (h, w), half-pixel centers
    yy = top[:, None] + (np.arange(h)[None, :] + 0.5) * ch[:, None] / h - 0.5
    xx = left[:, None] + (np.arange(w)[None, :] + 0.5) * cw[:, None] / w - 0.5
    y0f = np.floor(yy)
    x0f = np.floor(xx)
    wy = (yy - y0f).astype(np.float32)[:, :, None, None]  # (B, h, 1, 1)
    wx = (xx - x0f).astype(np.float32)[:, None, :, None]  # (B, 1, w, 1)
    ylo = top[:, None]
    yhi = (top + ch - 1)[:, None]
    xlo = left[:, None]
    xhi = (left + cw - 1)[:, None]
    y0 = np.clip(y0f.astype(np.int64), ylo, yhi)
    y1 = np.clip(y0 + 1, ylo, yhi)
    x0 = np.clip(x0f.astype(np.int64), xlo, xhi)
    x1 = np.clip(x0 + 1, xlo, xhi)
    bi = np.arange(b)[:, None, None]
    f = x.astype(np.float32)
    y0e, y1e = y0[:, :, None], y1[:, :, None]  # (B, h, 1)
    x0e, x1e = x0[:, None, :], x1[:, None, :]  # (B, 1, w)
    top_row = f[bi, y0e, x0e] * (1 - wx) + f[bi, y0e, x1e] * wx
    bot_row = f[bi, y1e, x0e] * (1 - wx) + f[bi, y1e, x1e] * wx
    return top_row * (1 - wy) + bot_row * wy


class FusedCropFlipNormalize:
    """CIFAR-style crop + flip + normalize as ONE pass over the batch.

    Uses the native C++ kernel (mgwfbp_tpu.native) when available — a single
    read of the uint8 batch producing normalized float32 — with a
    bit-identical NumPy fallback (randomness is drawn host-side with the
    same call order either way, so native and fallback produce the same
    bytes for the same seed)."""

    wants_rng = True

    def __init__(self, mean, std, pad: int = 4, p_flip: float = 0.5):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.pad = pad
        self.p_flip = p_flip

    def __call__(self, x: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        b = x.shape[0]
        ys = rng.integers(0, 2 * self.pad + 1, size=b)
        xs = rng.integers(0, 2 * self.pad + 1, size=b)
        flips = rng.random(b) < self.p_flip
        if x.dtype == np.uint8:
            from mgwfbp_tpu import native

            out = native.fused_crop_flip_normalize(
                x, ys, xs, flips.astype(np.uint8), self.mean, self.std,
                self.pad,
            )
            if out is not None:
                return out
        # fallback: crop_at_offsets returns a fresh array, flip in place;
        # use the SAME affine factorization (px*scale - shift) as the C++
        # kernel so both paths round identically in float32
        x = crop_at_offsets(x, ys, xs, self.pad)
        x[flips] = x[flips, :, ::-1]
        scale = (1.0 / (255.0 * self.std)).astype(np.float32)
        shift = (self.mean / self.std).astype(np.float32)
        return x.astype(np.float32) * scale - shift


class Augment:
    """Composable seeded augmentation pipeline for the loader's transform
    slot. `wants_rng` tells ShardedLoader to pass its per-batch Generator."""

    wants_rng = True

    def __init__(self, *stages: Callable):
        self.stages = stages

    def __call__(self, x: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        for s in self.stages:
            x = s(x, rng)
        return x


def train_augment(dataset: str) -> Augment | None:
    """Reference training transforms by dataset (dl_trainer.py:331-336,
    381-385); None where the reference doesn't augment (mnist, ptb, an4)."""
    name = dataset.lower()
    if name == "cifar10":
        return Augment(random_crop, random_hflip)
    if name == "imagenet":
        return Augment(random_resized_crop, random_hflip)
    return None


def chain(*transforms) -> Callable:
    """Compose transforms left-to-right; rng-aware stages get the Generator.
    The composite wants an rng iff any member does."""
    members = [t for t in transforms if t is not None]

    class _Chain:
        wants_rng = any(getattr(t, "wants_rng", False) for t in members)

        def __call__(self, x, rng=None):
            for t in members:
                if getattr(t, "wants_rng", False):
                    x = t(x, rng)
                else:
                    x = t(x)
            return x

    return _Chain()
