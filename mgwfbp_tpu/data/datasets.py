"""Dataset constructors: real files when present, deterministic synthetic
fallback otherwise.

The reference's data layer (SURVEY.md §2.8): torchvision CIFAR-10/MNIST
downloads, an HDF5 single-file ImageNet (reference datasets.py:8-36 +
scripts/create_hdf5.py), a PTB word-LM reader (ptb_reader.py), and the AN4
audio pipeline. This container has no network egress, so every dataset has a
synthetic twin with the exact shapes/dtypes/cardinalities of the real one —
the benchmark path (throughput, scaling, schedule quality) is data-content
agnostic; accuracy runs use the real files when mounted at data_dir.

File formats understood:
  mnist    — idx ubyte files (train-images-idx3-ubyte, ...) under data_dir
  cifar10  — python-pickle batches (cifar-10-batches-py/) under data_dir
  imagenet — single HDF5 with train_img/train_labels/val_img/val_labels
             (reference datasets.py:14-18 layout)
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
from typing import Optional

import numpy as np

from mgwfbp_tpu.data.loader import ArrayDataset

CIFAR_MEAN = (0.4914, 0.4822, 0.4465)
CIFAR_STD = (0.2470, 0.2435, 0.2616)
MNIST_MEAN = (0.1307,)
MNIST_STD = (0.3081,)
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


def synthetic_images(
    n: int, hwc: tuple[int, int, int], num_classes: int, seed: int = 0
) -> ArrayDataset:
    """Deterministic fake image set with class-dependent means so that a
    model can actually fit it (convergence smoke tests need learnable
    signal, not pure noise)."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=n).astype(np.int32)
    base = rng.randint(0, 256, size=(n,) + hwc)
    # shift each image's intensity by its class so P(x|y) differs per class
    # (float scaling keeps a nonzero gradient of shift w.r.t. class even for
    # num_classes > 128, where integer division would collapse to 0)
    shift = np.round(labels * (128.0 / max(num_classes - 1, 1))).astype(np.int64)
    data = np.clip(base // 2 + shift[:, None, None, None], 0, 255).astype(np.uint8)
    return ArrayDataset(data=data, labels=labels, num_classes=num_classes)


def _smooth_field(rng: np.random.RandomState, hwc, low: int = 8) -> np.ndarray:
    """Low-frequency random field: white noise at `low` resolution,
    bilinearly upsampled to (H, W, C), unit RMS."""
    h, w, c = hwc
    coarse = rng.randn(low, low, c)
    ys = np.linspace(0, low - 1, h)
    xs = np.linspace(0, low - 1, w)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, low - 1)
    x1 = np.minimum(x0 + 1, low - 1)
    fy = (ys - y0)[:, None, None]
    fx = (xs - x0)[None, :, None]
    field = (
        coarse[np.ix_(y0, x0)] * (1 - fy) * (1 - fx)
        + coarse[np.ix_(y1, x0)] * fy * (1 - fx)
        + coarse[np.ix_(y0, x1)] * (1 - fy) * fx
        + coarse[np.ix_(y1, x1)] * fy * fx
    )
    return field / max(float(np.sqrt((field**2).mean())), 1e-8)


def synthetic_images_hard(
    n: int,
    hwc: tuple[int, int, int],
    num_classes: int,
    seed: int = 0,
    world_seed: int = 1234,
    n_styles: int = 64,
    class_amp: float = 4.0,
    style_amp: float = 24.0,
    noise_std: float = 40.0,
    max_shift: int = 4,
) -> ArrayDataset:
    """Held-out-generalization synthetic twin (the "hard" mode).

    This container has no network egress, so the real CIFAR-10 corpus is
    unobtainable; this generator is the honest substitute for convergence
    runs. Unlike `synthetic_images` (a per-sample intensity shift a model
    memorizes in one epoch), classification here requires learning latent
    generative factors that generalize to held-out draws:

      x = 128 + class_amp * basis[label]           (weak class signal)
            + style_amp * styles[k]                (strong class-INDEPENDENT
                                                    nuisance factor, shared
                                                    across classes)
            + noise_std * white noise,
      randomly circular-shifted by up to `max_shift` px and flipped.

    The class basis and style bank are drawn from `world_seed` (shared by
    train and val builds); per-sample draws come from `seed`, so a val set
    built with a different `seed` contains only unseen samples of the same
    generative process — held-out accuracy measures generalization, not
    memorization. The style amplitude dominating the class amplitude makes
    the task non-linear-separable-at-a-glance, and the noise floor keeps
    single-epoch accuracy well below ceiling.
    """
    h, w, c = hwc
    wrng = np.random.RandomState(world_seed)
    basis = np.stack(
        [_smooth_field(wrng, hwc) for _ in range(num_classes)]
    )  # (K, H, W, C)
    styles = np.stack([_smooth_field(wrng, hwc) for _ in range(n_styles)])
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=n).astype(np.int32)
    style_ix = rng.randint(0, n_styles, size=n)
    x = (
        128.0
        + class_amp * basis[labels]
        + style_amp * styles[style_ix]
        + noise_std * rng.randn(n, h, w, c)
    )
    # random circular shift + horizontal flip (cheap per-sample geometry)
    dy = rng.randint(-max_shift, max_shift + 1, size=n)
    dx = rng.randint(-max_shift, max_shift + 1, size=n)
    flip = rng.rand(n) < 0.5
    for i in range(n):
        if dy[i] or dx[i]:
            x[i] = np.roll(x[i], (dy[i], dx[i]), axis=(0, 1))
        if flip[i]:
            x[i] = x[i, :, ::-1]
    data = np.clip(x, 0, 255).astype(np.uint8)
    return ArrayDataset(data=data, labels=labels, num_classes=num_classes)


# ---------------------------------------------------------------------------
# MNIST (idx files)
# ---------------------------------------------------------------------------


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(dims)


def load_mnist(data_dir: str, split: str = "train") -> Optional[ArrayDataset]:
    prefix = "train" if split == "train" else "t10k"
    for suffix in ("", ".gz"):
        img = os.path.join(data_dir, f"{prefix}-images-idx3-ubyte{suffix}")
        lbl = os.path.join(data_dir, f"{prefix}-labels-idx1-ubyte{suffix}")
        if os.path.exists(img) and os.path.exists(lbl):
            data = _read_idx(img)[..., None]  # (N, 28, 28, 1)
            labels = _read_idx(lbl).astype(np.int32)
            return ArrayDataset(data=data, labels=labels, num_classes=10)
    return None


# ---------------------------------------------------------------------------
# CIFAR-10 (pickle batches)
# ---------------------------------------------------------------------------


def load_cifar10(data_dir: str, split: str = "train") -> Optional[ArrayDataset]:
    root = os.path.join(data_dir, "cifar-10-batches-py")
    if not os.path.isdir(root):
        return None
    files = (
        [f"data_batch_{i}" for i in range(1, 6)] if split == "train" else ["test_batch"]
    )
    xs, ys = [], []
    for fn in files:
        path = os.path.join(root, fn)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        xs.append(d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        ys.append(np.asarray(d[b"labels"], dtype=np.int32))
    return ArrayDataset(
        data=np.concatenate(xs), labels=np.concatenate(ys), num_classes=10
    )


# ---------------------------------------------------------------------------
# ImageNet (single HDF5, reference datasets.py layout)
# ---------------------------------------------------------------------------


class HDF5ImageDataset:
    """Lazy HDF5-backed dataset with the reference's key layout
    (reference datasets.py:8-36: train_img/train_labels/val_img/val_labels,
    swmr single-file). Indexable like ArrayDataset but reads on demand."""

    def __init__(
        self, path: str, split: str = "train", num_classes: Optional[int] = None
    ):
        import h5py

        self._f = h5py.File(path, "r", libver="latest", swmr=True)
        key = "train" if split == "train" else "val"
        self.data = self._f[f"{key}_img"]
        self.labels = np.asarray(self._f[f"{key}_labels"], dtype=np.int32)
        # the real corpus is 1000-class; smaller files (subset builds from
        # imagenet_hdf5.py) carry their own label range. Infer over BOTH
        # splits — a class present only in val must still fit the head, or
        # out-of-range labels would silently corrupt eval metrics.
        if num_classes is None:
            num_classes = 1
            for k in ("train_labels", "val_labels"):
                if k in self._f:
                    arr = np.asarray(self._f[k])
                    if arr.size:
                        num_classes = max(num_classes, int(arr.max()) + 1)
        self.num_classes = num_classes

    def __len__(self) -> int:
        return len(self.labels)


def load_imagenet_hdf5(
    data_dir: str, split: str = "train"
) -> Optional[HDF5ImageDataset]:
    for name in ("imagenet.hdf5", "imagenet-shuffled.hdf5"):
        path = os.path.join(data_dir, name)
        if os.path.exists(path):
            return HDF5ImageDataset(path, split)
    return None


def create_hdf5(
    images: np.ndarray, labels: np.ndarray, val_images: np.ndarray,
    val_labels: np.ndarray, out_path: str,
) -> None:
    """Build the single-file HDF5 layout (reference scripts/create_hdf5.py:
    46-108: NxSxSx3 uint8 + int labels under train_/val_ keys)."""
    import h5py

    with h5py.File(out_path, "w") as f:
        f.create_dataset("train_img", data=images, dtype="uint8")
        f.create_dataset("train_labels", data=labels.astype(np.int64))
        f.create_dataset("val_img", data=val_images, dtype="uint8")
        f.create_dataset("val_labels", data=val_labels.astype(np.int64))
