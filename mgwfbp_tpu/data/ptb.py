"""Penn Treebank word-level LM data.

Parity target: reference ptb_reader.py — vocab built from the training text
(:14-24, frequency-sorted word->id after <eos> substitution), corpus
tokenized to one long id stream (:32-54), and `num_steps`-windowed LM samples
with next-token targets (TrainDataset/TestDataset :56-102). Synthetic twin
generates a Markov-ish id stream with the same vocab size so the lstm
workload runs without the dataset files.
"""

from __future__ import annotations

import collections
import os
from typing import Optional

import numpy as np

from mgwfbp_tpu.data.loader import ArrayDataset

VOCAB_SIZE = 10000
NUM_STEPS = 35  # reference BPTT window (dl_trainer.py:459)


def build_vocab(path: str) -> dict[str, int]:
    """Frequency-sorted vocab (reference _build_vocab, ptb_reader.py:14-24:
    ids assigned by (-count, word) order, so id 0 = most frequent word;
    the ordering is an arbitrary relabeling for the model, but matching it
    makes tokenized streams comparable token-for-token)."""
    counter: collections.Counter = collections.Counter()
    with open(path) as f:
        for line in f:
            counter.update(line.split() + ["<eos>"])
    pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
    return {w: i for i, (w, _) in enumerate(pairs)}


def tokenize(path: str, vocab: dict[str, int]) -> np.ndarray:
    ids = []
    with open(path) as f:
        for line in f:
            for w in line.split() + ["<eos>"]:
                if w in vocab:
                    ids.append(vocab[w])
    return np.asarray(ids, dtype=np.int32)


def windowed_lm_dataset(stream: np.ndarray, num_steps: int = NUM_STEPS,
                        vocab_size: int = VOCAB_SIZE) -> ArrayDataset:
    """Non-overlapping (input, target) windows: inputs are stream[i:i+T],
    targets stream[i+1:i+T+1] (reference TrainDataset windowing)."""
    n = (len(stream) - 1) // num_steps
    x = stream[: n * num_steps].reshape(n, num_steps)
    y = stream[1 : n * num_steps + 1].reshape(n, num_steps)
    return ArrayDataset(data=x, labels=y, num_classes=vocab_size)


def load_ptb_stream(data_dir: str, split: str = "train") -> Optional[tuple]:
    """(token stream, vocab size) for a PTB split, or None if files absent."""
    train_path = os.path.join(data_dir, "ptb.train.txt")
    split_path = os.path.join(data_dir, f"ptb.{split}.txt")
    if not (os.path.exists(train_path) and os.path.exists(split_path)):
        return None
    vocab = build_vocab(train_path)
    stream = tokenize(split_path, vocab)
    return stream, max(len(vocab), VOCAB_SIZE)


def load_ptb(data_dir: str, split: str = "train",
             num_steps: int = NUM_STEPS) -> Optional[ArrayDataset]:
    out = load_ptb_stream(data_dir, split)
    if out is None:
        return None
    stream, vocab_size = out
    return windowed_lm_dataset(stream, num_steps, vocab_size)


def synthetic_ptb_stream(n_windows: int = 512, num_steps: int = NUM_STEPS,
                         vocab_size: int = VOCAB_SIZE, seed: int = 0) -> np.ndarray:
    """Deterministic pseudo-corpus with local structure (each token biased by
    its predecessor) so perplexity can actually improve during smoke runs."""
    rng = np.random.RandomState(seed)
    total = n_windows * num_steps + 1
    stream = np.empty(total, dtype=np.int32)
    stream[0] = rng.randint(vocab_size)
    noise = rng.randint(0, vocab_size, size=total)
    take_noise = rng.rand(total) < 0.15
    for i in range(1, total):
        stream[i] = noise[i] if take_noise[i] else (stream[i - 1] * 31 + 7) % vocab_size
    return stream


def synthetic_ptb(n_windows: int = 512, num_steps: int = NUM_STEPS,
                  vocab_size: int = VOCAB_SIZE, seed: int = 0) -> ArrayDataset:
    return windowed_lm_dataset(
        synthetic_ptb_stream(n_windows, num_steps, vocab_size, seed),
        num_steps, vocab_size,
    )


def carry_layout(
    stream: np.ndarray,
    num_steps: int,
    batch_size: int,
    rank: int = 0,
    nranks: int = 1,
    vocab_size: int = VOCAB_SIZE,
) -> ArrayDataset:
    """Stateful-BPTT batch layout for one rank.

    The corpus is split into ``batch_size * nranks`` CONTIGUOUS sub-streams;
    rank r owns streams [r*B, (r+1)*B). The local dataset is window-major —
    sample ``w*B + j`` is window w of owned stream j — so a sequential
    drop_last loader of batch_size yields batches whose element j is
    textually contiguous with element j of the previous batch. That is the
    layout the carried LSTM hidden state requires (classic PTB batching);
    sample-wise DistributedSampler sharding would hand the carry
    discontiguous text every step.
    """
    nstreams = batch_size * nranks
    tokens_per_stream = (len(stream) - 1) // nstreams
    wps = tokens_per_stream // num_steps
    if wps == 0:
        raise ValueError(
            f"stream of {len(stream)} tokens too short for "
            f"{nstreams} streams x {num_steps} steps"
        )
    usable = nstreams * wps * num_steps
    x = stream[:usable].reshape(nstreams, wps, num_steps)
    y = stream[1 : usable + 1].reshape(nstreams, wps, num_steps)
    lo, hi = rank * batch_size, (rank + 1) * batch_size
    xl = x[lo:hi].transpose(1, 0, 2).reshape(wps * batch_size, num_steps)
    yl = y[lo:hi].transpose(1, 0, 2).reshape(wps * batch_size, num_steps)
    return ArrayDataset(data=xl, labels=yl, num_classes=vocab_size)
