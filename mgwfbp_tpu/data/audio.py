"""AN4 audio pipeline: spectrograms, duration bucketing, CTC labels, greedy
decoding.

Parity targets (SURVEY.md §2.8): the reference's audio_data/ manifests
(an4.py:19-87 builds "wav_path,txt_path" CSVs; utils.py:11-37 duration-sorts
them) plus the pieces it imports from deepspeech.pytorch but does NOT vendor
(dl_trainer.py:493-519: SpectrogramDataset, AudioDataLoader,
DistributedBucketingSampler, GreedyDecoder) — so unlike the reference, the
an4 workload is runnable from this repo alone. Labels: the 29-char CTC
alphabet of the reference's labels.json with blank at index 0 (matches
optax.ctc_loss blank_id=0).

TPU discipline: every batch is padded to ONE static (max_time, max_label)
shape — variable shapes under jit cause recompilation storms (SURVEY.md §7
hard parts); duration bucketing keeps the padding waste low, mirroring the
reference's duration-sorted buckets.
"""

from __future__ import annotations

import dataclasses
import os
import wave
from typing import Iterator, Optional

import numpy as np

from mgwfbp_tpu.data.sharding import ShardInfo

# Reference labels.json: blank, apostrophe, A-Z, space = 29 symbols.
LABELS = "_'ABCDEFGHIJKLMNOPQRSTUVWXYZ "
BLANK_ID = 0
LABEL_TO_ID = {c: i for i, c in enumerate(LABELS)}

SAMPLE_RATE = 16000
WINDOW_SIZE = 0.02  # 320 samples -> 161 rfft bins
WINDOW_STRIDE = 0.01
NUM_FREQ = int(SAMPLE_RATE * WINDOW_SIZE) // 2 + 1  # 161


def text_to_ids(text: str) -> np.ndarray:
    ids = [LABEL_TO_ID[c] for c in text.upper() if c in LABEL_TO_ID and c != "_"]
    return np.asarray(ids, dtype=np.int32)


def ids_to_text(ids) -> str:
    return "".join(LABELS[i] for i in ids if 0 <= i < len(LABELS))


def log_spectrogram(signal: np.ndarray, sample_rate: int = SAMPLE_RATE) -> np.ndarray:
    """STFT log-magnitude, per-utterance normalized — the deepspeech.pytorch
    SpectrogramDataset recipe with the reference's audio_conf (HAMMING
    window, reference models/lstman4.py:8-19; n_fft=320, hop=160)."""
    n_fft = int(sample_rate * WINDOW_SIZE)
    hop = int(sample_rate * WINDOW_STRIDE)
    if len(signal) < n_fft:
        signal = np.pad(signal, (0, n_fft - len(signal)))
    window = np.hamming(n_fft)
    nframes = 1 + (len(signal) - n_fft) // hop
    frames = np.lib.stride_tricks.as_strided(
        signal,
        shape=(nframes, n_fft),
        strides=(signal.strides[0] * hop, signal.strides[0]),
    )
    spect = np.abs(np.fft.rfft(frames * window, axis=1))  # (T, 161)
    spect = np.log1p(spect)
    mean, std = spect.mean(), spect.std()
    return ((spect - mean) / (std + 1e-6)).astype(np.float32)


def read_wav(path: str) -> np.ndarray:
    with wave.open(path, "rb") as w:
        data = np.frombuffer(w.readframes(w.getnframes()), dtype=np.int16)
    return data.astype(np.float32) / 32768.0


def load_manifest(path: str) -> list[tuple[str, str]]:
    """Rows of "wav_path,transcript_path" (reference audio_data manifests).

    Relative entries resolve against the MANIFEST's own directory, so a
    committed manifest (data/an4_memcheck) reproduces wherever the repo is
    checked out instead of hardcoding the build machine's absolute layout
    (ADVICE r5 #3). Absolute entries pass through untouched — the fetch
    scripts write those for scratch data dirs."""
    base = os.path.dirname(os.path.abspath(path))
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                wav, txt = line.split(",")[:2]
                rows.append(tuple(
                    p if os.path.isabs(p)
                    else os.path.normpath(os.path.join(base, p))
                    for p in (wav, txt)
                ))
    return rows


@dataclasses.dataclass
class Utterance:
    spect: np.ndarray  # (T, 161) float32
    labels: np.ndarray  # (L,) int32

    @property
    def duration(self) -> int:
        return self.spect.shape[0]


class AudioBatchLoader:
    """Duration-bucketed, rank-sharded CTC batch loader.

    Batches are dicts {x, y, input_lengths, label_lengths} padded to the
    GLOBAL (max_time, max_label) so the jitted step compiles once
    (DistributedBucketingSampler semantics with static shapes).
    """

    def __init__(
        self,
        utterances: list[Utterance],
        batch_size: int,
        shard: ShardInfo = ShardInfo(),
        max_time: Optional[int] = None,
        max_label: Optional[int] = None,
        seed: int = 0,
        shuffle_batches: bool = True,
    ):
        if not utterances:
            raise ValueError("no utterances")
        self.utts = sorted(utterances, key=lambda u: u.duration)
        self.batch_size = batch_size
        self.shard = shard
        self.max_time = max_time or max(u.duration for u in self.utts)
        self.max_label = max_label or max(len(u.labels) for u in self.utts)
        self.seed = seed
        self.shuffle_batches = shuffle_batches
        self.epoch = 0
        self._rebatch()

    def _rebatch(self) -> None:
        # duration-sorted contiguous batches, then rank round-robin
        bs = self.batch_size
        nb = len(self.utts) // bs
        self._global_batches = [
            list(range(b * bs, (b + 1) * bs)) for b in range(nb)
        ]

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def set_batch_size(self, batch_size: int) -> None:
        """Re-batch the precomputed duration-sorted groups at a new size
        (batching is EAGER here, unlike ShardedLoader, so mutating the
        attribute alone would silently keep the old batches)."""
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.batch_size = min(batch_size, len(self.utts))
        self._rebatch()

    @property
    def num_batches(self) -> int:
        return len(self._global_batches) // self.shard.nranks

    def __len__(self) -> int:
        return self.num_batches

    def __iter__(self) -> Iterator[dict]:
        order = np.arange(len(self._global_batches))
        if self.shuffle_batches:
            rng = np.random.RandomState(
                (self.seed * 1_000_003 + self.epoch) % (2**31 - 1)
            )
            rng.shuffle(order)
        mine = order[self.shard.rank :: self.shard.nranks][: self.num_batches]
        for bi in mine:
            members = [self.utts[i] for i in self._global_batches[bi]]
            B = len(members)
            x = np.zeros((B, self.max_time, NUM_FREQ), np.float32)
            y = np.zeros((B, self.max_label), np.int32)
            ilen = np.zeros((B,), np.int32)
            llen = np.zeros((B,), np.int32)
            for j, u in enumerate(members):
                t = min(u.duration, self.max_time)
                l = min(len(u.labels), self.max_label)
                x[j, :t] = u.spect[:t]
                y[j, :l] = u.labels[:l]
                ilen[j] = t
                llen[j] = l
            yield {"x": x, "y": y, "input_lengths": ilen, "label_lengths": llen}


def load_an4(
    data_dir: str, split: str = "train"
) -> Optional[list[Utterance]]:
    """Load utterances from an AN4 manifest + wav/txt files if present."""
    manifest = os.path.join(data_dir, f"an4_{split}_manifest.csv")
    if not os.path.exists(manifest):
        return None
    utts = []
    for wav, txt in load_manifest(manifest):
        if not (os.path.exists(wav) and os.path.exists(txt)):
            continue
        with open(txt) as f:
            transcript = f.read().strip()
        utts.append(
            Utterance(
                spect=log_spectrogram(read_wav(wav)),
                labels=text_to_ids(transcript),
            )
        )
    return utts or None


def synthetic_an4(
    n: int = 64, seed: int = 0, min_time: int = 80, max_time: int = 201,
    max_label: int = 24,
) -> list[Utterance]:
    """Deterministic fake utterances with duration spread (exercises the
    bucketing) and label/spect correlation via per-symbol frequency bumps so
    CTC loss can actually fall."""
    rng = np.random.RandomState(seed)
    utts = []
    for _ in range(n):
        t = int(rng.randint(min_time, max_time + 1))
        nlab = int(rng.randint(3, max_label + 1))
        labels = rng.randint(1, len(LABELS), size=nlab).astype(np.int32)
        spect = rng.randn(t, NUM_FREQ).astype(np.float32) * 0.5
        # paint each label's signature band across its time slice
        slice_len = max(t // nlab, 1)
        for k, lab in enumerate(labels):
            band = (int(lab) * 5) % (NUM_FREQ - 4)
            s = k * slice_len
            spect[s : s + slice_len, band : band + 4] += 2.0
        utts.append(Utterance(spect=spect, labels=labels))
    return utts


def an4_prepare(
    data_dir: str,
    batch_size: int,
    shard: ShardInfo = ShardInfo(),
    seed: int = 0,
    synthetic: Optional[bool] = None,
):
    """DataBundle for the an4 workload (dispatcher hook, data/__init__)."""
    from mgwfbp_tpu.data import DataBundle

    train = val = None
    if not synthetic:
        train = load_an4(data_dir, "train")
        val = load_an4(data_dir, "val")
    is_synth = train is None or val is None
    if is_synth:
        if synthetic is False:
            raise FileNotFoundError(f"AN4 manifests not found under {data_dir!r}")
        train = synthetic_an4(96, seed=seed)
        val = synthetic_an4(24, seed=seed + 1)
    max_time = max(u.duration for u in train + val)
    max_label = max(len(u.labels) for u in train + val)
    train_loader = AudioBatchLoader(
        train, batch_size, shard, max_time, max_label, seed
    )
    val_loader = AudioBatchLoader(
        val, batch_size, shard, max_time, max_label, seed,
        shuffle_batches=False,
    )
    return DataBundle(
        train=train_loader,
        val=val_loader,
        num_classes=len(LABELS),
        synthetic=is_synth,
        num_batches_per_epoch=len(train_loader),
    )


# ---------------------------------------------------------------------------
# Greedy CTC decoding + WER/CER (reference imports GreedyDecoder from
# deepspeech.pytorch, dl_trainer.py:519,891-910)
# ---------------------------------------------------------------------------


def greedy_decode(logits: np.ndarray, lengths: np.ndarray) -> list[str]:
    """argmax -> collapse repeats -> drop blanks, per sequence."""
    out = []
    ids = np.asarray(logits).argmax(-1)  # (B, T)
    for row, t in zip(ids, np.asarray(lengths)):
        row = row[: int(t)]
        collapsed = [int(r) for r, prev in zip(row, np.r_[-1, row[:-1]]) if r != prev]
        out.append(ids_to_text([c for c in collapsed if c != BLANK_ID]))
    return out


def _edit_distance(a: list, b: list) -> int:
    dp = list(range(len(b) + 1))
    for i in range(1, len(a) + 1):
        prev = dp[0]
        dp[0] = i
        for j in range(1, len(b) + 1):
            cur = dp[j]
            dp[j] = min(dp[j] + 1, dp[j - 1] + 1, prev + (a[i - 1] != b[j - 1]))
            prev = cur
    return dp[-1]


def wer(hyp: str, ref: str) -> float:
    rw = ref.split()
    if not rw:
        return 0.0 if not hyp.split() else 1.0
    return _edit_distance(hyp.split(), rw) / len(rw)


def cer(hyp: str, ref: str) -> float:
    if not ref:
        return 0.0 if not hyp else 1.0
    return _edit_distance(list(hyp), list(ref)) / len(ref)
