"""Checkpoint / resume via orbax.

Parity target (SURVEY.md §5): reference `save_checkpoint` /
`load_model_from_file` (dl_trainer.py:946-947, 307-312 — torch.save of
{'state','epoch','iter'} and counter restore), rank-0 `--pretrain` load +
parameter re-broadcast (dist_trainer.py:32-39,66). Differences by design:
  * orbax writes sharded/replicated jax arrays directly — the "broadcast
    after load" step is a sharding constraint, not a collective we code;
  * the epoch-boundary save the reference constructs but never executes
    (dl_trainer.py:769-777 builds the filename, no write) actually saves here.

Resilience layer (ISSUE 5): checkpoints are **step-indexed** — the orbax
step key is the global optimizer iteration, so a preempted run resumes
from the exact step, not the last epoch boundary. Each snapshot carries
the position needed to rebuild the data stream deterministically
(`epoch`, `epoch_step` — the loader is a pure function of
(seed, epoch, batch index), so position IS the iterator state) plus the
BPTT carry for stateful models; the train-state RNG rides in the state
itself. A sidecar ``steps_index.json`` (written atomically via
``os.replace``) maps steps to epoch metadata so epoch-oriented consumers
(`evaluate --all-epochs`) keep working without restoring every payload;
directories written by the old epoch-keyed format load transparently
(legacy mode: the orbax step IS the epoch).

Checkpoint directory naming encodes the experiment config like the
reference's log/checkpoint dirs (dl_trainer.py:771-777).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from mgwfbp_tpu.runtime import coordination as coord
from mgwfbp_tpu.train.step import TrainState

INDEX_FILE = "steps_index.json"
INDEX_VERSION = 1


class CheckpointRestoreError(RuntimeError):
    """A checkpoint exists but cannot be restored into the current model/
    optimizer structure. Carries the offending leaves (shape/dtype/
    structure diffs) instead of a raw orbax traceback, and names the
    likely cause: config drift between the saving and restoring run."""

    def __init__(self, message: str, mismatches: Optional[list[str]] = None):
        super().__init__(message)
        self.mismatches = list(mismatches or [])


@dataclasses.dataclass
class Snapshot:
    state: TrainState
    epoch: int
    iteration: int
    # optimizer steps already completed INSIDE `epoch` when this snapshot
    # was taken; 0 on an epoch boundary. With the deterministic loader,
    # (epoch, epoch_step) fully names the data-iterator position.
    epoch_step: int = 0
    mid_epoch: bool = False
    carry: Any = None  # BPTT hidden state (carry models), else None


class Checkpointer:
    """Step-indexed checkpoint manager over one run directory."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self._dir = os.path.abspath(directory)
        self._max_to_keep = max_to_keep
        os.makedirs(self._dir, exist_ok=True)
        # GC is ours, not orbax's: retention must be CLASS-aware (see
        # _gc) — orbax's flat max_to_keep would let a burst of
        # --ckpt-every-steps saves evict the per-epoch history that
        # `evaluate --all-epochs` / model averaging read
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(create=True),
            # register the handler up front: a FRESH manager must be able
            # to read item_metadata of existing steps (the proactive
            # shape/dtype drift check) before any save taught it the type
            item_handlers=ocp.StandardCheckpointHandler(),
        )
        self._index = self._load_index()

    # -- sidecar index ----------------------------------------------------
    def _index_path(self) -> str:
        return os.path.join(self._dir, INDEX_FILE)

    def _load_index(self) -> dict:
        try:
            with open(self._index_path()) as f:
                idx = json.load(f)
        except (OSError, ValueError):
            return {}
        if idx.get("version") != INDEX_VERSION:
            return {}
        return dict(idx.get("steps", {}))

    def _write_index(self) -> None:
        # drop entries whose orbax payload was garbage-collected, then
        # write-temp + rename so a mid-write kill never corrupts the index
        live = {str(s) for s in self._mgr.all_steps()}
        self._index = {k: v for k, v in self._index.items() if k in live}
        if not coord.is_primary():
            # multi-host: exactly ONE writer for the sidecar — every
            # process keeps the same in-memory index (the save/restore
            # calls are collective), but two processes racing the
            # tmp+rename on a shared FS could commit a torn view; the
            # commit barrier in save() orders everyone behind process 0
            return
        tmp = self._index_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"version": INDEX_VERSION, "steps": self._index}, f)
        os.replace(tmp, self._index_path())

    # -- save -------------------------------------------------------------
    def save(self, snap: Snapshot, wait: bool = False) -> None:
        """Atomic step-indexed save (orbax commits via tmp-dir + rename).

        The orbax step key is the GLOBAL iteration. Saving a step that
        already exists (an epoch boundary landing on a just-written
        ``--ckpt-every-steps`` checkpoint) only updates the index metadata
        — the state payload is identical by construction.

        Multi-host: `save` is a COLLECTIVE — every process calls it with
        the same snapshot (orbax coordinates the payload so the tmp-dir +
        atomic-rename commit happens exactly once, on the primary); the
        sidecar index is written by process 0 only (`_write_index`), and
        a commit barrier at the end keeps any process from returning —
        and, on the preemption-drain path, EXITING — before the commit is
        durable, so a preempt mid-save can never leave torn state."""
        step = int(snap.iteration)
        entry = {
            "epoch": int(snap.epoch),
            "epoch_step": int(snap.epoch_step),
            "mid_epoch": bool(snap.mid_epoch),
            "has_carry": snap.carry is not None,
        }
        if step in self._mgr.all_steps():
            prev = self._index.get(str(step), {})
            if prev:
                # the stored payload is immutable (identical state), so
                # the existing entry keeps describing it — has_carry and
                # epoch_step MUST stay (a boundary re-save over a
                # mid-epoch save does not strip the payload's carry); an
                # epoch-boundary re-save only PROMOTES the entry (never
                # demote a boundary back to mid-epoch)
                entry = dict(prev)
                entry["epoch"] = int(snap.epoch)
                if not snap.mid_epoch:
                    entry["mid_epoch"] = False
            self._index[str(step)] = entry
            self._gc()  # a promotion changes class budgets too
            self._write_index()
            if wait:
                # the payload at this step may still be an in-flight async
                # save; an explicit durability request (preemption drain)
                # must not be dropped just because the bytes are deduped
                self._mgr.wait_until_finished()
            self._commit_barrier(step)
            return
        payload = {
            "state": snap.state,
            "meta": {
                "epoch": int(snap.epoch),
                "iteration": int(snap.iteration),
                "epoch_step": int(snap.epoch_step),
                "mid_epoch": int(snap.mid_epoch),
            },
        }
        if snap.carry is not None:
            payload["carry"] = snap.carry
        self._mgr.save(step, args=ocp.args.StandardSave(payload))
        self._index[str(step)] = entry
        self._gc()
        self._write_index()
        if wait:
            self._mgr.wait_until_finished()
        self._commit_barrier(step)

    def _commit_barrier(self, step: int) -> None:
        """Multi-host rendezvous at the end of every save: no process may
        proceed until process 0's sidecar commit (and, for wait=True, the
        orbax payload commit) is on disk. No-op single-process."""
        if coord.process_count() > 1:
            coord.barrier(f"ckpt_commit_{step}")

    def _gc(self) -> None:
        """Class-aware retention: keep the newest `max_to_keep`
        epoch-BOUNDARY checkpoints AND, separately, the newest
        `max_to_keep` mid-epoch STEP checkpoints, so frequent
        --ckpt-every-steps saves never evict the per-epoch history."""
        if not self._max_to_keep or self._max_to_keep <= 0:
            return
        bounds: list[int] = []
        mids: list[int] = []
        for step in sorted(self._mgr.all_steps()):
            e = self._index.get(str(step))
            if e is not None and e.get("mid_epoch", False):
                mids.append(step)
            else:
                bounds.append(step)  # boundary, or legacy epoch-keyed
        keep = set(bounds[-self._max_to_keep:])
        keep |= set(mids[-self._max_to_keep:])
        for step in bounds + mids:
            if step not in keep:
                self._mgr.delete(step)

    # -- listing ----------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def _epoch_boundaries(self) -> dict[int, int]:
        """{epoch: step} for every epoch-boundary snapshot. Orbax steps
        absent from the index are legacy epoch-keyed saves (step == epoch)."""
        out: dict[int, int] = {}
        for step in sorted(self._mgr.all_steps()):
            entry = self._index.get(str(step))
            if entry is None:  # legacy format
                out[int(step)] = int(step)
            elif not entry.get("mid_epoch", False):
                out[int(entry["epoch"])] = int(step)
        return out

    def latest_epoch(self) -> Optional[int]:
        bounds = self._epoch_boundaries()
        return max(bounds) if bounds else None

    def all_epochs(self) -> list[int]:
        return sorted(self._epoch_boundaries())

    # -- restore ----------------------------------------------------------
    def restore(
        self,
        target_state: TrainState,
        epoch: Optional[int] = None,
        step: Optional[int] = None,
        carry_template: Any = None,
    ) -> Optional[Snapshot]:
        """Restore into the structure of `target_state` (shapes/dtypes must
        match the current model/optimizer — the reference has the same
        contract via load_state_dict). `epoch` selects that epoch's
        boundary snapshot, `step` an exact iteration; default is the
        latest snapshot of any kind. Structure/shape/dtype mismatches
        raise `CheckpointRestoreError` naming the offending leaves."""
        if step is None:
            if epoch is not None:
                step = self._epoch_boundaries().get(int(epoch))
            else:
                step = self._mgr.latest_step()
        if step is None or step not in self._mgr.all_steps():
            return None
        entry = self._index.get(str(step))
        healed = False
        if entry is None:
            # no index entry: either a genuine legacy epoch-keyed payload,
            # or a NEW-format step whose sidecar write was killed between
            # the orbax commit and os.replace (the preemption grace period
            # expiring mid-drain). Probe the stored metadata — misreading
            # a new payload as legacy would turn a mid-epoch snapshot into
            # an epoch boundary and silently skip the rest of the epoch.
            entry = self._probe_format(int(step))
            healed = entry is not None
        if entry is None:
            return self._restore_legacy(target_state, int(step))
        template: dict[str, Any] = {
            "state": target_state,
            "meta": {
                "epoch": 0, "iteration": 0, "epoch_step": 0, "mid_epoch": 0,
            },
        }
        if entry.get("has_carry", False):
            if carry_template is None:
                raise CheckpointRestoreError(
                    f"checkpoint step {step} in {self._dir!r} carries a "
                    "model carry (BPTT hidden state) but no carry template "
                    "was supplied — restore through a trainer built for "
                    "the same stateful model"
                )
            template["carry"] = carry_template
        restored = self._restore_checked(int(step), template)
        meta = restored["meta"]
        if healed:
            # repair the sidecar from the payload's own bookkeeping so the
            # next open doesn't have to probe again
            self._index[str(step)] = {
                "epoch": int(meta["epoch"]),
                "epoch_step": int(meta["epoch_step"]),
                "mid_epoch": bool(int(meta["mid_epoch"])),
                "has_carry": "carry" in restored,
            }
            self._write_index()
            entry = self._index[str(step)]
        # the INDEX is authoritative for epoch/mid_epoch: a boundary save
        # deduped onto an earlier mid-epoch payload promotes the entry
        # while the payload's meta still says mid_epoch — trusting the
        # payload would make the promoted boundary resume as mid-epoch
        mid_epoch = bool(entry.get("mid_epoch", int(meta["mid_epoch"])))
        return Snapshot(
            state=restored["state"],
            epoch=int(entry.get("epoch", meta["epoch"])),
            iteration=int(meta["iteration"]),
            epoch_step=int(meta["epoch_step"]),
            mid_epoch=mid_epoch,
            carry=restored.get("carry"),
        )

    def _probe_format(self, step: int) -> Optional[dict]:
        """Minimal index entry inferred from stored metadata for an
        UNINDEXED step, or None when the payload really is the legacy
        epoch-keyed format (2-key meta, no epoch_step)."""
        try:
            md = self._mgr.item_metadata(step)
        except Exception:  # noqa: BLE001 — undecidable: treat as legacy
            return None
        if not isinstance(md, dict) or not isinstance(md.get("meta"), dict):
            return None
        if "epoch_step" not in md["meta"]:
            return None
        return {"has_carry": "carry" in md}

    def _restore_legacy(
        self, target_state: TrainState, step: int
    ) -> Snapshot:
        """Epoch-keyed payloads from the pre-resilience format: the orbax
        step is the epoch, meta has only {'epoch','iteration'}."""
        template = {
            "state": target_state,
            "meta": {"epoch": 0, "iteration": 0},
        }
        restored = self._restore_checked(step, template)
        return Snapshot(
            state=restored["state"],
            epoch=int(restored["meta"]["epoch"]),
            iteration=int(restored["meta"]["iteration"]),
        )

    def _restore_checked(self, step: int, template: Any) -> Any:
        # proactive shape/dtype validation: orbax's StandardRestore does
        # NOT fail on a mismatched template — it hands back the saved
        # shapes, deferring the blow-up to the first jitted dispatch with
        # an inscrutable shape error. Diff the stored metadata against the
        # template FIRST and fail here, naming the drifted leaves.
        mismatches = self._template_diff(step, template)
        if mismatches:
            raise CheckpointRestoreError(
                self._drift_message(step, mismatches), mismatches=mismatches
            )
        try:
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(template)
            )
        except CheckpointRestoreError:
            raise
        except Exception as e:  # noqa: BLE001 — rewrapped with context
            raise CheckpointRestoreError(
                self._drift_message(step, []) + f" (orbax: {e})"
            ) from e

    def _drift_message(self, step: int, mismatches: list[str]) -> str:
        detail = (
            "; offending leaves:\n  " + "\n  ".join(mismatches[:20])
            if mismatches
            else ""
        )
        return (
            f"cannot restore checkpoint step {step} from {self._dir!r} "
            "into the current model/optimizer structure — likely config "
            "drift (the checkpoint was saved under a different --dnn / "
            f"optimizer / precision configuration){detail}"
        )

    def _template_diff(self, step: int, template: Any) -> list[str]:
        """Human-readable (path: saved vs expected) diffs between the
        stored payload's metadata and the restore template — best effort;
        metadata unavailable degrades to the wrapped orbax message."""
        try:
            saved_md = self._mgr.item_metadata(step)
            saved = {
                _path_str(kp): v
                for kp, v in jax.tree_util.tree_flatten_with_path(saved_md)[0]
            }
            want = {
                _path_str(kp): v
                for kp, v in jax.tree_util.tree_flatten_with_path(
                    jax.eval_shape(lambda: template)
                )[0]
            }
        except Exception:  # noqa: BLE001 — diffing is best-effort
            return []
        if not saved or not any(
            hasattr(v, "shape") for v in saved.values()
        ):
            # metadata unavailable/uninterpretable: no diff evidence —
            # let the actual restore decide instead of crying drift
            return []
        out = []
        for path in sorted(set(saved) | set(want)):
            if path.startswith("meta."):
                continue  # bookkeeping ints; never the drifted leaves
            s, w = saved.get(path), want.get(path)
            if s is None:
                out.append(f"{path}: missing in checkpoint (expected "
                           f"{_leaf_desc(w)})")
            elif w is None:
                out.append(f"{path}: present in checkpoint "
                           f"({_leaf_desc(s)}) but not in the current "
                           "structure")
            elif _leaf_desc(s) != _leaf_desc(w):
                out.append(f"{path}: checkpoint has {_leaf_desc(s)}, "
                           f"current structure wants {_leaf_desc(w)}")
        return out

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()


def _path_str(kp) -> str:
    """Canonical dotted path for a tree_flatten_with_path key path.

    Orbax metadata comes back as plain nested dicts while the restore
    template carries dataclass pytrees (TrainState), so DictKey vs
    GetAttrKey must compare equal for the same logical leaf."""
    names = []
    for entry in kp:
        name = getattr(entry, "key", None)
        if name is None:
            name = getattr(entry, "name", None)
        if name is None:
            name = getattr(entry, "idx", None)
        names.append(str(name))
    return ".".join(names)


def _leaf_desc(leaf: Any) -> str:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None and dtype is None:
        return type(leaf).__name__
    return f"{np.dtype(dtype).name if dtype is not None else '?'}" \
           f"{tuple(shape) if shape is not None else ''}"
