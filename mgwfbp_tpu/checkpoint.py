"""Checkpoint / resume via orbax.

Parity target (SURVEY.md §5): reference `save_checkpoint` /
`load_model_from_file` (dl_trainer.py:946-947, 307-312 — torch.save of
{'state','epoch','iter'} and counter restore), rank-0 `--pretrain` load +
parameter re-broadcast (dist_trainer.py:32-39,66). Differences by design:
  * orbax writes sharded/replicated jax arrays directly — the "broadcast
    after load" step is a sharding constraint, not a collective we code;
  * the epoch-boundary save the reference constructs but never executes
    (dl_trainer.py:769-777 builds the filename, no write) actually saves here.

Resilience layer (ISSUE 5): checkpoints are **step-indexed** — the orbax
step key is the global optimizer iteration, so a preempted run resumes
from the exact step, not the last epoch boundary. Each snapshot carries
the position needed to rebuild the data stream deterministically
(`epoch`, `epoch_step` — the loader is a pure function of
(seed, epoch, batch index), so position IS the iterator state) plus the
BPTT carry for stateful models; the train-state RNG rides in the state
itself. A sidecar ``steps_index.json`` (written atomically via
``os.replace``) maps steps to epoch metadata so epoch-oriented consumers
(`evaluate --all-epochs`) keep working without restoring every payload;
directories written by the old epoch-keyed format load transparently
(legacy mode: the orbax step IS the epoch).

Checkpoint directory naming encodes the experiment config like the
reference's log/checkpoint dirs (dl_trainer.py:771-777).

Shard-native format (ISSUE 13): the orbax payload above stores the
REPLICATED interchange form, which forces every sharded path
(rs_opt_ag / rs_fwd_ag) to gather its 1/world state to the host before
a save — exactly the idiom that cannot scale to a pod. The sharded
format writes, per step, one `sharded/<step>/p<i>/` subtree PER
PROCESS holding only that process's shard rows as plain ``.npy``
files, plus one ``manifest.json`` (process 0) recording world size,
mesh axes, and the per-leaf shard layout (which merge group and offset
each parameter-tree leaf packs into). Restore re-slices per leaf
straight from the source files (numpy memmaps), so an N-way checkpoint
restores onto M processes — or a different merge schedule — without
ever materializing a world-sized buffer or even one fully-replicated
leaf for a sharded target. Replicated sections (params on the in-step
lowerings, batch stats, the optax tree on unsharded runs) are written
once, by process 0. The ``steps_index.json`` sidecar + commit barrier
below keep the exactly-once semantics for both formats; the legacy
orbax payloads keep loading transparently, and ``--ckpt-format
replicated`` keeps writing them for interchange with old runs. The
format assumes the group shares the checkpoint filesystem (the same
assumption the orbax payload made).
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import threading
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from mgwfbp_tpu.runtime import coordination as coord
from mgwfbp_tpu.train.step import TrainState

INDEX_FILE = "steps_index.json"
INDEX_VERSION = 1

# shard-native format (ISSUE 13)
SHARD_SUBDIR = "sharded"
MANIFEST_FILE = "manifest.json"
SHARD_FORMAT_VERSION = 1


class CheckpointRestoreError(RuntimeError):
    """A checkpoint exists but cannot be restored into the current model/
    optimizer structure. Carries the offending leaves (shape/dtype/
    structure diffs) instead of a raw orbax traceback, and names the
    likely cause: config drift between the saving and restoring run."""

    def __init__(self, message: str, mismatches: Optional[list[str]] = None):
        super().__init__(message)
        self.mismatches = list(mismatches or [])


@dataclasses.dataclass
class Snapshot:
    state: TrainState
    epoch: int
    iteration: int
    # optimizer steps already completed INSIDE `epoch` when this snapshot
    # was taken; 0 on an epoch boundary. With the deterministic loader,
    # (epoch, epoch_step) fully names the data-iterator position.
    epoch_step: int = 0
    mid_epoch: bool = False
    carry: Any = None  # BPTT hidden state (carry models), else None
    # True when `state` is already in LIVE form on the caller's mesh
    # (sharded leaves as global arrays, carry as this process's local
    # block) — the shard-native restore path; the caller must skip the
    # replicate + re-scatter interchange steps
    native: bool = False
    # extra restore facts riding along on the shard-native path (the
    # manifest's meta section: saved world size, steps_per_epoch, the
    # LR-schedule anchor) — None on the replicated/orbax path
    manifest_meta: Optional[dict] = None


# ---------------------------------------------------------------------------
# shard-native payload helpers (ISSUE 13)
# ---------------------------------------------------------------------------


def _np_dtype(name: str) -> np.dtype:
    """Resolve a manifest dtype string, including ml_dtypes extended
    types (bfloat16) that plain np.dtype does not know."""
    return np.dtype(jnp.dtype(str(name)))


def _viewed(arr: np.ndarray, dtype: np.dtype) -> np.ndarray:
    """Reinterpret raw bytes as `dtype`. np.load round-trips extended
    dtypes (bfloat16) as void records of the same itemsize; the manifest
    dtype is authoritative, so view the bytes back."""
    arr = np.asarray(arr)
    if arr.dtype == dtype:
        return arr
    if arr.dtype.itemsize != dtype.itemsize:
        raise ValueError(
            f"cannot view {arr.dtype} as {dtype}: itemsize "
            f"{arr.dtype.itemsize} != {dtype.itemsize}"
        )
    return arr.view(dtype)


def _leaf_doc(path: str, arr: Any) -> dict:
    return {
        "path": str(path),
        "shape": [int(s) for s in getattr(arr, "shape", ())],
        "dtype": jnp.dtype(arr.dtype).name
        if hasattr(arr, "dtype") else "float32",
    }


def _doc_matches(doc: dict, arr: Any) -> bool:
    return (
        tuple(doc.get("shape", ())) == tuple(getattr(arr, "shape", ()))
        and _np_dtype(doc.get("dtype", "float32"))
        == _np_dtype(jnp.dtype(arr.dtype).name)
    )


def _fsync_dir_files(directory: str) -> None:
    """fsync every regular file under `directory` plus the directory
    entry itself (best-effort on filesystems without dir fsync)."""
    for name in os.listdir(directory):
        path = os.path.join(directory, name)
        if not os.path.isfile(path):
            continue
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    try:
        dfd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


def peek_steps(directory: str) -> list[int]:
    """Committed-looking steps under a checkpoint directory WITHOUT
    opening an orbax manager — the cheap probe the cross-world resume
    scan runs over every sibling tag directory."""
    out: set[int] = set()
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        if name.isdigit():  # orbax step dirs
            out.add(int(name))
    shard_root = os.path.join(directory, SHARD_SUBDIR)
    try:
        snames = os.listdir(shard_root)
    except OSError:
        snames = []
    for name in snames:
        if name.isdigit() and os.path.exists(
            os.path.join(shard_root, name, MANIFEST_FILE)
        ):
            out.add(int(name))
    return sorted(out)


class ShardSource:
    """Reader over one committed shard-native step directory.

    All file access is through numpy memmaps sliced per element range, so
    a consumer re-slicing an N-way layout onto M shard rows touches only
    the bytes those rows need — never a world-sized buffer, never a full
    replicated leaf unless `read_leaf` (the replicated-target path) is
    called explicitly.
    """

    def __init__(self, step_dir: str, manifest: dict):
        self.step_dir = step_dir
        self.manifest = manifest
        self._mmaps: dict[str, np.ndarray] = {}
        # row -> owning process (lowest-index owner wins, mirroring the
        # save-side dedup rule)
        self._row_owner: dict[int, tuple[int, int]] = {}
        for p, doc in sorted(
            (int(k), v) for k, v in (manifest.get("processes") or {}).items()
        ):
            for pos, r in enumerate(doc.get("rows", ())):
                self._row_owner.setdefault(int(r), (p, pos))

    # -- raw file access ---------------------------------------------------
    def _file(self, proc: int, name: str) -> str:
        return os.path.join(self.step_dir, f"p{proc:05d}", name + ".npy")

    def _mmap(self, proc: int, name: str, shape, dtype: np.dtype):
        key = f"{proc}/{name}"
        mm = self._mmaps.get(key)
        if mm is None:
            path = self._file(proc, name)
            try:
                mm = np.load(path, mmap_mode="r")
            except (OSError, ValueError) as e:
                raise CheckpointRestoreError(
                    f"shard-native checkpoint {self.step_dir!r} is missing "
                    f"or corrupt: process {proc} file {name}.npy "
                    f"({e})"
                ) from e
            self._mmaps[key] = mm
        want = tuple(int(s) for s in shape)
        if tuple(mm.shape) != want:
            raise CheckpointRestoreError(
                f"shard-native checkpoint {self.step_dir!r}: process "
                f"{proc} file {name}.npy has shape {tuple(mm.shape)}, "
                f"manifest expects {want} {np.dtype(dtype).name} — the "
                "payload is truncated or was written by a different run"
            )
        return mm

    # -- manifest accessors ------------------------------------------------
    @property
    def world(self) -> int:
        return int(self.manifest["world"])

    @property
    def meta(self) -> dict:
        return dict(self.manifest.get("meta") or {})

    @property
    def leaves(self) -> list[dict]:
        return list(self.manifest.get("leaves") or [])

    def section_kind(self, section: str) -> str:
        return str((self.manifest.get(section) or {}).get("kind", "none"))

    def section_docs(self, section: str) -> list[dict]:
        """Per-leaf docs of a section. `params` (sharded or replicated)
        and sharded `opt` slots mirror the parameter tree; replicated
        `opt`/`batch_stats` carry their own flattened leaf lists."""
        if section == "params":
            return self.leaves
        doc = self.manifest.get(section) or {}
        if section == "opt" and doc.get("kind") == "sharded":
            return self.leaves
        return list(doc.get("leaves") or [])

    def opt_slots(self) -> int:
        return int((self.manifest.get("opt") or {}).get("slots", 0))

    # -- sharded-section readers -------------------------------------------
    def leaf_slice_reader(
        self, section: str, slot: Optional[int] = None
    ) -> Callable[[int, int, int], np.ndarray]:
        """Returns read(leaf_index, start, stop) -> flat array of that
        element range of tree leaf `leaf_index`, regardless of whether the
        source section is stored sharded (group-row files) or replicated
        (per-leaf files). For the replicated `opt` section a `slot`
        addresses the optax tree through the saver-recorded
        slot_leaf_index map (slot s of params-tree leaf j -> flat optax
        leaf), so a sharded target can re-slice a replicated-opt source."""
        kind = self.section_kind(section)
        prefix = section if slot is None else f"{section}.s{slot}"
        if kind == "replicated":
            docs = self.section_docs(section)
            remap = None
            if section == "opt" and slot is not None:
                idx_map = (self.manifest.get("opt") or {}).get(
                    "slot_leaf_index"
                )
                if idx_map is None:
                    raise CheckpointRestoreError(
                        f"checkpoint {self.step_dir!r}: replicated "
                        "optimizer section has no slot_leaf_index map — "
                        "cannot re-slice it onto a sharded optimizer"
                    )
                remap = [int(x) for x in idx_map[int(slot)]]

            def read_rep(j: int, a: int, b: int) -> np.ndarray:
                k = remap[j] if remap is not None else j
                doc = docs[k]
                dt = _np_dtype(doc["dtype"])
                mm = self._mmap(0, f"{section}.l{k}", doc["shape"], dt)
                flat = np.asarray(mm).reshape(-1)
                return _viewed(flat[a:b], dt)

            return read_rep
        if kind != "sharded":
            raise CheckpointRestoreError(
                f"checkpoint {self.step_dir!r} has no {section!r} section "
                f"(kind={kind!r}) — saved under a different configuration"
            )
        layout = self.manifest["layout"]
        shard_sizes = [int(s) for s in layout["shard_sizes"]]
        dtypes = [_np_dtype(d) for d in layout["group_dtypes"]]
        slots = [tuple(int(x) for x in s) for s in layout["leaf_slots"]]

        def read(j: int, a: int, b: int) -> np.ndarray:
            gi, off = slots[j]
            s = shard_sizes[gi]
            dt = dtypes[gi]
            out = np.empty((b - a,), dt)
            lo = off + a
            hi = off + b
            pos = lo
            while pos < hi:
                r = pos // s
                owner = self._row_owner.get(r)
                if owner is None:
                    raise CheckpointRestoreError(
                        f"checkpoint {self.step_dir!r}: shard row {r} of "
                        f"group {gi} belongs to no process in the manifest"
                    )
                proc, local = owner
                nrows = len(self.manifest["processes"][str(proc)]["rows"])
                mm = self._mmap(proc, f"{prefix}.g{gi}", (nrows, s), dt)
                c0 = pos - r * s
                c1 = min(hi - r * s, s)
                seg = _viewed(mm[local, c0:c1], dt)
                out[pos - lo : pos - lo + (c1 - c0)] = seg
                pos = r * s + c1
            return out

        return read

    def read_leaf(self, section: str, j: int, slot: Optional[int] = None):
        """One FULL leaf (replicated-target path — materializes the
        leaf, by design). With `slot`, `j` indexes the parameter tree
        (slot subtrees mirror it); otherwise the section's own docs."""
        docs = self.leaves if slot is not None else self.section_docs(section)
        doc = docs[j]
        n = int(np.prod(doc["shape"])) if doc["shape"] else 1
        read = self.leaf_slice_reader(section, slot=slot)
        return read(j, 0, n).reshape([int(s) for s in doc["shape"]])

    def read_rows(
        self,
        section: str,
        slot: Optional[int],
        dst_leaf_slots: list[tuple[int, int]],
        dst_shard_sizes: list[int],
        dst_group_dtypes: list[np.dtype],
        rows: list[int],
    ) -> list[np.ndarray]:
        """Re-slice the source section onto a DESTINATION padded-bucket
        layout: returns, per destination group, the (len(rows), shard)
        buffer holding exactly `rows` of the destination's (world, shard)
        global buffer. Padding regions are zero (bitwise-identical to what
        a fresh scatter packs). Only the source bytes those rows cover are
        read — no world-sized intermediate, no full leaf."""
        read = self.leaf_slice_reader(section, slot=slot)
        leaves = self.leaves
        sizes = [
            int(np.prod(doc["shape"])) if doc["shape"] else 1
            for doc in leaves
        ]
        # destination group -> [(leaf j, offset)] members
        members: dict[int, list[tuple[int, int]]] = {}
        for j, (gi, off) in enumerate(dst_leaf_slots):
            members.setdefault(int(gi), []).append((j, int(off)))
        out = []
        row_pos = {r: k for k, r in enumerate(rows)}
        for gi, s in enumerate(dst_shard_sizes):
            buf = np.zeros((len(rows), int(s)), dst_group_dtypes[gi])
            for j, off in members.get(gi, ()):
                n = sizes[j]
                for r in rows:
                    lo = max(off, r * s)
                    hi = min(off + n, (r + 1) * s)
                    if lo >= hi:
                        continue
                    seg = read(j, lo - off, hi - off)
                    buf[row_pos[r], lo - r * s : hi - r * s] = seg
            out.append(buf)
        return out

    # -- carry -------------------------------------------------------------
    def carry_doc(self) -> Optional[dict]:
        return self.manifest.get("carry") or None

    def _carry_runs(self) -> list[tuple[int, int, int, int]]:
        """(start, stop, process, offset-in-file) per saved run: each
        process's file concatenates its runs in manifest order, so the
        file offset of a run is the length of that process's earlier
        runs. Runs may interleave across processes (multi-slice data
        shardings do); the reader never assumes contiguity."""
        out = []
        for p, runs in (self.carry_doc().get("runs") or {}).items():
            off = 0
            for a, b in runs:
                out.append((int(a), int(b), int(p), off))
                off += int(b) - int(a)
        return sorted(out)

    def read_carry_range(self, li: int, start: int, stop: int) -> np.ndarray:
        """Rows [start, stop) of carry leaf `li` along dim 0, assembled
        from whichever processes' local blocks cover them."""
        doc = self.carry_doc()
        leaf = doc["leaves"][li]
        dt = _np_dtype(leaf["dtype"])
        gshape = [int(s) for s in leaf["shape"]]
        runs = self._carry_runs()
        file_rows = {}
        for a, b, p, _ in runs:
            file_rows[p] = file_rows.get(p, 0) + (b - a)
        pieces = []
        pos = start
        while pos < stop:
            hit = None
            for a, b, p, off in runs:
                if a <= pos < b:
                    hit = (a, b, p, off)
                    break
            if hit is None:
                raise CheckpointRestoreError(
                    f"checkpoint {self.step_dir!r}: carry rows "
                    f"[{pos}, {stop}) of leaf {li} are covered by no "
                    "process in the manifest"
                )
            a, b, p, off = hit
            mm = self._mmap(
                p, f"carry.l{li}", [file_rows[p]] + gshape[1:], dt
            )
            hi = min(b, stop)
            lo_f = off + (pos - a)
            hi_f = off + (hi - a)
            pieces.append(_viewed(mm[lo_f:hi_f], dt))
            pos = hi
        return np.concatenate(pieces) if len(pieces) > 1 else np.array(
            pieces[0]
        )

    # -- validation (satellite: fail fast, named) ---------------------------
    def validate(self) -> None:
        """Probe every file the manifest promises; a missing/truncated/
        mis-shaped shard fails HERE with the process, section, and
        expected-vs-found layout — never a raw numpy traceback deep in a
        restore."""
        problems: list[str] = []
        m = self.manifest
        layout = m.get("layout") or {}
        shard_sizes = [int(s) for s in layout.get("shard_sizes", ())]
        dtypes = [str(d) for d in layout.get("group_dtypes", ())]
        sharded_sections: list[tuple[str, Optional[int]]] = []
        if self.section_kind("params") == "sharded":
            sharded_sections.append(("params", None))
        if self.section_kind("opt") == "sharded":
            for s in range(self.opt_slots()):
                sharded_sections.append(("opt", s))
        for p_str, doc in sorted((m.get("processes") or {}).items()):
            p = int(p_str)
            rows = list(doc.get("rows", ()))
            for section, slot in sharded_sections:
                prefix = section if slot is None else f"{section}.s{slot}"
                for gi, s in enumerate(shard_sizes):
                    name = f"{prefix}.g{gi}"
                    want = (len(rows), s)
                    problems.extend(
                        self._check_file(p, name, want, dtypes[gi])
                    )
            carry = m.get("carry") or None
            if carry and p_str in (carry.get("runs") or {}):
                nrows = sum(
                    int(b) - int(a) for a, b in carry["runs"][p_str]
                )
                for li, leaf in enumerate(carry["leaves"]):
                    want = tuple(
                        [nrows] + [int(x) for x in leaf["shape"][1:]]
                    )
                    problems.extend(self._check_file(
                        p, f"carry.l{li}", want, leaf["dtype"],
                    ))
        for section in ("params", "opt", "batch_stats"):
            kind = self.section_kind(section)
            if kind != "replicated":
                continue
            docs = (
                self.leaves if section == "params"
                else (self.manifest.get(section) or {}).get("leaves") or []
            )
            for j, doc in enumerate(docs):
                problems.extend(self._check_file(
                    0, f"{section}.l{j}", tuple(doc["shape"]), doc["dtype"],
                    leaf=doc.get("path"),
                ))
        if problems:
            raise CheckpointRestoreError(
                f"shard-native checkpoint step {m.get('step')} in "
                f"{self.step_dir!r} failed validation; offending "
                "shard(s):\n  " + "\n  ".join(problems[:20]),
                mismatches=problems,
            )

    def _check_file(
        self, proc: int, name: str, want_shape, want_dtype,
        leaf: Optional[str] = None,
    ) -> list[str]:
        where = f"process {proc}, file {name}.npy"
        if leaf:
            where += f" (leaf {leaf})"
        path = self._file(proc, name)
        try:
            mm = np.load(path, mmap_mode="r")
        except FileNotFoundError:
            return [f"{where}: missing (expected "
                    f"{tuple(want_shape)} {want_dtype})"]
        except (OSError, ValueError) as e:
            return [f"{where}: unreadable ({e}); expected "
                    f"{tuple(want_shape)} {want_dtype}"]
        if tuple(mm.shape) != tuple(want_shape):
            return [f"{where}: found shape {tuple(mm.shape)}, expected "
                    f"{tuple(want_shape)} {want_dtype}"]
        if mm.dtype.itemsize != _np_dtype(want_dtype).itemsize:
            return [f"{where}: found dtype {mm.dtype}, expected "
                    f"{want_dtype}"]
        return []


class _AsyncShardSave:
    """One in-flight asynchronous shard-native save (single slot).

    Ownership protocol (what makes this race-free, and what the THR
    checker's THR001 is calibrated against): the submitting (step-loop)
    thread fills every field, hands the slot to the writer thread, and
    touches nothing but `done` until `done.is_set()` — the writer thread
    owns `error` exclusively until then, and `done.set()` is the
    publication edge (threading.Event carries the memory ordering). All
    group operations — step agreement, dedup vote, payload barrier,
    manifest commit — happen on the submitting thread (submit_sharded /
    poll_async); the writer thread performs ONLY local filesystem I/O,
    so the SPMD lockstep contract (collectives issued from one thread in
    one program order) is untouched.
    """

    def __init__(self, step: int, manifest: dict, entry: dict,
                 nbytes: int):
        self.step = step
        self.manifest = manifest
        self.entry = entry
        self.nbytes = nbytes
        self.t0 = time.perf_counter()
        self.final: Optional[str] = None  # payload dir, set by the writer
        self.error: Optional[str] = None
        self.done = threading.Event()
        self.thread: Optional[threading.Thread] = None


class Checkpointer:
    """Step-indexed checkpoint manager over one run directory."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self._dir = os.path.abspath(directory)
        self._max_to_keep = max_to_keep
        os.makedirs(self._dir, exist_ok=True)
        # GC is ours, not orbax's: retention must be CLASS-aware (see
        # _gc) — orbax's flat max_to_keep would let a burst of
        # --ckpt-every-steps saves evict the per-epoch history that
        # `evaluate --all-epochs` / model averaging read
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(create=True),
            # register the handler up front: a FRESH manager must be able
            # to read item_metadata of existing steps (the proactive
            # shape/dtype drift check) before any save taught it the type
            item_handlers=ocp.StandardCheckpointHandler(),
        )
        self._index = self._load_index()
        # single-slot async shard-native save (ISSUE 16): at most one
        # in-flight background payload write; a new save drains it first
        self._async: Optional[_AsyncShardSave] = None

    # -- sidecar index ----------------------------------------------------
    def _index_path(self) -> str:
        return os.path.join(self._dir, INDEX_FILE)

    def _load_index(self) -> dict:
        try:
            with open(self._index_path()) as f:
                idx = json.load(f)
        except (OSError, ValueError):
            return {}
        if idx.get("version") != INDEX_VERSION:
            return {}
        return dict(idx.get("steps", {}))

    def _write_index(self) -> None:
        # drop entries whose payload was garbage-collected, then
        # write-temp + rename so a mid-write kill never corrupts the index
        live = {str(s) for s in self.all_steps()}
        self._index = {k: v for k, v in self._index.items() if k in live}
        if not coord.is_primary():  # graft: noqa[RUN004] -- the save paths commit-barrier after every sidecar write; the restore-path heal is an opportunistic p0 repair peers never read mid-restore
            # multi-host: exactly ONE writer for the sidecar — every
            # process keeps the same in-memory index (the save/restore
            # calls are collective), but two processes racing the
            # tmp+rename on a shared FS could commit a torn view; the
            # commit barrier in save() orders everyone behind process 0
            return
        tmp = self._index_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"version": INDEX_VERSION, "steps": self._index}, f)
        os.replace(tmp, self._index_path())

    # -- shard-native payload (ISSUE 13) ----------------------------------
    def _shard_root(self) -> str:
        return os.path.join(self._dir, SHARD_SUBDIR)

    def _shard_step_dir(self, step: int) -> str:
        return os.path.join(self._shard_root(), f"{int(step):08d}")

    def _sharded_steps(self) -> list[int]:
        """Committed (manifest present) shard-native steps."""
        out = []
        try:
            names = os.listdir(self._shard_root())
        except OSError:
            return []
        for name in names:
            if not name.isdigit():
                continue
            if os.path.exists(os.path.join(
                self._shard_root(), name, MANIFEST_FILE
            )):
                out.append(int(name))
        return sorted(out)

    def all_steps(self) -> list[int]:
        """Every committed step, both formats."""
        return sorted(set(self._mgr.all_steps()) | set(self._sharded_steps()))

    def entry_format(self, step: int) -> Optional[str]:
        """'sharded' | 'orbax' | None for an uncommitted step."""
        if os.path.exists(os.path.join(
            self._shard_step_dir(step), MANIFEST_FILE
        )):
            return "sharded"
        if step in self._mgr.all_steps():
            return "orbax"
        return None

    def open_sharded(self, step: int) -> ShardSource:
        """Validated reader over a committed shard-native step."""
        step_dir = self._shard_step_dir(step)
        path = os.path.join(step_dir, MANIFEST_FILE)
        try:
            with open(path) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointRestoreError(
                f"shard-native checkpoint step {step} in {self._dir!r} "
                f"has no readable manifest ({e}) — the save never "
                "committed or the directory is torn"
            ) from e
        if manifest.get("format_version") != SHARD_FORMAT_VERSION:
            raise CheckpointRestoreError(
                f"shard-native checkpoint step {step} in {self._dir!r} "
                f"has format_version {manifest.get('format_version')!r}; "
                f"this build reads version {SHARD_FORMAT_VERSION}"
            )
        src = ShardSource(step_dir, manifest)
        src.validate()
        return src

    def save_sharded(
        self,
        manifest: dict,
        files: dict[str, np.ndarray],
        wait: bool = False,
    ) -> dict:
        """Shard-native save: write THIS process's `files` under its own
        subtree, then commit via the manifest + sidecar (process 0) behind
        the same barriers `save` uses. `manifest` is the trainer-built
        document (world/mesh/layout/leaves/processes/meta — see the module
        docstring); `files` maps file stems to this process's local
        arrays (replicated sections included on process 0 only).

        Saving onto an already-committed step only promotes the index
        entry, exactly like the orbax path (an epoch boundary landing on
        a fresh --ckpt-every-steps snapshot). Returns
        {"duration_s", "bytes"} for the telemetry `checkpoint` event.
        """
        # single writer slot: an in-flight async save commits before a
        # new snapshot of the same state family starts (collective —
        # every process drains here before its step agreement below)
        self.drain_async(durable=wait)
        t0 = time.perf_counter()
        step, entry, nbytes, already = self._sharded_head(manifest, files)
        # graft: group-uniform -- 'already' is the agree_all dedup vote from _sharded_head: every process holds the same value
        if already:
            self._promote_sharded(step, manifest, entry)
            return {
                "duration_s": time.perf_counter() - t0, "bytes": 0,
            }
        self._write_shard_payload(step, files, wait=wait)
        self._commit_sharded(step, manifest, entry, wait=wait)
        return {"duration_s": time.perf_counter() - t0, "bytes": nbytes}

    def _sharded_head(
        self, manifest: dict, files: dict[str, np.ndarray]
    ) -> tuple[int, dict, int, bool]:
        """Group-agreed preamble of every shard-native save: the step-key
        uniformity check, the sidecar entry, the payload size, and the
        collective dedup decision. Runs on the submitting thread for the
        async path too — the writer thread never issues a collective."""
        step = int(manifest["step"])
        if coord.process_count() > 1 and not coord.agree_uniform(
            float(step)
        ):
            raise RuntimeError(
                f"shard-native save: processes disagree on the step key "
                f"(this process: {step}) — the group diverged; refusing "
                "to commit a torn checkpoint"
            )
        meta = manifest.get("meta") or {}
        entry = {
            "format": "sharded",
            "epoch": int(meta.get("epoch", 0)),
            "epoch_step": int(meta.get("epoch_step", 0)),
            "mid_epoch": bool(meta.get("mid_epoch", False)),
            "has_carry": bool(manifest.get("carry")),
        }
        nbytes = int(sum(np.asarray(a).nbytes for a in files.values()))
        already = step in self.all_steps()
        if coord.process_count() > 1:
            # the dedup decision reads host-local filesystem state (the
            # sidecar + shard dirs); a host with a torn local view taking
            # the promote-only early path would skip the payload barrier
            # its peers still enter (RUN003). Promote only when EVERY
            # process sees the step committed; otherwise all re-save —
            # the payload write is idempotent (tmp + os.replace)
            already = coord.agree_all(already)
        return step, entry, nbytes, already

    def _promote_sharded(
        self, step: int, manifest: dict, entry: dict
    ) -> None:
        """Index-entry promotion for an already-committed step (an epoch
        boundary landing on a fresh --ckpt-every-steps snapshot)."""
        meta = manifest.get("meta") or {}
        prev = self._index.get(str(step), {})
        if prev:
            # same dedup/promotion contract as the orbax path: the
            # payload at this step is immutable, only the entry's
            # epoch/boundary class may move (and never backwards)
            entry = dict(prev)
            entry["epoch"] = int(meta.get("epoch", entry.get("epoch", 0)))
            if not meta.get("mid_epoch", False):
                entry["mid_epoch"] = False
        self._index[str(step)] = entry
        self._gc()
        self._write_index()
        self._commit_barrier(step)

    def _write_shard_payload(
        self, step: int, files: dict[str, np.ndarray], wait: bool
    ) -> str:
        """THIS process's payload subtree: tmp dir + np.save + os.replace.
        Purely local filesystem work — no group ops, no Checkpointer
        state writes — which is exactly what licenses running it on the
        async writer thread. Returns the committed subtree path."""
        step_dir = self._shard_step_dir(step)
        pid = coord.process_index()
        os.makedirs(step_dir, exist_ok=True)
        tmp = os.path.join(step_dir, f".tmp.p{pid:05d}.{os.getpid()}")
        os.makedirs(tmp, exist_ok=True)
        for name, arr in files.items():
            np.save(os.path.join(tmp, name + ".npy"), np.asarray(arr))
        final = os.path.join(step_dir, f"p{pid:05d}")
        if os.path.isdir(final):  # a torn previous attempt never committed
            shutil.rmtree(final)
        os.replace(tmp, final)
        if wait:
            # the drain path's durability request: np.save leaves the
            # bytes in the page cache; a preempting machine may go away
            # right after the rc-75 exit, so flush this process's files
            # (and the dir entry) before the commit barriers release
            _fsync_dir_files(final)
        return final

    def _commit_sharded(
        self, step: int, manifest: dict, entry: dict, wait: bool
    ) -> None:
        """Commit a written payload: payload barrier, p0 manifest +
        sidecar, group success vote, commit barrier. Collective — always
        runs on the submitting thread, never the async writer."""
        # every process's subtree must be durable before the manifest
        # (the commit record) appears
        if coord.process_count() > 1:
            coord.barrier(f"ckpt_shard_payload_{step}")
        # the window between the payload barrier and the commit barrier
        # must stay BALANCED: if p0's manifest/sidecar write raised while
        # its peers marched on to the commit barrier, they would wait out
        # the full barrier timeout on a process that already unwound (the
        # latent multi-host hang the SPMD checker's RUN003 formalizes).
        # A local failure therefore becomes a GROUP decision: everyone
        # agrees on commit success and everyone raises together.
        step_dir = self._shard_step_dir(step)
        commit_err: Optional[str] = None
        try:
            if coord.is_primary():
                mpath = os.path.join(step_dir, MANIFEST_FILE)
                mtmp = mpath + ".tmp"
                with open(mtmp, "w") as f:
                    json.dump(manifest, f)
                    if wait:
                        f.flush()
                        os.fsync(f.fileno())
                os.replace(mtmp, mpath)
            self._index[str(step)] = entry
            self._gc()
            self._write_index()
            if wait and coord.is_primary():
                # the COMMIT RECORD must be at least as durable as the
                # payload it commits: flush the manifest's directory entry
                # and the sidecar, or a power cut after the rc-75 exit can
                # keep the payload while losing the fact it committed
                _fsync_dir_files(step_dir)
                try:
                    fd = os.open(self._index_path(), os.O_RDONLY)
                    try:
                        os.fsync(fd)
                    finally:
                        os.close(fd)
                except OSError:
                    pass
        except (OSError, ValueError, TypeError) as e:
            commit_err = f"{type(e).__name__}: {e}"
        ok = commit_err is None
        if coord.process_count() > 1:
            ok = coord.agree_all(ok)
        if not ok:
            raise RuntimeError(
                f"shard-native commit of step {step} failed "
                f"({commit_err or 'on a peer process'}); no process "
                "recorded the step as committed — restore falls back to "
                "the previous checkpoint"
            )
        self._commit_barrier(step)

    # -- async shard-native save (ISSUE 16) -------------------------------
    def submit_sharded(
        self, manifest: dict, files: dict[str, np.ndarray]
    ) -> Optional[dict]:
        """Start a shard-native save WITHOUT blocking the step loop on
        the payload write. The group-agreed preamble (step uniformity +
        dedup vote) still runs here, synchronously — it is collective —
        but the np.save of this process's subtree moves to a background
        thread; the commit (also collective) happens later, on the
        calling thread, via poll_async()/drain_async().

        Ownership contract: the caller hands `files` over — the arrays
        must not be mutated after submission (the trainer's payload
        builder materializes fresh host copies per call, so the step
        loop updating device state cannot touch them).

        Returns None when the save is now in flight, or the sync-path
        stats dict when the step was already committed (dedup promotes
        the index entry immediately — there is no payload to write).
        """
        self.drain_async()  # single slot: retire any previous save first
        t0 = time.perf_counter()
        step, entry, nbytes, already = self._sharded_head(manifest, files)
        # graft: group-uniform -- 'already' is the agree_all dedup vote from _sharded_head: every process holds the same value
        if already:
            self._promote_sharded(step, manifest, entry)
            return {
                "duration_s": time.perf_counter() - t0, "bytes": 0,
            }
        slot = _AsyncShardSave(step, manifest, entry, nbytes)
        slot.thread = threading.Thread(
            target=self._shard_payload_worker, args=(slot, files),
            name=f"ckpt-shard-writer-{step}", daemon=True,
        )
        self._async = slot
        slot.thread.start()
        return None

    def _shard_payload_worker(
        self, slot: _AsyncShardSave, files: dict[str, np.ndarray]
    ) -> None:
        """Async writer thread body: local payload I/O only (see the
        _AsyncShardSave ownership protocol). Group ops are off-limits
        here — the commit waits for poll_async on the loop thread."""
        try:
            slot.final = self._write_shard_payload(
                slot.step, files, wait=False
            )
        except Exception as e:  # noqa: BLE001 — the error crosses the
            # thread boundary through the slot; poll_async re-raises it
            # on the loop thread as a group-agreed commit failure
            slot.error = f"{type(e).__name__}: {e}"
        finally:
            slot.done.set()

    def poll_async(
        self, block: bool = False, durable: bool = False
    ) -> Optional[dict]:
        """Retire the in-flight async save if (on multi-host: the whole
        group's) payload write has finished; otherwise return None.

        COLLECTIVE on multi-host — every process must call it at the
        same point in its program (the trainer polls at the same
        agree-interval cadence that gates preemption agreement), because
        the completion check is a group vote: committing when only THIS
        process's payload landed would publish a manifest over peers'
        unwritten subtrees. With block=True, waits for the local writer
        first (the drain paths). durable=True upgrades the commit to the
        fsync'd rc-75 contract, flushing the payload post-hoc.

        Returns the telemetry fields for the `checkpoint` event
        ({"step", "duration_s", "bytes", "async", "meta"}) once the save
        commits; raises if any process's payload write failed (all
        processes raise together — the agree_all vote below).
        """
        slot = self._async
        if slot is None:
            return None
        if block:
            slot.done.wait()
        done = slot.done.is_set()
        if coord.process_count() > 1:
            done = coord.agree_all(done)
        if not done:
            return None
        self._async = None
        if slot.thread is not None:
            slot.thread.join()
        ok = slot.error is None
        if coord.process_count() > 1:
            ok = coord.agree_all(ok)
        if not ok:
            raise RuntimeError(
                f"async shard payload write for step {slot.step} failed "
                f"({slot.error or 'on a peer process'}); no process "
                "committed the step — restore falls back to the previous "
                "checkpoint"
            )
        if durable and slot.final is not None:
            # the payload was written lazily (page cache); the rc-75
            # drain wants it durable before the commit record appears
            _fsync_dir_files(slot.final)
        self._commit_sharded(
            slot.step, slot.manifest, slot.entry, wait=durable
        )
        return {
            "step": slot.step,
            "duration_s": time.perf_counter() - slot.t0,
            "bytes": slot.nbytes,
            "async": True,
            "meta": dict(slot.manifest.get("meta") or {}),
        }

    def drain_async(self, durable: bool = False) -> Optional[dict]:
        """Block until any in-flight async save has committed (collective
        on multi-host, like poll_async). No-op when the slot is empty."""
        return self.poll_async(block=True, durable=durable)

    def abandon_async(self) -> Optional[int]:
        """Drop the in-flight async save WITHOUT committing (the rollback
        path: the snapshot comes from the suspect regime, and its step
        key may be re-reached after the replay). Purely local — no
        collectives, so it is safe at any group state as long as every
        process takes the same decision (rollback is broadcast-agreed).
        The manifest never appears, so restore ignores the payload and a
        later save of the same step overwrites it. Returns the abandoned
        step, or None when the slot was empty."""
        slot = self._async
        if slot is None:
            return None
        self._async = None
        if slot.thread is not None:
            # wait out the local writer: a replayed save can re-reach
            # this step key and must not race the old worker's tmp dir
            slot.thread.join()
        return slot.step

    def pending_async_step(self) -> Optional[int]:
        """Step key of the in-flight async save, or None."""
        slot = self._async
        return None if slot is None else slot.step

    # -- save -------------------------------------------------------------
    def save(self, snap: Snapshot, wait: bool = False) -> None:
        """Atomic step-indexed save (orbax commits via tmp-dir + rename).

        The orbax step key is the GLOBAL iteration. Saving a step that
        already exists (an epoch boundary landing on a just-written
        ``--ckpt-every-steps`` checkpoint) only updates the index metadata
        — the state payload is identical by construction.

        Multi-host: `save` is a COLLECTIVE — every process calls it with
        the same snapshot (orbax coordinates the payload so the tmp-dir +
        atomic-rename commit happens exactly once, on the primary); the
        sidecar index is written by process 0 only (`_write_index`), and
        a commit barrier at the end keeps any process from returning —
        and, on the preemption-drain path, EXITING — before the commit is
        durable, so a preempt mid-save can never leave torn state."""
        step = int(snap.iteration)
        entry = {
            "epoch": int(snap.epoch),
            "epoch_step": int(snap.epoch_step),
            "mid_epoch": bool(snap.mid_epoch),
            "has_carry": snap.carry is not None,
        }
        already = step in self.all_steps()
        if coord.process_count() > 1:
            # same contract as save_sharded: the dedup reads host-local
            # filesystem state, and a split decision is a split save
            # protocol (the promote path and the payload path issue
            # different collective sequences) — agree before branching
            already = coord.agree_all(already)
        if already:
            prev = self._index.get(str(step), {})
            if prev:
                # the stored payload is immutable (identical state), so
                # the existing entry keeps describing it — has_carry and
                # epoch_step MUST stay (a boundary re-save over a
                # mid-epoch save does not strip the payload's carry); an
                # epoch-boundary re-save only PROMOTES the entry (never
                # demote a boundary back to mid-epoch)
                entry = dict(prev)
                entry["epoch"] = int(snap.epoch)
                if not snap.mid_epoch:
                    entry["mid_epoch"] = False
            self._index[str(step)] = entry
            self._gc()  # a promotion changes class budgets too
            self._write_index()
            if wait:
                # the payload at this step may still be an in-flight async
                # save; an explicit durability request (preemption drain)
                # must not be dropped just because the bytes are deduped
                self._mgr.wait_until_finished()
            self._commit_barrier(step)
            return
        payload = {
            "state": snap.state,
            "meta": {
                "epoch": int(snap.epoch),
                "iteration": int(snap.iteration),
                "epoch_step": int(snap.epoch_step),
                "mid_epoch": int(snap.mid_epoch),
            },
        }
        if snap.carry is not None:
            payload["carry"] = snap.carry
        self._mgr.save(step, args=ocp.args.StandardSave(payload))
        self._index[str(step)] = entry
        self._gc()
        self._write_index()
        if wait:
            self._mgr.wait_until_finished()
        self._commit_barrier(step)

    def _commit_barrier(self, step: int) -> None:
        """Multi-host rendezvous at the end of every save: no process may
        proceed until process 0's sidecar commit (and, for wait=True, the
        orbax payload commit) is on disk. No-op single-process."""
        if coord.process_count() > 1:
            coord.barrier(f"ckpt_commit_{step}")

    def _gc(self) -> None:
        """Class-aware retention: keep the newest `max_to_keep`
        epoch-BOUNDARY checkpoints AND, separately, the newest
        `max_to_keep` mid-epoch STEP checkpoints, so frequent
        --ckpt-every-steps saves never evict the per-epoch history."""
        if not self._max_to_keep or self._max_to_keep <= 0:
            return
        bounds: list[int] = []
        mids: list[int] = []
        for step in self.all_steps():
            e = self._index.get(str(step))
            if e is not None and e.get("mid_epoch", False):
                mids.append(step)
            else:
                bounds.append(step)  # boundary, or legacy epoch-keyed
        keep = set(bounds[-self._max_to_keep:])
        keep |= set(mids[-self._max_to_keep:])
        sharded = set(self._sharded_steps())
        for step in bounds + mids:
            if step in keep:
                continue
            if step in sharded:
                # shard-native payloads live on the shared checkpoint FS;
                # one deleter (the sidecar owner) keeps peers from racing
                # the rmtree
                if coord.is_primary():
                    shutil.rmtree(
                        self._shard_step_dir(step), ignore_errors=True
                    )
            else:
                self._mgr.delete(step)

    # -- listing ----------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return max(steps) if steps else None

    def _epoch_boundaries(self) -> dict[int, int]:
        """{epoch: step} for every epoch-boundary snapshot. Orbax steps
        absent from the index are legacy epoch-keyed saves (step == epoch)."""
        out: dict[int, int] = {}
        sharded = set(self._sharded_steps())
        for step in self.all_steps():
            entry = self._index.get(str(step))
            if entry is None and step in sharded:
                # sidecar lost mid-drain: the manifest's own meta is the
                # payload's bookkeeping — heal from it, never misread a
                # shard-native step as a legacy epoch-keyed one
                entry = self._heal_sharded_entry(step)
            if entry is None:  # legacy format
                out[int(step)] = int(step)
            elif not entry.get("mid_epoch", False):
                out[int(entry["epoch"])] = int(step)
        return out

    def _heal_sharded_entry(self, step: int) -> dict:
        """Index entry rebuilt from a committed shard-native manifest
        (the sidecar write was killed between the payload commit and
        os.replace). Repairs the in-memory index; the next save persists
        it."""
        try:
            with open(os.path.join(
                self._shard_step_dir(step), MANIFEST_FILE
            )) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            doc = {}
        meta = doc.get("meta") or {}
        entry = {
            "format": "sharded",
            "epoch": int(meta.get("epoch", 0)),
            "epoch_step": int(meta.get("epoch_step", 0)),
            "mid_epoch": bool(meta.get("mid_epoch", False)),
            "has_carry": bool(doc.get("carry")),
        }
        self._index[str(step)] = entry
        return entry

    def latest_epoch(self) -> Optional[int]:
        bounds = self._epoch_boundaries()
        return max(bounds) if bounds else None

    def all_epochs(self) -> list[int]:
        return sorted(self._epoch_boundaries())

    # -- restore ----------------------------------------------------------
    def restore(
        self,
        target_state: TrainState,
        epoch: Optional[int] = None,
        step: Optional[int] = None,
        carry_template: Any = None,
    ) -> Optional[Snapshot]:
        """Restore into the structure of `target_state` (shapes/dtypes must
        match the current model/optimizer — the reference has the same
        contract via load_state_dict). `epoch` selects that epoch's
        boundary snapshot, `step` an exact iteration; default is the
        latest snapshot of any kind. Structure/shape/dtype mismatches
        raise `CheckpointRestoreError` naming the offending leaves."""
        if step is None:
            if epoch is not None:
                step = self._epoch_boundaries().get(int(epoch))
            else:
                step = self.latest_step()
        if step is None or step not in self.all_steps():
            return None
        if self.entry_format(step) == "sharded":
            # shard-native payload: reconstruct the REPLICATED interchange
            # form this template path promises (per-leaf reads; sharded
            # consumers restore natively via open_sharded instead)
            return self._restore_sharded_template(
                int(step), target_state, carry_template
            )
        entry = self._index.get(str(step))
        healed = False
        if entry is None:
            # no index entry: either a genuine legacy epoch-keyed payload,
            # or a NEW-format step whose sidecar write was killed between
            # the orbax commit and os.replace (the preemption grace period
            # expiring mid-drain). Probe the stored metadata — misreading
            # a new payload as legacy would turn a mid-epoch snapshot into
            # an epoch boundary and silently skip the rest of the epoch.
            entry = self._probe_format(int(step))
            healed = entry is not None
        if entry is None:
            return self._restore_legacy(target_state, int(step))
        template: dict[str, Any] = {
            "state": target_state,
            "meta": {
                "epoch": 0, "iteration": 0, "epoch_step": 0, "mid_epoch": 0,
            },
        }
        if entry.get("has_carry", False):
            if carry_template is None:
                raise CheckpointRestoreError(
                    f"checkpoint step {step} in {self._dir!r} carries a "
                    "model carry (BPTT hidden state) but no carry template "
                    "was supplied — restore through a trainer built for "
                    "the same stateful model"
                )
            template["carry"] = carry_template
        restored = self._restore_checked(int(step), template)
        meta = restored["meta"]
        if healed:
            # repair the sidecar from the payload's own bookkeeping so the
            # next open doesn't have to probe again
            self._index[str(step)] = {
                "epoch": int(meta["epoch"]),
                "epoch_step": int(meta["epoch_step"]),
                "mid_epoch": bool(int(meta["mid_epoch"])),
                "has_carry": "carry" in restored,
            }
            self._write_index()
            entry = self._index[str(step)]
        # the INDEX is authoritative for epoch/mid_epoch: a boundary save
        # deduped onto an earlier mid-epoch payload promotes the entry
        # while the payload's meta still says mid_epoch — trusting the
        # payload would make the promoted boundary resume as mid-epoch
        mid_epoch = bool(entry.get("mid_epoch", int(meta["mid_epoch"])))
        return Snapshot(
            state=restored["state"],
            epoch=int(entry.get("epoch", meta["epoch"])),
            iteration=int(meta["iteration"]),
            epoch_step=int(meta["epoch_step"]),
            mid_epoch=mid_epoch,
            carry=restored.get("carry"),
        )

    # -- shard-native template reconstruction -----------------------------
    @staticmethod
    def _tree_docs(tree: Any) -> list[tuple[str, Any]]:
        return [
            (jax.tree_util.keystr(kp), leaf)
            for kp, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
        ]

    def _diff_leaf_docs(
        self, docs: list[dict], template: Any, what: str
    ) -> list[str]:
        """(path: saved vs expected) diffs between manifest leaf docs and
        the restore template's leaves — the shard-native twin of
        `_template_diff`."""
        want = {p: leaf for p, leaf in self._tree_docs(template)}
        saved = {d["path"]: d for d in docs}
        out = []
        for path in sorted(set(saved) | set(want)):
            s, w = saved.get(path), want.get(path)
            if s is None:
                out.append(f"{what}{path}: missing in checkpoint "
                           f"(expected {_leaf_desc(w)})")
            elif w is None:
                out.append(f"{what}{path}: present in checkpoint "
                           f"({s['dtype']}{tuple(s['shape'])}) but not in "
                           "the current structure")
            elif not _doc_matches(s, w):
                out.append(
                    f"{what}{path}: checkpoint has "
                    f"{s['dtype']}{tuple(s['shape'])}, current structure "
                    f"wants {_leaf_desc(w)}"
                )
        return out

    def _restore_sharded_template(
        self,
        step: int,
        target_state: TrainState,
        carry_template: Any = None,
    ) -> Snapshot:
        """Rebuild the replicated interchange Snapshot from a shard-native
        payload: per-leaf reads off the source files, whichever layout
        (sharded group buffers or per-leaf replicated files) the saver
        used. This is the path template-driven consumers (`evaluate
        --all-epochs`, tools, cross-comm-op interchange) ride; sharded
        trainers restore natively through `open_sharded` instead."""
        src = self.open_sharded(step)
        mismatches = self._diff_leaf_docs(
            src.leaves, target_state.params, "params"
        )
        meta = src.meta
        opt_kind = src.section_kind("opt")
        if opt_kind == "replicated":
            mismatches += self._diff_leaf_docs(
                (src.manifest.get("opt") or {}).get("leaves") or [],
                target_state.opt_state, "opt_state",
            )
        if mismatches:
            raise CheckpointRestoreError(
                self._drift_message(step, mismatches), mismatches=mismatches
            )
        # params + batch stats
        p_treedef = jax.tree_util.tree_structure(target_state.params)
        params = jax.tree_util.tree_unflatten(
            p_treedef,
            [
                jnp.asarray(src.read_leaf("params", j))
                for j in range(len(src.leaves))
            ],
        )
        bs_docs = (src.manifest.get("batch_stats") or {}).get("leaves") or []
        bs_diff = self._diff_leaf_docs(
            bs_docs, target_state.batch_stats, "batch_stats"
        )
        if bs_diff:
            raise CheckpointRestoreError(
                self._drift_message(step, bs_diff), mismatches=bs_diff
            )
        bs_treedef = jax.tree_util.tree_structure(target_state.batch_stats)
        batch_stats = jax.tree_util.tree_unflatten(
            bs_treedef,
            [
                jnp.asarray(src.read_leaf("batch_stats", j))
                for j in range(len(bs_docs))
            ],
        )
        # optimizer state
        if opt_kind == "replicated":
            o_docs = (src.manifest.get("opt") or {}).get("leaves") or []
            o_treedef = jax.tree_util.tree_structure(target_state.opt_state)
            opt_state = jax.tree_util.tree_unflatten(
                o_treedef,
                [
                    jnp.asarray(src.read_leaf("opt", j))
                    for j in range(len(o_docs))
                ],
            )
        elif opt_kind == "sharded":
            opt_state = self._opt_from_sharded(src, target_state, meta)
        else:  # "none": a save that carried no optimizer state
            opt_state = target_state.opt_state
        rng = target_state.rng
        if src.manifest.get("rng") is not None:
            rng = jnp.asarray(
                np.asarray(src.manifest["rng"], np.uint32), rng.dtype
            )
        state = target_state.replace(
            step=jnp.asarray(
                int(meta.get("train_step", meta.get("iteration", step))),
                target_state.step.dtype,
            ),
            params=params,
            batch_stats=batch_stats,
            opt_state=opt_state,
            rng=rng,
        )
        carry = None
        if src.carry_doc():
            if carry_template is None:
                raise CheckpointRestoreError(
                    f"checkpoint step {step} in {self._dir!r} carries a "
                    "model carry (BPTT hidden state) but no carry template "
                    "was supplied — restore through a trainer built for "
                    "the same stateful model"
                )
            cdoc = src.carry_doc()
            carry = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(carry_template),
                [
                    src.read_carry_range(
                        li, 0, int(leaf["shape"][0])
                    ).reshape([int(s) for s in leaf["shape"]])
                    for li, leaf in enumerate(cdoc["leaves"])
                ],
            )
        entry = self._index.get(str(step)) or self._heal_sharded_entry(step)
        return Snapshot(
            state=state,
            epoch=int(entry.get("epoch", meta.get("epoch", 0))),
            iteration=int(meta.get("iteration", step)),
            epoch_step=int(meta.get("epoch_step", 0)),
            mid_epoch=bool(entry.get(
                "mid_epoch", meta.get("mid_epoch", False)
            )),
            carry=carry,
            manifest_meta=meta,
        )

    def _opt_from_sharded(
        self, src: ShardSource, target_state: TrainState, meta: dict
    ) -> Any:
        """Sharded opt slots -> the replicated optax structure of the
        template: slot s's per-leaf reads land in the s-th params-shaped
        subtree of the optax tree, count leaves take the saved count."""
        from mgwfbp_tpu.parallel.allreduce import (
            _map_count_leaves,
            _map_params_subtrees,
        )

        slots = src.opt_slots()
        p_treedef = jax.tree_util.tree_structure(target_state.params)
        slot_trees = []
        for s in range(slots):
            slot_trees.append(jax.tree_util.tree_unflatten(
                p_treedef,
                [
                    jnp.asarray(src.read_leaf("opt", j, slot=s))
                    for j in range(len(src.leaves))
                ],
            ))
        it = iter(slot_trees)
        consumed = []

        def take(sub):
            try:
                new = next(it)
            except StopIteration:
                raise CheckpointRestoreError(
                    f"checkpoint in {self._dir!r}: optimizer template "
                    f"carries more params-shaped subtrees than the saved "
                    f"{slots} slot(s) — optimizer config drift"
                ) from None
            consumed.append(new)
            return jax.tree_util.tree_map(
                lambda ref, a: jnp.asarray(a, ref.dtype), sub, new
            )

        out = _map_params_subtrees(
            target_state.opt_state, target_state.params, take
        )
        if len(consumed) != slots:
            raise CheckpointRestoreError(
                f"cannot restore checkpoint step {src.manifest.get('step')} "
                f"from {self._dir!r}: saved optimizer has {slots} sharded "
                f"slot(s) but the current optimizer template consumes "
                f"{len(consumed)} — optimizer config drift"
            )
        count = jnp.asarray(int(meta.get("opt_count", 0)), jnp.int32)
        return _map_count_leaves(
            out, lambda leaf: jnp.asarray(count, leaf.dtype)
        )

    def _probe_format(self, step: int) -> Optional[dict]:
        """Minimal index entry inferred from stored metadata for an
        UNINDEXED step, or None when the payload really is the legacy
        epoch-keyed format (2-key meta, no epoch_step)."""
        try:
            md = self._mgr.item_metadata(step)
        except Exception:  # noqa: BLE001 — undecidable: treat as legacy
            return None
        if not isinstance(md, dict) or not isinstance(md.get("meta"), dict):
            return None
        if "epoch_step" not in md["meta"]:
            return None
        return {"has_carry": "carry" in md}

    def _restore_legacy(
        self, target_state: TrainState, step: int
    ) -> Snapshot:
        """Epoch-keyed payloads from the pre-resilience format: the orbax
        step is the epoch, meta has only {'epoch','iteration'}."""
        template = {
            "state": target_state,
            "meta": {"epoch": 0, "iteration": 0},
        }
        restored = self._restore_checked(step, template)
        return Snapshot(
            state=restored["state"],
            epoch=int(restored["meta"]["epoch"]),
            iteration=int(restored["meta"]["iteration"]),
        )

    def _restore_checked(self, step: int, template: Any) -> Any:
        # proactive shape/dtype validation: orbax's StandardRestore does
        # NOT fail on a mismatched template — it hands back the saved
        # shapes, deferring the blow-up to the first jitted dispatch with
        # an inscrutable shape error. Diff the stored metadata against the
        # template FIRST and fail here, naming the drifted leaves.
        mismatches = self._template_diff(step, template)
        if mismatches:
            raise CheckpointRestoreError(
                self._drift_message(step, mismatches), mismatches=mismatches
            )
        try:
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(template)
            )
        except CheckpointRestoreError:
            raise
        except Exception as e:  # noqa: BLE001 — rewrapped with context
            raise CheckpointRestoreError(
                self._drift_message(step, []) + f" (orbax: {e})"
            ) from e

    def _drift_message(self, step: int, mismatches: list[str]) -> str:
        detail = (
            "; offending leaves:\n  " + "\n  ".join(mismatches[:20])
            if mismatches
            else ""
        )
        return (
            f"cannot restore checkpoint step {step} from {self._dir!r} "
            "into the current model/optimizer structure — likely config "
            "drift (the checkpoint was saved under a different --dnn / "
            f"optimizer / precision configuration){detail}"
        )

    def _template_diff(self, step: int, template: Any) -> list[str]:
        """Human-readable (path: saved vs expected) diffs between the
        stored payload's metadata and the restore template — best effort;
        metadata unavailable degrades to the wrapped orbax message."""
        try:
            saved_md = self._mgr.item_metadata(step)
            saved = {
                _path_str(kp): v
                for kp, v in jax.tree_util.tree_flatten_with_path(saved_md)[0]
            }
            want = {
                _path_str(kp): v
                for kp, v in jax.tree_util.tree_flatten_with_path(
                    jax.eval_shape(lambda: template)
                )[0]
            }
        except Exception:  # noqa: BLE001 — diffing is best-effort
            return []
        if not saved or not any(
            hasattr(v, "shape") for v in saved.values()
        ):
            # metadata unavailable/uninterpretable: no diff evidence —
            # let the actual restore decide instead of crying drift
            return []
        out = []
        for path in sorted(set(saved) | set(want)):
            if path.startswith("meta."):
                continue  # bookkeeping ints; never the drifted leaves
            s, w = saved.get(path), want.get(path)
            if s is None:
                out.append(f"{path}: missing in checkpoint (expected "
                           f"{_leaf_desc(w)})")
            elif w is None:
                out.append(f"{path}: present in checkpoint "
                           f"({_leaf_desc(s)}) but not in the current "
                           "structure")
            elif _leaf_desc(s) != _leaf_desc(w):
                out.append(f"{path}: checkpoint has {_leaf_desc(s)}, "
                           f"current structure wants {_leaf_desc(w)}")
        return out

    def wait(self) -> None:
        """Durability point: both async machineries (orbax's background
        commit and the shard-native writer slot) are drained. Collective
        on multi-host when a shard save is pending — call it from the
        same program point on every process (the trainer's callers do)."""
        self.drain_async()
        self._mgr.wait_until_finished()

    def close(self) -> None:
        slot = self._async
        if slot is not None:
            if coord.process_count() == 1:
                # single process: the drain is pure local work + commit;
                # finishing it is strictly better than dropping the save
                try:
                    self.drain_async()
                except RuntimeError:
                    pass  # a failed payload write must not block close
            else:
                # multi-host close is the DISORDERLY path (orderly exits
                # drain at a boundary save / wait() first): peers may
                # already be gone, so the collective commit could hang on
                # a dead process. Abandon the uncommitted save — the
                # manifest never appeared, so restore ignores the torn
                # subtrees and falls back to the last committed step.
                self._async = None
                import warnings

                warnings.warn(
                    f"close() with async shard save of step {slot.step} "
                    "still in flight on a multi-host run: abandoning the "
                    "uncommitted save (restore uses the previous "
                    "committed step)",
                    RuntimeWarning,
                    stacklevel=2,
                )
        self._mgr.close()


def _path_str(kp) -> str:
    """Canonical dotted path for a tree_flatten_with_path key path.

    Orbax metadata comes back as plain nested dicts while the restore
    template carries dataclass pytrees (TrainState), so DictKey vs
    GetAttrKey must compare equal for the same logical leaf."""
    names = []
    for entry in kp:
        name = getattr(entry, "key", None)
        if name is None:
            name = getattr(entry, "name", None)
        if name is None:
            name = getattr(entry, "idx", None)
        names.append(str(name))
    return ".".join(names)


def _leaf_desc(leaf: Any) -> str:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None and dtype is None:
        return type(leaf).__name__
    return f"{np.dtype(dtype).name if dtype is not None else '?'}" \
           f"{tuple(shape) if shape is not None else ''}"
