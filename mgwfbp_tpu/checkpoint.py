"""Checkpoint / resume via orbax.

Parity target (SURVEY.md §5): reference `save_checkpoint` /
`load_model_from_file` (dl_trainer.py:946-947, 307-312 — torch.save of
{'state','epoch','iter'} and counter restore), rank-0 `--pretrain` load +
parameter re-broadcast (dist_trainer.py:32-39,66). Differences by design:
  * orbax writes sharded/replicated jax arrays directly — the "broadcast
    after load" step is a sharding constraint, not a collective we code;
  * the epoch-boundary save the reference constructs but never executes
    (dl_trainer.py:769-777 builds the filename, no write) actually saves here.

Checkpoint directory naming encodes the experiment config like the
reference's log/checkpoint dirs (dl_trainer.py:771-777).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from mgwfbp_tpu.train.step import TrainState


@dataclasses.dataclass
class Snapshot:
    state: TrainState
    epoch: int
    iteration: int


class Checkpointer:
    """Epoch-indexed checkpoint manager over one run directory."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, snap: Snapshot, wait: bool = False) -> None:
        payload = {
            "state": snap.state,
            "meta": {"epoch": snap.epoch, "iteration": snap.iteration},
        }
        self._mgr.save(snap.epoch, args=ocp.args.StandardSave(payload))
        if wait:
            self._mgr.wait_until_finished()

    def latest_epoch(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_epochs(self) -> list[int]:
        return sorted(self._mgr.all_steps())

    def restore(
        self, target_state: TrainState, epoch: Optional[int] = None
    ) -> Optional[Snapshot]:
        """Restore into the structure of `target_state` (shapes/dtypes must
        match the current model/optimizer — the reference has the same
        contract via load_state_dict)."""
        step = epoch if epoch is not None else self._mgr.latest_step()
        if step is None:
            return None
        template = {
            "state": target_state,
            "meta": {"epoch": 0, "iteration": 0},
        }
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(template)
        )
        return Snapshot(
            state=restored["state"],
            epoch=int(restored["meta"]["epoch"]),
            iteration=int(restored["meta"]["iteration"]),
        )

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
