"""Online cost-model drift detection (and the live straggler monitor).

MG-WFBP's merge schedule is only optimal while the alpha-beta cost model
tracks the hardware (arXiv:1811.11141) — and the cross-step rs_fwd_ag
split makes the model two-phase and even easier to silently invalidate
(DeAR, arXiv:2302.12445). Until now nothing NOTICED when predicted and
measured diverged mid-run: the autotuner corrects the model once, at its
race, and every later regime change (congestion, thermal throttle, a
noisy neighbor on the fabric) just ran the stale schedule. This module
watches the two live signals a run can afford to watch (pure host
arithmetic, zero device syncs) and raises schema-versioned alarms:

  * **comm residual** (`kind='comm_residual'`): the cost model's
    predicted merge-group communication versus a measured attribution.
    With trace-attributed per-group seconds (real TPU op metadata) the
    residual is per group and ABSOLUTE — a direct measurement refutes a
    prediction on both sides of the band. Without a trace the aggregate
    estimator is the measured non-backward step share (step - tb, the
    same step-delta attribution `autotune.step_delta_observations`
    refits from), which is inflated by forward/dispatch overhead the
    model never claimed to price — so the aggregate channel is
    BASELINE-RELATIVE: the first ``baseline_window`` observations learn
    the healthy predicted/measured ratio, and the alarm fires when the
    CURRENT ratio drifts from that baseline by more than ``band`` in
    either direction. The unmodeled overhead cancels in the
    ratio-of-ratios: a 10x calibration error (or a hardware regime
    change of the same size) surfaces as ~10x regardless of how much
    overhead pads the estimator. Startup miscalibration is the
    autotuner's job (`--autotune` races and refits before epoch 0); this
    channel guards the model's truthfulness AFTER that point.
  * **step trend** (`kind='step_trend'`): an EWMA of the window step time
    versus a baseline window frozen at detector start (or last reset) —
    the live "this job got slower" signal, whatever the cause.

Alarms carry hysteresis on both edges — ``hysteresis`` consecutive
out-of-band observations to raise, the same count in-band to clear — so a
noisy boundary can never flap the alarm (pinned by the unit tests).

The trainer consumes the returned `DriftAlarm`s: each becomes a
``drift_alarm`` telemetry event (and thereby a gauge on /metrics), and —
behind ``MGWFBP_DRIFT_REAUTOTUNE=1`` — a raised comm-residual alarm
triggers a forced re-autotune through the existing hot-swap seam
(`Trainer._swap_reducer` via `Trainer.autotune(force=True)`); on a
multi-host group the trigger rides `coordination.agree_any` so every
process enters the lockstep race together.

`StragglerDetector` is the multi-host sibling: per agree-interval the
group gathers its window step times (`coordination.gather_values`), and a
process consistently slower than the fastest by more than
``MGWFBP_STRAGGLER_BAND`` raises a ``straggler`` alarm naming it.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

_ENV_BAND = "MGWFBP_DRIFT_BAND"
_ENV_TREND_BAND = "MGWFBP_DRIFT_TREND_BAND"
_ENV_WINDOW = "MGWFBP_DRIFT_WINDOW"
_ENV_HYSTERESIS = "MGWFBP_DRIFT_HYSTERESIS"
_ENV_EWMA = "MGWFBP_DRIFT_EWMA_ALPHA"
_ENV_REAUTOTUNE = "MGWFBP_DRIFT_REAUTOTUNE"
_ENV_STRAGGLER_BAND = "MGWFBP_STRAGGLER_BAND"
_ENV_STRAGGLER_MIN = "MGWFBP_STRAGGLER_MIN_EXCESS_S"


def _env_float(name: str, default: float) -> float:
    raw = (os.environ.get(name) or "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not a number") from None


@dataclasses.dataclass(frozen=True)
class DriftConfig:
    """Detector thresholds. ``band`` is the comm-residual ratio band
    (alarm when predicted/measured leaves [1/band, band]; <= 0 disables
    the comm detector), ``trend_band`` the step-trend excess fraction
    (alarm when ewma > baseline * (1 + trend_band); <= 0 disables),
    ``baseline_window`` how many observations freeze the trend baseline,
    ``hysteresis`` the consecutive out-of-band (and, symmetrically,
    in-band) observations required to raise (clear) an alarm."""

    band: float = 3.0
    trend_band: float = 0.5
    baseline_window: int = 5
    ewma_alpha: float = 0.3
    hysteresis: int = 2
    straggler_band: float = 0.25
    # absolute floor on the straggler excess: the probed local busy time
    # (host prep) is small, so a purely relative band would alarm on
    # millisecond noise between healthy hosts
    straggler_min_excess_s: float = 0.02

    @classmethod
    def from_env(cls) -> "DriftConfig":
        base = cls()
        return cls(
            band=_env_float(_ENV_BAND, base.band),
            trend_band=_env_float(_ENV_TREND_BAND, base.trend_band),
            baseline_window=max(
                int(_env_float(_ENV_WINDOW, base.baseline_window)), 1
            ),
            ewma_alpha=min(
                max(_env_float(_ENV_EWMA, base.ewma_alpha), 0.01), 1.0
            ),
            hysteresis=max(
                int(_env_float(_ENV_HYSTERESIS, base.hysteresis)), 1
            ),
            straggler_band=_env_float(
                _ENV_STRAGGLER_BAND, base.straggler_band
            ),
            straggler_min_excess_s=_env_float(
                _ENV_STRAGGLER_MIN, base.straggler_min_excess_s
            ),
        )


def reautotune_enabled(environ=None) -> bool:
    return (environ or os.environ).get(_ENV_REAUTOTUNE) == "1"


@dataclasses.dataclass(frozen=True)
class DriftAlarm:
    """One alarm edge: ``active=True`` raises, ``False`` clears. Maps 1:1
    onto the ``drift_alarm`` telemetry event."""

    kind: str  # 'comm_residual' | 'step_trend'
    residual: float  # ratio (comm) or excess fraction (trend) at the edge
    band: float
    active: bool
    group: int = -1  # arrival-order merge group, -1 = aggregate


class Hysteresis:
    """Two-edge debounce: `k` consecutive True updates raise, `k`
    consecutive False updates clear; anything else holds the current
    state. Returns the edge ('raise' / 'clear') or None."""

    def __init__(self, k: int):
        self.k = max(int(k), 1)
        self.active = False
        self._over = 0
        self._under = 0

    def update(self, exceeded: bool) -> Optional[str]:
        if exceeded:
            self._over += 1
            self._under = 0
        else:
            self._under += 1
            self._over = 0
        if not self.active and self._over >= self.k:
            self.active = True
            return "raise"
        if self.active and self._under >= self.k:
            self.active = False
            return "clear"
        return None


class DriftDetector:
    """Rolling predicted-vs-measured residuals + EWMA step-time trend.

    Feed one call per observation window (the trainer uses its log
    window). All inputs are plain host floats; every method is cheap
    enough for the step loop's logging cadence."""

    def __init__(self, config: Optional[DriftConfig] = None):
        self.config = config or DriftConfig.from_env()
        self.reset()

    def reset(self) -> None:
        """Forget baselines and alarm state — called after a re-autotune
        installs a corrected model (the old residuals described the old
        model) and at construction."""
        c = self.config
        self._trend_hyst = Hysteresis(c.hysteresis)
        self._comm_hyst: dict[int, Hysteresis] = {}
        self._baseline: list[float] = []
        self._baseline_mean: Optional[float] = None
        self._ewma: Optional[float] = None
        self._ratio_baseline: list[float] = []
        self._ratio_baseline_mean: Optional[float] = None

    @property
    def active(self) -> bool:
        return self._trend_hyst.active or any(
            h.active for h in self._comm_hyst.values()
        )

    def clear_alarms(self) -> list[DriftAlarm]:
        """Clear-edges for every currently-active alarm (residual at the
        neutral value). Emit these BEFORE `reset()` when the alarm state
        is being resolved out-of-band (a re-autotune installed a
        corrected model) — a bare reset would leave the raised alarms
        active forever in every consumer of the event stream."""
        out = []
        if self._trend_hyst.active:
            out.append(DriftAlarm(
                kind="step_trend", residual=0.0,
                band=float(self.config.trend_band), active=False,
            ))
        for gi, h in self._comm_hyst.items():
            if h.active:
                out.append(DriftAlarm(
                    kind="comm_residual", residual=1.0,
                    band=float(self.config.band), active=False, group=gi,
                ))
        return out

    # -- step-time trend ---------------------------------------------------
    def observe_step_window(self, step_s: float) -> list[DriftAlarm]:
        """One measured window-mean step time. The first
        ``baseline_window`` observations freeze the baseline; after that
        the EWMA is compared against baseline * (1 + trend_band)."""
        c = self.config
        if c.trend_band <= 0 or step_s <= 0.0:
            return []
        if self._baseline_mean is None:
            self._baseline.append(float(step_s))
            if len(self._baseline) >= c.baseline_window:
                self._baseline_mean = sum(self._baseline) / len(
                    self._baseline
                )
            return []
        self._ewma = (
            float(step_s)
            if self._ewma is None
            else c.ewma_alpha * float(step_s)
            + (1.0 - c.ewma_alpha) * self._ewma
        )
        excess = self._ewma / self._baseline_mean - 1.0
        edge = self._trend_hyst.update(excess > c.trend_band)
        if edge is None:
            return []
        return [DriftAlarm(
            kind="step_trend", residual=float(excess),
            band=float(c.trend_band), active=(edge == "raise"),
        )]

    # -- comm residuals ----------------------------------------------------
    def observe_comm(
        self,
        predicted_s: Sequence[float],
        measured_s: Optional[Sequence[float]] = None,
        measured_total_s: Optional[float] = None,
    ) -> list[DriftAlarm]:
        """Predicted per-group comm seconds vs a measured attribution.

        ``measured_s`` (trace-attributed, per group) checks each group's
        ratio ABSOLUTELY, both sides of the band — a direct measurement
        refutes the prediction outright. Without it, ``measured_total_s``
        must be the measured non-backward step share (step - tb); that
        estimator carries unmodeled forward/dispatch overhead, so the
        aggregate (group=-1) channel learns the healthy
        predicted/measured ratio over the first ``baseline_window``
        observations and alarms when the CURRENT ratio drifts from the
        baseline by more than ``band`` either way — the residual reported
        is the drift FACTOR (current ratio / baseline ratio).
        """
        c = self.config
        if c.band <= 0 or not len(predicted_s):
            return []
        alarms: list[DriftAlarm] = []
        if measured_s is not None and len(measured_s) == len(predicted_s):
            for gi, (p, m) in enumerate(zip(predicted_s, measured_s)):
                m = float(m)
                if m <= 0.0:
                    continue
                ratio = float(p) / m
                hyst = self._comm_hyst.setdefault(
                    gi, Hysteresis(c.hysteresis)
                )
                edge = hyst.update(ratio > c.band or ratio < 1.0 / c.band)
                if edge is not None:
                    alarms.append(DriftAlarm(
                        kind="comm_residual", residual=float(ratio),
                        band=float(c.band), active=(edge == "raise"),
                        group=gi,
                    ))
            return alarms
        if measured_total_s is None or measured_total_s <= 0.0:
            return []
        ratio = float(sum(float(p) for p in predicted_s)) / float(
            measured_total_s
        )
        if self._ratio_baseline_mean is None:
            self._ratio_baseline.append(ratio)
            if len(self._ratio_baseline) >= c.baseline_window:
                self._ratio_baseline_mean = sum(self._ratio_baseline) / len(
                    self._ratio_baseline
                )
            return []
        if self._ratio_baseline_mean <= 0.0:
            return []
        rel = ratio / self._ratio_baseline_mean
        hyst = self._comm_hyst.setdefault(-1, Hysteresis(c.hysteresis))
        edge = hyst.update(rel > c.band or rel < 1.0 / c.band)
        if edge is not None:
            alarms.append(DriftAlarm(
                kind="comm_residual", residual=float(rel),
                band=float(c.band), active=(edge == "raise"), group=-1,
            ))
        return alarms


@dataclasses.dataclass(frozen=True)
class StragglerAlarm:
    """One straggler edge; maps onto the ``straggler`` event (the slow
    process is named `slow_process` — the merge tool owns the `process`
    key for the emitting stream)."""

    slow_process: int
    excess_s: float
    step_s_max: float
    step_s_min: float
    active: bool


class StragglerDetector:
    """Excess monitor over the group's gathered per-process local busy
    times: alarm when the slowest exceeds the fastest BOTH relatively
    (by more than ``band``) and absolutely (by more than
    ``min_excess_s`` — the probed signal is host-side prep time, small
    enough that a purely relative band would alarm on ms noise) for
    ``hysteresis`` consecutive probes; clears symmetrically."""

    def __init__(
        self, band: float, hysteresis: int = 2,
        min_excess_s: float = 0.02,
    ):
        self.band = float(band)
        self.min_excess_s = float(min_excess_s)
        self._hyst = Hysteresis(hysteresis)
        self._raised_proc: Optional[int] = None

    @property
    def active(self) -> bool:
        return self._hyst.active

    def observe(self, step_times: Sequence[float]) -> Optional[
        StragglerAlarm
    ]:
        times = [float(t) for t in step_times]
        if self.band <= 0 or len(times) < 2 or min(times) <= 0.0:
            return None
        fastest = min(times)
        slowest = max(times)
        slow_idx = max(range(len(times)), key=lambda i: times[i])
        edge = self._hyst.update(
            (slowest - fastest) / fastest > self.band
            and slowest - fastest > self.min_excess_s
        )
        if edge is None:
            return None
        if edge == "raise":
            self._raised_proc = int(slow_idx)
        # a clear edge resolves the RAISED alarm: name the process that
        # alarm named, not whichever healthy process happens to argmax
        # the now-near-equal probe — raise and clear rows must pair up
        # for anyone reading the stream
        named = (
            int(slow_idx) if edge == "raise"
            else int(self._raised_proc if self._raised_proc is not None
                     else slow_idx)
        )
        if edge == "clear":
            self._raised_proc = None
        return StragglerAlarm(
            slow_process=named,
            excess_s=float(slowest - fastest),
            step_s_max=float(slowest),
            step_s_min=float(fastest),
            active=(edge == "raise"),
        )
