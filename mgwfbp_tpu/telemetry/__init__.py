"""Run-observability subsystem: typed event stream, overlap-efficiency
accounting, Chrome-trace / Prometheus export, and the LIVE plane.

Every layer feeds one append-only, schema-versioned JSONL stream per run
(`telemetry/events.py`); `telemetry/overlap.py` turns per-group comm times
(trace-attributed or cost-model-predicted) into the paper's exposed-vs-
hidden accounting; `telemetry/export.py` renders the stream for Perfetto
and Prometheus (one metric registry shared with the live endpoint);
`telemetry/serve.py` serves /metrics, /healthz and /status per process
from an in-memory aggregator fed by the same stream;
`telemetry/drift.py` watches predicted-vs-measured cost-model residuals
and the multi-host straggler signal; `tools/telemetry_report.py` prints
the human summary.
"""

from mgwfbp_tpu.telemetry.drift import (
    DriftAlarm,
    DriftConfig,
    DriftDetector,
    StragglerDetector,
)
from mgwfbp_tpu.telemetry.health import (
    HealthAlarm,
    HealthConfig,
    HealthDetector,
)
from mgwfbp_tpu.telemetry.recorder import (
    FlightRecorder,
    list_bundles,
    read_bundle,
    tee_observers,
)
from mgwfbp_tpu.telemetry.events import (
    EVENT_SCHEMA_VERSION,
    EVENT_TYPES,
    EventWriter,
    events_of,
    find_stream_paths,
    read_event_set,
    read_events,
    stream_filename,
)
from mgwfbp_tpu.telemetry.fleet import (
    ChildScrape,
    FleetServer,
    fleet_status,
    render_fleet_metrics,
    scrape_fleet,
    start_fleet_server,
    write_fleet_sd,
)
from mgwfbp_tpu.telemetry.overlap import (
    GroupOverlap,
    OverlapSummary,
    attribute_overlap,
    group_comm_times,
    summarize,
)
from mgwfbp_tpu.telemetry.serve import (
    MetricsAggregator,
    TelemetryServer,
    start_metrics_server,
)

__all__ = [
    "DriftAlarm",
    "DriftConfig",
    "DriftDetector",
    "StragglerDetector",
    "HealthAlarm",
    "HealthConfig",
    "HealthDetector",
    "FlightRecorder",
    "list_bundles",
    "read_bundle",
    "tee_observers",
    "ChildScrape",
    "FleetServer",
    "fleet_status",
    "render_fleet_metrics",
    "scrape_fleet",
    "start_fleet_server",
    "write_fleet_sd",
    "MetricsAggregator",
    "TelemetryServer",
    "start_metrics_server",
    "EVENT_SCHEMA_VERSION",
    "EVENT_TYPES",
    "EventWriter",
    "events_of",
    "find_stream_paths",
    "read_event_set",
    "read_events",
    "stream_filename",
    "GroupOverlap",
    "OverlapSummary",
    "attribute_overlap",
    "group_comm_times",
    "summarize",
]
