"""Run-observability subsystem: typed event stream, overlap-efficiency
accounting, Chrome-trace / Prometheus export.

Every layer feeds one append-only, schema-versioned JSONL stream per run
(`telemetry/events.py`); `telemetry/overlap.py` turns per-group comm times
(trace-attributed or cost-model-predicted) into the paper's exposed-vs-
hidden accounting; `telemetry/export.py` renders the stream for Perfetto
and Prometheus; `tools/telemetry_report.py` prints the human summary.
"""

from mgwfbp_tpu.telemetry.events import (
    EVENT_SCHEMA_VERSION,
    EVENT_TYPES,
    EventWriter,
    events_of,
    find_stream_paths,
    read_event_set,
    read_events,
    stream_filename,
)
from mgwfbp_tpu.telemetry.overlap import (
    GroupOverlap,
    OverlapSummary,
    attribute_overlap,
    group_comm_times,
    summarize,
)

__all__ = [
    "EVENT_SCHEMA_VERSION",
    "EVENT_TYPES",
    "EventWriter",
    "events_of",
    "find_stream_paths",
    "read_event_set",
    "read_events",
    "stream_filename",
    "GroupOverlap",
    "OverlapSummary",
    "attribute_overlap",
    "group_comm_times",
    "summarize",
]
