"""Online training-health detection (the model-watching sibling of
`telemetry/drift.py`).

The observability plane so far watches the MACHINE — comm drift,
stragglers, device time. Nothing watched the MODEL: a loss spike, a
gradient explosion, or a plateau is invisible until a human reads scalars
post-hoc, by which point the evidence is gone. This module consumes the
per-step `health` statistics the jitted step packs into its EXISTING
metrics psum (train/step.py — zero extra collectives, read one step late
through the PR-5 deque idiom) and raises schema-versioned
``health_alarm`` edges:

  * **loss spike** (`kind='loss_spike'`): the step loss versus its own
    EWMA — alarm when loss exceeds ``spike_band`` times the smoothed
    trend (a non-finite loss always counts as exceeded: NaN comparisons
    are False, which would otherwise make the worst failure invisible).
  * **gradient explosion** (`kind='grad_explosion'`): the global gradient
    L2 norm versus a baseline frozen over the first ``baseline_window``
    observations — alarm when the norm exceeds ``explosion_band`` times
    the healthy baseline (non-finite norms count as exceeded).
  * **plateau** (`kind='plateau'`): no relative loss improvement better
    than ``plateau_delta`` for ``plateau_window`` consecutive
    observations — the "this run stopped learning" signal.
  * **compression error** (`kind='compression_error'`): when a
    sparsifying compressor is live, the worst per-group relative top-k
    error versus its frozen baseline — the ROADMAP compression item's
    convergence guard (DeAR, arXiv:2302.12445: compression wins only
    hold while convergence is monitored).

Every channel sits behind the same two-edge `Hysteresis` the drift
detector uses — ``hysteresis`` consecutive out-of-band observations to
raise, the same count in-band to clear — so a noisy boundary can never
flap an alarm. All inputs are plain host floats at the guard-drain
cadence; nothing here may ever touch a device value.

The trainer consumes the returned `HealthAlarm`s: each becomes a
``health_alarm`` telemetry event (thereby an active alarm on /status and
/fleet/status, a counter on /metrics, and — through the flight recorder
tee — a postmortem-bundle trigger, telemetry/recorder.py).
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Optional, Sequence

from mgwfbp_tpu.telemetry.drift import Hysteresis, _env_float

_ENV_ENABLE = "MGWFBP_HEALTH"
_ENV_SPIKE_BAND = "MGWFBP_HEALTH_SPIKE_BAND"
_ENV_EXPLOSION_BAND = "MGWFBP_HEALTH_EXPLOSION_BAND"
_ENV_PLATEAU_WINDOW = "MGWFBP_HEALTH_PLATEAU_WINDOW"
_ENV_PLATEAU_DELTA = "MGWFBP_HEALTH_PLATEAU_DELTA"
_ENV_WINDOW = "MGWFBP_HEALTH_WINDOW"
_ENV_EWMA = "MGWFBP_HEALTH_EWMA_ALPHA"
_ENV_HYSTERESIS = "MGWFBP_HEALTH_HYSTERESIS"
_ENV_COMPRESSION_BAND = "MGWFBP_HEALTH_COMPRESSION_BAND"


def health_enabled(environ=None) -> bool:
    """The detector master switch (MGWFBP_HEALTH; default on — the
    statistics stream regardless, this gates only the alarm logic)."""
    return (environ or os.environ).get(_ENV_ENABLE, "1") != "0"


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Detector thresholds. ``spike_band`` is the loss/EWMA ratio that
    raises a loss-spike alarm (<= 0 disables the channel);
    ``explosion_band`` the grad-norm/baseline ratio (<= 0 disables);
    ``plateau_window`` how many consecutive no-improvement observations
    raise a plateau (0 disables), ``plateau_delta`` the relative loss
    improvement that resets the window; ``baseline_window`` how many
    observations freeze the grad-norm/compression baselines;
    ``hysteresis`` the consecutive out-of-band (and symmetrically
    in-band) observations required to raise (clear) any alarm;
    ``compression_band`` the compression-error/baseline ratio (<= 0
    disables)."""

    spike_band: float = 2.0
    explosion_band: float = 10.0
    plateau_window: int = 200
    plateau_delta: float = 1e-3
    baseline_window: int = 10
    ewma_alpha: float = 0.1
    hysteresis: int = 2
    compression_band: float = 1.5

    @classmethod
    def from_env(cls) -> "HealthConfig":
        base = cls()
        return cls(
            spike_band=_env_float(_ENV_SPIKE_BAND, base.spike_band),
            explosion_band=_env_float(
                _ENV_EXPLOSION_BAND, base.explosion_band
            ),
            plateau_window=max(
                int(_env_float(_ENV_PLATEAU_WINDOW, base.plateau_window)), 0
            ),
            plateau_delta=_env_float(_ENV_PLATEAU_DELTA, base.plateau_delta),
            baseline_window=max(
                int(_env_float(_ENV_WINDOW, base.baseline_window)), 1
            ),
            ewma_alpha=min(
                max(_env_float(_ENV_EWMA, base.ewma_alpha), 0.01), 1.0
            ),
            hysteresis=max(
                int(_env_float(_ENV_HYSTERESIS, base.hysteresis)), 1
            ),
            compression_band=_env_float(
                _ENV_COMPRESSION_BAND, base.compression_band
            ),
        )


@dataclasses.dataclass(frozen=True)
class HealthAlarm:
    """One alarm edge: ``active=True`` raises, ``False`` clears. Maps 1:1
    onto the ``health_alarm`` telemetry event."""

    kind: str  # 'loss_spike' | 'grad_explosion' | 'plateau' |
    # 'compression_error'
    value: float  # the residual ratio (or plateau observation count)
    band: float
    active: bool
    group: int = -1  # reserved for per-group channels; -1 = aggregate


def _finite(v: float) -> bool:
    return isinstance(v, (int, float)) and math.isfinite(v)


class HealthDetector:
    """Loss-spike EWMA, grad-norm explosion band, plateau window, and the
    compression-error trend — one `observe` call per drained step.

    The statistics arrive one step late (the trainer's health deque) and
    are already globally reduced (they rode the step's metrics psum), so
    every process of a group observes identical values and raises
    identical edges — no agreement collective is needed."""

    def __init__(self, config: Optional[HealthConfig] = None):
        self.config = config or HealthConfig.from_env()
        self.reset()

    def reset(self) -> None:
        """Forget baselines and alarm state (construction, and after a
        rollback restores an older model whose statistics the learned
        baselines no longer describe)."""
        c = self.config
        self._spike_hyst = Hysteresis(c.hysteresis)
        self._explosion_hyst = Hysteresis(c.hysteresis)
        self._plateau_hyst = Hysteresis(c.hysteresis)
        self._compression_hyst = Hysteresis(c.hysteresis)
        self._loss_ewma: Optional[float] = None
        self._norm_baseline: list[float] = []
        self._norm_baseline_mean: Optional[float] = None
        self._best_loss: Optional[float] = None
        self._since_improvement = 0
        self._comp_baseline: list[float] = []
        self._comp_baseline_mean: Optional[float] = None
        self._comp_ewma: Optional[float] = None

    @property
    def active(self) -> bool:
        return any(
            h.active
            for h in (
                self._spike_hyst, self._explosion_hyst,
                self._plateau_hyst, self._compression_hyst,
            )
        )

    def clear_alarms(self) -> list[HealthAlarm]:
        """Clear-edges for every currently-active alarm (neutral values).
        Emit these BEFORE `reset()` when the state is resolved
        out-of-band (a rollback restored a healthy model) — a bare reset
        would leave raised alarms active forever in every stream
        consumer."""
        c = self.config
        out = []
        for hyst, kind, band in (
            (self._spike_hyst, "loss_spike", c.spike_band),
            (self._explosion_hyst, "grad_explosion", c.explosion_band),
            (self._plateau_hyst, "plateau", float(c.plateau_window)),
            (self._compression_hyst, "compression_error",
             c.compression_band),
        ):
            if hyst.active:
                out.append(HealthAlarm(
                    kind=kind, value=0.0, band=float(band), active=False,
                ))
        return out

    def observe(
        self,
        loss: float,
        grad_norm: float,
        compression_errors: Optional[Sequence[float]] = None,
    ) -> list[HealthAlarm]:
        """One drained step's health statistics -> alarm edges (possibly
        several channels at once — a NaN loss usually trips loss_spike
        and grad_explosion together)."""
        out: list[HealthAlarm] = []
        out.extend(self._observe_loss(float(loss)))
        out.extend(self._observe_norm(float(grad_norm)))
        out.extend(self._observe_plateau(float(loss)))
        if compression_errors:
            out.extend(self._observe_compression(
                max(float(e) for e in compression_errors)
            ))
        return out

    # -- loss spike --------------------------------------------------------
    def _observe_loss(self, loss: float) -> list[HealthAlarm]:
        c = self.config
        if c.spike_band <= 0:
            return []
        if self._loss_ewma is None:
            if _finite(loss):
                self._loss_ewma = loss
            return []
        denom = max(abs(self._loss_ewma), 1e-12)
        if _finite(loss):
            ratio = loss / denom
            exceeded = ratio > c.spike_band
        else:
            # NaN/inf loss: comparisons are False, which would make the
            # WORST spike invisible — force the exceeded edge
            ratio = float("inf")
            exceeded = True
        edge = self._spike_hyst.update(exceeded)
        if _finite(loss):
            # the EWMA tracks the healthy trend only: folding a spike in
            # would teach the baseline that spikes are normal
            if not exceeded:
                self._loss_ewma = (
                    c.ewma_alpha * loss
                    + (1.0 - c.ewma_alpha) * self._loss_ewma
                )
        if edge is None:
            return []
        return [HealthAlarm(
            kind="loss_spike", value=float(ratio),
            band=float(c.spike_band), active=(edge == "raise"),
        )]

    # -- gradient explosion ------------------------------------------------
    def _observe_norm(self, norm: float) -> list[HealthAlarm]:
        c = self.config
        if c.explosion_band <= 0:
            return []
        if self._norm_baseline_mean is None:
            if _finite(norm):
                if norm > 0.0:
                    self._norm_baseline.append(norm)
                    if len(self._norm_baseline) >= c.baseline_window:
                        self._norm_baseline_mean = sum(
                            self._norm_baseline
                        ) / len(self._norm_baseline)
                # a finite pre-baseline norm is an in-band observation:
                # it must be able to CLEAR a pre-baseline non-finite
                # raise, not leave it stuck until the baseline freezes
                edge = self._explosion_hyst.update(False)
                value = 1.0
            else:
                # a non-finite norm before the baseline froze is still an
                # explosion — alarm on it rather than waiting for a
                # baseline that a NaN-wedged run will never produce
                edge = self._explosion_hyst.update(True)
                value = float("inf")
            if edge is not None:
                return [HealthAlarm(
                    kind="grad_explosion", value=value,
                    band=float(c.explosion_band),
                    active=(edge == "raise"),
                )]
            return []
        if _finite(norm):
            ratio = norm / max(self._norm_baseline_mean, 1e-30)
            exceeded = ratio > c.explosion_band
        else:
            ratio = float("inf")
            exceeded = True
        edge = self._explosion_hyst.update(exceeded)
        if edge is None:
            return []
        return [HealthAlarm(
            kind="grad_explosion", value=float(ratio),
            band=float(c.explosion_band), active=(edge == "raise"),
        )]

    # -- plateau -----------------------------------------------------------
    def _observe_plateau(self, loss: float) -> list[HealthAlarm]:
        c = self.config
        if c.plateau_window <= 0:
            return []
        if not _finite(loss):
            return []  # a NaN loss is loss_spike's problem, not stagnation
        if self._best_loss is None:
            self._best_loss = loss
            self._since_improvement = 0
            return []
        improved = loss < self._best_loss - c.plateau_delta * max(
            abs(self._best_loss), 1e-12
        )
        if improved:
            self._best_loss = loss
            self._since_improvement = 0
        else:
            self._since_improvement += 1
        edge = self._plateau_hyst.update(
            self._since_improvement >= c.plateau_window
        )
        if edge is None:
            return []
        return [HealthAlarm(
            kind="plateau", value=float(self._since_improvement),
            band=float(c.plateau_window), active=(edge == "raise"),
        )]

    # -- compression-error trend ---------------------------------------------
    def _observe_compression(self, err: float) -> list[HealthAlarm]:
        """Worst per-group relative top-k error vs its frozen baseline —
        a drifting error means the sparsifier is discarding a growing
        gradient share and convergence is at risk (the ROADMAP
        compression item's guard, landed ahead of the scheduling work)."""
        c = self.config
        if c.compression_band <= 0 or not _finite(err):
            return []
        self._comp_ewma = (
            err if self._comp_ewma is None
            else c.ewma_alpha * err + (1.0 - c.ewma_alpha) * self._comp_ewma
        )
        if self._comp_baseline_mean is None:
            self._comp_baseline.append(err)
            if len(self._comp_baseline) >= c.baseline_window:
                self._comp_baseline_mean = sum(self._comp_baseline) / len(
                    self._comp_baseline
                )
            return []
        ratio = self._comp_ewma / max(self._comp_baseline_mean, 1e-30)
        edge = self._compression_hyst.update(ratio > c.compression_band)
        if edge is None:
            return []
        return [HealthAlarm(
            kind="compression_error", value=float(ratio),
            band=float(c.compression_band), active=(edge == "raise"),
        )]
