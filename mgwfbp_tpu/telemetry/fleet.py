"""Fleet observability fan-in: one group-level view over the per-process
live planes (ISSUE 10, closing ROADMAP live-observability follow-up (c)).

PR 9's plane is strictly per-process: each training process serves its
own /metrics, /healthz, /status (`telemetry/serve.py`). The operator of a
supervised multi-process job wants ONE place to ask "which host is slow,
what alarms are up, is the group healthy" — live, not post-hoc from
merged JSONL. The supervisor already knows every child's metrics
endpoint (the port-file sidecars cover even ephemeral `MGWFBP_METRICS_PORT=0`
binds), so it serves the fan-in:

  /fleet/metrics   every child's /metrics scraped, parsed back through
                   the shared registry (`export.parse_metrics_text`), and
                   re-rendered merged under a ``process`` label
                   (`export.render_labeled_metrics`) plus fleet-level
                   gauges — ONE registry end to end, so the fleet render
                   and the per-process render cannot drift;
  /fleet/status    JSON: every child's /status document, a LIVE straggler
                   table (per-process mean step seconds, excess vs the
                   fastest — `tools/telemetry_merge.py`'s
                   mean-excess-vs-fastest semantics over the live rolling
                   window instead of merged spans), the slowest-process
                   attribution, the union of active drift/straggler
                   alarms across the group (each tagged with its emitting
                   process), the per-process deep-profiling window table
                   (each child's /profile state machine + last result),
                   and the unreachable list;
  /fleet/profile   ``?steps=N`` fans the per-process /profile?steps=N arm
                   out to EVERY child in one call (ISSUE 11, the ROADMAP
                   fleet seam) — per-child timeouts, per-child outcome in
                   the response; without a query, the aggregated
                   per-process window table alone.

Every child scrape carries a HARD timeout and the children are scraped
concurrently, so one wedged child makes the fan-in report it unreachable
— never hang the fan-in (a hang here must fail `tools/check.sh`'s smoke,
not wedge it).

`write_fleet_sd` persists the scrape targets in Prometheus HTTP service
discovery (`http_sd` / file_sd) format, so an external Prometheus can
consume `fleet.json` directly (README "Live observability").
"""

from __future__ import annotations

import json
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

from mgwfbp_tpu.utils.logging import get_logger

# per-child scrape budget; the fan-in request as a whole is bounded by
# this (children are scraped concurrently), so a dead or wedged child
# costs one timeout, not a hang
SCRAPE_TIMEOUT_S = 2.0

# targets map: process key -> (host, port). Training children are keyed
# by int process index; serving replicas (ISSUE 19) ride under str keys
# ("serve0", "serve1", ...) so the same map carries both roles.
TargetMap = Dict[object, Tuple[str, int]]


@dataclass
class ChildScrape:
    """One child's scraped live state (best-effort: `error` records a
    failed/timed-out scrape; a child with `status` answered)."""

    process: object  # int training index or "serve<i>" replica key
    host: str
    port: int
    status: Optional[dict] = None
    values: dict = field(default_factory=dict)
    error: Optional[str] = None

    @property
    def reachable(self) -> bool:
        return self.status is not None


def _http_get(url: str, timeout_s: float) -> str:
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return resp.read().decode()


def scrape_child(
    process: int, host: str, port: int,
    timeout_s: float = SCRAPE_TIMEOUT_S,
) -> ChildScrape:
    """Fetch one child's /status + /metrics; failures land in `.error`,
    never raise — a dead child is a REPORT, not a fan-in failure."""
    from mgwfbp_tpu.telemetry.export import parse_metrics_text

    out = ChildScrape(process=process, host=host, port=port)
    base = f"http://{host}:{port}"
    try:
        out.status = json.loads(_http_get(f"{base}/status", timeout_s))
    except Exception as e:  # noqa: BLE001 — refused/timeout are expected
        out.error = f"/status: {e}"
        return out
    try:
        out.values = parse_metrics_text(
            _http_get(f"{base}/metrics", timeout_s)
        )
    except Exception as e:  # noqa: BLE001 — half-scraped beats hung
        out.error = f"/metrics: {e}"
    return out


def scrape_fleet(
    targets: TargetMap, timeout_s: float = SCRAPE_TIMEOUT_S,
) -> list[ChildScrape]:
    """Scrape every target concurrently (process order in the result).
    Total wall time is bounded by ~one scrape budget, not targets * budget
    — the hard-timeout contract the check.sh smoke pins."""
    if not targets:
        return []
    # mixed int/str keys (training children + serve replicas) sort by
    # their string form — a plain sorted() would TypeError on int vs str
    items = sorted(targets.items(), key=lambda kv: str(kv[0]))
    with ThreadPoolExecutor(max_workers=min(len(items), 16)) as pool:
        futs = [
            pool.submit(scrape_child, idx, host, port, timeout_s)
            for idx, (host, port) in items
        ]
        return [f.result() for f in futs]


def straggler_table(children: list[ChildScrape]) -> list[dict]:
    """LIVE analog of `tools/telemetry_merge.straggler_table`: one row per
    reachable child with a step-seconds window gauge, its excess over the
    fastest process (the group-synchronous cost it adds — the merge
    tool's mean-excess-vs-fastest semantics applied to the live rolling
    `mgwfbp_step_seconds` window instead of merged post-hoc spans)."""
    rows = []
    for c in children:
        if not c.reachable:
            continue
        step_s = c.values.get("mgwfbp_step_seconds")
        if step_s is None:
            continue
        rows.append({
            "process": c.process,
            "step": c.values.get("mgwfbp_current_step"),
            "steps_total": c.values.get("mgwfbp_steps_total", 0),
            "mean_step_s": float(step_s),
            "overlap_efficiency": c.values.get(
                "mgwfbp_overlap_efficiency"
            ),
        })
    if not rows:
        return rows
    fastest = min(r["mean_step_s"] for r in rows)
    for r in rows:
        r["excess_s"] = r["mean_step_s"] - fastest
        r["excess_pct"] = (
            (r["mean_step_s"] / fastest - 1.0) * 100.0
            if fastest > 0 else 0.0
        )
    return rows


def active_alarms(children: list[ChildScrape]) -> list[dict]:
    """Union of the group's active drift/straggler alarms, each tagged
    with the process whose stream raised it (a straggler alarm is
    group-agreed so every child reports it; dedup keeps one copy, listing
    the reporting processes)."""
    merged: dict = {}
    for c in children:
        if not c.reachable:
            continue
        for a in (c.status or {}).get("active_alarms", []):
            key = json.dumps(
                {k: a.get(k) for k in ("alarm", "kind", "group",
                                       "slow_process")},
                sort_keys=True,
            )
            row = merged.setdefault(key, dict(a, processes=[]))
            row["processes"].append(c.process)
    return sorted(
        merged.values(),
        key=lambda r: (str(r.get("alarm")), str(r.get("kind", ""))),
    )


def fleet_postmortems(children: list[ChildScrape]) -> list[dict]:
    """One row per reachable child that has written flight-recorder
    postmortem bundles (telemetry/recorder.py): bundle count + the recent
    manifests its /status reports — the fleet-wide postmortem index. An
    operator chasing a group-wide anomaly reads ONE endpoint and gets
    every process's evidence paths."""
    rows = []
    for c in children:
        if not c.reachable:
            continue
        pm = (c.status or {}).get("postmortems") or {}
        total = int(pm.get("total") or 0)
        if total <= 0:
            continue
        rows.append({
            "process": c.process,
            "total": total,
            "recent": pm.get("recent") or [],
        })
    return rows


def profile_windows(children: list[ChildScrape]) -> list[dict]:
    """One row per reachable child: its /profile window state machine
    (idle/armed/running/done/failed) and, when a window completed, the
    attribution + per-group table the child posted — the fleet-level view
    of PR 10's on-demand deep profiling."""
    rows = []
    for c in children:
        if not c.reachable:
            continue
        prof = (c.status or {}).get("profile") or {}
        row = {
            "process": c.process,
            "supported": prof.get("supported", False),
            "state": prof.get("state", "idle"),
        }
        for k in ("steps", "error"):
            if prof.get(k) is not None:
                row[k] = prof[k]
        result = prof.get("result")
        if result is not None:
            row["result"] = result
        rows.append(row)
    return rows


def arm_fleet_profile(
    targets: TargetMap, steps, timeout_s: float = SCRAPE_TIMEOUT_S,
) -> dict:
    """Fan /profile?steps=N out to every child concurrently (the ROADMAP
    '/fleet/profile' seam: a multi-host profile window is armed per
    process, and the step loop enters it in lockstep at the next
    agree-interval boundary — arming every child in ONE call is what
    makes the lockstep window reachable from outside). Per-child hard
    timeouts; a dead child is an entry in the response, never a hang."""
    steps = int(steps)  # the value is re-spliced into child URLs

    def arm_one(idx: int, host: str, port: int) -> tuple[int, dict]:
        try:
            doc = json.loads(_http_get(
                f"http://{host}:{port}/profile?steps={steps}", timeout_s
            ))
            return idx, {"armed": True, **doc}
        except Exception as e:  # noqa: BLE001 — refused/timeout expected
            return idx, {"armed": False, "error": str(e)}

    out: dict = {"steps": steps, "processes": {}}
    # serve replicas carry str keys; they answer the arm with their own
    # /profile document ("supported": false) like any other child
    items = sorted(targets.items(), key=lambda kv: str(kv[0]))
    if not items:
        return out
    with ThreadPoolExecutor(max_workers=min(len(items), 16)) as pool:
        futs = [
            pool.submit(arm_one, idx, host, port)
            for idx, (host, port) in items
        ]
        for f in futs:
            idx, doc = f.result()
            out["processes"][str(idx)] = doc
    out["armed"] = sum(
        1 for d in out["processes"].values() if d.get("armed")
    )
    return out


def fleet_status(
    children: list[ChildScrape], meta: Optional[dict] = None,
) -> dict:
    """The /fleet/status document."""
    table = straggler_table(children)
    slowest = None
    if table:
        worst = max(table, key=lambda r: r["excess_s"])
        if worst["excess_s"] > 0.0:
            slowest = {
                "process": worst["process"],
                "excess_s": worst["excess_s"],
                "excess_pct": worst["excess_pct"],
            }
    unreachable = [
        {"process": c.process, "target": f"{c.host}:{c.port}",
         "error": c.error}
        for c in children if not c.reachable
    ]
    doc = {
        "processes": {
            str(c.process): c.status for c in children if c.reachable
        },
        "reachable": sum(1 for c in children if c.reachable),
        "unreachable": unreachable,
        "healthy": bool(children) and not unreachable and all(
            (c.status or {}).get("healthy") for c in children if c.reachable
        ),
        "straggler_table": table,
        "slowest_process": slowest,
        "active_alarms": active_alarms(children),
        "profile_windows": profile_windows(children),
        "postmortems": fleet_postmortems(children),
    }
    if meta:
        doc.update(meta)
    return doc


def fleet_metric_values(
    children: list[ChildScrape],
) -> tuple[dict, dict]:
    """(per-process series, fleet-level extras) for
    `export.render_labeled_metrics`."""
    series = {
        str(c.process): c.values for c in children
        if c.reachable and c.values
    }
    table = straggler_table(children)
    extra = {
        "mgwfbp_fleet_processes": sum(1 for c in children if c.reachable),
        "mgwfbp_fleet_unreachable": sum(
            1 for c in children if not c.reachable
        ),
    }
    if table:
        extra["mgwfbp_fleet_straggler_excess_seconds"] = max(
            r["excess_s"] for r in table
        )
    return series, extra


def render_fleet_metrics(children: list[ChildScrape]) -> str:
    from mgwfbp_tpu.telemetry.export import render_labeled_metrics

    series, extra = fleet_metric_values(children)
    return render_labeled_metrics(series, label="process", extra=extra)


def write_fleet_sd(
    path: str, targets: TargetMap, labels: Optional[dict] = None,
    roles: Optional[dict] = None,
) -> list[dict]:
    """Persist the scrape targets in Prometheus HTTP-SD / file-SD format
    (one target group per process, ``process`` + ``role`` labels each),
    atomically. A Prometheus `http_sd_configs`/`file_sd_configs` entry
    pointed at this file scrapes every child without guessing ports
    (README). ``roles`` maps a target key to its role label; targets not
    listed default to ``train``."""
    doc = [
        {
            "targets": [f"{host}:{port}"],
            "labels": {
                "job": "mgwfbp",
                "process": str(idx),
                "role": str((roles or {}).get(idx, "train")),
                **(labels or {}),
            },
        }
        for idx, (host, port) in sorted(
            targets.items(), key=lambda kv: str(kv[0])
        )
    ]
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)
    return doc


class _FleetHandler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        from urllib.parse import parse_qs, urlsplit

        srv: FleetServer = self.server.fleet  # type: ignore[attr-defined]
        split = urlsplit(self.path)
        path = split.path.rstrip("/") or "/"
        try:
            if path == "/fleet/metrics":
                body = srv.render_metrics().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
                code = 200
            elif path == "/fleet/profile":
                query = parse_qs(split.query)
                code = 200
                if "steps" in query:
                    # validate HERE: the raw decoded value is re-spliced
                    # into every child URL, so garbage (or smuggled query
                    # params) must die at the fan-in, not fan out
                    try:
                        steps = int(query["steps"][-1])
                    except ValueError:
                        doc = {"error": "steps must be an integer"}
                        code = 400
                    else:
                        doc = srv.arm_profile(steps)
                else:
                    doc = {"profile_windows": srv.render_profile_windows()}
                body = (json.dumps(doc, indent=1) + "\n").encode()
                ctype = "application/json"
            elif path in ("/fleet/status", "/"):
                body = (
                    json.dumps(srv.render_status(), indent=1) + "\n"
                ).encode()
                ctype = "application/json"
                code = 200
            else:
                body = (
                    b"not found: serve /fleet/metrics, /fleet/status, "
                    b"/fleet/profile\n"
                )
                ctype = "text/plain; charset=utf-8"
                code = 404
        except Exception as e:  # noqa: BLE001 — a scrape bug must answer
            # 500, not kill the handler thread silently
            body = (f"fleet fan-in error: {e}\n").encode()
            ctype = "text/plain; charset=utf-8"
            code = 500
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass


class FleetServer:
    """Background HTTP fan-in over a live target map.

    ``targets_provider`` returns the CURRENT process->endpoint map on
    every request (the supervisor's port files resolve lazily as children
    bind), ``meta_provider`` optional supervisor-level fields for the
    status document. Scrapes run per request with hard per-child
    timeouts; no state is cached — the answer is always the live one."""

    def __init__(
        self,
        targets_provider: Callable[[], TargetMap],
        port: int = 0,
        host: Optional[str] = None,
        scrape_timeout_s: float = SCRAPE_TIMEOUT_S,
        meta_provider: Optional[Callable[[], dict]] = None,
    ):
        # loopback by default, same posture (and env override) as the
        # per-process TelemetryServer
        if host is None:
            from mgwfbp_tpu.telemetry.serve import METRICS_HOST_ENV

            host = os.environ.get(METRICS_HOST_ENV) or "127.0.0.1"
        self._targets_provider = targets_provider
        self._meta_provider = meta_provider
        self.scrape_timeout_s = float(scrape_timeout_s)
        self._httpd = ThreadingHTTPServer((host, int(port)), _FleetHandler)
        self._httpd.daemon_threads = True
        self._httpd.fleet = self  # type: ignore[attr-defined]
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"mgwfbp-fleet:{self.port}",
            daemon=True,
        )
        self._thread.start()

    def _scrape(self) -> list[ChildScrape]:
        return scrape_fleet(
            self._targets_provider(), timeout_s=self.scrape_timeout_s
        )

    def render_metrics(self) -> str:
        return render_fleet_metrics(self._scrape())

    def render_status(self) -> dict:
        meta = self._meta_provider() if self._meta_provider else None
        return fleet_status(self._scrape(), meta=meta)

    def arm_profile(self, steps) -> dict:
        """Fan /profile?steps=N out to every currently-resolvable child
        (one call arms the whole group's lockstep window)."""
        return arm_fleet_profile(
            self._targets_provider(), steps, timeout_s=self.scrape_timeout_s
        )

    def render_profile_windows(self) -> list[dict]:
        return profile_windows(self._scrape())

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        try:
            httpd.shutdown()
            httpd.server_close()
        except Exception:  # noqa: BLE001 — teardown must never raise
            pass
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


def start_fleet_server(
    targets_provider: Callable[[], TargetMap],
    port: Optional[int],
    meta_provider: Optional[Callable[[], dict]] = None,
) -> Optional[FleetServer]:
    """FleetServer with the per-process server's degrade-don't-die
    contract: None when disabled (port None) or the bind fails."""
    if port is None:
        return None
    log = get_logger("mgwfbp.telemetry.fleet")
    try:
        server = FleetServer(
            targets_provider, int(port), meta_provider=meta_provider,
        )
    except OSError as e:
        log.warning(
            "fleet fan-in failed to bind port %s (%s); fleet "
            "observability disabled", port, e,
        )
        return None
    log.info(
        "fleet fan-in: http://%s:%d (/fleet/metrics /fleet/status)",
        server.host, server.port,
    )
    return server
