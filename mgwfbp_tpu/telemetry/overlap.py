"""Overlap-efficiency accounting: exposed vs hidden communication time.

The paper's headline metric, made measurable on any run. MG-WFBP merges
gradients so each bucket's collective starts as soon as its last member's
gradient is ready and rides *behind* the rest of the backward pass
(arXiv:1811.11141); DeAR frames the next wins as reasoning about exactly
which collective time is exposed vs overlapped (arXiv:2302.12445). This
module replays the step timeline the solver reasons about — gradient-ready
times from the per-layer backward profile tb, one serial link occupied by
the merge groups in arrival order — and splits every group's communication
time into

  * **hidden**: the part that executes while backward compute is still
    running (start .. backward end), and
  * **exposed**: the remainder, which lands on the step's critical path.

The aggregate **overlap efficiency** is hidden / total comm — 1.0 when the
schedule hides everything, 0.0 when every byte serializes after backward.

Per-group comm durations come from two attribution sources, combined by
`group_comm_times`:

  * **trace** — `profiling.trace_group_times`: profiler-trace events whose
    op metadata carries the `mgwfbp_groupNNNN` name scope (real TPU; the
    same introspection hook the jaxpr verifier matches on);
  * **cost-model** — the calibrated alpha-beta prediction per bucket
    (`solver.effective_cost_fn`), the fallback on backends whose traces
    drop the name stack (the virtual CPU mesh).

Everything here is pure host arithmetic over already-host data: calling it
adds zero device syncs.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class GroupOverlap:
    """One merge group's share of the replayed step timeline."""

    group: int  # arrival-order group index
    nbytes: int  # bucket payload on the wire
    start_s: float  # link-timeline start (ready[max member], link free)
    comm_s: float  # collective duration (measured or predicted)
    hidden_s: float  # portion overlapping backward compute
    exposed_s: float  # portion after backward end (critical path)


@dataclasses.dataclass(frozen=True)
class OverlapSummary:
    """Per-step overlap accounting for one schedule regime."""

    step_s: float  # measured seconds per optimizer step
    tb_total_s: float  # backward compute total (sum of tb)
    groups: tuple[GroupOverlap, ...]
    attribution: str  # 'trace' | 'cost-model'

    @property
    def comm_s(self) -> float:
        return sum(g.comm_s for g in self.groups)

    @property
    def hidden_s(self) -> float:
        return sum(g.hidden_s for g in self.groups)

    @property
    def exposed_s(self) -> float:
        return sum(g.exposed_s for g in self.groups)

    @property
    def efficiency(self) -> float:
        """hidden / total comm; a comm-free step is perfectly hidden."""
        total = self.comm_s
        if total <= 0.0:
            return 1.0
        return self.hidden_s / total

    @property
    def timeline_end_s(self) -> float:
        """End of the replayed bwd+comm timeline (export's render span)."""
        last_comm = max((g.start_s + g.comm_s for g in self.groups),
                        default=0.0)
        return max(self.tb_total_s, last_comm)

    def to_event_fields(self) -> dict:
        """The aggregate `overlap` telemetry record's payload."""
        return {
            "step_s": float(self.step_s),
            "tb_total_s": float(self.tb_total_s),
            "comm_s": float(self.comm_s),
            "hidden_s": float(self.hidden_s),
            "exposed_s": float(self.exposed_s),
            "efficiency": float(self.efficiency),
            "attribution": self.attribution,
            "timeline_end_s": float(self.timeline_end_s),
            "num_groups": len(self.groups),
        }

    def group_event_fields(self, step: int) -> list[dict]:
        """One `comm_group` telemetry record payload per merge group."""
        return [
            {
                "step": int(step),
                "group": g.group,
                "nbytes": int(g.nbytes),
                "comm_s": float(g.comm_s),
                "start_s": float(g.start_s),
                "hidden_s": float(g.hidden_s),
                "exposed_s": float(g.exposed_s),
                "attribution": self.attribution,
            }
            for g in self.groups
        ]


def attribute_overlap(
    groups: Sequence[Sequence[int]],
    tb: Sequence[float],
    comm_s: Sequence[float],
    nbytes: Sequence[int],
) -> list[GroupOverlap]:
    """Replay the backward/comm timeline and split each group's comm time.

    The recurrence is the solver's (`solver.simulate_groups`, itself the
    reference's taoc recurrence, distributed_optimizer.py:187-192): group
    g's collective starts at max(link free, ready[max(g)]) where ready is
    the cumulative backward profile; the part of [start, start + comm)
    before the backward end is hidden, the rest exposed. Durations may be
    measured (trace) or predicted (cost model); starts are always
    model-replayed — a trace yields per-scope totals, not start offsets.
    """
    if len(groups) != len(comm_s) or len(groups) != len(nbytes):
        raise ValueError(
            f"groups/comm_s/nbytes disagree: {len(groups)}/"
            f"{len(comm_s)}/{len(nbytes)}"
        )
    ready = np.cumsum(np.asarray(tb, dtype=np.float64))
    bwd_end = float(ready[-1]) if len(ready) else 0.0
    link_free = 0.0
    out: list[GroupOverlap] = []
    for gi, g in enumerate(groups):
        t = float(comm_s[gi])
        ready_at = float(ready[max(g)]) if len(g) and len(ready) else 0.0
        start = max(link_free, ready_at)
        hidden = min(max(bwd_end - start, 0.0), t)
        out.append(GroupOverlap(
            group=gi,
            nbytes=int(nbytes[gi]),
            start_s=start,
            comm_s=t,
            hidden_s=hidden,
            exposed_s=t - hidden,
        ))
        link_free = start + t
    return out


def group_comm_times(
    reducer,
    cost_model,
    measured: Optional[Sequence[float]] = None,
) -> tuple[list[float], list[int], str]:
    """(per-group seconds, per-group bytes, attribution) for a live reducer.

    `measured` is trace-attributed per-group wall-clock in layout order
    (`profiling.trace_group_times`) when the backend kept the
    `mgwfbp_groupNNNN` scopes in op metadata; otherwise the calibrated cost
    model predicts each bucket (`solver.effective_cost_fn`, which prices
    the rs_opt_ag update-in-the-middle consistently).
    """
    import numpy as _np

    from mgwfbp_tpu.parallel.solver import effective_cost_fn

    layout = reducer.layout
    nbytes = [
        int(layout.group_sizes[gi])
        * int(_np.dtype(layout.dtypes[gi]).itemsize)
        for gi in range(layout.num_groups)
    ]
    if measured is not None and len(measured) == layout.num_groups:
        return [float(t) for t in measured], nbytes, "trace"
    cost = effective_cost_fn(cost_model, reducer.comm_op)
    return [float(cost(b)) for b in nbytes], nbytes, "cost-model"


def summarize(
    reducer,
    cost_model,
    tb: Sequence[float],
    step_s: float,
    measured: Optional[Sequence[float]] = None,
) -> OverlapSummary:
    """Full overlap accounting for one live schedule regime.

    tb is the arrival-ordered per-layer backward profile (measured, or the
    size prior the solver fell back to); step_s the measured seconds per
    optimizer step the snapshot describes.
    """
    comm, nbytes, attribution = group_comm_times(
        reducer, cost_model, measured
    )
    rows = attribute_overlap(reducer.layout.groups, tb, comm, nbytes)
    return OverlapSummary(
        step_s=float(step_s),
        tb_total_s=float(sum(float(t) for t in tb)),
        groups=tuple(rows),
        attribution=attribution,
    )
