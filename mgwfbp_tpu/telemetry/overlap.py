"""Overlap-efficiency accounting: exposed vs hidden communication time.

The paper's headline metric, made measurable on any run. MG-WFBP merges
gradients so each bucket's collective starts as soon as its last member's
gradient is ready and rides *behind* the rest of the backward pass
(arXiv:1811.11141); DeAR frames the next wins as reasoning about exactly
which collective time is exposed vs overlapped (arXiv:2302.12445). This
module replays the step timeline the solver reasons about — gradient-ready
times from the per-layer backward profile tb, one serial link occupied by
the merge groups in arrival order — and splits every group's communication
time into

  * **hidden**: the part that executes while backward compute is still
    running (start .. backward end), and
  * **exposed**: the remainder, which lands on the step's critical path.

The aggregate **overlap efficiency** is hidden / total comm — 1.0 when the
schedule hides everything, 0.0 when every byte serializes after backward.

Per-group comm durations come from two attribution sources, combined by
`group_comm_times`:

  * **trace** — `profiling.trace_group_times`: profiler-trace events whose
    op metadata carries the `mgwfbp_groupNNNN` name scope (real TPU; the
    same introspection hook the jaxpr verifier matches on);
  * **cost-model** — the calibrated alpha-beta prediction per bucket
    (`solver.effective_cost_fn`), the fallback on backends whose traces
    drop the name stack (the virtual CPU mesh).

Everything here is pure host arithmetic over already-host data: calling it
adds zero device syncs.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class GroupOverlap:
    """One merge group's share of the replayed step timeline."""

    group: int  # arrival-order group index
    nbytes: int  # bucket payload on the wire
    start_s: float  # link-timeline start (ready[max member], link free)
    comm_s: float  # collective duration (measured or predicted)
    hidden_s: float  # portion overlapping compute (backward; + forward
    # for the cross-step deferred-AG leg)
    exposed_s: float  # portion on the critical path
    # cross-step (rs_fwd_ag) only: the deferred all-gather leg, which
    # executes during the NEXT step's forward. ag_start_s is anchored at
    # that step's start; comm_s above is the rs+ag TOTAL and start_s the
    # reduce-scatter leg's (step-anchored) start. Zero on in-step rows.
    ag_start_s: float = 0.0
    ag_s: float = 0.0
    # hierarchical (hier) only: the group's comm split by LINK — ici_s is
    # the inner reduce-scatter + all-gather legs, dcn_s this group's share
    # of its DCN group's cross-slice collective. comm_s = ici_s + dcn_s;
    # the split is what tells an operator WHICH interconnect is the
    # bottleneck. Zero on flat rows.
    ici_s: float = 0.0
    dcn_s: float = 0.0


@dataclasses.dataclass(frozen=True)
class OverlapSummary:
    """Per-step overlap accounting for one schedule regime."""

    step_s: float  # measured seconds per optimizer step
    tb_total_s: float  # backward compute total (sum of tb)
    groups: tuple[GroupOverlap, ...]
    attribution: str  # 'trace' | 'cost-model'
    # forward compute total — nonzero only for the cross-step (rs_fwd_ag)
    # regime, whose replayed timeline starts at the FORWARD (deferred AGs
    # hide behind it); in-step regimes replay backward-anchored as before
    tf_total_s: float = 0.0
    # where the replayed forward REGION ends (tf_total_s + AG-deadline
    # stalls) = where the backward begins; renderers anchor on this so a
    # stalled forward never desynchronizes the backward vs the RS spans
    fwd_end_s: float = 0.0

    @property
    def comm_s(self) -> float:
        return sum(g.comm_s for g in self.groups)

    @property
    def hidden_s(self) -> float:
        return sum(g.hidden_s for g in self.groups)

    @property
    def exposed_s(self) -> float:
        return sum(g.exposed_s for g in self.groups)

    @property
    def efficiency(self) -> float:
        """hidden / total comm; a comm-free step is perfectly hidden."""
        total = self.comm_s
        if total <= 0.0:
            return 1.0
        return self.hidden_s / total

    @property
    def timeline_end_s(self) -> float:
        """End of the replayed compute+comm timeline (export's render
        span). Cross-step rows count only their RS leg here (comm_s -
        ag_s): the AG leg lives at the timeline's start."""
        last_comm = max(
            (g.start_s + (g.comm_s - g.ag_s) for g in self.groups),
            default=0.0,
        )
        fwd = max(self.fwd_end_s, self.tf_total_s)
        return max(fwd + self.tb_total_s, last_comm)

    @property
    def ici_s(self) -> float:
        return sum(g.ici_s for g in self.groups)

    @property
    def dcn_s(self) -> float:
        return sum(g.dcn_s for g in self.groups)

    @property
    def bottleneck_link(self) -> Optional[str]:
        """'ici' or 'dcn' — the link carrying the larger comm share of a
        hierarchical regime (None on flat regimes, where only one link
        exists). The drift detector and the fleet console read this to
        name WHICH wire to blame before anyone re-autotunes."""
        if self.dcn_s <= 0.0:
            return None
        return "dcn" if self.dcn_s >= self.ici_s else "ici"

    def to_event_fields(self) -> dict:
        """The aggregate `overlap` telemetry record's payload."""
        out = {
            "step_s": float(self.step_s),
            "tb_total_s": float(self.tb_total_s),
            "tf_total_s": float(self.tf_total_s),
            "fwd_end_s": float(self.fwd_end_s),
            "comm_s": float(self.comm_s),
            "hidden_s": float(self.hidden_s),
            "exposed_s": float(self.exposed_s),
            "efficiency": float(self.efficiency),
            "attribution": self.attribution,
            "timeline_end_s": float(self.timeline_end_s),
            "num_groups": len(self.groups),
        }
        if self.dcn_s > 0.0:
            out["ici_s"] = float(self.ici_s)
            out["dcn_s"] = float(self.dcn_s)
            out["bottleneck_link"] = self.bottleneck_link
        return out

    def group_event_fields(self, step: int) -> list[dict]:
        """One `comm_group` telemetry record payload per merge group
        (cross-step rows add the deferred-AG leg's span fields)."""
        out = []
        for g in self.groups:
            fields = {
                "step": int(step),
                "group": g.group,
                "nbytes": int(g.nbytes),
                "comm_s": float(g.comm_s),
                "start_s": float(g.start_s),
                "hidden_s": float(g.hidden_s),
                "exposed_s": float(g.exposed_s),
                "attribution": self.attribution,
            }
            if g.ag_s > 0.0:
                fields["ag_start_s"] = float(g.ag_start_s)
                fields["ag_s"] = float(g.ag_s)
            if g.dcn_s > 0.0:
                fields["ici_s"] = float(g.ici_s)
                fields["dcn_s"] = float(g.dcn_s)
            out.append(fields)
        return out


def attribute_overlap(
    groups: Sequence[Sequence[int]],
    tb: Sequence[float],
    comm_s: Sequence[float],
    nbytes: Sequence[int],
) -> list[GroupOverlap]:
    """Replay the backward/comm timeline and split each group's comm time.

    The recurrence is the solver's (`solver.simulate_groups`, itself the
    reference's taoc recurrence, distributed_optimizer.py:187-192): group
    g's collective starts at max(link free, ready[max(g)]) where ready is
    the cumulative backward profile; the part of [start, start + comm)
    before the backward end is hidden, the rest exposed. Durations may be
    measured (trace) or predicted (cost model); starts are always
    model-replayed — a trace yields per-scope totals, not start offsets.
    """
    if len(groups) != len(comm_s) or len(groups) != len(nbytes):
        raise ValueError(
            f"groups/comm_s/nbytes disagree: {len(groups)}/"
            f"{len(comm_s)}/{len(nbytes)}"
        )
    ready = np.cumsum(np.asarray(tb, dtype=np.float64))
    bwd_end = float(ready[-1]) if len(ready) else 0.0
    link_free = 0.0
    out: list[GroupOverlap] = []
    for gi, g in enumerate(groups):
        t = float(comm_s[gi])
        ready_at = float(ready[max(g)]) if len(g) and len(ready) else 0.0
        start = max(link_free, ready_at)
        hidden = min(max(bwd_end - start, 0.0), t)
        out.append(GroupOverlap(
            group=gi,
            nbytes=int(nbytes[gi]),
            start_s=start,
            comm_s=t,
            hidden_s=hidden,
            exposed_s=t - hidden,
        ))
        link_free = start + t
    return out


def attribute_overlap_cross_step(
    groups: Sequence[Sequence[int]],
    tb: Sequence[float],
    tf: Sequence[float],
    rs_s: Sequence[float],
    ag_s: Sequence[float],
    nbytes: Sequence[int],
) -> tuple[list[GroupOverlap], float]:
    """The cross-step (rs_fwd_ag) replay: each group's comm splits into a
    deferred all-gather leg racing the FORWARD timeline (issued in
    forward-consumption order — reverse arrival — each gated by its first
    consuming layer's AG deadline) and a reduce-scatter leg racing the
    BACKWARD (the solver's taoc recurrence, offset to the forward's end).
    hidden = AG time inside the forward window + RS time inside the
    backward window; everything else is exposed — the overlap-efficiency
    headline stays honest about which side hid what. All times are
    step-anchored (0 = forward begin), unlike the in-step replay's
    backward anchor; `OverlapSummary.tf_total_s` marks the regime.

    Returns (rows, fwd_end_s): fwd_end_s is where the forward REGION
    actually ends — sum(tf) plus any AG-deadline stall — i.e. where the
    backward the RS starts were computed against begins; renderers must
    anchor the backward there, not at sum(tf)."""
    n = len(groups)
    if any(len(x) != n for x in (rs_s, ag_s, nbytes)):
        raise ValueError(
            f"groups/rs_s/ag_s/nbytes disagree: {n}/{len(rs_s)}/"
            f"{len(ag_s)}/{len(nbytes)}"
        )
    tf_total = float(np.sum(np.asarray(tf, np.float64))) if len(tf) else 0.0
    # forward phase replay (simulate_cross_step's recurrence)
    link = 0.0
    fwd = 0.0
    ag_starts = [0.0] * n
    for gi in reversed(range(n)):
        ag_starts[gi] = link
        link += float(ag_s[gi])
        fwd = max(fwd, link) + float(
            sum(tf[i] for i in groups[gi]) if len(tf) else 0.0
        )
    fwd_end = max(fwd, tf_total)
    # backward phase replay, offset to the forward's end; the RS link
    # opens once the AG queue drained (a comm-bound tail can outlive the
    # forward compute)
    ready = fwd_end + np.cumsum(np.asarray(tb, dtype=np.float64))
    bwd_end = float(ready[-1]) if len(ready) else fwd_end
    link_free = max(link, fwd_end)
    out: list[GroupOverlap] = []
    for gi, g in enumerate(groups):
        t_ag = float(ag_s[gi])
        t_rs = float(rs_s[gi])
        hidden_ag = min(max(fwd_end - ag_starts[gi], 0.0), t_ag)
        ready_at = float(ready[max(g)]) if len(g) and len(ready) else fwd_end
        rs_start = max(link_free, ready_at)
        hidden_rs = min(max(bwd_end - rs_start, 0.0), t_rs)
        out.append(GroupOverlap(
            group=gi,
            nbytes=int(nbytes[gi]),
            start_s=rs_start,
            comm_s=t_rs + t_ag,
            hidden_s=hidden_rs + hidden_ag,
            exposed_s=(t_rs - hidden_rs) + (t_ag - hidden_ag),
            ag_start_s=ag_starts[gi],
            ag_s=t_ag,
        ))
        link_free = rs_start + t_rs
    return out, fwd_end


def attribute_overlap_two_level(
    groups: Sequence[Sequence[int]],
    dcn_groups: Sequence[Sequence[int]],
    tb: Sequence[float],
    rs_s: Sequence[float],
    dcn_s: Sequence[float],
    ag_s: Sequence[float],
    nbytes: Sequence[int],
) -> list[GroupOverlap]:
    """The hierarchical (hier) replay: two serial links race the backward
    (`solver.simulate_groups_two_level`'s recurrence). Per inner group the
    ICI link carries its reduce-scatter (taoc recurrence) and — after the
    RS queue drains and its DCN group's cross-slice collective lands —
    its all-gather; the DCN link carries one collective per DCN group
    (`dcn_s`, one entry per DCN group), whose time and hidden share are
    apportioned to member groups by payload. hidden = time inside the
    backward window on EITHER link; the per-row ici_s/dcn_s split is what
    names the bottleneck link."""
    n = len(groups)
    if any(len(x) != n for x in (rs_s, ag_s, nbytes)):
        raise ValueError(
            f"groups/rs_s/ag_s/nbytes disagree: {n}/{len(rs_s)}/"
            f"{len(ag_s)}/{len(nbytes)}"
        )
    if len(dcn_s) != len(dcn_groups):
        raise ValueError(
            f"dcn_groups/dcn_s disagree: {len(dcn_groups)}/{len(dcn_s)}"
        )
    ready = np.cumsum(np.asarray(tb, dtype=np.float64))
    bwd_end = float(ready[-1]) if len(ready) else 0.0

    def hidden_in_bwd(start: float, dur: float) -> float:
        return min(max(bwd_end - start, 0.0), dur)

    # ICI link, RS phase
    ici_free = 0.0
    rs_start = [0.0] * n
    rs_done = [0.0] * n
    for gi, g in enumerate(groups):
        start = max(ici_free, float(ready[max(g)]) if len(g) else 0.0)
        rs_start[gi] = start
        ici_free = start + float(rs_s[gi])
        rs_done[gi] = ici_free
    # DCN link: apportion each DCN collective to its members by payload
    dcn_free = 0.0
    dcn_done = [0.0] * n
    g_dcn = [0.0] * n
    g_dcn_hidden = [0.0] * n
    for di, d in enumerate(dcn_groups):
        t = float(dcn_s[di])
        start = max(dcn_free, max(rs_done[gi] for gi in d))
        dcn_free = start + t
        hidden = hidden_in_bwd(start, t)
        total_b = float(sum(nbytes[gi] for gi in d)) or 1.0
        for gi in d:
            share = float(nbytes[gi]) / total_b
            dcn_done[gi] = dcn_free
            g_dcn[gi] = t * share
            g_dcn_hidden[gi] = hidden * share
    # ICI link, AG phase
    out: list[GroupOverlap] = []
    for gi in range(n):
        start = max(ici_free, dcn_done[gi])
        t_ag = float(ag_s[gi])
        ici_free = start + t_ag
        hidden = (
            hidden_in_bwd(rs_start[gi], float(rs_s[gi]))
            + g_dcn_hidden[gi]
            + hidden_in_bwd(start, t_ag)
        )
        comm = float(rs_s[gi]) + g_dcn[gi] + t_ag
        out.append(GroupOverlap(
            group=gi,
            nbytes=int(nbytes[gi]),
            start_s=rs_start[gi],
            comm_s=comm,
            hidden_s=hidden,
            exposed_s=comm - hidden,
            ici_s=float(rs_s[gi]) + t_ag,
            dcn_s=g_dcn[gi],
        ))
    return out


def group_comm_times(
    reducer,
    cost_model,
    measured: Optional[Sequence[float]] = None,
) -> tuple[list[float], list[int], str]:
    """(per-group seconds, per-group bytes, attribution) for a live reducer.

    `measured` is trace-attributed per-group wall-clock in layout order
    (`profiling.trace_group_times`) when the backend kept the
    `mgwfbp_groupNNNN` scopes in op metadata; otherwise the calibrated cost
    model predicts each bucket (`solver.effective_cost_fn`, which prices
    the rs_opt_ag update-in-the-middle consistently).
    """
    import numpy as _np

    from mgwfbp_tpu.parallel.solver import effective_cost_fn

    layout = reducer.layout
    nbytes = [
        int(layout.group_sizes[gi])
        * int(_np.dtype(layout.dtypes[gi]).itemsize)
        for gi in range(layout.num_groups)
    ]
    if measured is not None and len(measured) == layout.num_groups:
        return [float(t) for t in measured], nbytes, "trace"
    cost = effective_cost_fn(cost_model, reducer.comm_op)
    return [float(cost(b)) for b in nbytes], nbytes, "cost-model"


def summarize(
    reducer,
    cost_model,
    tb: Sequence[float],
    step_s: float,
    measured: Optional[Sequence[float]] = None,
    tf: Optional[Sequence[float]] = None,
) -> OverlapSummary:
    """Full overlap accounting for one live schedule regime.

    tb is the arrival-ordered per-layer backward profile (measured, or the
    size prior the solver fell back to); step_s the measured seconds per
    optimizer step the snapshot describes. For a cross-step (rs_fwd_ag)
    reducer, `tf` is the forward profile its deferred-AG legs race
    (defaults to `solver.forward_prior_tf(tb)`); per-group comm — trace
    totals cover BOTH legs of a group's scope — splits between the legs in
    the cost model's phase proportions (`solver.cross_step_phase_costs`).
    """
    comm, nbytes, attribution = group_comm_times(
        reducer, cost_model, measured
    )
    comm_op = getattr(reducer, "comm_op", "all_reduce")
    if comm_op == "hier":
        from mgwfbp_tpu.parallel.solver import (
            is_two_level,
            singleton_dcn_groups,
            two_level_leg_costs,
        )

        dcn_part = [
            list(d) for d in getattr(reducer.schedule, "dcn_groups", ())
        ] or singleton_dcn_groups(len(nbytes))
        if is_two_level(cost_model):
            rs_c, dcn_c, ag_c = two_level_leg_costs(cost_model)
        else:
            # a flat model cannot split the links; put everything on the
            # ICI side so the replay still runs (dcn_s = 0 marks the
            # split as unavailable rather than inventing one)
            rs_c = lambda b: 0.5 * float(cost_model.predict(b))  # noqa: E731
            ag_c = lambda b: 0.5 * float(cost_model.predict(b))  # noqa: E731
            dcn_c = lambda b: 0.0  # noqa: E731
        # Per-link pricing. The DCN link runs ONE collective per DCN
        # group over the members' concatenated shards — its cost is
        # dcn_c(sum of member bytes), exactly once (summing per-member
        # predictions would charge the DCN alpha per member, the very
        # overhead merging on DCN exists to avoid — and precisely in the
        # merged regime this accounting describes). ICI legs: TRACE
        # totals sum the mgwfbp_groupNNNN scopes only — the ICI legs
        # (the DCN collectives live under their own mgwfbp_dcngroupNNNN
        # scopes, which per-group attribution does not yet collect) — so
        # a measured t splits across the ICI legs and the DCN leg stays
        # model-priced; without a trace the leg costs price directly.
        dcn_s = [
            float(dcn_c(float(sum(nbytes[gi] for gi in d))))
            for d in dcn_part
        ]
        rs_s, ag_s = [], []
        for t, b in zip(comm, nbytes):
            r, a = rs_c(b), ag_c(b)
            if attribution == "trace":
                tot = max(r + a, 1e-30)
                rs_s.append(t * r / tot)
                ag_s.append(t * a / tot)
            else:
                rs_s.append(float(r))
                ag_s.append(float(a))
        rows = attribute_overlap_two_level(
            reducer.layout.groups, dcn_part, tb, rs_s, dcn_s, ag_s, nbytes
        )
        return OverlapSummary(
            step_s=float(step_s),
            tb_total_s=float(sum(float(t) for t in tb)),
            groups=tuple(rows),
            attribution=attribution,
        )
    if comm_op == "rs_fwd_ag":
        from mgwfbp_tpu.parallel.solver import (
            cross_step_phase_costs,
            forward_prior_tf,
        )

        if tf is None:
            tf = forward_prior_tf(tb)
        rs_c, ag_c = cross_step_phase_costs(cost_model)
        rs_s, ag_s = [], []
        for t, b in zip(comm, nbytes):
            r, a = rs_c(b), ag_c(b)
            frac = r / max(r + a, 1e-30)
            rs_s.append(t * frac)
            ag_s.append(t * (1.0 - frac))
        rows, fwd_end = attribute_overlap_cross_step(
            reducer.layout.groups, tb, tf, rs_s, ag_s, nbytes
        )
        return OverlapSummary(
            step_s=float(step_s),
            tb_total_s=float(sum(float(t) for t in tb)),
            tf_total_s=float(sum(float(t) for t in tf)),
            fwd_end_s=float(fwd_end),
            groups=tuple(rows),
            attribution=attribution,
        )
    rows = attribute_overlap(reducer.layout.groups, tb, comm, nbytes)
    return OverlapSummary(
        step_s=float(step_s),
        tb_total_s=float(sum(float(t) for t in tb)),
        groups=tuple(rows),
        attribution=attribution,
    )
