"""Anomaly-triggered flight recorder: a bounded in-memory event ring that
dumps an atomic postmortem bundle when any alarm fires.

When a drift alarm, straggler, health alarm, bad step, or watchdog stall
fires, the evidence that explains it — the last N steps' full-cadence
events, the live /status snapshot, the committed schedule and cost-model
state — is gone unless someone was already tracing. This module keeps
that evidence on a leash:

  * ``FlightRecorder.observe`` tees off the validated EventWriter stream
    (the same observer hook the MetricsAggregator uses — one validated
    stream feeds the JSONL file, the live endpoints, AND the ring), so
    the ring always holds the last ``ring_size`` records at full cadence,
    whatever the operator's scrape interval was.
  * ANY trigger event (``drift_alarm``/``straggler``/``health_alarm``
    raise edges, ``bad_step``, ``watchdog_stall``) writes one atomic
    postmortem bundle under ``<dir>/postmortems/NNNN/``:

      events.jsonl    the ring-buffer dump (ring order, oldest first)
      status.json     the /status snapshot (when an aggregator is wired)
      schedule.json   the committed merge schedule + cost-model state
      manifest.json   trigger event/step/wall, ring stats, bundle index
      profile.json    (later) the auto-armed /profile window's per-group
                      attribution, appended when the window completes

    The bundle is staged in ``NNNN.tmp.<pid>`` and os.replace'd into
    place, so a reader never sees a half-written bundle.
  * A **debounce window** (``debounce_s``) plus a hard bundle cap
    (``max_bundles``) keeps an alarm storm from writing unbounded
    bundles: within the window, follow-up triggers are counted in the
    open bundle's manifest-side statistics, not dumped again.
  * With ``MGWFBP_POSTMORTEM_PROFILE=1`` a trigger also arms a bounded
    ``/profile`` trace window through the aggregator's existing state
    machine (the step loop consumes it at the next boundary); the
    resulting ``profile`` event is appended to the open bundle as
    ``profile.json`` — the deep-trace slice lands next to the events
    that explain why it was taken.

Env knobs: ``MGWFBP_POSTMORTEM`` (0 disables), ``MGWFBP_POSTMORTEM_RING``
(ring size, default 512 records), ``MGWFBP_POSTMORTEM_DEBOUNCE_S``
(default 30), ``MGWFBP_POSTMORTEM_MAX`` (default 16 bundles/run),
``MGWFBP_POSTMORTEM_PROFILE`` (1 arms the deep-trace window),
``MGWFBP_POSTMORTEM_PROFILE_STEPS`` (window length, default 3).

Everything here is host-side file I/O on already-host JSON data — the
observer runs inside `EventWriter.emit`, whose contract already rejects
device values, so the recorder can never add a device sync; and a
recorder failure detaches that observer, never the run (the EventWriter's
observer contract).
"""

from __future__ import annotations

import collections
import json
import os
import re
import threading
import time
from typing import Callable, Optional

from mgwfbp_tpu.utils.logging import get_logger

_ENV_ENABLE = "MGWFBP_POSTMORTEM"
_ENV_RING = "MGWFBP_POSTMORTEM_RING"
_ENV_DEBOUNCE = "MGWFBP_POSTMORTEM_DEBOUNCE_S"
_ENV_MAX = "MGWFBP_POSTMORTEM_MAX"
_ENV_PROFILE = "MGWFBP_POSTMORTEM_PROFILE"
_ENV_PROFILE_STEPS = "MGWFBP_POSTMORTEM_PROFILE_STEPS"

DEFAULT_RING = 512
DEFAULT_DEBOUNCE_S = 30.0
DEFAULT_MAX_BUNDLES = 16
DEFAULT_PROFILE_STEPS = 3

# events that trip a postmortem dump; alarm-edge events trigger on their
# RAISE edge only (a clear edge is the system healing, not an anomaly)
TRIGGER_EVENTS = frozenset({
    "drift_alarm", "straggler", "health_alarm", "bad_step",
    "watchdog_stall",
})
_EDGE_EVENTS = frozenset({"drift_alarm", "straggler", "health_alarm"})


def recorder_enabled(environ=None) -> bool:
    return (environ or os.environ).get(_ENV_ENABLE, "1") != "0"


def _env_int(name: str, default: int) -> int:
    raw = (os.environ.get(name) or "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not an integer") from None


def tee_observers(*observers) -> Callable[[str, dict], None]:
    """Compose EventWriter observers (the writer holds ONE slot; the
    aggregator and the recorder both tee off it). A failing member is
    dropped — same detach-don't-die contract as the writer's own observer
    handling, applied per member so a broken recorder cannot freeze the
    live /metrics surface (or vice versa)."""
    live = [o for o in observers if o is not None]

    def observe(event: str, fields: dict) -> None:
        for o in tuple(live):
            try:
                o(event, fields)
            except Exception:  # noqa: BLE001 — observability must never
                # kill (or blind) the run it observes
                get_logger("mgwfbp.telemetry").exception(
                    "telemetry observer %r failed on %r; detaching it",
                    o, event,
                )
                try:
                    live.remove(o)
                except ValueError:
                    pass

    return observe


class FlightRecorder:
    """Bounded event ring + atomic postmortem bundles for one process.

    ``directory`` is the run's tag dir (bundles land under
    ``<directory>/postmortems/``). ``status_provider`` /
    ``schedule_provider`` return the live /status document and the
    committed schedule + cost-model state (wired by the trainer);
    ``profile_armer`` arms a bounded deep-trace window (the aggregator's
    `arm_profile`); ``event_sink`` emits the ``postmortem`` record back
    into the stream (the writer's own `emit` — safe: the recorder never
    re-triggers on it). Thread-safe: step loop and watchdog threads both
    emit."""

    def __init__(
        self,
        directory: str,
        ring_size: Optional[int] = None,
        debounce_s: Optional[float] = None,
        max_bundles: Optional[int] = None,
        status_provider: Optional[Callable[[], dict]] = None,
        schedule_provider: Optional[Callable[[], dict]] = None,
        profile_armer: Optional[Callable[[int], None]] = None,
        event_sink: Optional[Callable[..., None]] = None,
        suffix: str = "",
    ):
        # `suffix` disambiguates bundle names when several processes
        # share one tag dir (a multi-host group: each process records its
        # own ring) — ``NNNN.pK`` instead of two processes racing the
        # same ``NNNN`` rename
        if ring_size is None:
            ring_size = _env_int(_ENV_RING, DEFAULT_RING)
        if debounce_s is None:
            raw = (os.environ.get(_ENV_DEBOUNCE) or "").strip()
            debounce_s = float(raw) if raw else DEFAULT_DEBOUNCE_S
        if max_bundles is None:
            max_bundles = _env_int(_ENV_MAX, DEFAULT_MAX_BUNDLES)
        self.directory = os.path.join(directory, "postmortems")
        self.suffix = str(suffix)
        self.ring_size = max(int(ring_size), 1)
        self.debounce_s = max(float(debounce_s), 0.0)
        self.max_bundles = max(int(max_bundles), 0)
        self.status_provider = status_provider
        self.schedule_provider = schedule_provider
        self.profile_armer = profile_armer
        self.event_sink = event_sink
        self.profile_enabled = (
            os.environ.get(_ENV_PROFILE) == "1"
        )
        self.profile_steps = max(
            _env_int(_ENV_PROFILE_STEPS, DEFAULT_PROFILE_STEPS), 1
        )
        self.log = get_logger("mgwfbp.telemetry.recorder")
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=self.ring_size
        )
        # `postmortem` records waiting to be emitted into the stream:
        # emitting from inside the TRIGGER event's own observe would
        # write the postmortem row (and stamp its wall) BEFORE the
        # trigger record itself lands in the JSONL — the merged timeline
        # would show the bundle existing before its cause. Deferred
        # emissions flush at the next observe (any event), which on a
        # live run is at most one step away; `flush_events` covers
        # shutdown.
        self._pending_emits: list[dict] = []
        self._flushing = False
        self._seen = 0  # total records observed (ring stats)
        self._bundles: list[dict] = []  # written manifests, oldest first
        self._last_bundle_wall: Optional[float] = None
        self._suppressed = 0  # triggers swallowed by debounce/cap
        # when a trigger armed a profile window, the bundle dir its
        # `profile` event should be appended to (one outstanding at most)
        self._awaiting_profile: Optional[str] = None
        # resuming under the same tag continues the bundle sequence
        self._next_index = self._scan_existing()

    # -- the observer hook -------------------------------------------------
    def observe(self, event: str, fields: dict) -> None:
        """One validated telemetry record (the EventWriter tee)."""
        self.flush_events()
        rec = {"event": event, "wall": round(time.time(), 3), **fields}
        with self._lock:
            self._ring.append(rec)
            self._seen += 1
        if event == "profile":
            self._attach_profile(rec)
            return
        if event not in TRIGGER_EVENTS:
            return
        if event in _EDGE_EVENTS and not fields.get("active"):
            return  # clear edges heal, they don't trigger
        self._trigger(rec)

    def flush_events(self) -> None:
        """Emit any deferred `postmortem` records into the stream (called
        on every observe — so the record lands right after its trigger's
        row — and by the trainer at shutdown). Re-entrancy-guarded: the
        emit re-enters observe through the tee."""
        if self.event_sink is None:
            return
        with self._lock:
            if self._flushing or not self._pending_emits:
                return
            self._flushing = True
            pending, self._pending_emits = self._pending_emits, []
        try:
            for fields in pending:
                try:
                    self.event_sink("postmortem", **fields)
                except Exception as e:  # noqa: BLE001 — stream trouble
                    # must not take the recorder down
                    self.log.info("postmortem event emit failed (%s)", e)
        finally:
            with self._lock:
                self._flushing = False

    # -- bundles -----------------------------------------------------------
    def bundles(self) -> list[dict]:
        """Written bundle manifests, oldest first (the /postmortems
        document's source)."""
        with self._lock:
            return [dict(b) for b in self._bundles]

    @property
    def suppressed(self) -> int:
        with self._lock:
            return self._suppressed

    def _scan_existing(self) -> int:
        """Next bundle index: one past the highest NNNN already on disk
        FOR THIS RECORDER'S SUFFIX (a resume under the same tag must
        extend the sequence, not clobber the previous incarnation's
        bundles; another process's differently-suffixed bundles are not
        this sequence)."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return 0
        indices = []
        for n in names:
            if self.suffix:
                if not n.endswith(self.suffix):
                    continue
                n = n[: -len(self.suffix)]
            if n.isdigit():
                indices.append(int(n))
        return max(indices) + 1 if indices else 0

    def _trigger(self, rec: dict) -> None:
        now = time.time()
        with self._lock:
            if (
                self._last_bundle_wall is not None
                and now - self._last_bundle_wall < self.debounce_s
            ):
                self._suppressed += 1
                return
            if len(self._bundles) >= self.max_bundles:
                self._suppressed += 1  # hard cap per incarnation: an
                # alarm storm must never fill the disk with bundles
                return
            index = self._next_index
            self._next_index += 1
            self._last_bundle_wall = now
            ring = list(self._ring)
            seen = self._seen
            suppressed = self._suppressed
        manifest = self._write_bundle(
            index, rec, ring, seen, suppressed, now
        )
        if manifest is None:
            return
        with self._lock:
            self._bundles.append(manifest)
        if self.profile_enabled and self.profile_armer is not None:
            try:
                result = self.profile_armer(self.profile_steps)
                # the aggregator's arm_profile returns (http status, doc)
                # — a refused arm (409: a window is already armed/running
                # for someone else) must NOT claim that window's result
                # for this bundle
                armed = True
                if (
                    isinstance(result, tuple) and result
                    and isinstance(result[0], int)
                ):
                    armed = result[0] == 200
                if armed:
                    self._awaiting_profile = manifest["path"]
            except Exception as e:  # noqa: BLE001 — the window is an
                # attribution upgrade, never a gate
                self.log.info("postmortem profile arm failed (%s)", e)
        if self.event_sink is not None:
            # deferred: emitting here would land the record BEFORE the
            # trigger's own row (we are inside its observe); the next
            # observed event flushes it
            with self._lock:
                self._pending_emits.append({
                    "trigger": str(rec.get("event")),
                    "step": manifest["step"],
                    "path": manifest["path"],
                })
            if rec.get("event") == "watchdog_stall" and rec.get("abort"):
                # abort-bound stall: os._exit(86) follows this emit —
                # there will BE no next observe and trainer.close() never
                # runs. Flush NOW (accepting the one-row ordering
                # inversion) so the stream, /status, and the
                # supervisor's rc-86 stop message all name the stall's
                # own bundle, which is exactly the case the recorder
                # exists for.
                self.flush_events()

    def _write_bundle(
        self, index: int, trigger: dict, ring: list, seen: int,
        suppressed: int, wall: float,
    ) -> Optional[dict]:
        final = os.path.join(
            self.directory, f"{index:04d}{self.suffix}"
        )
        tmp = f"{final}.tmp.{os.getpid()}"
        # explicit missing-check: step 0 is a legitimate trigger step (a
        # NaN on the very first step), not the "no step" sentinel
        step = trigger.get("step")
        manifest = {
            "index": index,
            "wall": round(wall, 3),
            "trigger": str(trigger.get("event")),
            "step": int(step) if isinstance(step, (int, float)) else -1,
            "trigger_record": trigger,
            "ring_records": len(ring),
            "records_seen": seen,
            "ring_size": self.ring_size,
            "suppressed_before": suppressed,
            "path": final,
        }
        try:
            os.makedirs(tmp, exist_ok=True)
            with open(os.path.join(tmp, "events.jsonl"), "w") as f:
                for r in ring:
                    f.write(json.dumps(r) + "\n")
            status = None
            if self.status_provider is not None:
                try:
                    status = self.status_provider()
                except Exception as e:  # noqa: BLE001 — best-effort part
                    status = {"error": str(e)}
            with open(os.path.join(tmp, "status.json"), "w") as f:
                json.dump(status, f, indent=1)
            schedule = None
            if self.schedule_provider is not None:
                try:
                    schedule = self.schedule_provider()
                except Exception as e:  # noqa: BLE001
                    schedule = {"error": str(e)}
            with open(os.path.join(tmp, "schedule.json"), "w") as f:
                json.dump(schedule, f, indent=1)
            with open(os.path.join(tmp, "manifest.json"), "w") as f:
                json.dump(manifest, f, indent=1)
            os.replace(tmp, final)
        except OSError as e:
            self.log.warning(
                "postmortem bundle %04d not written (%s)", index, e,
            )
            try:
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
            except Exception:  # noqa: BLE001 — cleanup is best-effort
                pass
            return None
        self.log.warning(
            "postmortem bundle written: %s (trigger %s at step %s, %d "
            "ring record(s))",
            final, manifest["trigger"], manifest["step"], len(ring),
        )
        return manifest

    def _attach_profile(self, rec: dict) -> None:
        """A /profile window completed; if a postmortem armed it, land
        the per-group attribution inside that bundle."""
        with self._lock:
            target = self._awaiting_profile
            self._awaiting_profile = None
        if target is None:
            return
        try:
            with open(os.path.join(target, "profile.json"), "w") as f:
                json.dump(rec, f, indent=1)
        except OSError as e:
            self.log.info(
                "postmortem profile attach failed (%s)", e,
            )
            return
        with self._lock:
            for b in self._bundles:
                if b.get("path") == target:
                    b["profile"] = True
        self.log.info(
            "postmortem profile attribution attached: %s/profile.json",
            target,
        )


def read_bundle(path: str) -> dict:
    """Load one postmortem bundle directory back into a dict (the report
    tooling's reader): manifest + status + schedule + the ring events
    (+ profile when the auto-armed window landed)."""
    out: dict = {"path": path}
    for name in ("manifest", "status", "schedule", "profile"):
        p = os.path.join(path, f"{name}.json")
        if os.path.exists(p):
            with open(p) as f:
                out[name] = json.load(f)
    events_path = os.path.join(path, "events.jsonl")
    if os.path.exists(events_path):
        with open(events_path) as f:
            out["events"] = [
                json.loads(line) for line in f if line.strip()
            ]
    return out


_BUNDLE_NAME = re.compile(r"^\d{4,}(\.p\d+)?$")


def list_bundles(directory: str) -> list[str]:
    """Bundle directories under ``<directory>/postmortems``, index order
    — single-process ``NNNN`` names and a multi-host group's ``NNNN.pK``
    names both list (half-written ``.tmp.`` stages never do: os.replace
    makes a listed bundle complete by construction)."""
    root = os.path.join(directory, "postmortems")
    try:
        names = os.listdir(root)
    except OSError:
        return []
    return [
        os.path.join(root, n)
        for n in sorted(names) if _BUNDLE_NAME.match(n)
    ]
