"""Render a telemetry event stream for external viewers.

Two targets:

  * **Chrome trace** (`chrome://tracing` / Perfetto): the run's step
    timeline as complete ("ph": "X") events — a ``steps`` track of step
    spans, a ``backward`` track, one track per merge group's collective,
    and an ``optimizer`` track. Step spans come straight from the recorded
    host wall-clock; the intra-step structure is the overlap snapshot's
    replayed timeline (telemetry.overlap) scaled into each step span, so
    what Perfetto shows per step is exactly what the overlap accounting
    charged: where each group's comm sat relative to backward, and how
    much stuck out past it.
  * **Prometheus text exposition**: counters/gauges summarizing the same
    stream (steps, step seconds, overlap efficiency, exposed/hidden comm,
    resizes, checkpoints, watchdog stalls) for scrape-style monitoring.

Both are pure functions of the already-written JSONL records — no live run
required, no device access ever.
"""

from __future__ import annotations

import json
from typing import Optional

from mgwfbp_tpu.telemetry.events import events_of

# fixed track (tid) layout; merge-group tracks follow from _TID_GROUP0
_TID_STEPS = 0
_TID_BACKWARD = 1
_TID_OPTIMIZER = 2
_TID_FORWARD = 3  # cross-step (rs_fwd_ag) regimes only
_TID_GROUP0 = 10
_PID = 1


def _meta(name: str, pid: int, tid: Optional[int] = None, *,
          kind: str) -> dict:
    e: dict = {"ph": "M", "pid": pid, "name": kind,
               "args": {"name": name}}
    if tid is not None:
        e["tid"] = tid
    return e


def _span(name: str, tid: int, ts_us: float, dur_us: float,
          args: Optional[dict] = None) -> dict:
    e = {"ph": "X", "pid": _PID, "tid": tid, "name": name,
         "ts": round(ts_us, 3), "dur": round(max(dur_us, 0.0), 3),
         "cat": "mgwfbp"}
    if args:
        e["args"] = args
    return e


def latest_snapshot(records: list[dict]) -> tuple[Optional[dict], list[dict]]:
    """(last overlap record, its comm_group rows) — the schedule regime the
    intra-step render uses. comm_group rows are matched by the snapshot's
    step id, so a mid-run reschedule (autotune/resize) renders with the
    regime that was actually live last. Shared by this exporter and the
    report CLI so the table and the trace can never disagree on which
    regime they show."""
    overlaps = events_of(records, "overlap")
    if not overlaps:
        return None, []
    snap = overlaps[-1]
    rows = [
        r for r in events_of(records, "comm_group")
        if r.get("step") == snap.get("step")
    ]
    rows.sort(key=lambda r: r.get("group", 0))
    return snap, rows


def chrome_trace(records: list[dict]) -> dict:
    """Chrome-trace JSON object for a telemetry record list."""
    trace: list[dict] = [
        _meta("mgwfbp run", _PID, kind="process_name"),
        _meta("steps", _PID, _TID_STEPS, kind="thread_name"),
        _meta("backward", _PID, _TID_BACKWARD, kind="thread_name"),
        _meta("optimizer", _PID, _TID_OPTIMIZER, kind="thread_name"),
    ]
    snap, group_rows = latest_snapshot(records)
    cross_step = snap is not None and float(snap.get("tf_total_s", 0.0)) > 0.0
    if cross_step:
        trace.append(_meta(
            "forward", _PID, _TID_FORWARD, kind="thread_name",
        ))
    for r in group_rows:
        gi = int(r["group"])
        trace.append(_meta(
            f"comm group {gi:04d}", _PID, _TID_GROUP0 + gi,
            kind="thread_name",
        ))
    for s in events_of(records, "step"):
        ts = float(s["start_s"]) * 1e6
        dur = float(s["dur_s"]) * 1e6
        trace.append(_span(
            f"step {int(s['step'])}", _TID_STEPS, ts, dur,
            args={"epoch": s.get("epoch")},
        ))
        if snap is None:
            continue
        # scale the replayed model timeline (backward + comm + optimizer
        # tail) into this step's real span, so sub-spans nest inside it.
        # Cross-step regimes replay STEP-anchored (forward first, then
        # backward; the deferred-AG legs render on the forward region —
        # in steady state every step's opening forward IS the previous
        # step's "next forward"); in-step regimes stay backward-anchored.
        step_model_s = max(float(snap.get("step_s", 0.0)), 1e-12)
        scale = (dur / 1e6) / step_model_s
        tb_total = float(snap.get("tb_total_s", 0.0))
        # the backward anchors where the replayed forward REGION ends —
        # fwd_end_s includes AG-deadline stalls, so group RS spans (whose
        # starts were computed against that backward window) stay in sync
        # with the drawn backward even when a deferred gather stalled the
        # forward; the forward span covers the whole region incl. stalls
        fwd_end = 0.0
        if cross_step:
            fwd_end = max(
                float(snap.get("fwd_end_s", 0.0)),
                float(snap.get("tf_total_s", 0.0)),
            )
            trace.append(_span(
                "forward", _TID_FORWARD, ts, fwd_end * scale * 1e6,
            ))
        trace.append(_span(
            "backward", _TID_BACKWARD, ts + fwd_end * scale * 1e6,
            tb_total * scale * 1e6,
        ))
        for r in group_rows:
            gi = int(r["group"])
            ag_s = float(r.get("ag_s", 0.0))
            label = f"group {gi:04d} ({r.get('attribution', '?')})"
            if ag_s > 0.0:
                # the RS leg (start_s is already step-anchored) ...
                trace.append(_span(
                    f"{label} RS", _TID_GROUP0 + gi,
                    ts + float(r["start_s"]) * scale * 1e6,
                    (float(r["comm_s"]) - ag_s) * scale * 1e6,
                    args={
                        "nbytes": r.get("nbytes"),
                        "hidden_s": r.get("hidden_s"),
                        "exposed_s": r.get("exposed_s"),
                    },
                ))
                # ... and the deferred AG leg on the forward region
                trace.append(_span(
                    f"{label} deferred AG (prev step's gather)",
                    _TID_GROUP0 + gi,
                    ts + float(r.get("ag_start_s", 0.0)) * scale * 1e6,
                    ag_s * scale * 1e6,
                    args={"nbytes": r.get("nbytes")},
                ))
                continue
            trace.append(_span(
                label,
                _TID_GROUP0 + gi,
                ts + float(r["start_s"]) * scale * 1e6,
                float(r["comm_s"]) * scale * 1e6,
                args={
                    "nbytes": r.get("nbytes"),
                    "hidden_s": r.get("hidden_s"),
                    "exposed_s": r.get("exposed_s"),
                },
            ))
        timeline_end = float(snap.get("timeline_end_s", tb_total))
        opt_s = max(step_model_s - timeline_end, 0.0)
        if opt_s > 0.0:
            trace.append(_span(
                "optimizer/update", _TID_OPTIMIZER,
                ts + timeline_end * scale * 1e6, opt_s * scale * 1e6,
            ))
    header = next(iter(events_of(records, "header")), {})
    return {
        "traceEvents": trace,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "mgwfbp_tpu.telemetry",
            "schema_version": header.get("schema_version"),
            "run": header.get("run", {}),
        },
    }


def write_chrome_trace(path: str, records: list[dict]) -> dict:
    doc = chrome_trace(records)
    with open(path, "w") as f:
        json.dump(doc, f)
    return doc


# ---------------------------------------------------------------------------
# Metric registry: THE single statement of every Prometheus metric this
# framework exposes — names, kinds, help text. Both renderers read it:
# the post-hoc file dump (`prometheus_text`, below) and the live /metrics
# endpoint (`telemetry.serve.TelemetryServer`) render the SAME registry
# from the SAME aggregator (`serve.MetricsAggregator`), so the two
# surfaces cannot drift apart (ISSUE 9 satellite: the names/labels used
# to be built ad hoc inside prometheus_text).
# ---------------------------------------------------------------------------

# (name, kind, help). Order is the exposition order; values absent from
# the aggregator (e.g. no overlap snapshot yet) are simply not rendered.
METRICS: tuple[tuple[str, str, str], ...] = (
    ("mgwfbp_steps_total", "counter",
     "optimizer steps recorded in the telemetry stream"),
    ("mgwfbp_step_seconds", "gauge",
     "mean seconds per step over the last spans"),
    ("mgwfbp_current_step", "gauge",
     "latest optimizer step (host iteration counter)"),
    ("mgwfbp_current_epoch", "gauge", "latest epoch seen in the stream"),
    ("mgwfbp_overlap_efficiency", "gauge",
     "hidden / total communication time (latest snapshot)"),
    ("mgwfbp_comm_hidden_seconds", "gauge",
     "per-step communication hidden behind backward (latest)"),
    ("mgwfbp_comm_exposed_seconds", "gauge",
     "per-step communication on the critical path (latest)"),
    ("mgwfbp_resizes_total", "counter", "elastic worker-count resizes"),
    ("mgwfbp_checkpoints_total", "counter", "checkpoint saves"),
    ("mgwfbp_last_checkpoint_iteration", "gauge",
     "iteration of the most recent checkpoint save"),
    ("mgwfbp_watchdog_stalls_total", "counter",
     "watchdog stall detections"),
    ("mgwfbp_autotune_races_total", "counter",
     "autotune candidates raced"),
    ("mgwfbp_autotune_commits_total", "counter",
     "autotune schedule commits (race or cache)"),
    ("mgwfbp_bench_skips_total", "counter",
     "bench runs skipped (chip unavailable)"),
    ("mgwfbp_bad_steps_total", "counter",
     "steps dropped by the non-finite-gradient guard"),
    ("mgwfbp_rollbacks_total", "counter",
     "bad-step rollbacks to the last checkpoint"),
    ("mgwfbp_preempts_total", "counter", "graceful preemption drains"),
    ("mgwfbp_resumes_total", "counter", "restarts from a saved snapshot"),
    # self-healing supervisor (ISSUE 20)
    ("mgwfbp_failures_total", "counter",
     "hard failures observed (crash/oom_kill/wedged/unreachable/"
     "coordination)"),
    ("mgwfbp_heals_total", "counter",
     "healing actions applied (relaunch/shrink/respawn_serve/stop)"),
    ("mgwfbp_drift_alarms_total", "counter",
     "cost-model drift alarms raised (telemetry.drift)"),
    ("mgwfbp_drift_residual", "gauge",
     "latest drift residual (predicted/measured comm ratio, or "
     "step-trend excess fraction)"),
    ("mgwfbp_straggler_alarms_total", "counter",
     "live straggler alarms raised (multi-host probe)"),
    ("mgwfbp_straggler_excess_seconds", "gauge",
     "latest straggler probe: slowest minus fastest process window "
     "step seconds"),
    ("mgwfbp_active_alarms", "gauge",
     "currently-active drift/straggler/health alarms"),
    ("mgwfbp_profile_windows_total", "counter",
     "on-demand /profile trace windows completed"),
    # training-health telemetry + flight recorder (ISSUE 12)
    ("mgwfbp_health_loss", "gauge",
     "latest step loss from the in-jit health statistics"),
    ("mgwfbp_health_grad_norm", "gauge",
     "latest global gradient L2 norm (health statistics)"),
    ("mgwfbp_health_update_ratio", "gauge",
     "latest update/param L2-norm ratio (health statistics)"),
    ("mgwfbp_health_compression_error", "gauge",
     "latest worst per-group relative top-k compression error"),
    ("mgwfbp_health_alarms_total", "counter",
     "training-health alarms raised (telemetry.health)"),
    ("mgwfbp_postmortems_total", "counter",
     "flight-recorder postmortem bundles written"),
    # serving plane (ISSUE 19): request plane + hot-reload + shadow-eval
    ("mgwfbp_serve_requests_total", "counter",
     "predict requests served (cumulative, from serve_stats snapshots)"),
    ("mgwfbp_serve_reloads_total", "counter",
     "serving hot-reloads of a committed checkpoint"),
    ("mgwfbp_shadow_evals_total", "counter",
     "shadow-eval scores against freshly served checkpoints"),
    ("mgwfbp_serve_step", "gauge",
     "train step of the currently served checkpoint"),
    ("mgwfbp_serve_reload_lag_seconds", "gauge",
     "latest commit-to-served hot-reload lag"),
    ("mgwfbp_serve_queue_depth", "gauge",
     "predict request queue depth (latest dispatcher snapshot)"),
    ("mgwfbp_serve_batch_fill", "gauge",
     "mean fill ratio of flushed predict batch slots (latest snapshot)"),
    ("mgwfbp_serve_latency_p50_seconds", "gauge",
     "predict request latency p50 over the recent-request window"),
    ("mgwfbp_serve_latency_p95_seconds", "gauge",
     "predict request latency p95 over the recent-request window"),
    ("mgwfbp_serve_latency_p99_seconds", "gauge",
     "predict request latency p99 over the recent-request window"),
    ("mgwfbp_shadow_eval_loss", "gauge",
     "latest shadow-eval loss on the held-out stream"),
    ("mgwfbp_shadow_eval_delta", "gauge",
     "latest shadow-eval loss minus training loss (served-vs-training)"),
    # fleet fan-in synthesis (rendered only by telemetry/fleet.py's
    # /fleet/metrics, never by a per-process endpoint — registered here
    # so the fleet exposition flows through the same single registry)
    ("mgwfbp_fleet_processes", "gauge",
     "child processes answering the fleet fan-in scrape"),
    ("mgwfbp_fleet_unreachable", "gauge",
     "child processes that failed the fleet fan-in scrape"),
    ("mgwfbp_fleet_straggler_excess_seconds", "gauge",
     "slowest minus fastest process mean step seconds (live fan-in)"),
)

# event type -> counter metric (shared by the aggregator's incremental
# counting and anyone asking which events are counted at all)
EVENT_COUNTERS: dict[str, str] = {
    "step": "mgwfbp_steps_total",
    "resize": "mgwfbp_resizes_total",
    "checkpoint": "mgwfbp_checkpoints_total",
    "watchdog_stall": "mgwfbp_watchdog_stalls_total",
    "autotune_race": "mgwfbp_autotune_races_total",
    "autotune_commit": "mgwfbp_autotune_commits_total",
    "bench_skip": "mgwfbp_bench_skips_total",
    "bad_step": "mgwfbp_bad_steps_total",
    "rollback": "mgwfbp_rollbacks_total",
    "preempt": "mgwfbp_preempts_total",
    "resume": "mgwfbp_resumes_total",
    "failure": "mgwfbp_failures_total",
    "heal": "mgwfbp_heals_total",
    "profile": "mgwfbp_profile_windows_total",
    "postmortem": "mgwfbp_postmortems_total",
    "reload": "mgwfbp_serve_reloads_total",
    "shadow_eval": "mgwfbp_shadow_evals_total",
}


def render_metrics(values: dict) -> str:
    """Prometheus text exposition of a metric-value dict, in registry
    order. `values` maps registry names to numbers (int -> rendered as an
    integer, float -> %g); names missing from the dict are skipped, names
    outside the registry are rejected — an unregistered metric is exactly
    the file-dump-vs-live-endpoint drift this registry exists to stop."""
    known = {name for name, _, _ in METRICS}
    stray = set(values) - known
    if stray:
        raise ValueError(
            f"metrics {sorted(stray)} are not in telemetry.export.METRICS; "
            "register them there so every exposition surface shows them"
        )
    lines: list[str] = []
    for name, kind, help_ in METRICS:
        if name not in values:
            continue
        v = values[name]
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {v:g}" if isinstance(v, float)
                     else f"{name} {v}")
    return "\n".join(lines) + "\n"


def render_labeled_metrics(
    series: dict[str, dict],
    label: str = "process",
    extra: Optional[dict] = None,
) -> str:
    """Prometheus text exposition of SEVERAL processes' metric values
    merged under one label (the fleet fan-in's /fleet/metrics): for each
    registry metric, HELP/TYPE once, then one ``name{label="key"} value``
    line per series that carries it. ``extra`` holds unlabeled fleet-level
    values (the mgwfbp_fleet_* gauges). Same registry, same stray-name
    rejection as `render_metrics` — the fleet render and the per-process
    render flow through ONE metric statement and cannot drift."""
    known = {name for name, _, _ in METRICS}
    stray = set(extra or {}) - known
    for key, values in series.items():
        stray |= set(values) - known
    if stray:
        raise ValueError(
            f"metrics {sorted(stray)} are not in telemetry.export.METRICS; "
            "register them there so every exposition surface shows them"
        )
    extra = extra or {}
    lines: list[str] = []
    for name, kind, help_ in METRICS:
        rows: list[str] = []
        for key in sorted(series, key=str):
            values = series[key]
            if name not in values:
                continue
            v = values[name]
            val = f"{v:g}" if isinstance(v, float) else str(v)
            rows.append(f'{name}{{{label}="{key}"}} {val}')
        if name in extra:
            v = extra[name]
            val = f"{v:g}" if isinstance(v, float) else str(v)
            rows.append(f"{name} {val}")
        if not rows:
            continue
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {kind}")
        lines.extend(rows)
    return "\n".join(lines) + "\n"


def parse_metrics_text(text: str) -> dict:
    """`render_metrics`'s inverse: registry-named values from one
    process's Prometheus text exposition (the fleet fan-in scrapes child
    /metrics endpoints and re-renders them labeled). Unregistered names
    raise — a child exposing metrics this build's registry does not know
    means mismatched versions, which the operator should see, not a
    silently dropped series."""
    known = {name for name, _, _ in METRICS}
    out: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) != 2:
            raise ValueError(f"unparseable metrics line: {line!r}")
        name, raw = parts
        if name not in known:
            raise ValueError(
                f"metric {name!r} is not in telemetry.export.METRICS "
                "(scraped child runs a different registry version?)"
            )
        try:
            out[name] = int(raw)
        except ValueError:
            out[name] = float(raw)
    return out


def prometheus_text(records: list[dict]) -> str:
    """Prometheus text-exposition dump of the stream's counters/gauges.

    Implemented by replaying the records through the SAME aggregator the
    live /metrics endpoint serves from (`serve.MetricsAggregator`), so
    the file dump and the endpoint render identical values through one
    registry by construction."""
    from mgwfbp_tpu.telemetry.serve import MetricsAggregator

    agg = MetricsAggregator()
    agg.replay(records)
    return render_metrics(agg.values())


def write_prometheus(path: str, records: list[dict]) -> str:
    text = prometheus_text(records)
    with open(path, "w") as f:
        f.write(text)
    return text
