"""Structured run-observability event stream.

MG-WFBP's whole claim is that the merged schedule *hides* communication
behind the backward pass (arXiv:1811.11141); a production run must be able
to show that it actually does. This module is the spine of the telemetry
subsystem: an append-only, schema-versioned JSONL stream of TYPED records
every layer of the framework feeds — step spans from the trainer's (un-jitted)
step loop, per-merge-group comm spans with exposed/hidden attribution
(`telemetry.overlap`), autotune race rows, elastic resizes, checkpoint
saves, watchdog stalls, bench skips — so a post-mortem, an overlap report
(`tools/telemetry_report.py`), and a Chrome-trace render
(`telemetry.export`) all read from ONE greppable file.

Wire format: line 1 is a ``header`` record carrying ``schema_version``
(validated by the same `check_schema_version` the calibration profiles and
the schedule cache use); every following line is one event object::

    {"event": "step", "wall": 1722760000.1, "step": 12, "epoch": 0,
     "start_s": 3.41, "dur_s": 0.021}

Hot-path discipline: the writer NEVER touches the device. ``emit`` rejects
any field value that is not a plain JSON scalar/list/dict — handing it a
jax array (whose serialization would force a device sync) raises
``TypeError`` instead of silently stalling the step loop. Step spans are
host wall-clock around the *dispatch* of the async jitted step: once the
dispatch pipeline fills, their cadence equals realized step throughput,
and no block_until_ready / device_get is ever issued on their behalf
(enforced by the zero-sync guard in tests/test_telemetry.py and lint rule
JIT006 for the jitted side).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from mgwfbp_tpu.parallel.costmodel import check_schema_version

# Version 1 is the legacy headerless ScalarWriter JSONL
# ({"wall","step","tag","value"} rows, utils/summary.py) — `read_events`
# migrates it to `scalar` records. Version 2 is the typed stream below.
EVENT_SCHEMA_VERSION = 2
_LEGACY_SCALAR_VERSION = 1

# Typed records: event name -> required fields (beyond "event"/"wall").
# Extra fields are allowed — the schema names the invariants a reader may
# rely on, not the exhaustive payload.
EVENT_TYPES: dict[str, tuple[str, ...]] = {
    # run metadata; always the stream's first record
    "header": ("schema_version",),
    # one optimizer step: host wall-clock span around the async dispatch,
    # start_s relative to the stream's epoch (header wall)
    "step": ("step", "epoch", "start_s", "dur_s"),
    # one merge group's comm span within the step timeline (model-replayed
    # start, measured or predicted duration; see telemetry.overlap).
    # Hierarchical (hier) regimes additionally carry ici_s/dcn_s — the
    # group's comm split by link — and cross-step regimes ag_start_s/ag_s.
    "comm_group": ("step", "group", "nbytes", "comm_s", "start_s",
                   "hidden_s", "exposed_s", "attribution"),
    # aggregate overlap-efficiency snapshot for the surrounding step
    # regime; hier regimes add ici_s/dcn_s/bottleneck_link (which
    # interconnect carries the larger comm share)
    "overlap": ("step", "epoch", "step_s", "tb_total_s", "comm_s",
                "hidden_s", "exposed_s", "efficiency", "attribution"),
    # ScalarWriter view: the legacy scalar rows, now in the same stream
    "scalar": ("tag", "value", "step"),
    # epoch boundary (throughput trend anchor for the report CLI)
    "epoch": ("epoch", "steps", "dur_s"),
    # autotune: one raced candidate / the committed winner
    "autotune_race": ("label", "comm_op", "num_groups", "verified",
                      "measured_step_s"),
    "autotune_commit": ("winner", "comm_op", "num_groups", "source"),
    # elastic resize seam; schedule_source records which path won the
    # post-resize schedule ("schedule-cache" vs "solver" for an in-place
    # update_nworker, "relaunch-reshard" when a supervisor-driven
    # relaunch re-sharded a sibling world's shard-native checkpoint)
    "resize": ("old_world", "new_world", "schedule_source", "num_groups"),
    # a written snapshot; mid_epoch=True rows (the --ckpt-every-steps /
    # preemption-drain path) additionally carry epoch_step. Rows also
    # carry the save cost — duration_s + bytes (this process's payload)
    # + format ("sharded" | "replicated") — so the report tool and
    # flight recorder surface checkpoint-cost regressions
    "checkpoint": ("epoch", "iteration", "mid_epoch"),
    # watchdog stall/abort (also CRITICAL-logged; this makes it greppable
    # from the same file as the step records)
    "watchdog_stall": ("phase", "idle_s", "timeout_s", "abort"),
    # bench.py structured skip (chip unavailable)
    "bench_skip": ("detail",),
    # --- resilience layer (ISSUE 5) ------------------------------------
    # graceful preemption drain: the in-flight step finished, a
    # step-indexed checkpoint was written, the process exits rc 75
    "preempt": ("signal", "epoch", "iteration"),
    # non-finite-gradient guard: the jitted step dropped this update
    # (nonfinite = global count of non-finite gradient elements)
    "bad_step": ("step", "epoch", "nonfinite"),
    # K consecutive bad steps -> trainer rolled back to the last checkpoint
    "rollback": ("bad_steps", "restored_iteration", "restored_epoch"),
    # a restart picked up from a saved snapshot (mid_epoch = step-indexed
    # mid-epoch checkpoint, i.e. the preemption-safe resume path)
    "resume": ("epoch", "iteration", "mid_epoch"),
    # --- live observability plane (ISSUE 9) ----------------------------
    # cost-model drift (telemetry/drift.py): `kind` is 'comm_residual'
    # (predicted-vs-measured merge-group comm, `group` = arrival index or
    # -1 for the aggregate) or 'step_trend' (EWMA step time vs the
    # baseline window); `residual` is the ratio/excess that crossed (or
    # re-entered) `band`; active=True raises the alarm, False clears it
    # (hysteresis guarantees no flapping between the two)
    "drift_alarm": ("kind", "step", "residual", "band", "active"),
    # live multi-host straggler probe: per agree-interval the group
    # gathers its window step times (runtime/coordination); the slowest
    # process is named in `slow_process` (NOT 'process' — the merge tool
    # stamps each record with its emitting stream's process index under
    # that key). excess_s = slowest minus fastest window step seconds.
    "straggler": ("step", "slow_process", "excess_s", "step_s_max",
                  "step_s_min", "active"),
    # --- fleet console + deep profiling (ISSUE 10) ----------------------
    # one completed on-demand /profile trace window: `steps` live steps
    # traced, `attribution` 'trace' when per-group device time attributed
    # (device_s rides along per group, layout order) or 'none'
    "profile": ("step", "steps", "attribution"),
    # --- training-health telemetry + flight recorder (ISSUE 12) ---------
    # one optimizer step's model-health statistics, read one step LATE
    # off the jitted step's metrics psum (the PR-5 deque idiom — no
    # device_get on the dispatch path). grad_norm is the global gradient
    # L2 norm (post-reduction on the in-step lowerings; mean of the local
    # pre-reduction norms on the sharded rs_opt_ag/rs_fwd_ag paths),
    # update_ratio the update/param L2-norm ratio. `group_norms` rides
    # along as the per-merge-group grad-norm list (arrival order, [] when
    # no reducer), and `compression_error` as the per-group relative
    # top-k compression error when a sparsifying compressor is live.
    "health": ("step", "epoch", "loss", "grad_norm", "update_ratio"),
    # online health-detector edge (telemetry/health.py): `kind` is
    # 'loss_spike' | 'grad_explosion' | 'plateau' | 'compression_error';
    # `value` the residual that crossed (or re-entered) `band`;
    # active=True raises, False clears (two-edge Hysteresis — no flap)
    "health_alarm": ("kind", "step", "value", "band", "active"),
    # the flight recorder wrote one postmortem bundle (telemetry/
    # recorder.py): `trigger` names the alarm event that tripped it,
    # `step` the trigger's step, `path` the bundle directory
    "postmortem": ("trigger", "step", "path"),
    # --- serving plane (ISSUE 19) ---------------------------------------
    # the serving model hot-reloaded a newly committed shard-native
    # checkpoint: `step` the served train step after the swap, `lag_s`
    # commit-to-served latency (manifest mtime -> swap), `duration_s` the
    # load+install time itself
    "reload": ("step", "lag_s", "duration_s"),
    # shadow-eval scored the held-out stream against a freshly served
    # checkpoint; `train_loss` rides as an extra when the emitter knows it
    # so the report can plot served-vs-training loss from the stream alone
    "shadow_eval": ("step", "loss"),
    # periodic request-plane snapshot from the dispatcher: `requests` is
    # the CUMULATIVE served-request count, queue_depth the bounded queue's
    # instantaneous depth, batch_fill the mean fill ratio of flushed batch
    # slots since the last snapshot; latency quantiles ride as extras
    # (latency_p50_s/p95_s/p99_s over the recent-request window)
    "serve_stats": ("requests", "queue_depth", "batch_fill"),
    # --- self-healing supervisor (ISSUE 20) -----------------------------
    # the supervisor (or trainer) observed one HARD failure: `class` is
    # 'crash' | 'oom_kill' | 'wedge' | 'unreachable' | 'coordination',
    # `target` names the failed member ('p1', 'serve0', ...). rc/signal/
    # step ride as extras when known
    "failure": ("class", "target"),
    # the supervisor's healing policy acted on a failure: `action` is
    # 'relaunch' (same world) | 'shrink' (elastic resume at survivor
    # count) | 'respawn_serve' | 'stop' (budget exhausted / crash loop).
    # world/incarnation/restarts ride as extras
    "heal": ("action",),
}

_JSON_SCALARS = (str, int, float, bool, type(None))


def stream_filename(process_index: int = 0, process_count: int = 1) -> str:
    """Per-run stream file name. Single-process runs keep the historical
    ``telemetry.jsonl``; a multi-host group writes one stream PER PROCESS
    (``telemetry.pN.jsonl``, process_index/process_count in the header's
    run metadata) — `tools/telemetry_merge.py` reassembles the global
    timeline. One convention, shared by the trainer and the merge tool."""
    if process_count <= 1:
        return "telemetry.jsonl"
    return f"telemetry.p{int(process_index)}.jsonl"


def find_stream_paths(directory: str) -> list[str]:
    """Active stream files under `directory` (single- or multi-process
    naming), process order. Rotated ``.NNNN`` segments are NOT listed —
    `read_event_set` on an active path folds its segments in."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        if name == "telemetry.jsonl":
            out.append((-1, name))
        elif name.startswith("telemetry.p") and name.endswith(".jsonl"):
            idx = name[len("telemetry.p"):-len(".jsonl")]
            if idx.isdigit():
                out.append((int(idx), name))
    multi = [e for e in out if e[0] >= 0]
    if multi:
        # a multi-host group never writes the single-process name, so a
        # telemetry.jsonl sitting next to pN streams is a stale earlier
        # single-host run of the same (deterministic) tag — listing it
        # would silently interleave two different runs' timelines in the
        # merge
        out = multi
    return [os.path.join(directory, n) for _, n in sorted(out)]


def _check_jsonable(value, key: str) -> None:
    """Reject anything that is not already host-side JSON data.

    A device array here would force a host transfer during serialization —
    exactly the sync the telemetry contract forbids — so it fails loudly at
    the emit site instead."""
    if isinstance(value, _JSON_SCALARS):
        return
    if isinstance(value, (list, tuple)):
        for i, v in enumerate(value):
            _check_jsonable(v, f"{key}[{i}]")
        return
    if isinstance(value, dict):
        for k, v in value.items():
            _check_jsonable(v, f"{key}.{k}")
        return
    raise TypeError(
        f"telemetry field {key!r} is {type(value).__name__}, not plain JSON "
        "data; convert device values on a cold path first (telemetry must "
        "add zero device syncs to the step loop)"
    )


def _rotated_segments(path: str) -> list[str]:
    """Rotated sibling files of an active stream, oldest first.

    Rotation renames the active file to ``<path>.NNNN`` (zero-padded
    sequence); sort by that integer, NOT lexically, so segment 10 follows
    9 even if a hand-rotated unpadded name slipped in."""
    d = os.path.dirname(path) or "."
    base = os.path.basename(path)
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return []
    for name in names:
        if not name.startswith(base + "."):
            continue
        suffix = name[len(base) + 1:]
        if suffix.isdigit():
            out.append((int(suffix), os.path.join(d, name)))
    return [p for _, p in sorted(out)]


def _next_segment_index(path: str) -> int:
    """Index the ACTIVE stream at `path` will rotate into next: one past
    the highest existing segment — NOT the segment count, which would
    re-use (and os.replace would silently clobber) the newest surviving
    segment after an operator deletes old ones to reclaim disk."""
    segs = _rotated_segments(path)
    if not segs:
        return 0
    last = os.path.basename(segs[-1])
    return int(last.rsplit(".", 1)[1]) + 1


class EventWriter:
    """Append-only JSONL event stream (one run, process 0).

    Writes the versioned header when it creates (or first appends to an
    empty) file; re-opening an existing stream appends without a second
    header. Thread-safe for concurrent emitters (the watchdog fires from
    its daemon thread) — each record is one line-buffered write.

    Week-long jobs rotate by size (ROADMAP PR-4 follow-up): when the
    active file exceeds ``max_bytes`` (default from
    ``MGWFBP_TELEMETRY_MAX_MB``; unset/0 = never rotate) it is renamed to
    ``<path>.NNNN`` and a fresh segment opens. Every segment starts with
    its own header carrying the SET's original wall anchor and a
    ``segment`` index, so `read_event_set` reassembles one continuous
    timeline and a restart re-anchors correctly off the active segment.
    """

    def __init__(
        self,
        path: str,
        run: Optional[dict] = None,
        max_bytes: Optional[int] = None,
        observer=None,
    ):
        # observer(event, fields) is called for every emitted record AFTER
        # schema validation — the live metrics aggregator
        # (telemetry/serve.py) tees off here so the /metrics endpoint and
        # the JSONL file are fed by the SAME validated stream. A failing
        # observer is detached, never fatal: observability must not kill
        # the run it observes.
        self.observer = observer
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if max_bytes is None:
            mb = os.environ.get("MGWFBP_TELEMETRY_MAX_MB", "").strip()
            max_bytes = int(float(mb) * 1024 * 1024) if mb else 0
        self.max_bytes = max(int(max_bytes), 0)
        self._run = dict(run or {})
        self._segment = _next_segment_index(path)
        fresh = not (os.path.exists(path) and os.path.getsize(path) > 0)
        header_wall = None
        if not fresh:
            # re-opening (resume under the same tag): span timestamps stay
            # relative to the ORIGINAL header's wall clock, so appended
            # records extend the stream's timeline instead of restarting
            # at zero on top of the first run's spans (rotation headers
            # re-stamp that original anchor into every segment)
            try:
                with open(path) as f:
                    first = json.loads(f.readline())
                if first.get("event") == "header":
                    header_wall = float(first.get("wall", 0.0)) or None
                    self._run = dict(first.get("run", self._run) or {})
            except (OSError, ValueError):
                header_wall = None
        self._f = open(path, "a", buffering=1)  # line-buffered
        self._bytes = 0 if fresh else os.path.getsize(path)
        self._lock = threading.Lock()
        # stream-relative clock for span timestamps: monotonic, immune to
        # wall-clock steps mid-run; anchored at the stream header's wall
        self._t0 = time.perf_counter()
        self._anchor_wall = header_wall if header_wall else time.time()
        if header_wall is not None:
            self._t0 -= max(time.time() - header_wall, 0.0)
        if fresh:
            self._emit_record(
                "header",
                wall=self._anchor_wall,
                schema_version=EVENT_SCHEMA_VERSION,
                run=self._run,
                segment=self._segment,
            )

    def now(self) -> float:
        """Seconds since this writer opened (span-timestamp base)."""
        return time.perf_counter() - self._t0

    def emit(self, event: str, **fields) -> None:
        """Append one typed record. Unknown event names and missing
        required fields raise — a misspelled emitter must fail its test,
        not write rows no reader understands."""
        required = EVENT_TYPES.get(event)
        if required is None:
            raise ValueError(
                f"unknown telemetry event {event!r}; known: "
                f"{sorted(EVENT_TYPES)}"
            )
        missing = [k for k in required if k not in fields]
        if missing:
            raise ValueError(
                f"telemetry event {event!r} missing required field(s) "
                f"{missing}"
            )
        for k, v in fields.items():
            _check_jsonable(v, k)
        if self.observer is not None:
            try:
                self.observer(event, fields)
            except Exception:  # noqa: BLE001 — a broken aggregator must
                # not take the stream (or the run) down with it; but say
                # so loudly: from here on the live /metrics//status
                # surfaces freeze at their last values while the JSONL
                # keeps advancing
                import logging

                logging.getLogger("mgwfbp.telemetry").exception(
                    "telemetry observer failed on %r; detaching — live "
                    "metrics/health endpoints will no longer update",
                    event,
                )
                self.observer = None
        self._emit_record(event, wall=time.time(), **fields)

    def _emit_record(self, event: str, wall: float, **fields) -> None:
        rec = {"event": event, "wall": round(wall, 3), **fields}
        line = json.dumps(rec) + "\n"
        with self._lock:
            if self._f.closed:
                return
            self._f.write(line)
            self._bytes += len(line)
            if (
                self.max_bytes
                and self._bytes > self.max_bytes
                and event != "header"
            ):
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Roll the active file to the next ``<path>.NNNN`` segment and
        start a fresh one (caller holds the lock). A failed rename (e.g.
        read-only sibling dir entries) disables rotation rather than
        killing the run — same contract as every other telemetry failure."""
        self._f.close()
        target = f"{self.path}.{self._segment:04d}"
        try:
            os.replace(self.path, target)
        except OSError:
            self.max_bytes = 0  # rotation unavailable; keep appending
            self._f = open(self.path, "a", buffering=1)
            return
        self._segment += 1
        self._f = open(self.path, "a", buffering=1)
        self._bytes = 0
        # segment header: SAME schema + run + original wall anchor, so a
        # restart re-anchoring off this segment (and any reader of it in
        # isolation) sees the set's single continuous timeline
        rec = {
            "event": "header",
            "wall": round(self._anchor_wall, 3),
            "schema_version": EVENT_SCHEMA_VERSION,
            "run": self._run,
            "segment": self._segment,
        }
        line = json.dumps(rec) + "\n"
        self._f.write(line)
        self._bytes += len(line)

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


def migrate_legacy_scalars(rows: list[dict]) -> list[dict]:
    """Lift a v1 (headerless ScalarWriter) stream into v2 records."""
    out = []
    for r in rows:
        out.append({
            "event": "scalar",
            "wall": r.get("wall", 0.0),
            "tag": r.get("tag", ""),
            "value": r.get("value"),
            "step": r.get("step", 0),
        })
    return out


def read_events(path: str) -> list[dict]:
    """Load a telemetry stream, validating (and migrating) its schema.

    * v2 stream (leading ``header`` record): version-checked via
      `check_schema_version`; returns all records including the header.
    * v1 legacy stream (headerless ScalarWriter JSONL): each row migrates
      to a ``scalar`` record and a synthesized v2 header is prepended.
    * Anything stamped with a version this build does not read raises
      ValueError — a newer writer's file must fail loudly.
    """
    rows: list[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    if not rows:
        return []
    first = rows[0]
    if first.get("event") == "header" or "schema_version" in first:
        check_schema_version(
            first, path=path, supported=(EVENT_SCHEMA_VERSION,),
            what="telemetry event stream",
        )
        return rows
    # headerless: the legacy scalar layout (or garbage, which json.loads
    # above would already have rejected line-wise)
    migrated = migrate_legacy_scalars(rows)
    header = {
        "event": "header",
        "wall": migrated[0].get("wall", 0.0),
        "schema_version": EVENT_SCHEMA_VERSION,
        "run": {"migrated_from": _LEGACY_SCALAR_VERSION},
    }
    return [header] + migrated


def read_event_set(path: str) -> list[dict]:
    """Load a possibly-rotated stream: every ``<path>.NNNN`` segment in
    sequence order, then the active file. Each segment is schema-validated
    by `read_events`; the first header is kept and the per-segment
    continuation headers dropped, so consumers see ONE stream exactly as
    if rotation had never happened. A bare un-rotated file reads
    identically to `read_events`."""
    parts = _rotated_segments(path)
    if os.path.exists(path):
        parts = parts + [path]
    if not parts:
        raise FileNotFoundError(path)
    out: list[dict] = []
    for p in parts:
        rows = read_events(p)
        for r in rows:
            if r.get("event") == "header" and out:
                continue  # continuation header of a later segment
            out.append(r)
    return out


def events_of(records: list[dict], *names: str) -> list[dict]:
    """Filter records by event type (reader-side convenience)."""
    want = set(names)
    return [r for r in records if r.get("event") in want]
