"""Live observability plane: in-memory metric aggregation + HTTP endpoints.

The telemetry stream (telemetry/events.py) was post-hoc only: JSONL on
disk, rendered after the fact by tools/telemetry_report.py. A week-long
supervised multi-host run needs the same answers WHILE it runs — is this
job healthy, what step is it on, is the schedule still right, which host
is slow. This module serves them per process:

  * ``MetricsAggregator`` — an in-memory view fed by the SAME validated
    event stream the JSONL writer appends (the ``EventWriter.observer``
    tee), plus host-side schedule/health facts the trainer pushes. Pure
    host data in, pure host data out: nothing here may ever touch a
    device value (the zero-sync telemetry contract; the emit-site
    JSON-scalar check already rejects device arrays before they reach the
    observer).
  * ``TelemetryServer`` — an opt-in background HTTP server
    (``--metrics-port`` / ``MGWFBP_METRICS_PORT``; a multi-host group
    serves ``port + process_index`` per process) exposing

      /metrics   Prometheus text, rendered live from the aggregator
                 through the SAME registry as the post-hoc file dump
                 (telemetry.export.METRICS / render_metrics — the two
                 surfaces cannot drift);
      /healthz   liveness: 200 while the step loop makes progress, 503
                 once the watchdog reports a stall (sticky when the
                 stall is rc-86-abort-bound — the flip lands BEFORE the
                 process dies, so a prober sees unhealthy, not a reset
                 connection); a later step clears a non-abort stall;
      /status    JSON: run metadata, current step/epoch, the committed
                 merge schedule + comm_op, rolling overlap efficiency,
                 last checkpoint, bad-step/rollback counts, active
                 drift/straggler alarms, profile-window state;
      /profile   on-demand deep profiling (ISSUE 10): ``?steps=N`` arms a
                 bounded ``jax.profiler.trace`` window over the next N
                 live steps — the handler only flips host state; the step
                 loop runs the window at the next (multi-host: group-
                 agreed) boundary, writes a Chrome-trace slice, and posts
                 the per-merge-group device-attributed table back here.

  * The fleet fan-in (`telemetry/fleet.py`, served by the supervisor)
    scrapes these per-process endpoints and merges them under a
    ``process`` label through the SAME metric registry.

The server thread only ever reads the aggregator under its lock — it
issues no device calls, touches no jax state, and a dead server (port
collision, interface gone) degrades to a logged warning, never a failed
training run.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from mgwfbp_tpu.utils.logging import get_logger

METRICS_PORT_ENV = "MGWFBP_METRICS_PORT"
METRICS_HOST_ENV = "MGWFBP_METRICS_HOST"
# where to persist this process's ACTUAL bound port (JSON sidecar): the
# supervisor exports one path per child so the fleet fan-in and fleet.json
# never have to guess ports — the base+index convention cannot cover the
# ephemeral (base == 0) case at all
METRICS_PORT_FILE_ENV = "MGWFBP_METRICS_PORT_FILE"
# role-aware port namespace (ISSUE 19 satellite): serving replicas ride
# the SAME base-port convention as training children, displaced by this
# offset so `supervise --serve-replicas N` can never collide a serve
# replica's listen port with a training child's (train: base + index;
# serve: base + offset + index). 100 leaves room for any plausible
# training world below the serve band.
SERVE_PORT_OFFSET_ENV = "MGWFBP_SERVE_PORT_OFFSET"
DEFAULT_SERVE_PORT_OFFSET = 100

# hard ceiling on one /profile window: the endpoint is unauthenticated on
# loopback and the window syncs the device, so a request may never arm an
# unbounded trace
PROFILE_MAX_STEPS = 50

# rolling window for the mean-step gauge — matches the historical
# prometheus_text behavior (mean over the last <= 20 step spans)
_STEP_WINDOW = 20


def routable_host() -> str:
    """This machine's best routable address, for ADVERTISING a wildcard
    bind (0.0.0.0) to off-host scrapers: the fleet fan-in and an external
    Prometheus reading fleet.json need an address a peer host can dial,
    and the wildcard is not one. Resolution: the kernel's outbound-route
    pick (a UDP connect sends nothing), then the hostname's address, then
    loopback — each step degrades, never raises."""
    import socket as _socket

    try:
        s = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 9))
            host = s.getsockname()[0]
            if host and not host.startswith("0."):
                return host
        finally:
            s.close()
    except OSError:
        pass
    try:
        host = _socket.gethostbyname(_socket.gethostname())
        if host:
            return host
    except OSError:
        pass
    return "127.0.0.1"


def advertised_host(bound_host: str) -> str:
    """The address peers should DIAL for a server bound at `bound_host`:
    wildcard binds advertise the routable address, concrete binds
    advertise themselves."""
    if bound_host in ("", "0.0.0.0", "::"):
        return routable_host()
    return bound_host


def serve_port_offset() -> int:
    """The serving role's displacement above the training port band."""
    raw = (os.environ.get(SERVE_PORT_OFFSET_ENV) or "").strip()
    if not raw:
        return DEFAULT_SERVE_PORT_OFFSET
    try:
        off = int(raw)
    except ValueError:
        return DEFAULT_SERVE_PORT_OFFSET
    return off if off > 0 else DEFAULT_SERVE_PORT_OFFSET


def resolve_metrics_port(
    base_port: Optional[int], process_index: int = 0, role: str = "train",
) -> Optional[int]:
    """Concrete listen port for one process of a run: ``base + index`` so
    a multi-host group's processes serve distinct ports from ONE
    configured value (the supervisor exports a single environment).
    ``role='serve'`` displaces the whole band by ``serve_port_offset()``
    so serving replicas sharing a supervisor's base can never collide
    with its training children. ``base == 0`` asks the OS for an
    ephemeral port per process (the bound port is logged and available
    as ``TelemetryServer.port``); None disables the plane."""
    if base_port is None:
        return None
    base = int(base_port)
    if base < 0:
        raise ValueError(f"metrics port must be >= 0, got {base}")
    if role not in ("train", "serve"):
        raise ValueError(f"unknown metrics role {role!r}")
    offset = serve_port_offset() if role == "serve" else 0
    port = 0 if base == 0 else base + offset + int(process_index)
    if port > 65535:
        # base + index walked off the end of the port space; an
        # observability knob must degrade (the caller warns), not kill
        # the training process with an OverflowError out of socket.bind
        raise ValueError(
            f"metrics port {base} + role offset {offset} + "
            f"process_index {process_index} exceeds 65535"
        )
    return port


class MetricsAggregator:
    """In-memory metric/health/status state for one process's run.

    Fed two ways, both host-only:
      * ``observe(event, fields)`` — the EventWriter tee (live runs) or
        ``replay(records)`` over an already-written stream (file dump,
        supervisor post-mortems); rotated-segment continuation headers
        and per-process streams replay cleanly (headers only refresh run
        metadata).
      * explicit setters (``set_schedule``) for facts that are not
        events.

    Thread-safe: the step loop, the watchdog thread, and HTTP handler
    threads all touch it.
    """

    def __init__(self, run: Optional[dict] = None):
        self._lock = threading.Lock()
        self._run = dict(run or {})
        self._t0 = time.time()
        self._counts: collections.Counter = collections.Counter()
        self._step_durs: collections.deque = collections.deque(
            maxlen=_STEP_WINDOW
        )
        self._current_step: Optional[int] = None
        self._current_epoch: Optional[int] = None
        self._overlap: Optional[dict] = None
        self._last_checkpoint: Optional[dict] = None
        self._schedule: Optional[dict] = None
        self._last_drift_residual: Optional[float] = None
        self._last_straggler_excess: Optional[float] = None
        # training-health telemetry (ISSUE 12): the latest per-step
        # `health` record, and the flight recorder's recent bundle
        # manifests (fed by `postmortem` events — live tee or replay)
        self._health: Optional[dict] = None
        self._postmortems: collections.deque = collections.deque(maxlen=20)
        # serving plane (ISSUE 19): latest hot-reload / dispatcher
        # snapshot / shadow-eval facts, fed by the same validated stream
        # (`reload`, `serve_stats`, `shadow_eval` events)
        self._serving_step: Optional[int] = None
        self._reload_lag_s: Optional[float] = None
        self._serve_stats: Optional[dict] = None
        self._shadow: Optional[dict] = None
        # (kind, group/slow_process) -> alarm fields, kept while active
        self._active_alarms: dict = {}
        # health: None = healthy; else the reason string. Sticky once an
        # abort-bound stall landed (the process is about to os._exit(86))
        self._unhealthy: Optional[str] = None
        self._unhealthy_sticky = False
        # on-demand deep profiling (/profile?steps=N): the HTTP handler
        # only ARMS a request here; the trainer's step loop consumes it
        # at the next (group-agreed, on multi-host) step boundary and
        # posts the result back — the handler thread itself never touches
        # jax. `_profile_supported` flips True when a live trainer
        # attaches; a replay-only aggregator rejects arming.
        self._profile_supported = False
        self._profile_state = "idle"  # idle|armed|running|done|failed
        self._profile_steps: Optional[int] = None
        self._profile_result: Optional[dict] = None
        self._profile_error: Optional[str] = None

    # -- feeding -----------------------------------------------------------
    def observe(self, event: str, fields: dict) -> None:
        """One validated telemetry record (the EventWriter tee)."""
        with self._lock:
            self._observe_locked(event, fields)

    def replay(self, records) -> None:
        """Feed an already-written stream (rotated sets and per-process
        streams read by `events.read_event_set` replay as-is)."""
        with self._lock:
            for rec in records:
                ev = rec.get("event")
                if not ev:
                    continue
                self._observe_locked(
                    ev, {k: v for k, v in rec.items() if k != "event"}
                )

    def _observe_locked(self, event: str, fields: dict) -> None:
        from mgwfbp_tpu.telemetry.export import EVENT_COUNTERS

        counter = EVENT_COUNTERS.get(event)
        if counter:
            self._counts[counter] += 1
        if event == "header":
            run = fields.get("run")
            if isinstance(run, dict):
                self._run.update(run)
        elif event == "step":
            self._step_durs.append(float(fields.get("dur_s", 0.0)))
            self._current_step = int(fields.get("step", 0))
            self._current_epoch = int(fields.get("epoch", 0))
            if not self._unhealthy_sticky:
                # progress after a non-abort stall: the step loop moved
                # again, so liveness recovers
                self._unhealthy = None
        elif event == "epoch":
            self._current_epoch = int(fields.get("epoch", 0))
        elif event == "overlap":
            self._overlap = dict(fields)
        elif event == "checkpoint":
            self._last_checkpoint = dict(fields)
        elif event == "watchdog_stall":
            abort = bool(fields.get("abort"))
            self._unhealthy = (
                f"watchdog stall in {fields.get('phase')!r} after "
                f"{float(fields.get('idle_s', 0.0)):.0f}s"
                + (" — aborting (rc 86)" if abort else "")
            )
            if abort:
                self._unhealthy_sticky = True
        elif event == "drift_alarm":
            key = ("drift", fields.get("kind"), fields.get("group", -1))
            if fields.get("active"):
                self._counts["mgwfbp_drift_alarms_total"] += 1
                self._active_alarms[key] = dict(fields, alarm="drift")
            else:
                self._active_alarms.pop(key, None)
            self._last_drift_residual = float(fields.get("residual", 0.0))
        elif event == "straggler":
            key = ("straggler",)
            if fields.get("active"):
                self._counts["mgwfbp_straggler_alarms_total"] += 1
                self._active_alarms[key] = dict(fields, alarm="straggler")
            else:
                self._active_alarms.pop(key, None)
            self._last_straggler_excess = float(
                fields.get("excess_s", 0.0)
            )
        elif event == "health":
            self._health = dict(fields)
        elif event == "health_alarm":
            key = ("health", fields.get("kind"), fields.get("group", -1))
            if fields.get("active"):
                self._counts["mgwfbp_health_alarms_total"] += 1
                self._active_alarms[key] = dict(fields, alarm="health")
            else:
                self._active_alarms.pop(key, None)
        elif event == "postmortem":
            self._postmortems.append(dict(fields))
        elif event == "reload":
            self._serving_step = int(fields.get("step", 0))
            self._reload_lag_s = float(fields.get("lag_s", 0.0))
        elif event == "serve_stats":
            self._serve_stats = dict(fields)
        elif event == "shadow_eval":
            self._shadow = dict(fields)

    def set_schedule(
        self, comm_op: str, num_groups: int, policy_detail: str = "",
        predicted_nonoverlap_s: Optional[float] = None,
    ) -> None:
        """The committed merge schedule (trainer pushes this at build,
        autotune commit, and elastic resize — it is state, not an
        event)."""
        with self._lock:
            self._schedule = {
                "comm_op": str(comm_op),
                "num_groups": int(num_groups),
                "policy_detail": str(policy_detail),
            }
            if predicted_nonoverlap_s is not None:
                self._schedule["predicted_nonoverlap_s"] = float(
                    predicted_nonoverlap_s
                )

    # -- on-demand deep profiling (/profile) -------------------------------
    def enable_profile(self) -> None:
        """A live trainer attached: /profile?steps=N requests now have a
        consumer (the step loop polls `take_profile_request`)."""
        with self._lock:
            self._profile_supported = True

    def arm_profile(self, steps) -> tuple[int, dict]:
        """Arm a bounded trace window for the next `steps` live steps
        (the HTTP handler's side). Returns (http status, response doc)."""
        with self._lock:
            if not self._profile_supported:
                return 409, {
                    "error": "no live trainer attached to this endpoint "
                             "(replay-only aggregator cannot profile)",
                }
            try:
                n = int(steps)
            except (TypeError, ValueError):
                return 400, {"error": f"steps={steps!r} is not an integer"}
            if n < 1:
                return 400, {"error": f"steps must be >= 1, got {n}"}
            if self._profile_state in ("armed", "running"):
                return 409, {
                    "error": f"a profile window is already "
                             f"{self._profile_state}",
                    "state": self._profile_state,
                }
            n = min(n, PROFILE_MAX_STEPS)
            self._profile_state = "armed"
            self._profile_steps = n
            self._profile_error = None
            return 200, {
                "armed": True, "steps": n,
                "max_steps": PROFILE_MAX_STEPS,
            }

    def take_profile_request(self) -> Optional[int]:
        """Consume an armed request (the trainer's step loop; host-only,
        one lock acquire — the disarmed path stays zero-sync)."""
        with self._lock:
            if self._profile_state != "armed":
                return None
            self._profile_state = "running"
            return self._profile_steps

    def set_profile_result(self, result: dict) -> None:
        with self._lock:
            self._profile_state = "done"
            self._profile_result = dict(result)
            self._profile_error = None

    def fail_profile(self, reason: str) -> None:
        with self._lock:
            self._profile_state = "failed"
            self._profile_error = str(reason)

    def profile_status(self) -> dict:
        """The /profile GET document (no query = status/result)."""
        with self._lock:
            return self._profile_status_locked()

    def _profile_status_locked(self) -> dict:
        out: dict = {
            "supported": self._profile_supported,
            "state": self._profile_state,
            "max_steps": PROFILE_MAX_STEPS,
        }
        if self._profile_state in ("armed", "running"):
            out["steps"] = self._profile_steps
        if self._profile_result is not None:
            out["result"] = dict(self._profile_result)
        if self._profile_error is not None:
            out["error"] = self._profile_error
        return out

    # -- reading -----------------------------------------------------------
    def values(self) -> dict:
        """Registry-named metric values (export.render_metrics renders
        them; export.prometheus_text replays a stream into one of these,
        so the file dump equals the live endpoint by construction)."""
        from mgwfbp_tpu.telemetry.export import EVENT_COUNTERS

        with self._lock:
            out: dict = {
                name: 0 for name in EVENT_COUNTERS.values()
            }
            out["mgwfbp_drift_alarms_total"] = 0
            out["mgwfbp_straggler_alarms_total"] = 0
            out["mgwfbp_health_alarms_total"] = 0
            out.update(self._counts)
            if self._step_durs:
                out["mgwfbp_step_seconds"] = (
                    sum(self._step_durs) / len(self._step_durs)
                )
            if self._current_step is not None:
                out["mgwfbp_current_step"] = int(self._current_step)
            if self._current_epoch is not None:
                out["mgwfbp_current_epoch"] = int(self._current_epoch)
            if self._overlap is not None:
                out["mgwfbp_overlap_efficiency"] = float(
                    self._overlap.get("efficiency", 0.0)
                )
                out["mgwfbp_comm_hidden_seconds"] = float(
                    self._overlap.get("hidden_s", 0.0)
                )
                out["mgwfbp_comm_exposed_seconds"] = float(
                    self._overlap.get("exposed_s", 0.0)
                )
            if self._last_checkpoint is not None:
                out["mgwfbp_last_checkpoint_iteration"] = int(
                    self._last_checkpoint.get("iteration", 0)
                )
            if self._last_drift_residual is not None:
                out["mgwfbp_drift_residual"] = float(
                    self._last_drift_residual
                )
            if self._last_straggler_excess is not None:
                out["mgwfbp_straggler_excess_seconds"] = float(
                    self._last_straggler_excess
                )
            if self._health is not None:
                for key, name in (
                    ("loss", "mgwfbp_health_loss"),
                    ("grad_norm", "mgwfbp_health_grad_norm"),
                    ("update_ratio", "mgwfbp_health_update_ratio"),
                ):
                    v = self._health.get(key)
                    if v is not None:
                        out[name] = float(v)
                comp = self._health.get("compression_error") or []
                if comp:
                    out["mgwfbp_health_compression_error"] = max(
                        float(e) for e in comp
                    )
            if self._serving_step is not None:
                out["mgwfbp_serve_step"] = int(self._serving_step)
            if self._reload_lag_s is not None:
                out["mgwfbp_serve_reload_lag_seconds"] = float(
                    self._reload_lag_s
                )
            if self._serve_stats is not None:
                s = self._serve_stats
                out["mgwfbp_serve_requests_total"] = int(
                    s.get("requests", 0)
                )
                out["mgwfbp_serve_queue_depth"] = int(
                    s.get("queue_depth", 0)
                )
                out["mgwfbp_serve_batch_fill"] = float(
                    s.get("batch_fill", 0.0)
                )
                for key, name in (
                    ("latency_p50_s", "mgwfbp_serve_latency_p50_seconds"),
                    ("latency_p95_s", "mgwfbp_serve_latency_p95_seconds"),
                    ("latency_p99_s", "mgwfbp_serve_latency_p99_seconds"),
                ):
                    v = s.get(key)
                    if v is not None:
                        out[name] = float(v)
            if self._shadow is not None:
                out["mgwfbp_shadow_eval_loss"] = float(
                    self._shadow.get("loss", 0.0)
                )
                # served-vs-training loss gauge: the shadow event carries
                # train_loss when the emitter knows it (in-process mode);
                # a standalone replica falls back to the health stream
                train_loss = self._shadow.get("train_loss")
                if train_loss is None and self._health is not None:
                    train_loss = self._health.get("loss")
                if train_loss is not None:
                    out["mgwfbp_shadow_eval_delta"] = float(
                        self._shadow.get("loss", 0.0)
                    ) - float(train_loss)
            out["mgwfbp_active_alarms"] = len(self._active_alarms)
            return out

    def health(self) -> tuple[bool, str]:
        """(healthy?, reason) for /healthz."""
        with self._lock:
            if self._unhealthy is None:
                return True, "ok"
            return False, self._unhealthy

    def status(self) -> dict:
        """The /status JSON document."""
        with self._lock:
            healthy = self._unhealthy is None
            return {
                "run": dict(self._run),
                "healthy": healthy,
                "health_reason": "ok" if healthy else self._unhealthy,
                "uptime_s": round(time.time() - self._t0, 3),
                "step": self._current_step,
                "epoch": self._current_epoch,
                "schedule": dict(self._schedule) if self._schedule else None,
                "overlap_efficiency": (
                    float(self._overlap.get("efficiency", 0.0))
                    if self._overlap is not None else None
                ),
                "last_checkpoint": (
                    dict(self._last_checkpoint)
                    if self._last_checkpoint is not None else None
                ),
                "bad_steps": int(
                    self._counts.get("mgwfbp_bad_steps_total", 0)
                ),
                "rollbacks": int(
                    self._counts.get("mgwfbp_rollbacks_total", 0)
                ),
                "drift_alarms": int(
                    self._counts.get("mgwfbp_drift_alarms_total", 0)
                ),
                "straggler_alarms": int(
                    self._counts.get("mgwfbp_straggler_alarms_total", 0)
                ),
                "health_alarms": int(
                    self._counts.get("mgwfbp_health_alarms_total", 0)
                ),
                "health": (
                    dict(self._health) if self._health is not None else None
                ),
                "postmortems": self._postmortems_locked(),
                "active_alarms": [
                    dict(a) for a in self._active_alarms.values()
                ],
                "profile": self._profile_status_locked(),
                "serving": self._serving_locked(),
            }

    def _serving_locked(self) -> Optional[dict]:
        if (self._serving_step is None and self._serve_stats is None
                and self._shadow is None):
            return None
        return {
            "step": self._serving_step,
            "reload_lag_s": self._reload_lag_s,
            "reloads": int(
                self._counts.get("mgwfbp_serve_reloads_total", 0)
            ),
            "stats": (
                dict(self._serve_stats)
                if self._serve_stats is not None else None
            ),
            "shadow": (
                dict(self._shadow) if self._shadow is not None else None
            ),
        }

    def _postmortems_locked(self) -> dict:
        return {
            "total": int(
                self._counts.get("mgwfbp_postmortems_total", 0)
            ),
            "recent": [dict(b) for b in self._postmortems],
        }

    def postmortems(self) -> dict:
        """The /postmortems JSON document: bundle count + the recent
        manifests fed by `postmortem` events (the flight recorder's tee —
        live runs and replayed streams list identically)."""
        with self._lock:
            return self._postmortems_locked()


class _Handler(BaseHTTPRequestHandler):
    # the aggregator is attached to the server instance by TelemetryServer
    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        from urllib.parse import parse_qs, urlsplit

        agg: MetricsAggregator = self.server.aggregator  # type: ignore
        split = urlsplit(self.path)
        path = split.path.rstrip("/") or "/"
        if path == "/metrics":
            from mgwfbp_tpu.telemetry.export import render_metrics

            body = render_metrics(agg.values()).encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
            code = 200
        elif path == "/healthz":
            healthy, reason = agg.health()
            body = (reason + "\n").encode()
            ctype = "text/plain; charset=utf-8"
            code = 200 if healthy else 503
        elif path == "/profile":
            # ?steps=N arms a bounded trace window on the live trainer
            # (consumed at the next step boundary — next agree-interval
            # boundary on a multi-host group); no query = status/result
            query = parse_qs(split.query)
            if "steps" in query:
                code, doc = agg.arm_profile(query["steps"][-1])
            else:
                code, doc = 200, agg.profile_status()
            body = (json.dumps(doc, indent=1) + "\n").encode()
            ctype = "application/json"
        elif path == "/postmortems":
            # the flight recorder's bundle index (telemetry/recorder.py):
            # count + recent manifests, live — fed by `postmortem` events
            # through the same validated-stream tee as everything else
            body = (
                json.dumps(agg.postmortems(), indent=1) + "\n"
            ).encode()
            ctype = "application/json"
            code = 200
        elif path in ("/status", "/"):
            body = (json.dumps(agg.status(), indent=1) + "\n").encode()
            ctype = "application/json"
            code = 200
        else:
            body = (
                b"not found: serve /metrics, /healthz, /status, /profile, "
                b"/postmortems (POST /predict)\n"
            )
            ctype = "text/plain; charset=utf-8"
            code = 404
        self._respond(code, ctype, body)

    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        from urllib.parse import urlsplit

        path = urlsplit(self.path).path.rstrip("/") or "/"
        if path != "/predict":
            self._respond(
                404, "text/plain; charset=utf-8",
                b"not found: POST serves /predict only\n",
            )
            return
        # the serving plane attaches its PredictService here
        # (TelemetryServer.attach_predict); without one the route exists
        # but answers 503 — a prober can tell "no serving on this
        # process" from "route missing"
        service = getattr(self.server, "predict_service", None)
        if service is None:
            self._respond_json(
                503, {"error": "no serving model attached to this process"}
            )
            return
        try:
            n = int(self.headers.get("Content-Length") or 0)
            doc = json.loads(self.rfile.read(n) if n else b"")
            if not isinstance(doc, dict) or "inputs" not in doc:
                raise ValueError(
                    'body must be a JSON object with an "inputs" list'
                )
            inputs = doc["inputs"]
        except (ValueError, KeyError) as e:
            self._respond_json(400, {"error": f"bad request: {e}"})
            return
        # handle() blocks THIS handler thread until the dispatcher
        # flushes the batch (deadline-or-full); ThreadingHTTPServer keeps
        # other requests flowing meanwhile
        code, out = service.handle(inputs)
        self._respond_json(code, out)

    def _respond_json(self, code: int, doc: dict) -> None:
        self._respond(
            code, "application/json", (json.dumps(doc) + "\n").encode()
        )

    def _respond(self, code: int, ctype: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass


class TelemetryServer:
    """Background HTTP server over one MetricsAggregator.

    ``port == 0`` binds an ephemeral port (read it back from ``.port``).
    Construction failures (port in use) raise — callers that must not die
    wrap it (`start_metrics_server`). ``close()`` is idempotent."""

    def __init__(
        self,
        aggregator: MetricsAggregator,
        port: int,
        host: Optional[str] = None,
    ):
        # loopback by default: the endpoints are unauthenticated and
        # /status carries run metadata — exposing them on every
        # interface must be an explicit operator choice
        # (MGWFBP_METRICS_HOST=0.0.0.0 for a real Prometheus scrape)
        if host is None:
            host = os.environ.get(METRICS_HOST_ENV) or "127.0.0.1"
        self.aggregator = aggregator
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.aggregator = aggregator  # type: ignore[attr-defined]
        self._httpd.predict_service = None  # type: ignore[attr-defined]
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"mgwfbp-metrics:{self.port}",
            daemon=True,
        )
        self._thread.start()

    def attach_predict(self, service) -> None:
        """Open the POST /predict route over a serving plane's
        PredictService (None detaches — the route answers 503 again).
        Handler threads read the attribute per request; attach/detach is
        a single reference store, safe against in-flight requests."""
        httpd = self._httpd
        if httpd is not None:
            httpd.predict_service = service  # type: ignore[attr-defined]

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        try:
            httpd.shutdown()
            httpd.server_close()
        except Exception:  # noqa: BLE001 — teardown must never raise
            pass
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


def write_port_file(
    path: str, server: TelemetryServer, process_index: int,
    role: str = "train",
) -> None:
    """Persist the ACTUAL bound endpoint (atomic JSON sidecar) so the
    supervisor's fleet fan-in and the `fleet.json` scrape targets read
    real ports instead of assuming the base+index convention — which is
    simply wrong when the base is 0 (per-process ephemeral ports). The
    ``role`` field namespaces the sidecar: a serving replica's doc can
    never be mistaken for (or clobbered into) a training child's."""
    doc = {
        "process": int(process_index),
        "role": str(role),
        # a 0.0.0.0 bind advertises the ROUTABLE address (cross-host
        # seam): fleet.json targets must be dialable from other hosts
        "host": advertised_host(server.host),
        "bound_host": server.host,
        "port": int(server.port),
        "pid": os.getpid(),
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def start_metrics_server(
    aggregator: MetricsAggregator,
    base_port: Optional[int],
    process_index: int = 0,
    role: str = "train",
) -> Optional[TelemetryServer]:
    """Start the per-process metrics server, or None when disabled or the
    bind fails (logged — the plane is observability, not a dependency)."""
    log = get_logger("mgwfbp.telemetry.serve")
    try:
        port = resolve_metrics_port(base_port, process_index, role)
    except ValueError as e:
        log.warning("metrics server disabled: %s", e)
        return None
    if port is None:
        return None
    try:
        server = TelemetryServer(aggregator, port)
    except (OSError, OverflowError) as e:
        log.warning(
            "metrics server failed to bind port %d (%s); live "
            "observability disabled for this process", port, e,
        )
        return None
    port_file = (os.environ.get(METRICS_PORT_FILE_ENV) or "").strip()
    if port_file:
        try:
            write_port_file(port_file, server, process_index, role)
        except OSError as e:  # the sidecar is a convenience, not a gate
            log.warning("could not write metrics port file %s: %s",
                        port_file, e)
    log.info(
        "metrics server: http://%s:%d "
        "(/metrics /healthz /status /profile /postmortems)",
        server.host, server.port,
    )
    return server
