"""Decoder-only transformer LM with optional ring-attention sequence
parallelism.

The reference has no transformer and no sequence parallelism (its only LM is
the PTB LSTM, SURVEY.md §2.7/§5 "Long-context") — this is the TPU-native
long-context extension the `seq` mesh axis exists for. With `seq_axis` set
the module must run inside shard_map with the time dimension of its input
sharded over that axis: attention runs as a ring (parallel.ringattn), all
other ops are token-local, and positions are derived from
`lax.axis_index(seq_axis)` so embeddings see GLOBAL positions.

Architecture: Pre-LN blocks (LN -> causal MHA -> residual, LN -> GELU MLP ->
residual), learned position embeddings, final LN + untied output head.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from mgwfbp_tpu.parallel.ringattn import local_attention, ring_attention


class Block(nn.Module):
    d_model: int
    num_heads: int
    d_ff: int
    dropout: float
    seq_axis: Optional[str]
    attn_impl: str = "dense"

    @nn.compact
    def __call__(self, h: jax.Array, train: bool) -> jax.Array:
        b, t, d = h.shape
        dh = self.d_model // self.num_heads
        a_in = nn.LayerNorm(name="ln_attn")(h)
        qkv = nn.Dense(3 * self.d_model, name="qkv")(a_in)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, t, self.num_heads, dh)
        k = k.reshape(b, t, self.num_heads, dh)
        v = v.reshape(b, t, self.num_heads, dh)
        if self.seq_axis is not None:
            a = ring_attention(q, k, v, axis_name=self.seq_axis, causal=True)
        elif self.attn_impl == "flash":
            # Pallas kernel (ops/flashattn.py): scores never leave VMEM —
            # for long contexts where the dense (T, T) matrix can't fit.
            # Dense XLA is the measured default on this chip
            # (profiles/flashattn_tpu.json). Shapes outside the kernel's
            # block contract fall back to dense.
            from mgwfbp_tpu.ops import flash_attention, flash_supported

            if flash_supported(t, dh):
                a = flash_attention(q, k, v, causal=True)
            else:
                a = local_attention(q, k, v, causal=True)
        else:
            a = local_attention(q, k, v, causal=True)
        a = nn.Dense(self.d_model, name="proj")(a.reshape(b, t, d))
        a = nn.Dropout(self.dropout, deterministic=not train)(a)
        h = h + a
        m_in = nn.LayerNorm(name="ln_mlp")(h)
        m = nn.Dense(self.d_ff, name="up")(m_in)
        m = nn.gelu(m)
        m = nn.Dense(self.d_model, name="down")(m)
        m = nn.Dropout(self.dropout, deterministic=not train)(m)
        return h + m


class TransformerLM(nn.Module):
    """Causal LM over integer tokens. Input (B, T_local); returns logits
    (B, T_local, vocab). task='lm' WITHOUT carry (windowed, not BPTT)."""

    vocab_size: int
    d_model: int = 256
    num_heads: int = 4
    num_layers: int = 4
    d_ff: int = 1024
    max_len: int = 4096
    dropout: float = 0.1
    seq_axis: Optional[str] = None
    attn_impl: str = "dense"  # dense | flash (ops/flashattn.py Pallas kernel)

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        t_local = x.shape[1]
        # global positions: offset by this shard's place on the seq ring
        if self.seq_axis is not None:
            pos0 = lax.axis_index(self.seq_axis) * t_local
        else:
            pos0 = 0
        pos = pos0 + jnp.arange(t_local)
        h = nn.Embed(self.vocab_size, self.d_model, name="tok_embed")(x)
        h = h + nn.Embed(self.max_len, self.d_model, name="pos_embed")(pos)
        for i in range(self.num_layers):
            h = Block(
                self.d_model, self.num_heads, self.d_ff, self.dropout,
                self.seq_axis, self.attn_impl, name=f"Block_{i}",
            )(h, train)
        h = nn.LayerNorm(name="ln_out")(h)
        return nn.Dense(self.vocab_size, name="head")(h)
