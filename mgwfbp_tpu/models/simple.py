"""Small dense/conv models: MnistNet, LeNet, FCN5Net, LinearRegression,
Caffe-CIFAR.

Parity targets: reference dl_trainer.py:65-82 (MnistNet), models/lenet.py:5-24,
models/fcn.py:9-35 (FCN5Net, LinearRegression), models/caffe_cifar.py:10-59.
Re-designed as Flax/NHWC modules (see models/common.py conventions).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from mgwfbp_tpu.models.common import (
    dense_kernel_init,
    flatten,
    global_avg_pool,
    local_response_norm,
    max_pool,
)


class MnistNet(nn.Module):
    """2-conv/2-fc MNIST net (reference dl_trainer.py:65-82): conv10@5x5 ->
    pool -> conv20@5x5 -> dropout -> pool -> fc50 -> dropout -> fc10."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        x = nn.relu(max_pool(nn.Conv(10, (5, 5), padding="VALID")(x)))
        x = nn.Conv(20, (5, 5), padding="VALID")(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(max_pool(x))
        x = flatten(x)
        x = nn.relu(nn.Dense(50)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


class LeNet(nn.Module):
    """LeNet-5 (reference models/lenet.py:5-24): conv6@5x5/pool/conv16@5x5/
    pool/fc120/fc84/fc{num_classes}."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        x = nn.relu(nn.Conv(6, (5, 5), padding="SAME")(x))
        x = max_pool(x)
        x = nn.relu(nn.Conv(16, (5, 5), padding="VALID")(x))
        x = max_pool(x)
        x = flatten(x)
        x = nn.relu(nn.Dense(120)(x))
        x = nn.relu(nn.Dense(84)(x))
        return nn.Dense(self.num_classes)(x)


class FCN5Net(nn.Module):
    """5-layer fully-connected net (reference models/fcn.py:9-26)."""

    num_classes: int = 10
    hidden: int = 4096

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        x = flatten(x)
        for _ in range(3):
            x = nn.relu(nn.Dense(self.hidden, kernel_init=dense_kernel_init)(x))
        x = nn.relu(nn.Dense(1024, kernel_init=dense_kernel_init)(x))
        return nn.Dense(self.num_classes)(x)


class LinearRegression(nn.Module):
    """Single linear layer (reference models/fcn.py:28-35, dnn='lr')."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        return nn.Dense(self.num_classes)(flatten(x))


class CaffeCifar(nn.Module):
    """Caffe cifar10-quick style net (reference models/caffe_cifar.py:10-59):
    3x [conv5x5 + pool3x3s2] with LRN after the first two stages, then fc."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        x = nn.relu(nn.Conv(32, (5, 5), padding="SAME")(x))
        x = max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = local_response_norm(x, size=3)
        x = nn.relu(nn.Conv(32, (5, 5), padding="SAME")(x))
        x = max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = local_response_norm(x, size=3)
        x = nn.relu(nn.Conv(64, (5, 5), padding="SAME")(x))
        x = max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = flatten(x)
        x = nn.relu(nn.Dense(64)(x))
        return nn.Dense(self.num_classes)(x)
