"""VGG 11/13/16/19 for CIFAR (BN variant) and VGG-16 for ImageNet ('vgg16i').

Parity targets: reference models/vgg.py:14-38 (CIFAR VGG with a single
512->num_classes classifier) and the torchvision vgg16 the reference uses for
ImageNet (dl_trainer.py:121-122, dnn='vgg16i'). NHWC / Flax.
"""

from __future__ import annotations

from typing import Sequence, Union

import jax
from flax import linen as nn

from mgwfbp_tpu.models.common import ConvBN, conv_kernel_init, flatten, max_pool

# Layer configs: ints are conv widths, 'M' is 2x2 maxpool (classic VGG tables).
CFGS: dict[str, Sequence[Union[int, str]]] = {
    "vgg11": (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "vgg13": (64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "vgg16": (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M"),
    "vgg19": (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
              512, 512, 512, 512, "M", 512, 512, 512, 512, "M"),
}


class VGGCifar(nn.Module):
    """CIFAR VGG with BatchNorm and a single linear classifier on the 512-d
    pooled feature (reference models/vgg.py:14-38)."""

    cfg: str = "vgg16"
    num_classes: int = 10

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        for item in CFGS[self.cfg]:
            if item == "M":
                x = max_pool(x)
            else:
                x = ConvBN(int(item), (3, 3))(x, train)
        x = flatten(x)
        return nn.Dense(self.num_classes)(x)


class VGGImageNet(nn.Module):
    """ImageNet VGG (torchvision-style: plain convs, 3 fc layers with dropout;
    reference uses torchvision vgg16 at dl_trainer.py:121-122)."""

    cfg: str = "vgg16"
    num_classes: int = 1000

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        for item in CFGS[self.cfg]:
            if item == "M":
                x = max_pool(x)
            else:
                x = nn.relu(
                    nn.Conv(int(item), (3, 3), kernel_init=conv_kernel_init)(x)
                )
        x = flatten(x)
        x = nn.relu(nn.Dense(4096)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)
