"""ImageNet ResNets 18/34/50/101/152.

Parity target: reference models/imagenet_resnet.py:142-192 and the torchvision
models the reference actually dispatches to (dl_trainer.py:92-96). Re-designed
for TPU: NHWC, Flax linen, He fan-out init, bottleneck blocks sized so the
large matmul-equivalent convs tile cleanly onto the MXU.
"""

from __future__ import annotations

from typing import Sequence, Type

import jax
from flax import linen as nn

from mgwfbp_tpu.models.common import (
    BasicBlock,
    ConvBN,
    classifier_head,
    global_avg_pool,
    max_pool,
)


class Bottleneck(nn.Module):
    features: int
    strides: int = 1
    expansion: int = 4

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        out_features = self.features * self.expansion
        residual = x
        y = ConvBN(self.features, (1, 1))(x, train)
        y = ConvBN(self.features, (3, 3), (self.strides, self.strides))(y, train)
        y = ConvBN(out_features, (1, 1), use_relu=False)(y, train)
        if residual.shape != y.shape:
            residual = ConvBN(
                out_features, (1, 1), (self.strides, self.strides),
                use_relu=False, name="shortcut",
            )(x, train)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """Standard ImageNet ResNet: 7x7/2 stem + maxpool 3x3/2 + 4 stages at
    widths (64, 128, 256, 512)."""

    stage_sizes: Sequence[int]
    block: Type[nn.Module]
    num_classes: int = 1000

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        x = ConvBN(64, (7, 7), (2, 2))(x, train)
        x = max_pool(x, (3, 3), (2, 2), padding="SAME")
        for stage, nblocks in enumerate(self.stage_sizes):
            width = 64 * (2**stage)
            for i in range(nblocks):
                strides = 2 if (stage > 0 and i == 0) else 1
                x = self.block(width, strides)(x, train)
        x = global_avg_pool(x)
        return classifier_head(x, self.num_classes)


_CONFIGS = {
    18: ((2, 2, 2, 2), BasicBlock),
    34: ((3, 4, 6, 3), BasicBlock),
    50: ((3, 4, 6, 3), Bottleneck),
    101: ((3, 4, 23, 3), Bottleneck),
    152: ((3, 8, 36, 3), Bottleneck),
}


def imagenet_resnet(depth: int, num_classes: int = 1000) -> ResNet:
    if depth not in _CONFIGS:
        raise ValueError(f"unsupported ImageNet ResNet depth {depth}")
    sizes, block = _CONFIGS[depth]
    return ResNet(stage_sizes=sizes, block=block, num_classes=num_classes)
