"""AlexNet with local response normalization.

Parity target: reference models/alexnet.py:9-87 ("AlexNet + LRN", SURVEY.md
§2.7) and the torchvision alexnet dispatch (dl_trainer.py:123). NHWC / Flax;
LRN from models/common.py.
"""

from __future__ import annotations

import jax
from flax import linen as nn

from mgwfbp_tpu.models.common import (
    conv_kernel_init,
    flatten,
    local_response_norm,
    max_pool,
)


class AlexNet(nn.Module):
    num_classes: int = 1000

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        x = nn.relu(
            nn.Conv(64, (11, 11), (4, 4), padding=((2, 2), (2, 2)),
                    kernel_init=conv_kernel_init)(x)
        )
        x = local_response_norm(x)
        x = max_pool(x, (3, 3), (2, 2))
        x = nn.relu(nn.Conv(192, (5, 5), padding="SAME",
                            kernel_init=conv_kernel_init)(x))
        x = local_response_norm(x)
        x = max_pool(x, (3, 3), (2, 2))
        x = nn.relu(nn.Conv(384, (3, 3), kernel_init=conv_kernel_init)(x))
        x = nn.relu(nn.Conv(256, (3, 3), kernel_init=conv_kernel_init)(x))
        x = nn.relu(nn.Conv(256, (3, 3), kernel_init=conv_kernel_init)(x))
        x = max_pool(x, (3, 3), (2, 2))
        x = flatten(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096)(x))
        return nn.Dense(self.num_classes)(x)
