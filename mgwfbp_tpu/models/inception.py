"""Inception v3 and v4.

Parity targets: reference models/inceptionv4.py:264-358 (InceptionV4) and the
torchvision inception_v3 dispatch (dl_trainer.py:103-111, dnn='inceptionv3',
299x299 inputs). NHWC / Flax; factorized 7x1/1x7 convs keep the MXU busy with
large contractions instead of wide spatial kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from mgwfbp_tpu.models.common import (
    ConvBN,
    avg_pool,
    classifier_head,
    flatten,
    global_avg_pool,
    max_pool,
)


def _concat(*xs):
    return jnp.concatenate(list(xs), axis=-1)


# ---------------------------------------------------------------------------
# Inception v3
# ---------------------------------------------------------------------------


class InceptionA3(nn.Module):
    pool_features: int

    @nn.compact
    def __call__(self, x, train: bool = True):
        b1 = ConvBN(64, (1, 1))(x, train)
        b2 = ConvBN(48, (1, 1))(x, train)
        b2 = ConvBN(64, (5, 5))(b2, train)
        b3 = ConvBN(64, (1, 1))(x, train)
        b3 = ConvBN(96, (3, 3))(b3, train)
        b3 = ConvBN(96, (3, 3))(b3, train)
        b4 = avg_pool(x, (3, 3), (1, 1), padding="SAME")
        b4 = ConvBN(self.pool_features, (1, 1))(b4, train)
        return _concat(b1, b2, b3, b4)


class InceptionB3(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = True):
        b1 = ConvBN(384, (3, 3), (2, 2), padding="VALID")(x, train)
        b2 = ConvBN(64, (1, 1))(x, train)
        b2 = ConvBN(96, (3, 3))(b2, train)
        b2 = ConvBN(96, (3, 3), (2, 2), padding="VALID")(b2, train)
        b3 = max_pool(x, (3, 3), (2, 2))
        return _concat(b1, b2, b3)


class InceptionC3(nn.Module):
    c7: int

    @nn.compact
    def __call__(self, x, train: bool = True):
        b1 = ConvBN(192, (1, 1))(x, train)
        b2 = ConvBN(self.c7, (1, 1))(x, train)
        b2 = ConvBN(self.c7, (1, 7))(b2, train)
        b2 = ConvBN(192, (7, 1))(b2, train)
        b3 = ConvBN(self.c7, (1, 1))(x, train)
        b3 = ConvBN(self.c7, (7, 1))(b3, train)
        b3 = ConvBN(self.c7, (1, 7))(b3, train)
        b3 = ConvBN(self.c7, (7, 1))(b3, train)
        b3 = ConvBN(192, (1, 7))(b3, train)
        b4 = avg_pool(x, (3, 3), (1, 1), padding="SAME")
        b4 = ConvBN(192, (1, 1))(b4, train)
        return _concat(b1, b2, b3, b4)


class InceptionD3(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = True):
        b1 = ConvBN(192, (1, 1))(x, train)
        b1 = ConvBN(320, (3, 3), (2, 2), padding="VALID")(b1, train)
        b2 = ConvBN(192, (1, 1))(x, train)
        b2 = ConvBN(192, (1, 7))(b2, train)
        b2 = ConvBN(192, (7, 1))(b2, train)
        b2 = ConvBN(192, (3, 3), (2, 2), padding="VALID")(b2, train)
        b3 = max_pool(x, (3, 3), (2, 2))
        return _concat(b1, b2, b3)


class InceptionE3(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = True):
        b1 = ConvBN(320, (1, 1))(x, train)
        b2 = ConvBN(384, (1, 1))(x, train)
        b2 = _concat(
            ConvBN(384, (1, 3))(b2, train), ConvBN(384, (3, 1))(b2, train)
        )
        b3 = ConvBN(448, (1, 1))(x, train)
        b3 = ConvBN(384, (3, 3))(b3, train)
        b3 = _concat(
            ConvBN(384, (1, 3))(b3, train), ConvBN(384, (3, 1))(b3, train)
        )
        b4 = avg_pool(x, (3, 3), (1, 1), padding="SAME")
        b4 = ConvBN(192, (1, 1))(b4, train)
        return _concat(b1, b2, b3, b4)


class InceptionV3Aux(nn.Module):
    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = avg_pool(x, (5, 5), (3, 3))
        x = ConvBN(128, (1, 1))(x, train)
        x = ConvBN(768, (5, 5), padding="VALID")(x, train)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes)(x)


class InceptionV3(nn.Module):
    """299x299 Inception v3 with auxiliary head (train mode returns
    (logits, aux))."""

    num_classes: int = 1000
    aux_logits: bool = True

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = ConvBN(32, (3, 3), (2, 2), padding="VALID")(x, train)
        x = ConvBN(32, (3, 3), padding="VALID")(x, train)
        x = ConvBN(64, (3, 3))(x, train)
        x = max_pool(x, (3, 3), (2, 2))
        x = ConvBN(80, (1, 1))(x, train)
        x = ConvBN(192, (3, 3), padding="VALID")(x, train)
        x = max_pool(x, (3, 3), (2, 2))
        x = InceptionA3(32)(x, train)
        x = InceptionA3(64)(x, train)
        x = InceptionA3(64)(x, train)
        x = InceptionB3()(x, train)
        for c7 in (128, 160, 160, 192):
            x = InceptionC3(c7)(x, train)
        # Created unconditionally so param structure is mode-independent.
        aux = None
        if self.aux_logits:
            aux = InceptionV3Aux(self.num_classes, name="aux")(x, train)
        x = InceptionD3()(x, train)
        x = InceptionE3()(x, train)
        x = InceptionE3()(x, train)
        x = global_avg_pool(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        logits = classifier_head(x, self.num_classes)
        if self.aux_logits and train:
            return logits, aux
        return logits


# ---------------------------------------------------------------------------
# Inception v4 (reference models/inceptionv4.py:264-358)
# ---------------------------------------------------------------------------


class StemV4(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = True):
        x = ConvBN(32, (3, 3), (2, 2), padding="VALID")(x, train)
        x = ConvBN(32, (3, 3), padding="VALID")(x, train)
        x = ConvBN(64, (3, 3))(x, train)
        x = _concat(
            max_pool(x, (3, 3), (2, 2)),
            ConvBN(96, (3, 3), (2, 2), padding="VALID")(x, train),
        )
        a = ConvBN(64, (1, 1))(x, train)
        a = ConvBN(96, (3, 3), padding="VALID")(a, train)
        b = ConvBN(64, (1, 1))(x, train)
        b = ConvBN(64, (1, 7))(b, train)
        b = ConvBN(64, (7, 1))(b, train)
        b = ConvBN(96, (3, 3), padding="VALID")(b, train)
        x = _concat(a, b)
        return _concat(
            ConvBN(192, (3, 3), (2, 2), padding="VALID")(x, train),
            max_pool(x, (3, 3), (2, 2)),
        )


class InceptionA4(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = True):
        b1 = ConvBN(96, (1, 1))(x, train)
        b2 = ConvBN(64, (1, 1))(x, train)
        b2 = ConvBN(96, (3, 3))(b2, train)
        b3 = ConvBN(64, (1, 1))(x, train)
        b3 = ConvBN(96, (3, 3))(b3, train)
        b3 = ConvBN(96, (3, 3))(b3, train)
        b4 = avg_pool(x, (3, 3), (1, 1), padding="SAME")
        b4 = ConvBN(96, (1, 1))(b4, train)
        return _concat(b1, b2, b3, b4)


class ReductionA4(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = True):
        b1 = ConvBN(384, (3, 3), (2, 2), padding="VALID")(x, train)
        b2 = ConvBN(192, (1, 1))(x, train)
        b2 = ConvBN(224, (3, 3))(b2, train)
        b2 = ConvBN(256, (3, 3), (2, 2), padding="VALID")(b2, train)
        b3 = max_pool(x, (3, 3), (2, 2))
        return _concat(b1, b2, b3)


class InceptionB4(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = True):
        b1 = ConvBN(384, (1, 1))(x, train)
        b2 = ConvBN(192, (1, 1))(x, train)
        b2 = ConvBN(224, (1, 7))(b2, train)
        b2 = ConvBN(256, (7, 1))(b2, train)
        b3 = ConvBN(192, (1, 1))(x, train)
        b3 = ConvBN(192, (7, 1))(b3, train)
        b3 = ConvBN(224, (1, 7))(b3, train)
        b3 = ConvBN(224, (7, 1))(b3, train)
        b3 = ConvBN(256, (1, 7))(b3, train)
        b4 = avg_pool(x, (3, 3), (1, 1), padding="SAME")
        b4 = ConvBN(128, (1, 1))(b4, train)
        return _concat(b1, b2, b3, b4)


class ReductionB4(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = True):
        b1 = ConvBN(192, (1, 1))(x, train)
        b1 = ConvBN(192, (3, 3), (2, 2), padding="VALID")(b1, train)
        b2 = ConvBN(256, (1, 1))(x, train)
        b2 = ConvBN(256, (1, 7))(b2, train)
        b2 = ConvBN(320, (7, 1))(b2, train)
        b2 = ConvBN(320, (3, 3), (2, 2), padding="VALID")(b2, train)
        b3 = max_pool(x, (3, 3), (2, 2))
        return _concat(b1, b2, b3)


class InceptionC4(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = True):
        b1 = ConvBN(256, (1, 1))(x, train)
        b2 = ConvBN(384, (1, 1))(x, train)
        b2 = _concat(
            ConvBN(256, (1, 3))(b2, train), ConvBN(256, (3, 1))(b2, train)
        )
        b3 = ConvBN(384, (1, 1))(x, train)
        b3 = ConvBN(448, (3, 1))(b3, train)
        b3 = ConvBN(512, (1, 3))(b3, train)
        b3 = _concat(
            ConvBN(256, (1, 3))(b3, train), ConvBN(256, (3, 1))(b3, train)
        )
        b4 = avg_pool(x, (3, 3), (1, 1), padding="SAME")
        b4 = ConvBN(256, (1, 1))(b4, train)
        return _concat(b1, b2, b3, b4)


class InceptionV4(nn.Module):
    """299x299 Inception v4 (reference models/inceptionv4.py:264-358):
    stem + 4xA + ReductionA + 7xB + ReductionB + 3xC + head."""

    num_classes: int = 1000

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = StemV4()(x, train)
        for _ in range(4):
            x = InceptionA4()(x, train)
        x = ReductionA4()(x, train)
        for _ in range(7):
            x = InceptionB4()(x, train)
        x = ReductionB4()(x, train)
        for _ in range(3):
            x = InceptionC4()(x, train)
        x = global_avg_pool(x)
        x = nn.Dropout(0.2, deterministic=not train)(x)
        return classifier_head(x, self.num_classes)
