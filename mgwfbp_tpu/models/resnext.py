"""ResNeXt-29 for CIFAR (aggregated grouped-conv bottlenecks).

Parity target: reference models/resnext.py:110-126 (`CifarResNeXt`, depth 29).
NHWC / Flax; grouped convolution maps to `feature_group_count`, which XLA:TPU
lowers to a single batched MXU contraction.
"""

from __future__ import annotations

import jax
from flax import linen as nn

from mgwfbp_tpu.models.common import ConvBN, classifier_head, global_avg_pool


class ResNeXtBlock(nn.Module):
    """1x1 reduce -> 3x3 grouped -> 1x1 expand, with projection shortcut."""

    features: int  # output width of the block
    cardinality: int = 8
    base_width: int = 64
    strides: int = 1

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        # width of the grouped conv: D = C * base_width * (features / 256)
        # (standard ResNeXt widening rule, keeps FLOPs comparable to ResNet)
        d = self.cardinality * int(self.base_width * self.features / 256)
        residual = x
        y = ConvBN(d, (1, 1))(x, train)
        y = ConvBN(d, (3, 3), (self.strides, self.strides),
                   groups=self.cardinality)(y, train)
        y = ConvBN(self.features, (1, 1), use_relu=False)(y, train)
        if residual.shape != y.shape:
            residual = ConvBN(
                self.features, (1, 1), (self.strides, self.strides),
                use_relu=False, name="shortcut",
            )(x, train)
        return nn.relu(y + residual)


class ResNeXt29(nn.Module):
    """depth 29 = 3 stages x 3 blocks x 3 convs + stem/head (reference
    models/resnext.py)."""

    num_classes: int = 10
    cardinality: int = 8
    base_width: int = 64
    widths: tuple[int, ...] = (256, 512, 1024)

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        x = ConvBN(64, (3, 3))(x, train)
        for stage, width in enumerate(self.widths):
            for i in range(3):
                strides = 2 if (stage > 0 and i == 0) else 1
                x = ResNeXtBlock(
                    width, self.cardinality, self.base_width, strides
                )(x, train)
        x = global_avg_pool(x)
        return classifier_head(x, self.num_classes)
