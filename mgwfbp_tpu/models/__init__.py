"""Model zoo registry.

Parity target: reference dl_trainer.py:87-135 `create_net`, which dispatches
22 model names to local modules or torchvision. Here every architecture is a
Flax module built in-repo (SURVEY.md §2.7 inventory). `create_model` returns
the module plus a `ModelMeta` describing the canonical input so callers
(trainer, tests, bench) can build example batches without per-model switches.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax.numpy as jnp

# Dataset -> (num_classes, example input HWC / sequence spec)
DATASET_CLASSES = {
    "mnist": 10,
    "cifar10": 10,
    "imagenet": 1000,
    "ptb": 10000,
    "an4": 29,  # CTC label alphabet, reference labels.json (29 chars)
}


@dataclasses.dataclass(frozen=True)
class ModelMeta:
    name: str
    dataset: str
    num_classes: int
    # example input shape WITHOUT batch dim; image models: (H, W, C) NHWC;
    # lm models: (seq_len,) int tokens; ctc audio: (time, freq)
    input_shape: tuple[int, ...]
    input_dtype: Any = jnp.float32
    task: str = "classify"  # classify | lm | ctc
    has_aux_logits: bool = False  # googlenet/inceptionv3 style aux heads
    has_carry: bool = False  # recurrent models with BPTT carry state


_REGISTRY: dict[str, Callable[[int], tuple[Any, ModelMeta]]] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


def model_names() -> list[str]:
    return sorted(_REGISTRY)


# canonical image input per dataset (used to keep meta.input_shape consistent
# under dataset overrides)
DATASET_INPUT_HWC = {
    "mnist": (28, 28, 1),
    "cifar10": (32, 32, 3),
    "imagenet": (224, 224, 3),
}


def create_model(name: str, dataset: Optional[str] = None, num_classes: Optional[int] = None):
    """Build (module, meta) for a model name (reference create_net,
    dl_trainer.py:87-135). dataset/num_classes override the model's default;
    for image models a dataset override also retargets meta.input_shape so
    callers building batches from meta stay consistent."""
    if name not in _REGISTRY:
        raise ValueError(f"unknown model {name!r}; known: {model_names()}")
    factory = _REGISTRY[name]
    module, meta = factory(num_classes)
    if dataset is not None and dataset != meta.dataset:
        nc = num_classes or DATASET_CLASSES.get(dataset, meta.num_classes)
        if nc != meta.num_classes:
            module, meta = factory(nc)
        updates: dict = {"dataset": dataset}
        if meta.task == "classify" and dataset in DATASET_INPUT_HWC:
            updates["input_shape"] = DATASET_INPUT_HWC[dataset]
        meta = dataclasses.replace(meta, **updates)
    return module, meta


def _image_meta(name, dataset, nc, hwc, **kw) -> ModelMeta:
    return ModelMeta(name=name, dataset=dataset, num_classes=nc, input_shape=hwc, **kw)


MNIST_HWC = (28, 28, 1)
CIFAR_HWC = (32, 32, 3)
IMAGENET_HWC = (224, 224, 3)


@register("mnistnet")
def _mnistnet(nc):
    from mgwfbp_tpu.models.simple import MnistNet

    nc = nc or 10
    return MnistNet(nc), _image_meta("mnistnet", "mnist", nc, MNIST_HWC)


@register("lenet")
def _lenet(nc):
    from mgwfbp_tpu.models.simple import LeNet

    nc = nc or 10
    return LeNet(nc), _image_meta("lenet", "mnist", nc, MNIST_HWC)


@register("fcn5net")
def _fcn5(nc):
    from mgwfbp_tpu.models.simple import FCN5Net

    nc = nc or 10
    return FCN5Net(nc), _image_meta("fcn5net", "mnist", nc, MNIST_HWC)


@register("lr")
def _linreg(nc):
    from mgwfbp_tpu.models.simple import LinearRegression

    nc = nc or 10
    return LinearRegression(nc), _image_meta("lr", "mnist", nc, MNIST_HWC)


@register("caffe_cifar")
def _caffe_cifar(nc):
    from mgwfbp_tpu.models.simple import CaffeCifar

    nc = nc or 10
    return CaffeCifar(nc), _image_meta("caffe_cifar", "cifar10", nc, CIFAR_HWC)


def _register_cifar_resnet(depth: int):
    @register(f"resnet{depth}")
    def _factory(nc, depth=depth):
        from mgwfbp_tpu.models.resnet_cifar import CifarResNet

        nc = nc or 10
        return (
            CifarResNet(depth=depth, num_classes=nc),
            _image_meta(f"resnet{depth}", "cifar10", nc, CIFAR_HWC),
        )


for _d in (20, 32, 44, 56, 110):
    _register_cifar_resnet(_d)


@register("preresnet110")
def _preresnet110(nc):
    from mgwfbp_tpu.models.resnet_cifar import preresnet110

    nc = nc or 10
    return preresnet110(nc), _image_meta("preresnet110", "cifar10", nc, CIFAR_HWC)


@register("preresnet20")
def _preresnet20(nc):
    from mgwfbp_tpu.models.resnet_cifar import preresnet20

    nc = nc or 10
    return preresnet20(nc), _image_meta("preresnet20", "cifar10", nc, CIFAR_HWC)


def _register_imagenet_resnet(depth: int):
    @register(f"resnet{depth}")
    def _factory(nc, depth=depth):
        from mgwfbp_tpu.models.resnet_imagenet import imagenet_resnet

        nc = nc or 1000
        return (
            imagenet_resnet(depth, nc),
            _image_meta(f"resnet{depth}", "imagenet", nc, IMAGENET_HWC),
        )


for _d in (18, 34, 50, 101, 152):
    _register_imagenet_resnet(_d)


def _register_vgg_cifar(depth: int):
    @register(f"vgg{depth}")
    def _factory(nc, depth=depth):
        from mgwfbp_tpu.models.vgg import VGGCifar

        nc = nc or 10
        return (
            VGGCifar(cfg=f"vgg{depth}", num_classes=nc),
            _image_meta(f"vgg{depth}", "cifar10", nc, CIFAR_HWC),
        )


for _d in (11, 13, 16, 19):
    _register_vgg_cifar(_d)


@register("vgg16i")
def _vgg16i(nc):
    from mgwfbp_tpu.models.vgg import VGGImageNet

    nc = nc or 1000
    return (
        VGGImageNet(cfg="vgg16", num_classes=nc),
        _image_meta("vgg16i", "imagenet", nc, IMAGENET_HWC),
    )


@register("alexnet")
def _alexnet(nc):
    from mgwfbp_tpu.models.alexnet import AlexNet

    nc = nc or 1000
    return AlexNet(nc), _image_meta("alexnet", "imagenet", nc, IMAGENET_HWC)


@register("resnext29")
def _resnext29(nc):
    from mgwfbp_tpu.models.resnext import ResNeXt29

    nc = nc or 10
    return ResNeXt29(num_classes=nc), _image_meta("resnext29", "cifar10", nc, CIFAR_HWC)


@register("densenet")
def _densenet_bc(nc):
    from mgwfbp_tpu.models.densenet import densenet_bc_100_12

    nc = nc or 10
    return densenet_bc_100_12(nc), _image_meta("densenet", "cifar10", nc, CIFAR_HWC)


def _register_imagenet_densenet(depth: int):
    @register(f"densenet{depth}")
    def _factory(nc, depth=depth):
        from mgwfbp_tpu.models.densenet import imagenet_densenet

        nc = nc or 1000
        return (
            imagenet_densenet(depth, nc),
            _image_meta(f"densenet{depth}", "imagenet", nc, IMAGENET_HWC),
        )


for _d in (121, 161, 201):
    _register_imagenet_densenet(_d)


@register("googlenet")
def _googlenet(nc):
    from mgwfbp_tpu.models.googlenet import GoogLeNet

    nc = nc or 1000
    return (
        GoogLeNet(num_classes=nc),
        _image_meta("googlenet", "imagenet", nc, IMAGENET_HWC, has_aux_logits=True),
    )


@register("inceptionv3")
def _inceptionv3(nc):
    from mgwfbp_tpu.models.inception import InceptionV3

    nc = nc or 1000
    return (
        InceptionV3(num_classes=nc),
        _image_meta("inceptionv3", "imagenet", nc, (299, 299, 3), has_aux_logits=True),
    )


@register("inceptionv4")
def _inceptionv4(nc):
    from mgwfbp_tpu.models.inception import InceptionV4

    nc = nc or 1000
    return (
        InceptionV4(num_classes=nc),
        _image_meta("inceptionv4", "imagenet", nc, (299, 299, 3)),
    )


@register("lstm")
def _lstm(nc):
    from mgwfbp_tpu.models.lstm import PTBLSTM

    nc = nc or DATASET_CLASSES["ptb"]
    return (
        PTBLSTM(vocab_size=nc),
        ModelMeta(
            name="lstm", dataset="ptb", num_classes=nc, input_shape=(35,),
            input_dtype=jnp.int32, task="lm", has_carry=True,
        ),
    )


@register("transformer")
def _transformer(nc):
    from mgwfbp_tpu.models.transformer import TransformerLM

    nc = nc or DATASET_CLASSES["ptb"]
    return (
        TransformerLM(vocab_size=nc),
        ModelMeta(
            name="transformer", dataset="ptb", num_classes=nc,
            input_shape=(35,), input_dtype=jnp.int32, task="lm",
            has_carry=False,
        ),
    )


@register("lstman4")
def _lstman4(nc):
    from mgwfbp_tpu.models.deepspeech import DeepSpeech

    nc = nc or DATASET_CLASSES["an4"]
    return (
        DeepSpeech(num_classes=nc),
        ModelMeta(
            name="lstman4", dataset="an4", num_classes=nc,
            input_shape=(201, 161), task="ctc",  # (time, freq=161)
        ),
    )
