"""CIFAR ResNets: classic post-activation (resnet20/32/44/56/110), a
pre-activation variant (preresnet), and a modified-init variant (resnet_mod).

Parity targets: reference models/resnet.py:40-147 (CifarResNet + depth
factories), models/preresnet.py:113-151, models/resnet_mod.py:129-167,
models/res_utils.py:4-37 (downsample blocks). Re-designed for TPU: NHWC,
Flax linen, He fan-out init (models/common.py).

Structure (He et al. CIFAR recipe): conv3x3(16) -> 3 stages of n basic blocks
at widths (16, 32, 64), strides (1, 2, 2), n = (depth - 2) // 6 -> global
average pool -> fc. Projection (1x1 conv) shortcut when shape changes.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from mgwfbp_tpu.models.common import (
    BasicBlock,
    ConvBN,
    bn_kwargs,
    classifier_head,
    conv_kernel_init,
    global_avg_pool,
)


class PreActBlock(nn.Module):
    """Pre-activation basic block (reference models/preresnet.py): bn-relu-conv
    twice; shortcut taken after the first activation when projecting."""

    features: int
    strides: int = 1

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        pre = nn.relu(
            nn.BatchNorm(use_running_average=not train, momentum=0.9, **bn_kwargs())(x)
        )
        needs_proj = x.shape[-1] != self.features or self.strides != 1
        residual = (
            nn.Conv(
                self.features, (1, 1), (self.strides, self.strides),
                use_bias=False, kernel_init=conv_kernel_init, name="shortcut",
            )(pre)
            if needs_proj
            else x
        )
        y = nn.Conv(
            self.features, (3, 3), (self.strides, self.strides),
            use_bias=False, kernel_init=conv_kernel_init,
        )(pre)
        y = nn.relu(nn.BatchNorm(use_running_average=not train, momentum=0.9, **bn_kwargs())(y))
        y = nn.Conv(self.features, (3, 3), use_bias=False, kernel_init=conv_kernel_init)(y)
        return y + residual


class CifarResNet(nn.Module):
    """depth = 6n+2 post-activation CIFAR ResNet (reference models/resnet.py:
    40-107; factories :109-147)."""

    depth: int = 20
    num_classes: int = 10
    widths: Sequence[int] = (16, 32, 64)
    preact: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        if (self.depth - 2) % 6 != 0:
            raise ValueError(f"CIFAR ResNet depth must be 6n+2, got {self.depth}")
        n = (self.depth - 2) // 6
        block = PreActBlock if self.preact else BasicBlock
        if self.preact:
            x = nn.Conv(
                self.widths[0], (3, 3), use_bias=False, kernel_init=conv_kernel_init
            )(x)
        else:
            x = ConvBN(self.widths[0], (3, 3))(x, train)
        for stage, width in enumerate(self.widths):
            for i in range(n):
                strides = 2 if (stage > 0 and i == 0) else 1
                x = block(width, strides)(x, train)
        if self.preact:
            x = nn.relu(nn.BatchNorm(use_running_average=not train, momentum=0.9, **bn_kwargs())(x))
        x = global_avg_pool(x)
        return classifier_head(x, self.num_classes)


def preresnet110(num_classes: int = 10) -> CifarResNet:
    """Pre-activation ResNet-110 (reference models/preresnet.py:113-151)."""
    return CifarResNet(depth=110, num_classes=num_classes, preact=True)


def preresnet20(num_classes: int = 10) -> CifarResNet:
    return CifarResNet(depth=20, num_classes=num_classes, preact=True)
