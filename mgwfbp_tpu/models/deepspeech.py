"""DeepSpeech-style CTC acoustic model (the reference's 'lstman4' workload).

Parity target: reference models/lstm_models.py:148-321 — `MaskConv` (:45-72,
two 2-D convs over (time, freq) with hardtanh and padding masks), `BatchRNN`
(:83-105, sequence-wise BatchNorm + bidirectional RNN with summed directions),
`Lookahead` (:108-145, context conv for unidirectional mode), `SequenceWise`
(:21-42, time-flattened BatchNorm before the classifier); factory
models/lstman4.py:8-33. Loss is CTC — warp-ctc in the reference
(dl_trainer.py:214-215), `optax.ctc_loss` here (pure XLA, SURVEY.md §2.9).

TPU re-design notes: NHWC convs on (B, T, F, 1) spectrograms; fixed padded T
with explicit length masking (no pack_padded_sequence — static shapes for
XLA). Default topology matches the reference's an4 config: unidirectional
RNN layers + Lookahead convolution; bidirectional=True swaps in paired
forward/reverse nn.RNN scans with summed directions.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from mgwfbp_tpu.models.common import bn_kwargs


def hardtanh_0_20(x: jax.Array) -> jax.Array:
    return jnp.clip(x, 0.0, 20.0)


def conv_out_length(lengths: jax.Array, kernel: int, stride: int, pad: int) -> jax.Array:
    """Output time-length of a VALID-with-explicit-pad conv (reference
    MaskConv recomputes output lengths the same way, lstm_models.py:252-262)."""
    return (lengths + 2 * pad - kernel) // stride + 1


def length_mask(lengths: jax.Array, max_len: int) -> jax.Array:
    """(B,) -> (B, max_len) boolean validity mask."""
    return jnp.arange(max_len)[None, :] < lengths[:, None]


class MaskConv(nn.Module):
    """Two conv+BN+hardtanh stages over (time, freq); activations at padded
    time steps are zeroed after each stage (reference lstm_models.py:45-72)."""

    @nn.compact
    def __call__(self, x: jax.Array, lengths: jax.Array, train: bool = True):
        # x: (B, T, F, 1); lengths: (B,) valid time steps.
        # Reference geometry (lstm_models.py conv stack): kernels 41/21 with
        # stride 2 act on the FREQUENCY axis (161 -> 81 -> 41), kernel 11
        # with strides 2 then 1 acts on TIME — so rnn feature size is 41*32.
        def stage(x, lengths, features, kt, kf, st, sf):
            pt, pf = kt // 2, kf // 2
            x = nn.Conv(
                features, (kt, kf), (st, sf),
                padding=((pt, pt), (pf, pf)), use_bias=False,
            )(x)
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9, **bn_kwargs())(x)
            x = hardtanh_0_20(x)
            lengths = conv_out_length(lengths, kt, st, pt)
            mask = length_mask(lengths, x.shape[1])
            return x * mask[:, :, None, None], lengths

        x, lengths = stage(x, lengths, 32, 11, 41, 2, 2)
        x, lengths = stage(x, lengths, 32, 11, 21, 1, 2)
        return x, lengths


class BatchRNN(nn.Module):
    """Sequence-wise BatchNorm + bidirectional LSTM with summed directions
    (reference lstm_models.py:83-105)."""

    hidden_size: int
    batch_norm: bool = True
    bidirectional: bool = True

    @nn.compact
    def __call__(self, x: jax.Array, lengths: jax.Array, train: bool = True):
        # x: (B, T, H)
        if self.batch_norm:
            # SequenceWise BN: normalize over (B*T) per feature
            # (reference lstm_models.py:21-42)
            b, t, h = x.shape
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9, **bn_kwargs())(
                x.reshape(b * t, h)
            ).reshape(b, t, h)
        fwd = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size), name="fwd")
        if self.bidirectional:
            bwd = nn.RNN(
                nn.OptimizedLSTMCell(self.hidden_size), reverse=True,
                keep_order=True, name="bwd",
            )
            y = fwd(x, seq_lengths=lengths) + bwd(x, seq_lengths=lengths)
        else:
            y = fwd(x, seq_lengths=lengths)
        return y


class Lookahead(nn.Module):
    """Causal context convolution for unidirectional models (reference
    lstm_models.py:108-145): each step sees `context` future frames."""

    context: int = 20

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, t, h = x.shape
        # depthwise conv over time with right-context window
        pad = jnp.pad(x, ((0, 0), (0, self.context), (0, 0)))
        w = self.param(
            "weight", nn.initializers.lecun_normal(), (self.context + 1, h)
        )
        idx = jnp.arange(t)[:, None] + jnp.arange(self.context + 1)[None, :]
        windows = pad[:, idx, :]  # (B, T, context+1, H)
        return nn.relu(jnp.einsum("btch,ch->bth", windows, w))


class DeepSpeech(nn.Module):
    """conv stack + nb_layers x BatchRNN + SequenceWise BN + classifier
    (reference lstm_models.py:148-321; defaults from models/lstman4.py:8-33:
    LSTM, hidden 800, 5 layers, UNIDIRECTIONAL + Lookahead — the reference's
    create_net default is bidirectional=False, so its an4 headline config
    runs the lookahead-convolution variant; bidirectional=True remains
    selectable)."""

    num_classes: int = 29
    hidden_size: int = 800
    num_layers: int = 5
    bidirectional: bool = False
    sample_rate: int = 16000
    window_size: float = 0.02

    @nn.compact
    def __call__(
        self,
        spect: jax.Array,  # (B, T, F) log-spectrogram, F = 161 for 16kHz/20ms
        lengths: Optional[jax.Array] = None,  # (B,) valid frames
        train: bool = True,
    ) -> tuple[jax.Array, jax.Array]:
        """Returns (logits (B, T', num_classes), output_lengths (B,))."""
        b, t, f = spect.shape
        if lengths is None:
            lengths = jnp.full((b,), t, dtype=jnp.int32)
        x = spect[..., None]  # (B, T, F, 1)
        x, lengths = MaskConv()(x, lengths, train)
        # collapse (freq, channels) into features: (B, T', F'*32)
        bb, tt, ff, cc = x.shape
        x = x.reshape(bb, tt, ff * cc)
        for i in range(self.num_layers):
            x = BatchRNN(
                self.hidden_size,
                batch_norm=(i != 0),
                bidirectional=self.bidirectional,
                name=f"rnn_{i}",
            )(x, lengths, train)
        if not self.bidirectional:
            x = Lookahead()(x)
        bb, tt, hh = x.shape
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9, **bn_kwargs())(
            x.reshape(bb * tt, hh)
        ).reshape(bb, tt, hh)
        logits = nn.Dense(self.num_classes, use_bias=False)(x)
        return logits, lengths
