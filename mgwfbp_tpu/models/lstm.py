"""PTB word-level language model: 2-layer LSTM, 1500-d hidden.

Parity target: reference models/lstm.py:5-47 (embedding 10000->1500, two
stacked LSTM layers, dropout 0.65, linear decoder; `repackage_hidden` at
:42-47 detaches the BPTT carry between windows). TPU re-design: time axis is
scanned with `flax.linen.RNN` (lax.scan under jit — static shapes, no Python
loop), carry is threaded through the train step as explicit state, and the
detach is implicit because the carry crosses the jit boundary each window.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

Carry = Any  # tuple over layers of LSTMCell carries ((c, h), ...)


class PTBLSTM(nn.Module):
    vocab_size: int = 10000
    hidden_size: int = 1500
    num_layers: int = 2
    dropout: float = 0.65

    def initial_carry(self, batch_size: int, dtype=jnp.float32) -> Carry:
        """Zero carry for a fresh epoch (reference init_hidden)."""
        shape = (batch_size, self.hidden_size)
        return tuple(
            (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(self.num_layers)
        )

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,  # (B, T) int32
        carry: Optional[Carry] = None,
        train: bool = True,
    ) -> tuple[jax.Array, Carry]:
        """Returns (logits (B, T, V), new_carry)."""
        if carry is None:
            carry = self.initial_carry(tokens.shape[0])
        x = nn.Embed(self.vocab_size, self.hidden_size, name="embedding")(tokens)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        new_carry = []
        for layer in range(self.num_layers):
            rnn = nn.RNN(
                nn.OptimizedLSTMCell(self.hidden_size),
                return_carry=True,
                name=f"lstm_{layer}",
            )
            c, x = rnn(x, initial_carry=carry[layer])
            new_carry.append(c)
            x = nn.Dropout(self.dropout, deterministic=not train)(x)
        logits = nn.Dense(self.vocab_size, name="decoder")(x)
        return logits, tuple(new_carry)


def repackage_carry(carry: Carry) -> Carry:
    """Detach the BPTT carry (reference models/lstm.py:42-47). Under jit the
    carry returned from a step is already a leaf array; stop_gradient makes
    the intent explicit when composing windows inside one program."""
    return jax.tree_util.tree_map(jax.lax.stop_gradient, carry)
