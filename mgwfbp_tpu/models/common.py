"""Shared building blocks for the model zoo.

TPU-first conventions used throughout `mgwfbp_tpu.models`:
  * NHWC layout (XLA:TPU's native conv layout — feeds the MXU without
    transposes; the reference's NCHW is a CUDA/cuDNN idiom).
  * `flax.linen` modules with a `train: bool` argument controlling BatchNorm
    running-statistics mode and dropout.
  * Kaiming/He fan-out initialization for convs, matching the reference
    models' `init.kaiming_normal_` style (reference models/resnet.py,
    models/imagenet_resnet.py weight-init loops).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

ModuleDef = Any

# He/fan-out normal: the standard ResNet conv init.
conv_kernel_init = nn.initializers.variance_scaling(2.0, "fan_out", "truncated_normal")
dense_kernel_init = nn.initializers.lecun_normal()


def bn_kwargs() -> dict:
    """BatchNorm computation-dtype override, as constructor kwargs.

    flax keeps batch-statistics reductions in float32 regardless of the
    mixed-precision policy — the numerically safe default, enforced by
    BOTH the module dtype and `force_float32_reductions=True` (flax
    promotes the stats reduction to f32 even when dtype is bf16). On an
    HBM-bound model those f32 reduce passes are measurable traffic
    (~5.5% of resnet50's device time in the r4 roofline);
    MGWFBP_BN_DTYPE=bfloat16 sets dtype AND drops the forced promotion
    so the reduce passes really run in bf16 and the cut can be MEASURED
    against the step time (the MFU ablation knob). Default: empty, flax's
    safe f32 stats."""
    import os

    s = os.environ.get("MGWFBP_BN_DTYPE")
    if not s:
        return {}
    return {"dtype": jnp.dtype(s), "force_float32_reductions": False}


class ConvBN(nn.Module):
    """Conv + BatchNorm (+ optional relu) — the workhorse of every CNN here.

    BatchNorm carries running stats in the `batch_stats` collection; callers
    thread `train` down so a single module definition serves both the jitted
    train step and eval.
    """

    features: int
    kernel_size: Sequence[int] = (3, 3)
    strides: Sequence[int] = (1, 1)
    padding: Any = "SAME"
    use_relu: bool = True
    groups: int = 1

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        x = nn.Conv(
            self.features,
            kernel_size=tuple(self.kernel_size),
            strides=tuple(self.strides),
            padding=self.padding,
            use_bias=False,
            feature_group_count=self.groups,
            kernel_init=conv_kernel_init,
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5,
            **bn_kwargs(),
        )(x)
        if self.use_relu:
            x = nn.relu(x)
        return x


class BasicBlock(nn.Module):
    """Post-activation residual basic block: conv-bn-relu, conv-bn, add, relu.
    Shared by the CIFAR and ImageNet ResNets (reference models/resnet.py
    BasicBlock / models/imagenet_resnet.py BasicBlock are the same block)."""

    features: int
    strides: int = 1
    expansion: int = 1

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        residual = x
        y = ConvBN(self.features, (3, 3), (self.strides, self.strides))(x, train)
        y = ConvBN(self.features, (3, 3), use_relu=False)(y, train)
        if residual.shape != y.shape:
            residual = ConvBN(
                self.features, (1, 1), (self.strides, self.strides),
                use_relu=False, name="shortcut",
            )(x, train)
        return nn.relu(y + residual)


def local_response_norm(
    x: jax.Array, size: int = 5, alpha: float = 1e-4, beta: float = 0.75, k: float = 2.0
) -> jax.Array:
    """Local response normalization across channels (AlexNet's LRN; reference
    models/alexnet.py uses an LRN layer). NHWC input; window over C.

    y_c = x_c / (k + alpha/size * sum_{c' in window} x_{c'}^2)^beta
    """
    sq = jnp.square(x)
    half = size // 2
    # Sum a sliding window over the channel axis via reduce_window (XLA folds
    # this into a cheap fused op; channel counts here are small).
    summed = jax.lax.reduce_window(
        sq,
        0.0,
        jax.lax.add,
        window_dimensions=(1, 1, 1, size),
        window_strides=(1, 1, 1, 1),
        padding=((0, 0), (0, 0), (0, 0), (half, size - 1 - half)),
    )
    return x / jnp.power(k + (alpha / size) * summed, beta)


def max_pool(x, window=(2, 2), strides=None, padding="VALID"):
    return nn.max_pool(x, window, strides or window, padding)


def avg_pool(x, window=(2, 2), strides=None, padding="VALID"):
    return nn.avg_pool(x, window, strides or window, padding)


def global_avg_pool(x: jax.Array) -> jax.Array:
    """NHWC -> NC global average pool."""
    return jnp.mean(x, axis=(1, 2))


def classifier_head(x: jax.Array, num_classes: int, name: str = "fc") -> jax.Array:
    return nn.Dense(num_classes, kernel_init=dense_kernel_init, name=name)(x)


def flatten(x: jax.Array) -> jax.Array:
    return x.reshape((x.shape[0], -1))
