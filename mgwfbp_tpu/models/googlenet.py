"""GoogLeNet (Inception v1) with auxiliary classifiers.

Parity target: reference models/googlenet.py:53-233 (inception blocks with aux
logits). NHWC / Flax. In training mode the module returns
(logits, aux1_logits, aux2_logits); the trainer combines them with the classic
0.3 aux weight. Eval returns logits only.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from mgwfbp_tpu.models.common import (
    ConvBN,
    avg_pool,
    classifier_head,
    flatten,
    global_avg_pool,
    max_pool,
)


class Inception(nn.Module):
    """The 4-branch inception module: 1x1 / 1x1-3x3 / 1x1-5x5 / pool-1x1."""

    b1: int
    b2_reduce: int
    b2: int
    b3_reduce: int
    b3: int
    b4: int

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        y1 = ConvBN(self.b1, (1, 1))(x, train)
        y2 = ConvBN(self.b2_reduce, (1, 1))(x, train)
        y2 = ConvBN(self.b2, (3, 3))(y2, train)
        y3 = ConvBN(self.b3_reduce, (1, 1))(x, train)
        y3 = ConvBN(self.b3, (5, 5))(y3, train)
        y4 = max_pool(x, (3, 3), (1, 1), padding="SAME")
        y4 = ConvBN(self.b4, (1, 1))(y4, train)
        return jnp.concatenate([y1, y2, y3, y4], axis=-1)


class AuxHead(nn.Module):
    """Auxiliary classifier: 5x5/3 avgpool -> 1x1 conv(128) -> fc1024 -> fc."""

    num_classes: int

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        x = avg_pool(x, (5, 5), (3, 3))
        x = ConvBN(128, (1, 1))(x, train)
        x = flatten(x)
        x = nn.relu(nn.Dense(1024)(x))
        x = nn.Dropout(0.7, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


class GoogLeNet(nn.Module):
    num_classes: int = 1000
    aux_logits: bool = True

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True):
        x = ConvBN(64, (7, 7), (2, 2))(x, train)
        x = max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = ConvBN(64, (1, 1))(x, train)
        x = ConvBN(192, (3, 3))(x, train)
        x = max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = Inception(64, 96, 128, 16, 32, 32)(x, train)   # 3a -> 256
        x = Inception(128, 128, 192, 32, 96, 64)(x, train)  # 3b -> 480
        x = max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = Inception(192, 96, 208, 16, 48, 64)(x, train)   # 4a -> 512
        # Aux params are created unconditionally so the variable tree has the
        # same structure whichever mode init ran in; only the *return* is
        # gated on train.
        aux1 = None
        if self.aux_logits:
            aux1 = AuxHead(self.num_classes, name="aux1")(x, train)
        x = Inception(160, 112, 224, 24, 64, 64)(x, train)  # 4b
        x = Inception(128, 128, 256, 24, 64, 64)(x, train)  # 4c
        x = Inception(112, 144, 288, 32, 64, 64)(x, train)  # 4d -> 528
        aux2 = None
        if self.aux_logits:
            aux2 = AuxHead(self.num_classes, name="aux2")(x, train)
        x = Inception(256, 160, 320, 32, 128, 128)(x, train)  # 4e -> 832
        x = max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = Inception(256, 160, 320, 32, 128, 128)(x, train)  # 5a
        x = Inception(384, 192, 384, 48, 128, 128)(x, train)  # 5b -> 1024
        x = global_avg_pool(x)
        x = nn.Dropout(0.4, deterministic=not train)(x)
        logits = classifier_head(x, self.num_classes)
        if self.aux_logits and train:
            return logits, aux1, aux2
        return logits
