"""DenseNets: DenseNet-BC 100-12 for CIFAR and DenseNet-121/161/201 for
ImageNet.

Parity targets: reference models/densenet.py:99-101 (CIFAR DenseNet-BC) and
the torchvision densenet121/161/201 dispatch (dl_trainer.py:97-102).
NHWC / Flax. Dense connectivity is expressed by channel concatenation, which
XLA fuses with the following BN/conv.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from mgwfbp_tpu.models.common import (
    avg_pool,
    bn_kwargs,
    classifier_head,
    conv_kernel_init,
    global_avg_pool,
    max_pool,
)


class DenseLayer(nn.Module):
    """Bottleneck dense layer: BN-ReLU-Conv1x1(4k) -> BN-ReLU-Conv3x3(k)."""

    growth_rate: int

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        y = nn.BatchNorm(use_running_average=not train, momentum=0.9, **bn_kwargs())(x)
        y = nn.relu(y)
        y = nn.Conv(4 * self.growth_rate, (1, 1), use_bias=False,
                    kernel_init=conv_kernel_init)(y)
        y = nn.BatchNorm(use_running_average=not train, momentum=0.9, **bn_kwargs())(y)
        y = nn.relu(y)
        y = nn.Conv(self.growth_rate, (3, 3), padding="SAME", use_bias=False,
                    kernel_init=conv_kernel_init)(y)
        return jnp.concatenate([x, y], axis=-1)


class Transition(nn.Module):
    """Compression transition: BN-ReLU-Conv1x1(theta*C) + 2x2 avgpool."""

    features: int

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9, **bn_kwargs())(x)
        x = nn.relu(x)
        x = nn.Conv(self.features, (1, 1), use_bias=False,
                    kernel_init=conv_kernel_init)(x)
        return avg_pool(x)


class DenseNet(nn.Module):
    block_config: Sequence[int]
    growth_rate: int = 32
    num_init_features: int = 64
    compression: float = 0.5
    num_classes: int = 1000
    imagenet_stem: bool = True

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        if self.imagenet_stem:
            x = nn.Conv(self.num_init_features, (7, 7), (2, 2), padding="SAME",
                        use_bias=False, kernel_init=conv_kernel_init)(x)
            x = nn.relu(nn.BatchNorm(use_running_average=not train, momentum=0.9, **bn_kwargs())(x))
            x = max_pool(x, (3, 3), (2, 2), padding="SAME")
        else:
            x = nn.Conv(self.num_init_features, (3, 3), padding="SAME",
                        use_bias=False, kernel_init=conv_kernel_init)(x)
        for bi, nlayers in enumerate(self.block_config):
            for _ in range(nlayers):
                x = DenseLayer(self.growth_rate)(x, train)
            if bi != len(self.block_config) - 1:
                x = Transition(int(x.shape[-1] * self.compression))(x, train)
        x = nn.relu(nn.BatchNorm(use_running_average=not train, momentum=0.9, **bn_kwargs())(x))
        x = global_avg_pool(x)
        return classifier_head(x, self.num_classes)


def densenet_bc_100_12(num_classes: int = 10) -> DenseNet:
    """CIFAR DenseNet-BC depth 100, growth 12 (reference models/densenet.py:
    99-101): 3 blocks of (100-4)/6 = 16 bottleneck layers each."""
    return DenseNet(
        block_config=(16, 16, 16), growth_rate=12, num_init_features=24,
        num_classes=num_classes, imagenet_stem=False,
    )


_IMAGENET_CONFIGS = {
    121: ((6, 12, 24, 16), 32, 64),
    161: ((6, 12, 36, 24), 48, 96),
    201: ((6, 12, 48, 32), 32, 64),
}


def imagenet_densenet(depth: int, num_classes: int = 1000) -> DenseNet:
    cfg, growth, init = _IMAGENET_CONFIGS[depth]
    return DenseNet(
        block_config=cfg, growth_rate=growth, num_init_features=init,
        num_classes=num_classes,
    )
