"""Cost measurement: communication micro-benchmark + layer-wise backward
timing.

Parity targets (SURVEY.md §2.4): reference profiling.py —
`CommunicationProfiler` (:150-183, allreduce sweep over 8K..504K-element
tensors, 5 warmup + N timed each, feeding the sklearn alpha-beta fit at
distributed_optimizer.py:105-127) and `Profiling`/`benchmark` (:13-147,
per-parameter autograd hooks timestamping gradient arrival over 5 warmup +
50 timed full fwd/bwd iterations).

TPU re-design: there are no per-op host hooks under jit (SURVEY.md §7 "hard
parts"), so
  * the comm profiler times REAL `lax.pmean` collectives of each size inside
    a tiny jitted shard_map program (block_until_ready timing), then fits
    alpha-beta with the closed-form least squares from costmodel;
  * layer-wise backward durations are estimated by measuring the true total
    backward time and distributing it over arrival-ordered gradient leaves
    proportionally to an analytic per-leaf backward-cost weight (parameter
    volume — the dominant term for conv/dense layers). The merge solver is
    explicitly tolerant of approximate tb (it only compares arrival gaps
    against alpha); measured totals anchor the scale, which is what matters.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from mgwfbp_tpu.parallel.costmodel import AlphaBeta, fit_alpha_beta
from mgwfbp_tpu.parallel.mesh import DATA_AXIS

# Reference sweep: 8K..504K float32 elements in 8K steps (profiling.py:158-160)
# extended upward: TPU interconnects only hit peak bandwidth at MBs.
DEFAULT_SIZES = tuple(int(2**k) for k in range(13, 25))  # 8K .. 16M elements


@dataclasses.dataclass
class CommProfile:
    sizes_bytes: list[float]
    times_s: list[float]
    model: AlphaBeta


def profile_allreduce(
    mesh: Mesh,
    sizes: Sequence[int] = DEFAULT_SIZES,
    warmup: int = 5,
    iters: int = 20,
    axis_name: str = DATA_AXIS,
    dtype=jnp.float32,
) -> CommProfile:
    """Time one pmean per payload size on the real mesh; fit t = a + b*bytes.

    Reference protocol: CommunicationProfiler.benchmark (profiling.py:163-182)
    with synchronize-per-iteration; here each timed call is a jitted psum
    program completed with block_until_ready.
    """
    times, nbytes = [], []
    itemsize = jnp.dtype(dtype).itemsize
    for n in sizes:

        def f(x):
            return lax.pmean(x, axis_name)

        fn = jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False
            )
        )
        x = jnp.ones((n,), dtype)
        for _ in range(warmup):
            fn(x).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(iters):
            fn(x).block_until_ready()
        dt = (time.perf_counter() - t0) / iters
        times.append(dt)
        nbytes.append(n * itemsize)
    return CommProfile(
        sizes_bytes=nbytes, times_s=times, model=fit_alpha_beta(nbytes, times)
    )


def backward_cost_weights(params: Any, perm: Sequence[int]) -> np.ndarray:
    """Analytic per-leaf backward-cost weights in arrival order.

    Parameter volume is the per-layer cost proxy: for dense layers backward
    FLOPs ~ 2*numel*batch; for convs ~ 2*numel*output_positions*batch — the
    spatial factor varies, but relative ordering within a model is dominated
    by numel (the reference's measured tb correlates with layer size for the
    same reason its threshold policy packs by element count).
    """
    leaves = jax.tree_util.tree_leaves(params)
    w = np.asarray(
        [float(np.prod(leaves[j].shape)) if leaves[j].shape else 1.0 for j in perm]
    )
    return w / max(w.sum(), 1e-12)


def measure_step_time(
    fn: Callable, *args, warmup: int = 5, iters: int = 50
) -> float:
    """5 warmup + 50 timed iterations (reference benchmark protocol,
    profiling.py:100-101). fn must return a pytree of device arrays."""
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def benchmark_backward(
    loss_fn: Callable,
    params: Any,
    loss_args: tuple,
    perm: Sequence[int],
    warmup: int = 5,
    iters: int = 50,
) -> list[float]:
    """Layer-wise backward durations tb (arrival order): measured total
    backward wall-clock distributed by analytic weights.

    loss_fn(params, *loss_args) -> scalar. The returned list feeds
    `solver.build_schedule` exactly like the reference's measured
    `layerwise_times` (dist_trainer.py:45-51).
    """
    grad_fn = jax.jit(jax.grad(lambda p: loss_fn(p, *loss_args)))
    total = measure_step_time(grad_fn, params, warmup=warmup, iters=iters)
    weights = backward_cost_weights(params, perm)
    return [float(total * w) for w in weights]


def benchmark_trainer_backward(
    model: Any,
    meta: Any,
    params: Any,
    batch_stats: Any,
    example_batch: dict,
    perm: Sequence[int],
    warmup: int = 5,
    iters: int = 50,
) -> list[float]:
    """benchmark(trainer) parity (reference profiling.py:95-147): time the
    model's full backward on one device and return arrival-ordered tb."""
    from mgwfbp_tpu.train.step import make_loss_fn

    loss_fn = make_loss_fn(model, meta)
    rng = jax.random.PRNGKey(0)
    carry = None
    if getattr(meta, "has_carry", False):
        carry = model.initial_carry(example_batch["x"].shape[0])

    def scalar_loss(p, batch):
        loss, _ = loss_fn(p, batch_stats, batch, rng, carry)
        return loss

    return benchmark_backward(
        scalar_loss, params, (example_batch,), perm, warmup=warmup, iters=iters
    )
