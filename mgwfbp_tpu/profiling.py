"""Cost measurement: communication micro-benchmark + layer-wise backward
timing.

Parity targets (SURVEY.md §2.4): reference profiling.py —
`CommunicationProfiler` (:150-183, allreduce sweep over 8K..504K-element
tensors, 5 warmup + N timed each, feeding the sklearn alpha-beta fit at
distributed_optimizer.py:105-127) and `Profiling`/`benchmark` (:13-147,
per-parameter autograd hooks timestamping gradient arrival over 5 warmup +
50 timed full fwd/bwd iterations).

TPU re-design: there are no per-op host hooks under jit (SURVEY.md §7 "hard
parts"), so
  * the comm profiler times REAL `lax.pmean` collectives of each size inside
    a tiny jitted shard_map program (block_until_ready timing), then fits
    alpha-beta with the closed-form least squares from costmodel;
  * layer-wise backward durations are MEASURED by profiler-trace
    attribution (`trace_layerwise_backward`): one `jax.profiler.trace` of
    the jitted backward, device op durations mapped to gradient leaves via
    the jax name-stack scopes XLA preserves in op metadata (the TPU answer
    to the reference's per-parameter hook timestamps, profiling.py:31-48);
    per-scope time splits among a scope's leaves by parameter volume, the
    unattributed residual is spread by the volume prior, and the sum is
    normalized to the measured total backward wall-clock;
  * when tracing yields nothing attributable (exotic backends), the
    fallback distributes the measured TOTAL by the volume prior alone
    (`benchmark_backward`) — measured scale, approximate shape.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from mgwfbp_tpu.parallel.costmodel import AlphaBeta, fit_alpha_beta
from mgwfbp_tpu.parallel.mesh import DATA_AXIS
from mgwfbp_tpu.utils.platform import get_shard_map

shard_map = get_shard_map()

# Reference sweep: 8K..504K float32 elements in 8K steps (profiling.py:158-160)
# extended upward: TPU interconnects only hit peak bandwidth at MBs.
DEFAULT_SIZES = tuple(int(2**k) for k in range(13, 25))  # 8K .. 16M elements


@dataclasses.dataclass
class CommProfile:
    sizes_bytes: list[float]
    times_s: list[float]
    model: AlphaBeta


def profile_allreduce(
    mesh: Mesh,
    sizes: Sequence[int] = DEFAULT_SIZES,
    warmup: int = 5,
    iters: int = 20,
    axis_name: str = DATA_AXIS,
    dtype=jnp.float32,
) -> CommProfile:
    """Time one pmean per payload size on the real mesh; fit t = a + b*bytes.

    Reference protocol: CommunicationProfiler.benchmark (profiling.py:163-182)
    with synchronize-per-iteration; here each timed call is a jitted psum
    program completed with block_until_ready.
    """
    times, nbytes = [], []
    itemsize = jnp.dtype(dtype).itemsize
    for n in sizes:

        def f(x):
            return lax.pmean(x, axis_name)

        fn = jax.jit(
            shard_map(
                f, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False
            )
        )
        x = jnp.ones((n,), dtype)
        for _ in range(warmup):
            fn(x).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(iters):
            fn(x).block_until_ready()
        dt = (time.perf_counter() - t0) / iters
        times.append(dt)
        nbytes.append(n * itemsize)
    return CommProfile(
        sizes_bytes=nbytes, times_s=times, model=fit_alpha_beta(nbytes, times)
    )


def profile_allgather(
    mesh: Mesh,
    sizes: Sequence[int] = DEFAULT_SIZES,
    warmup: int = 5,
    iters: int = 20,
    axis_name: str = DATA_AXIS,
    dtype=jnp.float32,
) -> CommProfile:
    """Time one tiled all-gather per payload size on the real mesh.

    ``sizes`` are FULL-payload element counts (the same axis as
    `profile_allreduce`): each member holds n/P elements and the gather
    reassembles n — exactly the AG leg of an n-element ring all-reduce,
    and exactly what the cross-step rs_fwd_ag lowering defers into the
    next step's forward. The ratio of this sweep to the full-collective
    sweep fits `ag_fraction` (`fit_ag_fraction`), replacing the solver's
    halved-split prior with the link's measured RS/AG asymmetry
    (ROADMAP PR-7 follow-up b)."""
    times, nbytes = [], []
    itemsize = jnp.dtype(dtype).itemsize
    world = int(mesh.shape[axis_name])
    for n in sizes:
        shard = max(n // world, 1)

        def f(x):
            return lax.all_gather(x, axis_name, tiled=True)

        fn = jax.jit(
            shard_map(
                f, mesh=mesh, in_specs=P(axis_name), out_specs=P(),
                check_vma=False,
            )
        )
        x = jnp.ones((shard * world,), dtype)
        for _ in range(warmup):
            fn(x).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(iters):
            fn(x).block_until_ready()
        dt = (time.perf_counter() - t0) / iters
        times.append(dt)
        nbytes.append(shard * world * itemsize)
    return CommProfile(
        sizes_bytes=nbytes, times_s=times, model=fit_alpha_beta(nbytes, times)
    )


def profile_two_level(
    ici: int,
    dcn: int,
    sizes: Sequence[int] = DEFAULT_SIZES,
    warmup: int = 5,
    iters: int = 20,
    allgather: bool = False,
    noop_baseline: bool = False,
    devices: Optional[Sequence] = None,
    dtype=jnp.float32,
):
    """Per-axis alpha-beta calibration of an (ici x dcn) two-axis mesh —
    the `calibrate --two-level` engine (previously private to
    tools/two_level_validation.py).

    Times a pmean over ONLY the inner (data/ICI) axis and ONLY the outer
    (dcn) axis at every payload size. ``noop_baseline=True`` additionally
    sweeps a no-collective program (each standalone sweep bakes one
    program dispatch into its curve; a fused hierarchical program pays it
    once, so composition consumers subtract it — the validation tool's
    dispatch correction; the calibrate CLI has no consumer for it, so the
    default skips that third of the sweep wall time). With
    ``allgather=True`` a tiled inner-axis AG sweep additionally fits the
    ICI link's ag_fraction (the RS/AG split the two-link solver's leg
    costs use).

    Returns (model, raw): `model` is a TwoLevelAlphaBeta whose members
    are full SampledCost curves (persist with `costmodel.save_profile` —
    schema-stamped, loads anywhere a two-level profile loads), `raw` the
    per-size sweeps keyed by FULL payload bytes plus the mesh/axis names
    for callers that keep measuring on the same mesh (the validation
    tool's hier-vs-flat sweep).

    On a virtual CPU mesh both "axes" share one memory fabric, so the
    constants differ only by group size/contention — fine for validating
    the model's COMPOSITION, meaningless as DCN physics; calibrate on a
    real multi-slice topology for production constants."""
    from mgwfbp_tpu.parallel.costmodel import SampledCost, TwoLevelAlphaBeta
    from mgwfbp_tpu.parallel.mesh import DCN_AXIS, MeshSpec, make_mesh

    if dcn <= 1:
        raise ValueError(f"--two-level needs dcn > 1 (got {dcn})")
    mesh = make_mesh(
        MeshSpec(data=ici, dcn=dcn),
        devices=(
            list(devices)[: ici * dcn]
            if devices is not None
            else jax.devices()[: ici * dcn]
        ),
    )
    itemsize = jnp.dtype(dtype).itemsize

    def sweep(body) -> dict[int, float]:
        out = {}
        for n in sizes:
            fn = jax.jit(shard_map(
                body, mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False,
            ))
            x = jnp.ones((n,), dtype)
            for _ in range(warmup):
                fn(x).block_until_ready()
            t0 = time.perf_counter()
            for _ in range(iters):
                fn(x).block_until_ready()
            out[n * itemsize] = (time.perf_counter() - t0) / iters
        return out

    t_ici = sweep(lambda x: lax.pmean(x, DATA_AXIS))
    t_dcn = sweep(lambda x: lax.pmean(x, DCN_AXIS))
    t_noop = sweep(lambda x: x * 1.0) if noop_baseline else {}
    nbytes = sorted(t_ici)
    ab_ici = fit_alpha_beta(nbytes, [t_ici[b] for b in nbytes])
    ab_dcn = fit_alpha_beta(nbytes, [t_dcn[b] for b in nbytes])
    ag_fraction = 0.5
    if allgather:
        full = CommProfile(
            sizes_bytes=list(nbytes),
            times_s=[t_ici[b] for b in nbytes],
            model=ab_ici,
        )
        ag_prof = profile_allgather(
            mesh, sizes=sizes, warmup=warmup, iters=iters,
            axis_name=DATA_AXIS, dtype=dtype,
        )
        ag_fraction = fit_ag_fraction(full, ag_prof)
    # sampled curves, not just the 2-parameter fits: one flat beta cannot
    # describe payload-dependent per-byte cost (cache regimes on CPU, DMA
    # pipelining on TPU) — same reason flat calibrations persist curves
    model = TwoLevelAlphaBeta(
        ici=SampledCost(
            sizes_bytes=tuple(nbytes),
            times_s=tuple(t_ici[b] for b in nbytes),
            ab=ab_ici,
            ag_fraction=ag_fraction,
        ),
        dcn=SampledCost(
            sizes_bytes=tuple(nbytes),
            times_s=tuple(t_dcn[b] for b in nbytes),
            ab=ab_dcn,
        ),
        ici_size=int(ici),
        dcn_size=int(dcn),
    )
    raw = {
        "mesh": mesh,
        "inner_axis": DATA_AXIS,
        "outer_axis": DCN_AXIS,
        "sizes_bytes": list(nbytes),
        "ici_s": t_ici,
        "dcn_s": t_dcn,
        "noop_s": t_noop,
        "ag_fraction": ag_fraction,
        "fit": {
            "ici": {"alpha": ab_ici.alpha, "beta": ab_ici.beta},
            "dcn": {"alpha": ab_dcn.alpha, "beta": ab_dcn.beta},
        },
    }
    return model, raw


def fit_ag_fraction(
    full: CommProfile, ag: CommProfile,
    lo: float = 0.05, hi: float = 0.95,
) -> float:
    """ag_fraction from paired sweeps: the median per-size ratio of the
    all-gather time to the full-collective time, clamped to [lo, hi] —
    a degenerate calibration (noise making AG "free" or "everything")
    must not zero out a whole phase of the cross-step timeline. The
    sweeps come from the same `calibrate` invocation over the same size
    list, so samples pair by INDEX (the recorded payload bytes differ
    when world does not divide a sweep size — the AG sweep rounds to
    whole shards). Mismatched sweeps fall back to the 0.5 prior with a
    warning: a silently unmeasured split stamped as measured is exactly
    what this function must not produce."""
    import logging

    ratios = [
        ag_t / full_t
        for full_t, ag_t in zip(full.times_s, ag.times_s)
        if full_t > 0.0
    ]
    if len(full.times_s) != len(ag.times_s) or not ratios:
        logging.getLogger("mgwfbp.profiling").warning(
            "fit_ag_fraction: sweeps do not pair (%d full vs %d ag "
            "samples); keeping the unmeasured 0.5 phase-split prior",
            len(full.times_s), len(ag.times_s),
        )
        return 0.5
    return float(min(max(float(np.median(ratios)), lo), hi))


def profile_group_overhead(
    mesh: Mesh,
    alpha: float,
    total_elems: int = 1 << 22,
    group_counts: Sequence[int] = (1, 2, 4, 8, 16, 32, 64),
    warmup: int = 3,
    iters: int = 10,
    axis_name: str = DATA_AXIS,
    dtype=jnp.float32,
) -> tuple[float, list[tuple[int, float]]]:
    """Measure gamma: the fixed per-collective overhead beyond alpha.

    Runs the production bucket path (`merged_psum` with the token chain) over
    a FIXED total payload split into k equal groups, for each k. Pack/unpack
    bytes are constant across k, so the fitted slope of time vs k is the
    marginal cost of one more collective: link startup (alpha) plus the
    pack/dispatch/scheduling overhead the alpha-beta model misses. Returns
    (gamma = max(slope - alpha, 0), [(k, seconds), ...]).

    This is the calibration VERDICT r3 #1 asks for: the reference's model
    (distributed_optimizer.py:166-177) prices a collective as alpha + beta*n
    only, which cannot explain measured multi-group deficits of ~0.5 ms per
    group on the CPU-8 mesh.
    """
    from mgwfbp_tpu.parallel.allreduce import make_merged_allreduce

    times: list[tuple[int, float]] = []
    for k in group_counts:
        per = max(total_elems // k, 1)
        leaves = [jnp.ones((per,), dtype) for _ in range(k)]
        reducer = make_merged_allreduce(
            leaves,
            axis_name=axis_name,
            policy="wfbp",  # one group per leaf = exactly k collectives
            names=[f"g{i:04d}" for i in range(k)],
        )

        def f(tree):
            return reducer(tree)

        fn = jax.jit(
            shard_map(
                f, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False
            )
        )
        for _ in range(warmup):
            jax.block_until_ready(fn(leaves))
        # min of 3 windows: a single window per k lets one host-load spike
        # bend the fitted slope (gamma varied ~3x across calibration runs);
        # the minimum estimates the undisturbed time
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(leaves)
            jax.block_until_ready(out)
            best = min(best, (time.perf_counter() - t0) / iters)
        times.append((k, best))
    ks = np.asarray([k for k, _ in times], np.float64)
    ts = np.asarray([t for _, t in times], np.float64)
    slope = float(((ks - ks.mean()) * (ts - ts.mean())).sum()
                  / max(((ks - ks.mean()) ** 2).sum(), 1e-30))
    return max(slope - alpha, 0.0), times


def profile_pack_overhead(
    mesh: Mesh,
    total_elems: int = 1 << 22,
    members: int = 32,
    warmup: int = 3,
    iters: int = 10,
    axis_name: str = DATA_AXIS,
    dtype=jnp.float32,
) -> float:
    """Measure pack_beta: the per-byte cost of bucketizing a MULTI-member
    group (flatten-concat before the collective + split-unpack after).

    Two programs with identical payload and collective count — one group of
    ONE tensor (reduce in place, no copy) vs one group of `members` tensors
    (real concat + split) — isolate the bucketization copy; the difference
    divided by the payload bytes is pack_beta (costmodel.AlphaBeta.pack_beta).
    """
    from mgwfbp_tpu.parallel.allreduce import make_merged_allreduce

    def timed(leaves):
        reducer = make_merged_allreduce(
            leaves,
            axis_name=axis_name,
            policy="single",
            names=[f"g{i:04d}" for i in range(len(leaves))],
        )
        fn = jax.jit(
            shard_map(
                lambda t: reducer(t), mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False,
            )
        )
        for _ in range(warmup):
            jax.block_until_ready(fn(leaves))
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = fn(leaves)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters

    per = max(total_elems // members, 1)
    # identical payload in both programs (per*members, not total_elems —
    # a remainder would bill the mono baseline for bytes the packed run
    # never reduces and bias pack_beta low)
    t_mono = timed([jnp.ones((per * members,), dtype)])
    t_packed = timed([jnp.ones((per,), dtype) for _ in range(members)])
    nbytes = float(per * members * jnp.dtype(dtype).itemsize)
    return max((t_packed - t_mono) / nbytes, 0.0)


def profile_overlap_capability(
    mesh: Mesh,
    payload_elems: int = 1 << 22,
    warmup: int = 3,
    iters: int = 10,
    axis_name: str = DATA_AXIS,
) -> float:
    """Measure how much collective time the platform hides behind compute.

    Times three jitted shard_map programs: C (a compute chain), R (one
    all-reduce of `payload_elems`), and T (both, dataflow-independent so
    the compiler MAY run them concurrently). Returns
    clip((C + R - T) / min(C, R), 0, 1): 1.0 when the collective fully
    disappears behind compute (real TPU ICI — async DMA collectives), 0.0
    when they serialize (virtual CPU mesh: collective thunks run on the
    same cores as compute). The solver's simulation blends its overlapped
    and serialized timelines by this factor (simulate_groups); the
    reference assumes 1.0 unconditionally (NCCL streams), which mispredicts
    any platform that cannot overlap.
    """
    w = jnp.ones((512, 512), jnp.float32) * 1e-3
    payload = jnp.ones((payload_elems,), jnp.float32)

    def compute_chain(k):
        def f(x, z):
            y = x
            for _ in range(k):
                y = jnp.tanh(y @ w)
            return y
        return f

    def comm_only(x, z):
        return lax.pmean(z, axis_name)

    def time_fn(body, out_spec):
        fn = jax.jit(
            shard_map(
                body, mesh=mesh, in_specs=(P(), P()), out_specs=out_spec,
                check_vma=False,
            )
        )
        x = jnp.ones((512, 512), jnp.float32)
        for _ in range(warmup):
            jax.block_until_ready(fn(x, payload))
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = fn(x, payload)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters

    r = time_fn(comm_only, P())
    c4 = time_fn(compute_chain(4), P())
    # scale the chain so C is comparable to R (overlap is best measured
    # when neither side trivially dominates)
    k = max(int(round(4 * r / max(c4, 1e-9))), 1)
    k = min(k, 512)
    c = time_fn(compute_chain(k), P())

    def both(x, z):
        return compute_chain(k)(x, z), lax.pmean(z, axis_name)

    t = time_fn(both, (P(), P()))
    denom = min(c, r)
    if denom <= 0:
        return 1.0
    return float(min(max((c + r - t) / denom, 0.0), 1.0))


def backward_cost_weights(params: Any, perm: Sequence[int]) -> np.ndarray:
    """Analytic per-leaf backward-cost weights in arrival order.

    Parameter volume is the per-layer cost proxy: for dense layers backward
    FLOPs ~ 2*numel*batch; for convs ~ 2*numel*output_positions*batch — the
    spatial factor varies, but relative ordering within a model is dominated
    by numel (the reference's measured tb correlates with layer size for the
    same reason its threshold policy packs by element count).
    """
    leaves = jax.tree_util.tree_leaves(params)
    w = np.asarray(
        [float(np.prod(leaves[j].shape)) if leaves[j].shape else 1.0 for j in perm]
    )
    return w / max(w.sum(), 1e-12)


def measure_step_time(
    fn: Callable, *args, warmup: int = 5, iters: int = 50
) -> float:
    """5 warmup + 50 timed iterations (reference benchmark protocol,
    profiling.py:100-101). fn must return a pytree of device arrays."""
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def time_carried_steps(
    step_once: Callable[[Any], Any],
    state: Any,
    iters: int,
    warmup: int = 1,
) -> tuple[Any, float]:
    """`measure_step_time` for LIVE training: time real steps while
    CARRYING the train state through, so every timed call is a genuine
    optimizer step on a fresh batch and nothing is discarded or replayed
    (the autotuner's race protocol — training never pauses or loses steps;
    `measure_step_time` re-feeds the same args, which donated-buffer steps
    cannot even accept twice).

    step_once(state) -> new_state must consume its own fresh batch per
    call. warmup steps (the first call compiles) run un-timed; the timed
    window is bracketed by one end sync like the bench protocol. Returns
    (final_state, sec_per_step).
    """
    for _ in range(max(warmup, 0)):
        state = step_once(state)
    jax.block_until_ready(state)
    t0 = time.perf_counter()
    n = max(iters, 1)
    for _ in range(n):
        state = step_once(state)
    jax.block_until_ready(state)
    return state, (time.perf_counter() - t0) / n


class TbProfile(list):
    """Arrival-ordered per-layer backward seconds, plus provenance.

    `source` records which path produced the numbers: 'trace' (profiler-
    event attribution, truly measured per layer) or 'volume-prior' (the
    measured TOTAL split by analytic numel weights — measured scale,
    approximate shape). A plain list everywhere it is consumed; the tag
    rides along for logs, the persisted tb_profile.json, and the autotune
    cache, so a schedule can always be audited back to how its tb was
    obtained."""

    def __init__(self, values, source: str = "volume-prior"):
        super().__init__(float(v) for v in values)
        self.source = source


def benchmark_backward(
    loss_fn: Callable,
    params: Any,
    loss_args: tuple,
    perm: Sequence[int],
    warmup: int = 5,
    iters: int = 50,
    names: Optional[Sequence[str]] = None,
) -> "TbProfile":
    """Layer-wise backward durations tb (arrival order).

    loss_fn(params, *loss_args) -> scalar. The returned list feeds
    `solver.build_schedule` exactly like the reference's measured
    `layerwise_times` (dist_trainer.py:45-51).

    With `names` (leaf key paths), the per-layer times are MEASURED by
    profiler-trace attribution (`trace_layerwise_backward`) scaled to the
    measured wall-clock total; the analytic numel-weight split of the
    measured total remains the documented fallback when no trace events
    attribute (exotic backends, or names not given). The result's
    `.source` tag records which path produced the numbers.
    """
    grad_fn = jax.jit(jax.grad(lambda p: loss_fn(p, *loss_args)))
    total = measure_step_time(grad_fn, params, warmup=warmup, iters=iters)
    if names is not None:
        tb = trace_layerwise_backward(
            grad_fn, params, names, perm, iters=min(max(iters, 1), 5),
            total_s=total,
        )
        if tb is not None:
            return TbProfile(tb, source="trace")
    weights = backward_cost_weights(params, perm)
    return TbProfile((total * w for w in weights), source="volume-prior")


def _leaf_scopes(names: Sequence[str]) -> list[str]:
    """Leaf key-path -> flax module scope string as it appears in jax name
    stacks: "['Block_1']['Conv_0']['kernel']" -> "Block_1/Conv_0"."""
    import re as _re

    scopes = []
    for nm in names:
        parts = _re.findall(r"\['([^']+)'\]", nm) or [nm]
        scopes.append("/".join(parts[:-1]) if len(parts) > 1 else parts[0])
    return scopes


def _trace_events(logdir: str) -> list[tuple[str, float]]:
    """(identifier, duration_us) of complete events in a jax profiler trace
    dir; identifier concatenates the event name with its args (the full
    jax/XLA metadata lives in either, depending on backend)."""
    import glob
    import gzip
    import json
    import os

    rows: list[tuple[str, float]] = []
    for p in glob.glob(
        os.path.join(logdir, "plugins", "profile", "*", "*.trace.json.gz")
    ):
        with gzip.open(p, "rt") as f:
            data = json.load(f)
        for e in data.get("traceEvents", []):
            if e.get("ph") == "X" and "dur" in e:
                ident = e.get("name", "")
                args = e.get("args")
                if isinstance(args, dict):
                    ident += " " + " ".join(str(v) for v in args.values())
                rows.append((ident, float(e["dur"])))
    return rows


def _with_trace_events(
    run: Callable[[], None],
    logdir: Optional[str] = None,
    prefix: str = "mgwfbp_trace_",
) -> list[tuple[str, float]]:
    """Run `run()` under `jax.profiler.trace` and return the collected
    (identifier, duration_us) rows. Owns (and removes) a temporary logdir
    when none is given — the shared scaffolding of every trace-attribution
    path (`trace_layerwise_backward`, `trace_group_times`)."""
    import shutil
    import tempfile

    own = logdir is None
    logdir = logdir or tempfile.mkdtemp(prefix=prefix)
    try:
        with jax.profiler.trace(logdir):
            run()
        return _trace_events(logdir)
    finally:
        if own:
            shutil.rmtree(logdir, ignore_errors=True)


def trace_layerwise_backward(
    grad_fn: Callable,
    params: Any,
    names: Sequence[str],
    perm: Sequence[int],
    iters: int = 5,
    logdir: Optional[str] = None,
    total_s: Optional[float] = None,
    prefer: str = "backward",
) -> Optional[list[float]]:
    """Measure per-leaf backward durations from a profiler trace.

    grad_fn(params) must be the jitted backward (already warmed up). Returns
    tb in ARRIVAL order (perm applied), normalized so sum(tb) equals the
    measured wall-clock total, or None when the trace has no attributable
    events (caller falls back to the volume prior).

    total_s: the wall-clock to normalize against. Pass a measurement taken
    under the PRODUCTION protocol (AOT executable, enough iterations to
    amortize per-call dispatch — `benchmark_trainer_backward` does this);
    the few traced iterations here carry profiler + dispatch overhead that
    inflated tb by >30% vs the measured step (VERDICT r3 Weak #3: the trace
    supplies the per-layer SHAPE, the scale must come from the same regime
    the schedule will run in).

    The reference timestamps each gradient's arrival from an autograd hook
    (reference profiling.py:31-48, 70-89); here the per-layer times come
    from the device timeline instead: every op XLA compiled from a module's
    forward carries that module's name-stack scope in its metadata, and the
    backward ops carry the same scope under `transpose(jvp(...))`.
    """
    total = (
        total_s
        if total_s is not None
        else measure_step_time(grad_fn, params, warmup=0, iters=iters)
    )

    def run():
        out = None
        for _ in range(iters):
            out = grad_fn(params)
        jax.block_until_ready(out)

    rows = _with_trace_events(run, logdir, prefix="mgwfbp_tb_trace_")
    if not rows:
        return None
    scopes = _leaf_scopes(names)
    scope_set = sorted(set(scopes), key=len, reverse=True)  # longest first
    # prefer events from the requested pass (XLA stamps backward ops with
    # `transpose(jvp(...))` in the name stack; forward ops carry the bare
    # module scope); fall back to any scope-tagged event
    if prefer == "forward":
        picked = [r for r in rows if "transpose" not in r[0]]
    else:
        picked = [r for r in rows if "transpose" in r[0]]
    pool = picked if picked else rows
    scope_time: dict[str, float] = {}
    for ident, dur in pool:
        for sc in scope_set:
            if sc and sc in ident:
                scope_time[sc] = scope_time.get(sc, 0.0) + dur
                break
    if not scope_time:
        return None
    leaves = jax.tree_util.tree_leaves(params)
    vol = [float(np.prod(leaves[j].shape)) or 1.0 for j in range(len(leaves))]
    # split each scope's time among its leaves by volume
    per_leaf = np.zeros(len(leaves))
    for sc, t in scope_time.items():
        members = [i for i, s in enumerate(scopes) if s == sc]
        if not members:
            continue
        w = np.asarray([vol[i] for i in members])
        w = w / w.sum()
        for i, wi in zip(members, w):
            per_leaf[i] += t * wi
    attributed = per_leaf.sum()
    if attributed <= 0:
        return None
    # unmatched leaves get the residual of the measured total, spread by
    # volume; then normalize the whole vector to the measured total
    missing = [i for i in range(len(leaves)) if per_leaf[i] == 0.0]
    per_leaf = per_leaf / attributed  # relative shares of traced time
    if missing:
        mvol = np.asarray([vol[i] for i in missing])
        share = float(mvol.sum()) / float(np.sum(vol))
        per_leaf *= 1.0 - share
        for i, w in zip(missing, mvol / mvol.sum()):
            per_leaf[i] = share * w
    tb_fwd = per_leaf * total
    return [float(tb_fwd[j]) for j in perm]


def benchmark_trainer_backward(
    model: Any,
    meta: Any,
    params: Any,
    batch_stats: Any,
    example_batch: dict,
    perm: Sequence[int],
    warmup: int = 5,
    iters: int = 50,
    names: Optional[Sequence[str]] = None,
    compute_dtype: Optional[Any] = None,
) -> list[float]:
    """benchmark(trainer) parity (reference profiling.py:95-147): measure
    the model's backward on one device and return arrival-ordered tb.

    With `names` (leaf key paths) the per-layer times come from profiler-
    trace attribution (`trace_layerwise_backward` — truly measured, like the
    reference's hook timestamps); otherwise, or when the trace yields
    nothing, the measured TOTAL is distributed by the volume prior.

    The TOTAL the per-layer shape is scaled to is measured under the same
    protocol the bench/training step uses — the AOT-compiled executable,
    >= 20 timed iterations, one end sync — so sum(tb) is comparable to (and
    bounded by) the measured step time; timing a freshly-jitted callable for
    a handful of iterations instead over-counts per-call dispatch (a full
    tunnel round trip per call on a remote chip), which fed the solver a
    >30% overestimate (VERDICT r3 Weak #3)."""
    from mgwfbp_tpu.train.step import make_loss_fn

    loss_fn = make_loss_fn(model, meta, compute_dtype=compute_dtype)
    rng = jax.random.PRNGKey(0)
    carry = None
    if getattr(meta, "has_carry", False):
        carry = model.initial_carry(example_batch["x"].shape[0])

    def scalar_loss(p, batch):
        loss, _ = loss_fn(p, batch_stats, batch, rng, carry)
        return loss

    if names is not None:
        grad_fn = jax.jit(lambda p: jax.grad(scalar_loss)(p, example_batch))
        run = grad_fn
        try:
            run = grad_fn.lower(params).compile()  # the bench protocol
        except Exception:
            pass
        for _ in range(max(warmup, 1)):
            jax.block_until_ready(run(params))
        total = measure_step_time(
            run, params, warmup=0, iters=max(iters, 20)
        )
        tb = trace_layerwise_backward(
            run, params, names, perm, iters=iters, total_s=total
        )
        if tb is not None:
            return TbProfile(tb, source="trace")
    return benchmark_backward(
        scalar_loss, params, (example_batch,), perm, warmup=warmup, iters=iters
    )


def benchmark_trainer_forward(
    model: Any,
    meta: Any,
    params: Any,
    batch_stats: Any,
    example_batch: dict,
    perm: Sequence[int],
    warmup: int = 5,
    iters: int = 50,
    names: Optional[Sequence[str]] = None,
    compute_dtype: Optional[Any] = None,
) -> "TbProfile":
    """`benchmark_trainer_backward`'s twin for the FORWARD pass: measure
    the model's loss forward on one device and return arrival-ordered
    per-layer durations tf.

    This is the forward timeline the cross-step (rs_fwd_ag) solver prices
    deferred all-gathers against: group g's gather must land before the
    forward reaches its first consuming layer, so the solver needs to know
    how much forward compute precedes each layer. Attribution mirrors the
    backward benchmark: profiler-trace events keyed by module name-stack
    scopes where the backend preserves them (prefer='forward' keeps the
    non-`transpose` events), the measured total split by the volume prior
    otherwise; the measured TOTAL always comes from the AOT-compiled
    executable under the bench protocol, like tb.
    """
    from mgwfbp_tpu.train.step import make_loss_fn

    loss_fn = make_loss_fn(model, meta, compute_dtype=compute_dtype)
    rng = jax.random.PRNGKey(0)
    carry = None
    if getattr(meta, "has_carry", False):
        carry = model.initial_carry(example_batch["x"].shape[0])

    def scalar_loss(p, batch):
        loss, _ = loss_fn(p, batch_stats, batch, rng, carry)
        return loss

    fwd_fn = jax.jit(lambda p: scalar_loss(p, example_batch))
    run = fwd_fn
    try:
        run = fwd_fn.lower(params).compile()  # the bench protocol
    except Exception:
        pass
    for _ in range(max(warmup, 1)):
        jax.block_until_ready(run(params))
    total = measure_step_time(run, params, warmup=0, iters=max(iters, 20))
    if names is not None:
        tf = trace_layerwise_backward(
            run, params, names, perm, iters=min(max(iters, 1), 5),
            total_s=total, prefer="forward",
        )
        if tf is not None:
            return TbProfile(tf, source="trace")
    weights = backward_cost_weights(params, perm)
    return TbProfile((total * w for w in weights), source="volume-prior")


# ---------------------------------------------------------------------------
# Layer-profile persistence (tb_profile.json and calibrate --forward's
# output). Version history:
#   1 — unstamped legacy: backward only ({tb_s, arrival_names, total_s,
#       source});
#   2 — adds schema_version and the optional forward timeline (tf_s,
#       tf_total_s, tf_source) the cross-step solver consumes.
# ---------------------------------------------------------------------------

LAYER_PROFILE_SCHEMA_VERSION = 2


def load_layer_profile(path: str) -> dict:
    """Read a persisted layer profile (tb_profile.json format).

    Returns the dict with `tb_s` and `tf_s` both present: a v1/legacy file
    (or a v2 file written before any forward benchmark ran) has no
    forward times, so `tf_s` defaults to ZEROS with a logged warning —
    "forward times defaulted to 0 — rs_fwd_ag disabled" — instead of a
    KeyError; a zero forward timeline makes the cross-step simulate see
    no forward compute to hide gathers behind, so no rs_fwd_ag schedule
    can win on it. Unknown future versions are rejected (the calibration
    profiles' `check_schema_version` convention)."""
    import json
    import logging

    from mgwfbp_tpu.parallel.costmodel import check_schema_version

    with open(path) as f:
        d = json.load(f)
    check_schema_version(
        d, path=path,
        supported=(1, LAYER_PROFILE_SCHEMA_VERSION),
        what="layer profile",
    )
    if not d.get("tf_s"):
        logging.getLogger("mgwfbp.profiling").warning(
            "%s: forward times defaulted to 0 — rs_fwd_ag disabled "
            "(re-profile with `python -m mgwfbp_tpu.calibrate --forward "
            "--model <dnn>` or a fresh training run to measure them)",
            path,
        )
        d["tf_s"] = [0.0] * len(d.get("tb_s", []))
        d.setdefault("tf_source", "absent")
    return d


def hlo_collective_scope_map(
    hlo_text: str, tag: str = "mgwfbp_group",
) -> dict[str, str]:
    """HLO instruction name -> merge-group scope, from COMPILED
    (post-optimization) HLO text.

    Backends that drop the jax name stack from profiler-trace event
    metadata (the virtual CPU mesh) still name each trace event after the
    HLO instruction it executed (``all-reduce.2``), and the compiled
    module's text keeps every instruction's ``metadata={op_name=...}`` —
    which carries the ``mgwfbp_groupNNNN`` scope the jaxpr verifier
    matches on. This map is the join key between the two: it lets
    `trace_group_times` attribute device time per merge group even where
    the name-stack path yields nothing (the live /profile endpoint's
    CPU-mesh regime)."""
    import re as _re

    instr = _re.compile(r"%([\w.\-]+)\s*=\s")
    scope = _re.compile(rf"op_name=\"[^\"]*?({_re.escape(tag)}\d+)")
    out: dict[str, str] = {}
    for line in hlo_text.splitlines():
        m = instr.search(line)
        if m is None:
            continue
        s = scope.search(line)
        if s is not None:
            out[m.group(1)] = s.group(1)
    return out


def _group_times_from_scopes(
    rows: Sequence[tuple[str, float]], num_groups: int, iters: int,
    scope_name=None,
) -> Optional[list[float]]:
    """The direct name-stack attribution: each group's time is the sum of
    the event durations whose identifier carries its scope, averaged over
    the traced steps (real TPU op metadata keeps the scope).

    ``scope_name`` maps a group index to its scope label; the default is
    the merge-group scope, and the hier lowering's DCN legs attribute by
    passing `allreduce.dcn_group_scope_name` instead (the per-link refit
    path — the two scope families never collide textually)."""
    if scope_name is None:
        from mgwfbp_tpu.parallel.allreduce import group_scope_name

        scope_name = group_scope_name
    out: list[float] = []
    for gi in range(num_groups):
        tag = scope_name(gi)
        dur_us = sum(dur for ident, dur in rows if tag in ident)
        if dur_us <= 0.0:
            return None  # partial attribution is worse than none
        out.append(dur_us * 1e-6 / max(iters, 1))
    return out


def _group_times_from_hlo_join(
    rows: Sequence[tuple[str, float]],
    num_groups: int,
    hlo_text: str,
    tag: str = "mgwfbp_group",
    scope_name=None,
) -> Optional[list[float]]:
    """Attribution fallback via the compiled-HLO join
    (`hlo_collective_scope_map`): trace events are matched by HLO
    instruction NAME, and each instruction's MEAN event duration is its
    per-device per-step time (one event per device per traced step, so
    the mean normalizes over both `iters` and device multiplicity —
    unlike the scope path, whose per-device traces carry only local
    events). A group's time is the sum over its instructions (rs/ag legs
    count once each). Returns None when any group attributes nothing.

    ``tag``/``scope_name`` parameterize the scope family, exactly like
    `_group_times_from_scopes` — the hier DCN legs join on
    ``mgwfbp_dcngroup`` (which ``mgwfbp_group``'s regex cannot match:
    the prefix character before 'group' differs)."""
    if scope_name is None:
        from mgwfbp_tpu.parallel.allreduce import group_scope_name

        scope_name = group_scope_name

    scope_map = hlo_collective_scope_map(hlo_text, tag=tag)
    if not scope_map:
        return None
    per_instr: dict[str, tuple[float, int]] = {}
    for ident, dur in rows:
        name = ident.split(" ", 1)[0]
        if name in scope_map:
            t, c = per_instr.get(name, (0.0, 0))
            per_instr[name] = (t + dur, c + 1)
    out: list[float] = []
    for gi in range(num_groups):
        want = scope_name(gi)
        total_us = 0.0
        found = False
        for name, sc in scope_map.items():
            if sc != want or name not in per_instr:
                continue
            t, c = per_instr[name]
            total_us += t / max(c, 1)
            found = True
        if not found:
            return None
        out.append(total_us * 1e-6)
    return out


def trace_group_times(
    run_steps: Callable[[], None],
    num_groups: int,
    iters: int = 1,
    logdir: Optional[str] = None,
    hlo_text: Optional[str] = None,
) -> Optional[list[float]]:
    """Measured per-merge-group wall-clock from a profiler trace.

    run_steps() must execute `iters` live training steps (carrying state)
    and block until done; every device op a merge group issues carries its
    `mgwfbp_groupNNNN` name scope in the op metadata (the same introspection
    hook the jaxpr verifier matches on), so each group's time is the sum of
    its scoped event durations, averaged over the traced steps.

    With ``hlo_text`` (the COMPILED text of the step being traced), a
    backend whose trace events drop the name stack still attributes: the
    events are named after HLO instructions, and the compiled module's
    per-instruction ``op_name`` metadata recovers each collective's group
    scope (`hlo_collective_scope_map` — the live /profile endpoint's
    CPU-mesh path).

    Returns arrival-order seconds per group per step, or None when the
    trace attributes nothing for some group on EITHER path — the
    autotuner then falls back to step-time deltas
    (`autotune.step_delta_observations`).
    """
    rows = _with_trace_events(
        run_steps, logdir, prefix="mgwfbp_group_trace_"
    )
    if not rows:
        return None
    out = _group_times_from_scopes(rows, num_groups, iters)
    if out is None and hlo_text:
        out = _group_times_from_hlo_join(rows, num_groups, hlo_text)
    return out


def trace_two_level_group_times(
    run_steps: Callable[[], None],
    num_groups: int,
    num_dcn_groups: int,
    iters: int = 1,
    logdir: Optional[str] = None,
    hlo_text: Optional[str] = None,
) -> tuple[Optional[list[float]], Optional[list[float]]]:
    """Per-LINK trace attribution of a hier schedule (ROADMAP hier
    follow-up b): ONE profiler trace, split two ways — the
    ``mgwfbp_groupNNNN`` scopes time each bucket's ICI legs (RS + AG),
    the ``mgwfbp_dcngroupNNNN`` scopes its DCN collective. Returns
    ``(ici_times, dcn_times)`` in arrival / DCN-partition order (seconds
    per step), either side None when its scopes attribute nothing —
    the autotuner then falls back exactly as `trace_group_times` does.

    This is what lets `costmodel.refit_two_level_from_observations`
    refit a drifted DCN link ALONE (its `dcn_observations` input)
    instead of smearing a whole-step drift factor over both links."""
    from mgwfbp_tpu.parallel.allreduce import dcn_group_scope_name

    rows = _with_trace_events(
        run_steps, logdir, prefix="mgwfbp_group_trace_"
    )
    if not rows:
        return None, None
    ici = _group_times_from_scopes(rows, num_groups, iters)
    dcn = _group_times_from_scopes(
        rows, num_dcn_groups, iters, scope_name=dcn_group_scope_name
    )
    if hlo_text:
        if ici is None:
            ici = _group_times_from_hlo_join(rows, num_groups, hlo_text)
        if dcn is None:
            dcn = _group_times_from_hlo_join(
                rows, num_dcn_groups, hlo_text,
                tag="mgwfbp_dcngroup", scope_name=dcn_group_scope_name,
            )
    return ici, dcn


def dcn_shard_nbytes(
    layout: Any,
    dcn_groups: Sequence[Sequence[int]],
    ici_size: int,
    comm_dtype: Optional[Any] = None,
) -> list[int]:
    """Per-DCN-group OUTER-wire payload bytes: the sum of the members'
    padded 1/ici_size bucket shards — exactly the concatenated payload
    the hier lowering's one cross-slice collective moves (and the byte
    convention `refit_two_level_from_observations` expects for its
    `dcn_observations`)."""
    out: list[int] = []
    for members in dcn_groups:
        total = 0
        for gi in members:
            n = int(layout.group_sizes[gi])
            padded = n + ((-n) % max(int(ici_size), 1))
            itemsize = np.dtype(
                comm_dtype if comm_dtype is not None else layout.dtypes[gi]
            ).itemsize
            total += (padded // max(int(ici_size), 1)) * int(itemsize)
        out.append(total)
    return out


def profile_update_beta(
    mesh: Mesh,
    total_elems: int = 1 << 22,
    warmup: int = 3,
    iters: int = 10,
    axis_name: str = DATA_AXIS,
    dtype=jnp.float32,
) -> float:
    """Measure update_beta: the per-BUCKET-byte cost of the fused shard
    optimizer update the rs_opt_ag lowering runs between the reduce-scatter
    and the param all-gather (costmodel.AlphaBeta.update_beta).

    Two single-group programs of identical payload and collective phases —
    the plain rs_ag reduction vs rs_opt_ag with an SGD-momentum shard
    update in the middle — isolate the update's link-timeline occupancy;
    the difference divided by the BUCKET bytes is update_beta. The 1/world
    factor is folded in automatically: the measured update touches only the
    1/world shard while the divisor is the full bucket, exactly the
    convention the solver's `effective_cost_fn` charges.
    """
    from mgwfbp_tpu.optim import OptimSpec
    from mgwfbp_tpu.parallel.allreduce import make_merged_allreduce

    world = mesh.shape[axis_name]
    leaves = [jnp.ones((total_elems,), dtype)]
    names = ["g0000"]

    def timed(fn, *args) -> float:
        for _ in range(warmup):
            jax.block_until_ready(fn(*args))
        best = float("inf")
        for _ in range(3):  # min-of-3 windows, like profile_group_overhead
            t0 = time.perf_counter()
            out = None
            for _ in range(iters):
                out = fn(*args)
            jax.block_until_ready(out)
            best = min(best, (time.perf_counter() - t0) / iters)
        return best

    rs = make_merged_allreduce(
        leaves, axis_name=axis_name, policy="single", names=names,
        comm_op="rs_ag",
    )
    fn_rs = jax.jit(
        shard_map(
            lambda t: rs(t), mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
    )
    t_rs = timed(fn_rs, leaves)

    spec = OptimSpec(lr=1e-3, kind="sgd", momentum=0.9)
    opt_red = make_merged_allreduce(
        leaves, axis_name=axis_name, policy="single", names=names,
        comm_op="rs_opt_ag", optim_spec=spec, world_size=world,
    )
    opt_state = opt_red.optim.init()
    state_spec = opt_red.optim.partition_spec()
    fn_opt = jax.jit(
        shard_map(
            lambda g, p, o: opt_red.reduce_and_update(g, p, o),
            mesh=mesh,
            in_specs=(P(), P(), state_spec),
            out_specs=(P(), state_spec),
            check_vma=False,
        )
    )
    t_opt = timed(fn_opt, leaves, leaves, opt_state)
    nbytes = float(total_elems * jnp.dtype(dtype).itemsize)
    return max((t_opt - t_rs) / nbytes, 0.0)
