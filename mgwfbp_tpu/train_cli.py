"""Training CLI — the launcher surface of the framework.

Parity targets (SURVEY.md §2.3, L7/L6): reference dist_trainer.py __main__
(:105-143 argparse: batch-size, nworkers, dnn, dataset, nsteps-update,
compressor/density/threshold) and the exp_configs/*.conf presets sourced by
dist_mpi.sh / single.sh. One CLI serves both the single-host and multi-host
paths (`--coordinator`/`--num-processes`/`--process-id` replace mpirun +
hostfiles; on a TPU pod slice these come from the runtime environment).

Examples:
  python -m mgwfbp_tpu.train_cli --dnn resnet20 --max-epochs 2 --synthetic
  python -m mgwfbp_tpu.train_cli --dnn resnet50 --dataset imagenet \
      --policy mgwfbp --connection ici
  python -m mgwfbp_tpu.train_cli --dnn resnet20 --policy threshold \
      --threshold 524288000   # single-group baseline (batch_dist_mpi.sh grid)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

from mgwfbp_tpu.config import PRESETS, TrainConfig, make_config


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mgwfbp-train",
        description="TPU-native MG-WFBP distributed training",
    )
    p.add_argument("--dnn", default="resnet20", help=f"model: {sorted(PRESETS)}")
    p.add_argument("--dataset", default=None)
    p.add_argument("--data-dir", dest="data_dir", default=None)
    p.add_argument("--batch-size", dest="batch_size", type=int, default=None,
                   help="PER-DEVICE batch (weak scaling, reference semantics)")
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--max-epochs", dest="max_epochs", type=int, default=None)
    p.add_argument("--nsteps-update", dest="nsteps_update", type=int,
                   default=None, help="gradient accumulation micro-steps")
    p.add_argument("--policy", default=None,
                   choices=["mgwfbp", "auto", "threshold", "single", "wfbp",
                            "none"],
                   help="merge policy; 'auto' simulates every candidate "
                        "schedule under the calibrated cost model and picks "
                        "the argmin; 'none' = XLA-fused oracle")
    p.add_argument("--threshold", type=int, default=None,
                   help="elements per group for --policy threshold")
    p.add_argument("--connection", default=None,
                   help="cost-model link class: ici|dcn|56GbIB|10GbE")
    p.add_argument("--comm-profile", dest="comm_profile", default=None,
                   help="path to calibrated alpha-beta json (see calibrate)")
    p.add_argument("--dtype", default=None,
                   help="compute dtype: float32 | bfloat16 (mixed precision;"
                        " master weights stay float32)")
    p.add_argument("--comm-dtype", dest="comm_dtype", default=None,
                   help="wire dtype for collectives, e.g. bfloat16")
    p.add_argument("--norm-clip", dest="norm_clip", type=float, default=None)
    p.add_argument("--lr-schedule", dest="lr_schedule", default=None)
    p.add_argument("--logdir", default=None)
    p.add_argument("--checkpoint-dir", dest="checkpoint_dir", default=None)
    p.add_argument("--ckpt-every-steps", dest="ckpt_every_steps", type=int,
                   default=None,
                   help="mid-epoch step-indexed checkpoint every N optimizer "
                        "steps (preemption-safe resume restarts from the "
                        "exact step; 0 = epoch boundaries only)")
    p.add_argument("--ckpt-format", dest="ckpt_format", default=None,
                   choices=["sharded", "replicated"],
                   help="checkpoint payload format (default sharded): "
                        "'sharded' saves each process's own shard rows + "
                        "a manifest (no world-sized gather; restores "
                        "re-shard onto any world size — the elastic-"
                        "resize path); 'replicated' keeps the legacy "
                        "orbax gathered form for interchange with old "
                        "runs. Restore reads either format transparently")
    p.add_argument("--no-ckpt-async", action="store_true",
                   help="make mid-epoch shard-native checkpoints block "
                        "the step loop (by default the payload write "
                        "runs on a background thread and the commit "
                        "lands at the next agree-interval step)")
    p.add_argument("--no-grad-guard", action="store_true",
                   help="disable the non-finite-gradient guard (by default "
                        "a NaN/inf gradient drops that update, emits a "
                        "bad_step event, and K consecutive bad steps roll "
                        "back to the last checkpoint)")
    p.add_argument("--bad-step-limit", dest="bad_step_limit", type=int,
                   default=None,
                   help="consecutive non-finite steps before rollback to "
                        "the last checkpoint (0 disables rollback)")
    p.add_argument("--no-health-stats", action="store_true",
                   help="disable the in-jit training-health statistics "
                        "(per-group grad norms, update/param ratio riding "
                        "the metrics psum) and with them the online health "
                        "detector + anomaly flight recorder")
    p.add_argument("--pretrain", default=None,
                   help="checkpoint directory to initialize weights from")
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--seq-parallel", dest="seq_parallel", type=int, default=None)
    p.add_argument("--num-steps", dest="num_steps", type=int, default=None,
                   help="LM window length (must divide by --seq-parallel)")
    p.add_argument("--num-batches-per-epoch", dest="num_batches_per_epoch",
                   type=int, default=None,
                   help="cap optimizer steps per epoch (smoke runs)")
    p.add_argument("--synthetic", action="store_true",
                   help="force synthetic data (no dataset files needed)")
    p.add_argument("--no-augment", action="store_true",
                   help="disable training-time data augmentation")
    p.add_argument("--tensorboard", action="store_true",
                   help="stream scalar events to <logdir>/<tag>/events.jsonl "
                        "(mirrors into TensorBoard files if tensorboardX is "
                        "installed)")
    p.add_argument("--telemetry", action="store_true",
                   help="structured run observability: step spans, per-group "
                        "comm spans with exposed/hidden overlap accounting, "
                        "autotune/resize/checkpoint/watchdog events — one "
                        "schema-versioned JSONL per run; render with "
                        "tools/telemetry_report.py (README 'Telemetry')")
    p.add_argument("--telemetry-dir", dest="telemetry_dir", default=None,
                   help="directory for the telemetry event stream (default "
                        "<logdir>/<tag>; implies --telemetry)")
    p.add_argument("--metrics-port", dest="metrics_port", type=int,
                   default=None,
                   help="live observability HTTP port (/metrics Prometheus, "
                        "/healthz watchdog-wired liveness, /status run "
                        "JSON, /profile?steps=N on-demand deep-trace "
                        "window with per-merge-group device attribution); "
                        "0 = ephemeral, multi-host serves "
                        "port+process_index per process (actual bound "
                        "ports persist via MGWFBP_METRICS_PORT_FILE for "
                        "the supervisor's /fleet fan-in); implies "
                        "--telemetry (MGWFBP_METRICS_PORT)")
    p.add_argument("--serve-shadow", action="store_true",
                   help="in-process serving plane (mgwfbp_tpu/serving/): "
                        "hot-reload every committed checkpoint into a "
                        "ServingModel, score a held-out shadow stream "
                        "against it (shadow_eval events + served-vs-"
                        "training loss gauge), and answer batched POST "
                        "/predict on the --metrics-port server; needs "
                        "--checkpoint-dir, implies --telemetry, single "
                        "process only (README 'Serving')")
    p.add_argument("--compressor", default=None,
                   choices=["none", "topk"],
                   help="gradient compressor (reference --compressor)")
    p.add_argument("--density", type=float, default=None,
                   help="kept-fraction for sparsifying compressors; 0 = "
                        "auto (cost-model chooser, may fall back to dense)")
    p.add_argument("--comm-op", dest="comm_op", default=None,
                   choices=["all_reduce", "rs_ag", "hier", "rs_opt_ag",
                            "rs_fwd_ag"],
                   help="bucket collective: monolithic all-reduce, "
                        "reduce-scatter + all-gather (DeAR-style), the "
                        "hierarchical two-level ICI+DCN lowering (requires "
                        "--dcn-slices > 1), reduce-scatter + SHARDED "
                        "optimizer update + param all-gather (ZeRO-1-style "
                        "1/world optimizer state; same wire bytes as "
                        "rs_ag), or rs_fwd_ag — the CROSS-STEP pipeline: "
                        "rs_opt_ag whose param all-gather is deferred into "
                        "the next step's forward, hiding comm behind "
                        "forward compute too (params carried as 1/world "
                        "shards; multi-host capable — checkpoints are "
                        "shard-native)")
    p.add_argument("--dcn-slices", dest="dcn_slices", type=int, default=None,
                   help="slices of a multi-slice pod: adds an outer "
                        "data-parallel mesh axis whose collectives cross "
                        "DCN (two-level cost model)")
    p.add_argument("--autotune", action="store_true",
                   help="closed-loop schedule autotuning: race verified "
                        "candidate schedules for a few real training steps "
                        "each, refit the cost model from the measurements, "
                        "commit the measured argmin and cache it (see "
                        "README 'Autotuning')")
    p.add_argument("--autotune-steps", dest="autotune_steps", type=int,
                   default=None,
                   help="timed steps per raced candidate (plus one "
                        "warmup/compile step each)")
    p.add_argument("--schedule-cache", dest="schedule_cache", default=None,
                   help="directory for committed autotune schedules "
                        "(default profiles/schedule_cache); a second run "
                        "with the same schedule-cache key (see "
                        "parallel/autotune.py cache_key) "
                        "skips the race")
    p.add_argument("--no-profile-backward", action="store_true",
                   help="skip the offline backward benchmark (size prior)")
    p.add_argument("--epochs", type=int, default=None,
                   help="run this many epochs from the resume point "
                        "(default: through --max-epochs, absolute)")
    p.add_argument("--coordinator", default=None,
                   help="multi-host coordinator address host:port")
    p.add_argument("--num-processes", dest="num_processes", type=int, default=None)
    p.add_argument("--process-id", dest="process_id", type=int, default=None)
    p.add_argument("--print-config", action="store_true")
    return p


def config_from_args(args: argparse.Namespace) -> TrainConfig:
    overrides = {
        k: getattr(args, k)
        for k in (
            "dataset", "data_dir", "batch_size", "lr", "max_epochs",
            "nsteps_update", "policy", "threshold", "connection",
            "comm_profile", "dtype", "comm_dtype", "norm_clip", "lr_schedule",
            "logdir", "checkpoint_dir", "pretrain", "seed", "seq_parallel",
            "num_steps", "num_batches_per_epoch", "compressor", "density",
            "comm_op", "dcn_slices", "autotune_steps", "schedule_cache",
            "telemetry_dir", "ckpt_every_steps", "bad_step_limit",
            "metrics_port", "ckpt_format",
        )
        if getattr(args, k, None) is not None
    }
    if args.no_augment:
        overrides["augment"] = False
    if args.no_grad_guard:
        overrides["grad_guard"] = False
    if args.no_ckpt_async:
        overrides["ckpt_async"] = False
    if args.no_health_stats:
        overrides["health_stats"] = False
    if args.tensorboard:
        overrides["tensorboard"] = True
    if args.telemetry or args.telemetry_dir or args.metrics_port is not None:
        # the live plane's aggregator is fed by the event stream, so
        # --metrics-port implies the stream (same as --telemetry-dir)
        overrides["telemetry"] = True
    if args.serve_shadow:
        # the plane's reload/shadow_eval/serve_stats events ride the
        # telemetry stream, so serving implies it too
        overrides["serve_shadow"] = True
        overrides["telemetry"] = True
    if args.autotune:
        overrides["autotune"] = True
    return make_config(args.dnn, **overrides)


_LAUNCH_CHAIN = (
    "resolution chain: --coordinator/--num-processes/--process-id flags "
    "> MGWFBP_COORDINATOR/MGWFBP_NUM_PROCESSES/MGWFBP_PROCESS_ID "
    "> SLURM_NTASKS/SLURM_PROCID > OMPI_COMM_WORLD_SIZE/"
    "OMPI_COMM_WORLD_RANK; `python -m mgwfbp_tpu.runtime.supervise` "
    "exports the full MGWFBP_* contract for local process groups"
)


def resolve_multihost(
    args: argparse.Namespace, environ: Optional[dict] = None,
) -> tuple[Optional[str], Optional[int], Optional[int]]:
    """(coordinator, num_processes, process_id) from the launcher
    fallback chain: explicit flags, then the env chain owned by
    `parallel.mesh.resolve_launch_env` (MGWFBP_* — the supervisor's
    launch contract — then SLURM, then OpenMPI). All-None means a
    single-host launch. A multi-host signal that cannot be completed
    (num_processes > 1 but no coordinator or process id resolvable)
    exits with the recipe instead of handing a half-configured launch to
    jax.distributed (whose failure surfaces as a backend-probe traceback
    or a silent hang)."""
    from mgwfbp_tpu.parallel.mesh import resolve_launch_env

    try:
        env_coord, env_num, env_pid = resolve_launch_env(
            os.environ if environ is None else environ
        )
    except ValueError as e:  # garbage env int -> clean CLI failure
        raise SystemExit(str(e)) from None
    coordinator = args.coordinator or env_coord
    num = (
        args.num_processes
        if args.num_processes is not None
        else env_num
    )
    pid = args.process_id if args.process_id is not None else env_pid
    if coordinator is None and pid is None and (num is None or num <= 1):
        return None, None, None  # single-host
    missing = []
    if num is None:
        missing.append("worker count (--num-processes / "
                       "MGWFBP_NUM_PROCESSES)")
    if num is not None and num > 1:
        if coordinator is None:
            missing.append("coordinator address (--coordinator / "
                           "MGWFBP_COORDINATOR, host:port)")
        if pid is None:
            missing.append("process id (--process-id / MGWFBP_PROCESS_ID "
                           "/ launcher rank env)")
    if missing:
        raise SystemExit(
            "multi-host launch signaled but incomplete — missing "
            + "; ".join(missing) + ". " + _LAUNCH_CHAIN
        )
    return coordinator, num, pid


def main(argv: Optional[list[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)
    if args.print_config:
        print(json.dumps(cfg.__dict__, indent=2, default=str))
        return 0
    from mgwfbp_tpu.utils.platform import (
        apply_platform_overrides, preflight_backend,
    )

    apply_platform_overrides()
    coordinator, num_processes, process_id = resolve_multihost(args)
    # any explicit distributed signal skips the probe: initialize() must
    # be the first backend touch on every process of a group
    multi_host = bool(
        coordinator is not None
        or process_id is not None
        or (num_processes or 0) > 1
    )
    if not multi_host:
        # fail fast on a wedged device grant instead of hanging in PJRT
        # init (MGWFBP_INIT_TIMEOUT_S tunes/disables). Single-process
        # only: jax.distributed.initialize() must run before any backend
        # touch, so a resolved multi-host launch skips the probe — there
        # the coordinator barrier itself surfaces a dead host.
        preflight_backend()
    from mgwfbp_tpu.parallel.mesh import init_distributed
    from mgwfbp_tpu.train.trainer import Trainer

    init_distributed(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    trainer = Trainer(
        cfg,
        profile_backward=not args.no_profile_backward,
        synthetic_data=True if args.synthetic else None,
    )
    from mgwfbp_tpu.runtime.coordination import CoordinationTimeout
    from mgwfbp_tpu.utils.faults import PREEMPT_RC, Preempted

    try:
        metrics = trainer.fit(args.epochs)
    except Preempted as p:
        # graceful drain already checkpointed and emitted the preempt
        # event; EX_TEMPFAIL tells the supervisor "restart me to resume"
        print(json.dumps({
            "preempted": True, "signal": p.signal_name,
            "epoch": p.epoch, "iteration": p.iteration,
        }))
        return PREEMPT_RC
    except CoordinationTimeout as ct:
        # a peer died or wedged mid-collective: the DRAIN-LESS
        # restart-friendly exit (no checkpoint barrier can complete
        # either) — the supervisor's healer resumes the group from the
        # last COMMITTED shard-native step
        print(json.dumps({
            "coordination_timeout": True, "op": ct.op,
            "timeout_s": ct.timeout_s,
            "iteration": trainer.iteration,
        }), flush=True)
        # with a peer dead, the distributed runtime's atexit shutdown
        # barrier can never complete — it waits out the peer's heartbeat
        # timeout and then LOG(FATAL)s (SIGABRT), overriding the rc.
        # Flush our own state and leave without interpreter teardown.
        trainer.close()
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(PREEMPT_RC)
    finally:
        trainer.close()
    print(json.dumps(metrics))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
