"""mgwfbp_tpu — a TPU-native distributed training framework with the
capabilities of HKBU-HPML/MG-WFBP (Merged-Gradient Wait-Free BackPropagation).

The reference (/root/reference) implements MG-WFBP as PyTorch autograd hooks
feeding Horovod/NCCL async allreduces (distributed_optimizer.py). This package
re-designs the same capability for TPU: an alpha-beta communication cost model
plus measured layer-wise backward times drive a merge schedule
(`parallel.solver`) whose groups are lowered to bucketed `jax.lax.psum`
collectives inside a `shard_map`-ped train step (`parallel.allreduce`), so
XLA's latency-hiding scheduler overlaps each group's all-reduce with the
remaining backward compute.

Layer map (mirrors SURVEY.md §1):
  - CLI/launchers      scripts/, train CLI (reference: dist_mpi.sh, single.sh)
  - Config             mgwfbp_tpu.config (reference: settings.py + exp_configs)
  - Training drivers   mgwfbp_tpu.train_cli / trainer (dist_trainer.py, dl_trainer.py)
  - MG-WFBP scheduler  mgwfbp_tpu.parallel.{solver,buckets,allreduce}
                       (distributed_optimizer.py)
  - Cost models        mgwfbp_tpu.parallel.costmodel, mgwfbp_tpu.profiling
                       (profiling.py, utils.py)
  - Communication      jax.lax collectives over the ICI/DCN mesh
                       (horovod.torch.mpi_ops / NCCL / OpenMPI)
"""

from mgwfbp_tpu.version import __version__

__all__ = ["__version__"]
