"""Make a JAX platform choice actually stick.

This container's sitecustomize registers the axon TPU-tunnel backend
programmatically, which means `JAX_PLATFORMS=cpu` in the environment is NOT
honored on its own — any entry point that relies on the env var silently
initializes the TPU tunnel instead (and hangs if the chip is unavailable).
That failure mode cost round 1 both driver checks (VERDICT.md Missing #1/#2).

Every CLI / driver entry point calls `apply_platform_overrides()` before its
first backend touch; the choice is plumbed through `jax.config`, which wins
over the programmatic registration.
"""

from __future__ import annotations

import os
from typing import Optional


def force_host_device_count(n: int) -> None:
    """Request n virtual CPU devices. Must run before jax initializes."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()


def apply_platform_overrides(
    platform: Optional[str] = None,
    host_device_count: Optional[int] = None,
) -> Optional[str]:
    """Force the JAX platform through the config API (env alone loses here).

    Resolution order for the platform: explicit arg, then MGWFBP_PLATFORM,
    then JAX_PLATFORMS (so `JAX_PLATFORMS=cpu python -m mgwfbp_tpu.train_cli`
    behaves the way the env var promises). Returns the platform forced, or
    None when no override was requested (default backend selection applies —
    on this box, the real TPU chip).
    """
    if platform is None:
        platform = (
            os.environ.get("MGWFBP_PLATFORM")
            or os.environ.get("JAX_PLATFORMS")
            or None
        )
    if host_device_count is None:
        env = os.environ.get("MGWFBP_HOST_DEVICES")
        host_device_count = int(env) if env else None
    if host_device_count:
        force_host_device_count(host_device_count)
    if not platform:
        return None
    import jax

    jax.config.update("jax_platforms", platform)
    return platform


def already_initialized_platforms() -> list[str]:
    """Platforms jax has already initialized a backend for (empty = none)."""
    try:
        from jax._src import xla_bridge

        return sorted(getattr(xla_bridge, "_backends", {}) or {})
    except Exception:
        return []


def preflight_backend(timeout_s: Optional[float] = None) -> list:
    """Initialize the JAX backend under a deadline; raise instead of hang.

    A wedged device grant makes PJRT init BLOCK INDEFINITELY inside
    make_c_api_client (observed on the tunneled chip: a killed client's
    stale server-side grant pinned the device for hours and every new
    client hung silently). A launcher that hangs can neither report nor
    retry; failing fast with an actionable error is the recovery seam
    (failure-detection parity, SURVEY.md §5).

    timeout_s: None reads MGWFBP_INIT_TIMEOUT_S (default 300); <= 0
    disables the deadline. Returns jax.devices() on success.
    """
    import threading

    if timeout_s is None:
        timeout_s = float(os.environ.get("MGWFBP_INIT_TIMEOUT_S", "300"))
    import jax

    if timeout_s <= 0:
        return jax.devices()
    box: dict = {}

    def init():
        try:
            box["devices"] = jax.devices()
        except BaseException as e:  # noqa: BLE001 — re-raised below
            box["error"] = e

    t = threading.Thread(target=init, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise RuntimeError(
            f"JAX backend init exceeded {timeout_s:.0f}s — device/tunnel "
            "unavailable (client blocked waiting for the device grant). "
            "Retry later, probe with `timeout 60 python -c 'import jax; "
            "jax.devices()'`, or raise MGWFBP_INIT_TIMEOUT_S."
        )
    if "error" in box:
        raise box["error"]
    return box["devices"]
