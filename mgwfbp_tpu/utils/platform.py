"""Make a JAX platform choice actually stick.

This container's sitecustomize registers the axon TPU-tunnel backend
programmatically, which means `JAX_PLATFORMS=cpu` in the environment is NOT
honored on its own — any entry point that relies on the env var silently
initializes the TPU tunnel instead (and hangs if the chip is unavailable).
That failure mode cost round 1 both driver checks (VERDICT.md Missing #1/#2).

Every CLI / driver entry point calls `apply_platform_overrides()` before its
first backend touch; the choice is plumbed through `jax.config`, which wins
over the programmatic registration.
"""

from __future__ import annotations

import os
from typing import Optional


def force_host_device_count(n: int) -> None:
    """Request n virtual CPU devices. Must run before jax initializes."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()


def apply_platform_overrides(
    platform: Optional[str] = None,
    host_device_count: Optional[int] = None,
) -> Optional[str]:
    """Force the JAX platform through the config API (env alone loses here).

    Resolution order for the platform: explicit arg, then MGWFBP_PLATFORM,
    then JAX_PLATFORMS (so `JAX_PLATFORMS=cpu python -m mgwfbp_tpu.train_cli`
    behaves the way the env var promises). Returns the platform forced, or
    None when no override was requested (default backend selection applies —
    on this box, the real TPU chip).
    """
    if platform is None:
        platform = (
            os.environ.get("MGWFBP_PLATFORM")
            or os.environ.get("JAX_PLATFORMS")
            or None
        )
    if host_device_count is None:
        env = os.environ.get("MGWFBP_HOST_DEVICES")
        host_device_count = int(env) if env else None
    if host_device_count:
        force_host_device_count(host_device_count)
    if not platform:
        return None
    import jax

    jax.config.update("jax_platforms", platform)
    return platform


def get_shard_map():
    """Version-portable `shard_map` (jax >= 0.6 `jax.shard_map`, else
    `jax.experimental.shard_map.shard_map`).

    The two spellings also renamed the replication-check kwarg
    (`check_rep` -> `check_vma`); the returned callable accepts EITHER
    name and translates to whatever the underlying implementation takes,
    so call sites can be written once against the modern signature.
    Positional use (`shard_map(f, mesh, in_specs=..., out_specs=...)`)
    passes through unchanged.
    """
    import functools
    import inspect

    import jax

    impl = getattr(jax, "shard_map", None)
    if impl is None:
        from jax.experimental.shard_map import shard_map as impl
    accepted = None
    has_var_kw = False
    try:
        params = inspect.signature(impl).parameters
        accepted = set(params)
        has_var_kw = any(
            p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
        )
    except (TypeError, ValueError):  # C-implemented or wrapped oddly
        pass

    check_names = ("check_vma", "check_rep")

    @functools.wraps(impl)
    def shard_map(*args, **kwargs):
        given = [n for n in check_names if n in kwargs]
        if given and accepted is not None:
            value = kwargs.pop(given[0])
            for extra in given[1:]:
                kwargs.pop(extra)
            for name in check_names:
                if name in accepted:
                    kwargs[name] = value
                    break
            else:
                if has_var_kw:
                    # a (*args, **kwargs) wrapper may still route the knob
                    # through; forward the caller's original spelling
                    kwargs[given[0]] = value
                # otherwise the check knob no longer exists; drop it
        return impl(*args, **kwargs)

    return shard_map


def axis_size(axis_name) -> int:
    """Static extent of a bound mesh axis, version-portable.

    `lax.axis_size` only exists on newer jax; `lax.psum(1, axis)` is
    statically folded to a Python int for a concrete unit operand on every
    version this repo supports, so it is the fallback. Accepts a single
    axis name or a tuple (product of extents).
    """
    from jax import lax

    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    size_fn = getattr(lax, "axis_size", None)
    total = 1
    for a in axes:
        total *= int(size_fn(a)) if size_fn is not None else int(lax.psum(1, a))
    return total


def already_initialized_platforms() -> list[str]:
    """Platforms jax has already initialized a backend for (empty = none)."""
    try:
        from jax._src import xla_bridge

        return sorted(getattr(xla_bridge, "_backends", {}) or {})
    except Exception:
        return []


# Peak dense-matmul FLOP/s per chip by device-kind substring (bf16 for TPU
# generations; for fp32 runs it is an upper bound, making MFU conservative.
# Tiny nominal value keeps MFU meaningful in CPU smoke runs). Shared by
# bench.py and tools/mfu_ablation.py so the table cannot drift.
PEAK_FLOPS_BY_DEVICE_KIND = [
    ("v5 lite", 197e12),  # TPU v5e
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v4", 275e12),
    ("v6", 918e12),  # Trillium
    ("cpu", 1e11),
]


def peak_flops(device_kind: str):
    """Peak FLOP/s for a device kind, or None when unknown."""
    kind = device_kind.lower()
    for sub, peak in PEAK_FLOPS_BY_DEVICE_KIND:
        if sub in kind:
            return peak
    return None


def env_float(
    name: str, default: float, environ=None,
) -> float:
    """Parse a float knob from the environment, failing fast WITH THE
    VARIABLE NAMED on garbage input (the MGWFBP_BARRIER_TIMEOUT_S
    precedent: a typo'd timeout must not surface as a bare float()
    traceback mid-drain, or worse silently fall back to a default that
    changes healing behavior). Unset/empty returns `default`."""
    raw = ((environ if environ is not None else os.environ).get(name)
           or "").strip()
    if not raw:
        return float(default)
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not a number") from None


def env_int(name: str, default: int, environ=None) -> int:
    """`env_float`'s integer sibling (same fail-fast naming contract)."""
    raw = ((environ if environ is not None else os.environ).get(name)
           or "").strip()
    if not raw:
        return int(default)
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not an integer") from None


class DeadlineExceeded(RuntimeError):
    """run_with_deadline hit its timeout (the worker thread is abandoned)."""


def run_with_deadline(fn, timeout_s: float, what: str = "operation"):
    """Run fn() on a daemon thread and wait at most timeout_s.

    Returns fn()'s value; raises DeadlineExceeded on timeout, else
    re-raises fn's own exception unchanged. One implementation of the
    spawn/box/join/is_alive watchdog pattern — backend init and the bench
    compute preflight both need it (a wedged remote device blocks
    arbitrary client calls indefinitely; a deadline turns the hang into a
    reportable error). The abandoned thread is a daemon: it cannot keep
    the process alive, but any C-level lock it holds stays held — callers
    should treat a DeadlineExceeded process as tainted and exit soon.
    """
    import threading

    box: dict = {}

    def work():
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised below
            box["error"] = e

    t = threading.Thread(target=work, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise DeadlineExceeded(
            f"{what} exceeded {timeout_s:.0f}s deadline"
        )
    if "error" in box:
        raise box["error"]
    return box["value"]


def preflight_backend(timeout_s: Optional[float] = None) -> list:
    """Initialize the JAX backend under a deadline; raise instead of hang.

    A wedged device grant makes PJRT init BLOCK INDEFINITELY inside
    make_c_api_client (observed on the tunneled chip: a killed client's
    stale server-side grant pinned the device for hours and every new
    client hung silently). A launcher that hangs can neither report nor
    retry; failing fast with an actionable error is the recovery seam
    (failure-detection parity, SURVEY.md §5).

    timeout_s: None reads MGWFBP_INIT_TIMEOUT_S (default 300); <= 0
    disables the deadline. Returns jax.devices() on success.
    """
    if timeout_s is None:
        timeout_s = float(os.environ.get("MGWFBP_INIT_TIMEOUT_S", "300"))
    import jax

    if timeout_s <= 0:
        return jax.devices()
    try:
        return run_with_deadline(
            jax.devices, timeout_s, what="JAX backend init"
        )
    except DeadlineExceeded:
        raise RuntimeError(
            f"JAX backend init exceeded {timeout_s:.0f}s — device/tunnel "
            "unavailable (client blocked waiting for the device grant). "
            "Retry later, probe with `timeout 60 python -c 'import jax; "
            "jax.devices()'`, or raise MGWFBP_INIT_TIMEOUT_S."
        ) from None
