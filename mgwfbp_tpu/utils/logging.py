"""Hostname-tagged logging with run-config tags.

Parity with the reference's logger setup (settings.py:42-53: formatter with
hostname, file+stream handlers) and its PREFIX run-tagging scheme
(settings.py:7-40: a string concatenated from the active feature flags so
every log line/dir identifies the experiment; dist_trainer.py:127-141 encodes
the full config in the log-dir name).
"""

from __future__ import annotations

import logging
import os
import socket
from typing import Mapping, Optional

_FMT = "%(asctime)s [{host}] %(levelname)s %(name)s: %(message)s"


def get_logger(
    name: str = "mgwfbp",
    logfile: Optional[str] = None,
    level: int = logging.INFO,
) -> logging.Logger:
    """Stream + optional file logger. Safe to call repeatedly: a second call
    with a DIFFERENT logfile (e.g. two Trainer runs in one process) swaps the
    file handler to the new path instead of silently logging to the old one.
    """
    logger = logging.getLogger(name)
    fmt = logging.Formatter(_FMT.format(host=socket.gethostname()))
    if not getattr(logger, "_mgwfbp_configured", False):
        logger.setLevel(level)
        sh = logging.StreamHandler()
        sh.setFormatter(fmt)
        logger.addHandler(sh)
        logger.propagate = False
        logger._mgwfbp_configured = True  # type: ignore[attr-defined]
    current = getattr(logger, "_mgwfbp_logfile", None)
    if logfile != current:
        for h in [h for h in logger.handlers if isinstance(h, logging.FileHandler)]:
            logger.removeHandler(h)
            h.close()
        if logfile:
            os.makedirs(os.path.dirname(logfile) or ".", exist_ok=True)
            fh = logging.FileHandler(logfile)
            fh.setFormatter(fmt)
            logger.addHandler(fh)
        logger._mgwfbp_logfile = logfile  # type: ignore[attr-defined]
    return logger


def run_tag(cfg: Mapping[str, object]) -> str:
    """Deterministic experiment tag from config entries, e.g.
    'resnet20-cifar10-n8-bs32-lr0.1-mgwfbp' (reference PREFIX +
    dist_trainer.py:127-128 dir naming)."""
    parts = []
    for k in ("dnn", "dataset", "nworkers", "batch_size", "lr", "policy",
              "threshold", "seed"):
        if k in cfg and cfg[k] is not None:
            v = cfg[k]
            prefix = {"nworkers": "n", "batch_size": "bs", "lr": "lr",
                      "threshold": "th", "seed": "s"}.get(k, "")
            parts.append(f"{prefix}{v}")
    return "-".join(str(p) for p in parts) if parts else "run"
