"""Training-progress watchdog: detect a hung step loop.

Failure-detection parity (SURVEY.md §5): the reference's failure handling
is passive (MPI aborts the world when a rank dies); a TPU client has a
quieter failure mode — the runtime call BLOCKS forever when the device
grant/tunnel wedges (observed in this container: a training process sat
20+ minutes inside one eval dispatch at ~0% CPU with no error). The
watchdog turns that silence into a signal: a daemon thread checks a
monotonic heartbeat the step loop touches; if no progress lands within
`timeout_s`, it logs CRITICAL with the stalled phase and (optionally,
MGWFBP_WATCHDOG_ABORT=1) hard-exits so a supervisor can restart, instead
of the job hanging until an external kill.

Zero overhead on the hot path: the heartbeat is one time.monotonic()
store per iteration, no locks (a torn read merely delays detection by one
interval).
"""

from __future__ import annotations

import faulthandler
import logging
import os
import sys
import threading
import time
from typing import Optional

from mgwfbp_tpu.utils.logging import get_logger


# Extra deadline for known-long silent phases (overridable; seconds).
# First XLA compile of a step program runs 20-40 s through the chip tunnel
# and longer for big models; an orbax save streams the full state to disk.
COMPILE_ALLOW_S = float(os.environ.get("MGWFBP_WATCHDOG_COMPILE_S", "600"))
CHECKPOINT_ALLOW_S = float(os.environ.get("MGWFBP_WATCHDOG_CKPT_S", "180"))


class ProgressWatchdog:
    """Arm around a step loop; `beat(phase)` from the loop body."""

    def __init__(
        self,
        timeout_s: Optional[float] = None,
        abort: Optional[bool] = None,
        check_interval_s: float = 10.0,
        on_stall=None,
    ):
        # on_stall(phase=..., idle_s=..., timeout_s=..., abort=...) is
        # called (from the watcher thread) each time the deadline fires —
        # the trainer hooks the telemetry stream here so stalls are
        # greppable from the same file as the step records. It runs BEFORE
        # a configured abort, and its own failure never masks the signal.
        env = os.environ.get("MGWFBP_WATCHDOG_S")
        self.timeout_s = (
            timeout_s
            if timeout_s is not None
            else (float(env) if env else 0.0)
        )
        self.abort = (
            abort
            if abort is not None
            else os.environ.get("MGWFBP_WATCHDOG_ABORT") == "1"
        )
        self.check_interval_s = check_interval_s
        self.on_stall = on_stall
        self.log = get_logger("mgwfbp.watchdog")
        self._last = time.monotonic()
        self._phase = "startup"
        self._allow = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fired = False

    @property
    def enabled(self) -> bool:
        return self.timeout_s > 0

    # graft: thread-safe -- lock-free heartbeat by design: stores are
    # GIL-atomic and the watcher tolerates one stale/lenient check (see
    # the _last/_allow ordering comment below); a lock here would let a
    # wedged holder stall the very thread meant to detect wedges
    def beat(self, phase: str = "step", allow_s: float = 0.0) -> None:
        """Record progress. `allow_s` extends the deadline for the phase
        being ENTERED — known-long silent phases (first-step XLA compile
        through a tunnel ~20-40 s+, orbax checkpoint save) legitimately
        outlast a per-step timeout, and hard-exiting a healthy run from
        inside its first compile is worse than late detection (ADVICE r4
        #3). The allowance applies until the next beat resets it."""
        self._phase = phase
        # _last strictly before _allow: if the watcher wakes mid-beat it may
        # see the fresh timestamp with the old (larger) allowance — one
        # overly lenient check — instead of a stale timestamp with zero
        # allowance, which would hard-exit a healthy run right as a long
        # compile finishes
        self._last = time.monotonic()
        self._allow = max(float(allow_s), 0.0)

    def _dump_all_stacks(self) -> None:
        """faulthandler dump of EVERY thread to stderr and to any log
        files the framework has open — the escalation step: a stalled run
        (especially one about to abort) must leave the blocked C-call's
        Python frames on disk, or a wedged dispatch is undiagnosable
        post-mortem. faulthandler is async-safe and needs no cooperation
        from the stuck thread."""
        streams = [sys.stderr]
        for name in ("mgwfbp.trainer", "mgwfbp.watchdog"):
            for h in logging.getLogger(name).handlers:
                stream = getattr(h, "stream", None)
                if stream is not None and stream not in streams:
                    streams.append(stream)
        for s in streams:
            try:
                s.write(
                    f"\n== watchdog stall in {self._phase!r}: all-thread "
                    "traceback dump ==\n"
                )
                # flush BEFORE the dump: faulthandler writes straight to
                # the fd, bypassing the Python buffer the banner sits in —
                # without this the banner lands AFTER the tracebacks
                s.flush()
                faulthandler.dump_traceback(file=s, all_threads=True)
                s.flush()
            except Exception:  # noqa: BLE001 — a closed/broken stream
                # must not mask the remaining dump targets or the abort
                continue

    def _watch(self) -> None:
        while not self._stop.wait(min(self.check_interval_s, self.timeout_s)):
            idle = time.monotonic() - self._last
            if idle > self.timeout_s + self._allow:
                self.fired = True
                self.log.critical(
                    "no training progress for %.0f s (stalled in %r; "
                    "timeout %.0f s) — likely a wedged device/tunnel or "
                    "blocked host call%s",
                    idle, self._phase, self.timeout_s,
                    "; aborting (MGWFBP_WATCHDOG_ABORT=1)"
                    if self.abort
                    else "",
                )
                # escalation BEFORE the optional abort: the stack dump is
                # the post-mortem; os._exit would otherwise take the
                # evidence down with the process
                self._dump_all_stacks()
                if self.on_stall is not None:
                    try:
                        self.on_stall(
                            phase=self._phase, idle_s=float(idle),
                            timeout_s=float(self.timeout_s),
                            abort=bool(self.abort),
                        )
                    except Exception:  # noqa: BLE001 — the stall signal
                        # must never be masked by its own reporting
                        self.log.exception("watchdog on_stall hook failed")
                if self.abort:
                    # os._exit: the stalled runtime call cannot be
                    # interrupted from Python — exiting the process is the
                    # only way to hand control back to a supervisor
                    os._exit(86)
                self.beat(self._phase)  # re-arm so it warns periodically

    def __enter__(self) -> "ProgressWatchdog":
        if self.enabled:
            self.beat("startup")
            self._thread = threading.Thread(target=self._watch, daemon=True)
            self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
